"""Indexed informer cache: shared read-optimized state for reconcilers.

Controller-runtime reconcilers never list the apiserver on their hot path —
they read an informer-fed cache with registrable indexers (client-go
cache.Indexer; FieldIndexer in controller-runtime terms), so a reconcile of
one Notebook costs O(its objects), not O(all objects).  Until now every
reconcile here did live `api.list()` scans (`_pods_of`, the owned
StatefulSet lookup, whole-fleet Notebook sweeps), which is O(cluster) work
per event — the exact shape Podracer (arXiv:2104.06272) identifies as the
throughput ceiling: workers must share a read-optimized store instead of
re-materializing state per task.

`InformerCache` subscribes to the same watch stream the Manager consumes
(kube/store.py fan-out in-memory; the reflector informers of
kube/client.py on a real cluster) and maintains:

  - per-kind object maps, primed lazily with a consistent
    `list_with_rv` snapshot and kept fresh by watch events (stale replays
    are dropped by resourceVersion comparison; deletions observed during a
    prime are tombstoned so the snapshot cannot resurrect them);
  - registrable indexers: `add_namespace_index`, `add_owner_uid_index`
    (controller ownerReference uid), and `add_label_index(kind, *keys)`
    for exact-label-selector lookups (the TPU worker pods are selected by
    their StatefulSet label);
  - `cache_index_lookups_total{index,result}` hit/miss accounting, so a
    dashboard shows when a hot path silently degraded to a brute scan.

Resume semantics mirror the Manager's `_WatchSession`: an injected watch
drop (kube/faults.py `drop_watch`) disconnects the cache too, and
reconnect resumes from the newest resourceVersion seen — or, when the
history window was compacted away (410 Gone), relists every primed kind
against the live store.  Priming and relists are recovery machinery, not
client traffic, and run fault-exempt.

The cache subscribes FILTERED (store.py kinds= filter) and widens its own
kind set lazily — the first read, indexer, or aggregate over a kind adds
it to the subscription before priming, so a kind nobody caches costs the
dispatch path nothing.

Read contract (matches ApiServer): `get` returns a PRIVATE copy — mutate
and update() freely.  `list`/`select`/`by_index` return the cached frozen
objects themselves with no per-object copy — READ-ONLY; mutating one
without a fresh get() + update() is a bug.

Incremental aggregates (`add_aggregate`) maintain per-group sums updated
O(changed) on each watch event, so metric census scrapes never rescan the
object maps (let alone the apiserver).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..utils import invariants
from .errors import GoneError
from .meta import KubeObject
from .store import EventType, WatchEvent, match_labels

IndexFn = Callable[[KubeObject], list]


def _rv_int(obj: KubeObject) -> int:
    rv = obj.metadata.resource_version
    if isinstance(rv, int):
        return rv
    try:
        return int(rv or 0)
    except (TypeError, ValueError):
        return 0


class InformerCache:
    """Watch-fed object cache with registrable indexers (see module doc)."""

    def __init__(self, api, registry=None, key_filter=None) -> None:
        self.api = api
        # sharded control plane (kube/shard.py): `key_filter(kind, ns,
        # name)` scopes what this cache stores — a replica's cache holds
        # only the keys its shard owns, so cache memory scales per-shard.
        # Events for keys that moved away EVICT the stale copy; resync()
        # realigns the map after ownership changes.
        self._key_filter = key_filter
        self._lock = invariants.tracked(
            threading.Lock(), "InformerCache._lock")
        # kind -> (namespace, name) -> KubeObject
        self._objects: dict[str, dict[tuple[str, str], KubeObject]] = {}
        self._primed: set[str] = set()
        # kinds mid-sync: deletions seen while the list snapshot is in
        # flight, so the merge cannot resurrect an object deleted after
        # the snapshot was taken
        self._tombstones: dict[str, set[tuple[str, str]]] = {}
        self._indexers: dict[str, dict[str, IndexFn]] = {}
        # (kind, index name) -> index key -> set of object keys
        self._indexes: dict[tuple[str, str], dict[str, set[tuple[str, str]]]] = {}
        self.lookups = None
        if registry is not None:
            self.lookups = registry.counter(
                "cache_index_lookups_total",
                "Indexed cache lookups by index and hit/miss outcome "
                "(miss = the read fell back to a brute-force scan)",
                labels=("index", "result"))
        # incremental aggregates: kind -> name -> fn(obj)->{group: value};
        # (kind, name) -> group -> running sum (updated alongside indexes)
        self._agg_fns: dict[str, dict[str, Callable[[KubeObject], dict]]] = {}
        self._aggs: dict[tuple[str, str], dict[str, float]] = {}
        # watch-resume state (in-memory backend only; the KubeClient's
        # reflector informers own their drop/relist recovery and never
        # disconnect this plain-callback watcher)
        self.connected = True
        self.drops = 0
        self.relists = 0
        self.last_rv = 0
        self._conn_lock = invariants.tracked(
            threading.Lock(), "InformerCache._conn_lock")
        # kinds this cache asked the store to stream (grown lazily; only
        # meaningful on the filtered in-memory backend)
        self._watched: set[str] = set()
        self._filtered = hasattr(api, "update_watch_kinds")
        if hasattr(api, "subscribe"):
            if self._filtered:
                api.subscribe(self, kinds=[])
            else:
                api.subscribe(self)
        else:
            api.watch(self)

    # -- watch feed -----------------------------------------------------------
    def __call__(self, ev: WatchEvent) -> None:
        rv = _rv_int(ev.obj)
        with self._lock:
            if rv > self.last_rv:
                self.last_rv = rv
            kind = ev.obj.kind
            key = (ev.obj.namespace, ev.obj.name)
            store = self._objects.setdefault(kind, {})
            old = store.get(key)
            if ev.type is EventType.DELETED:
                if kind in self._tombstones:
                    self._tombstones[kind].add(key)
                if old is not None:
                    if _rv_int(old) > rv:
                        # the stored object is a NEWER incarnation: a
                        # recreate raced ahead of this DELETED in the
                        # fan-out (a data-plane watcher recreating pods
                        # reacts inside the same notify pass) — evicting
                        # it would blind every indexed read until relist
                        return
                    del store[key]
                    self._deindex(kind, key, old)
            else:
                if self._key_filter is not None and \
                        not self._key_filter(kind, key[0], key[1]):
                    # not this shard's key: never store it, and evict any
                    # copy left from before ownership moved away
                    if old is not None:
                        del store[key]
                        self._deindex(kind, key, old)
                    return
                if old is not None and _rv_int(old) > rv:
                    return  # stale replay (resume overlap); keep the newer
                self._reindex(kind, key, old, ev.obj)
                store[key] = ev.obj

    def on_watch_dropped(self) -> None:
        self.drops += 1
        self.connected = False

    def ensure_connected(self) -> None:
        """Reconnect after an injected watch drop — resume from the last
        seen resourceVersion, or relist every primed kind on 410 Gone.
        The resume keeps the kind filter: per-kind history rings mean
        churn on kinds this cache never asked for cannot evict its
        window."""
        if self.connected:
            return
        with self._conn_lock:
            if self.connected:
                return
            kinds_filter = sorted(self._watched) if self._filtered else None
            try:
                self.api.subscribe(self, since_rv=self.last_rv,
                                   kinds=kinds_filter)
            except GoneError:
                self.api.subscribe(self, kinds=kinds_filter)
                self.relists += 1
                with self._lock:
                    kinds = sorted(self._primed)
                for kind in kinds:
                    self._sync_kind(kind, prune=True)
            self.connected = True

    def _ensure_watched(self, kind: str) -> None:
        """Add `kind` to the filtered subscription BEFORE any prime/index
        touches it, so no event can slip between snapshot and stream."""
        if not self._filtered:
            return
        with self._lock:
            if kind in self._watched:
                return
            self._watched.add(kind)
            kinds = sorted(self._watched)
        if self.connected:
            self.api.update_watch_kinds(self, kinds)

    # -- indexer registration -------------------------------------------------
    def add_indexer(self, kind: str, name: str, fn: IndexFn) -> None:
        """Register an index over `kind`; `fn(obj)` returns the index keys
        the object files under.  Idempotent by (kind, name): a second
        registration under the same name is a no-op, so setup functions may
        register shared indexes without coordinating.  Registration primes
        the kind (and adds it to the filtered subscription) so the index is
        complete and stays maintained."""
        self._ensure_primed(kind)
        with self._lock:
            per_kind = self._indexers.setdefault(kind, {})
            if name in per_kind:
                return
            per_kind[name] = fn
            idx: dict[str, set[tuple[str, str]]] = {}
            for key, obj in self._objects.get(kind, {}).items():
                for k in fn(obj):
                    idx.setdefault(k, set()).add(key)
            self._indexes[(kind, name)] = idx

    def add_aggregate(self, kind: str, name: str,
                      fn: Callable[[KubeObject], dict]) -> str:
        """Register an incremental aggregate over `kind`: `fn(obj)` returns
        {group_key: float} contributions, and the cache keeps per-group
        running sums updated on every watch event — O(changed) per event,
        O(groups) per read, never a rescan.  Idempotent by (kind, name).
        The metric census (core.metrics) reads its gauges off these."""
        self._ensure_primed(kind)
        with self._lock:
            per_kind = self._agg_fns.setdefault(kind, {})
            if name in per_kind:
                return name
            per_kind[name] = fn
            sums: dict[str, float] = {}
            for obj in self._objects.get(kind, {}).values():
                for k, v in fn(obj).items():
                    sums[k] = sums.get(k, 0.0) + v
            self._aggs[(kind, name)] = sums
        return name

    def aggregate(self, kind: str, name: str) -> dict[str, float]:
        """Current per-group sums of a registered aggregate.  Raises
        KeyError for an unregistered aggregate (same loud-failure contract
        as by_index)."""
        with self._lock:
            if name not in self._agg_fns.get(kind, {}):
                raise KeyError(f"no aggregate {name!r} registered for {kind}")
            return dict(self._aggs.get((kind, name), {}))

    def add_namespace_index(self, kind: str) -> str:
        self.add_indexer(kind, "namespace", lambda o: [o.namespace])
        return "namespace"

    def add_owner_uid_index(self, kind: str) -> str:
        def fn(obj: KubeObject) -> list:
            ref = obj.metadata.controller_owner()
            return [ref.uid] if ref is not None else []

        self.add_indexer(kind, "owner-uid", fn)
        return "owner-uid"

    def add_label_index(self, kind: str, *keys: str) -> str:
        """Exact-match label index over a fixed key set; `select()` with a
        selector over exactly these keys is served from it."""
        key_tuple = tuple(sorted(keys))
        name = "label:" + ",".join(key_tuple)

        def fn(obj: KubeObject) -> list:
            labels = obj.metadata.labels
            if not all(k in labels for k in key_tuple):
                return []
            return [",".join(f"{k}={labels[k]}" for k in key_tuple)]

        self.add_indexer(kind, name, fn)
        return name

    # -- reads (all deepcopied) -----------------------------------------------
    def get(self, kind: str, namespace: str, name: str) -> Optional[KubeObject]:
        self._ensure_primed(kind)
        with self._lock:
            obj = self._objects.get(kind, {}).get((namespace, name))
            return obj.deepcopy() if obj is not None else None

    # ApiServer-read-surface alias, so cache-or-api call sites stay uniform
    try_get = get

    def keys(self, kind: str,
             namespace: Optional[str] = None) -> list[tuple[str, str]]:
        """(namespace, name) keys of a kind — enqueue_all resyncs from this
        instead of materializing every object through the apiserver."""
        self._ensure_primed(kind)
        with self._lock:
            return sorted(k for k in self._objects.get(kind, {})
                          if namespace is None or k[0] == namespace)

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[dict[str, str]] = None
             ) -> list[KubeObject]:
        """Cache-backed list; namespace-scoped listings go through the
        namespace index when one is registered (hit), else scan the kind
        map (miss).  Returns the cached objects themselves — READ-ONLY
        frozen snapshots (see module doc); no per-object copy."""
        self._ensure_primed(kind)
        with self._lock:
            store = self._objects.get(kind, {})
            if namespace is None:
                objs = list(store.values())
            elif "namespace" in self._indexers.get(kind, {}):
                hits = self._indexes.get((kind, "namespace"), {}) \
                    .get(namespace, set())
                objs = [store[k] for k in hits if k in store]
                self._count("namespace", "hit")
            else:
                objs = [o for k, o in store.items() if k[0] == namespace]
                self._count("namespace", "miss")
            if label_selector:
                objs = [o for o in objs
                        if match_labels(o.metadata.labels, label_selector)]
            return sorted(objs, key=lambda o: (o.namespace, o.name))

    def select(self, kind: str, namespace: Optional[str],
               label_selector: Optional[dict[str, str]]) -> list[KubeObject]:
        """Label-selector lookup.  Served from the exact-key-set label
        index when one is registered for the selector's keys (hit), else a
        brute-force filtered scan (miss).  Read-only results, as list()."""
        if not label_selector:
            return self.list(kind, namespace)
        key_tuple = tuple(sorted(label_selector))
        name = "label:" + ",".join(key_tuple)
        self._ensure_primed(kind)
        with self._lock:
            store = self._objects.get(kind, {})
            if name in self._indexers.get(kind, {}):
                ikey = ",".join(f"{k}={label_selector[k]}" for k in key_tuple)
                hits = self._indexes.get((kind, name), {}).get(ikey, set())
                objs = [store[k] for k in hits
                        if k in store and (namespace is None
                                           or k[0] == namespace)]
                self._count(name, "hit")
            else:
                objs = [o for k, o in store.items()
                        if (namespace is None or k[0] == namespace)
                        and match_labels(o.metadata.labels, label_selector)]
                self._count(name, "miss")
            return sorted(objs, key=lambda o: (o.namespace, o.name))

    def by_index(self, kind: str, index: str, key: str) -> list[KubeObject]:
        """Objects filed under `key` in a registered index.  Raises
        KeyError for an unregistered index — a silent brute-scan fallback
        here would hide a missing setup-time registration forever.
        Read-only results, as list()."""
        self._ensure_primed(kind)
        with self._lock:
            if index not in self._indexers.get(kind, {}):
                raise KeyError(f"no index {index!r} registered for {kind}")
            store = self._objects.get(kind, {})
            hits = self._indexes.get((kind, index), {}).get(key, set())
            self._count(index, "hit")
            return sorted((store[k] for k in hits if k in store),
                          key=lambda o: (o.namespace, o.name))

    def resync(self, kind: str) -> list[tuple[str, str]]:
        """Realign the kind map with the live store under the CURRENT key
        filter — shard adoption (kube/shard.py) calls this after gaining
        keys, so objects whose events this cache skipped while another
        shard owned them appear, and keys that moved away drop.  Returns
        the keys the sweep newly admitted (they were not cached before),
        so the adoption path can enqueue exactly what moved instead of
        sweeping every key it holds."""
        self._ensure_primed(kind)
        return self._sync_kind(kind, prune=True)

    def stats(self) -> dict:
        with self._lock:
            return {
                "primed_kinds": sorted(self._primed),
                "watched_kinds": sorted(self._watched),
                "objects": {k: len(v) for k, v in self._objects.items()},
                "indexes": {f"{kind}/{name}": len(idx)
                            for (kind, name), idx in self._indexes.items()},
                "drops": self.drops,
                "relists": self.relists,
                "connected": self.connected,
            }

    # -- internals ------------------------------------------------------------
    def _count(self, index: str, result: str) -> None:
        if self.lookups is not None:
            self.lookups.labels(index, result).inc()

    def _reindex(self, kind: str, key: tuple[str, str],
                 old: Optional[KubeObject], new: KubeObject) -> None:
        for name, fn in self._indexers.get(kind, {}).items():
            idx = self._indexes.setdefault((kind, name), {})
            if old is not None:
                for k in fn(old):
                    bucket = idx.get(k)
                    if bucket is not None:
                        bucket.discard(key)
                        if not bucket:
                            del idx[k]
            for k in fn(new):
                idx.setdefault(k, set()).add(key)
        self._reaggregate(kind, old, new)

    def _deindex(self, kind: str, key: tuple[str, str],
                 old: KubeObject) -> None:
        for name, fn in self._indexers.get(kind, {}).items():
            idx = self._indexes.get((kind, name), {})
            for k in fn(old):
                bucket = idx.get(k)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del idx[k]
        self._reaggregate(kind, old, None)

    def _reaggregate(self, kind: str,
                     old: Optional[KubeObject],
                     new: Optional[KubeObject]) -> None:
        """O(changed) aggregate maintenance: subtract the old object's
        contributions, add the new one's.  Contributions are exact small
        counts, so the +/- arithmetic stays float-exact."""
        for name, fn in self._agg_fns.get(kind, {}).items():
            sums = self._aggs.setdefault((kind, name), {})
            if old is not None:
                for k, v in fn(old).items():
                    left = sums.get(k, 0.0) - v
                    if abs(left) < 1e-9:
                        sums.pop(k, None)
                    else:
                        sums[k] = left
            if new is not None:
                for k, v in fn(new).items():
                    sums[k] = sums.get(k, 0.0) + v

    def _ensure_primed(self, kind: str) -> None:
        with self._lock:
            if kind in self._primed:
                return
        # widen the filtered subscription FIRST: events landing between
        # the filter change and the snapshot merge via the rv guards
        self._ensure_watched(kind)
        self._sync_kind(kind, prune=False)
        with self._lock:
            self._primed.add(kind)

    def _list_live(self, kind: str) -> tuple[list[KubeObject], int]:
        """Consistent snapshot from the backing store, fault-exempt (this
        is cache machinery, not client traffic under test)."""
        def do() -> tuple[list[KubeObject], int]:
            lister = getattr(self.api, "list_with_rv", None)
            if lister is not None:
                if self._key_filter is not None:
                    # predicate pushdown: a sharded cache lists only its
                    # owned keys instead of materializing the whole
                    # fleet and filtering here (O(owned), not O(fleet),
                    # per resync — the dominant cost of an adoption
                    # sweep at 100k keys).  Backends without the
                    # parameter (remote KubeClient) fall back to the
                    # full list.
                    kf = self._key_filter
                    try:
                        return lister(
                            kind,
                            predicate=lambda ns, name: kf(kind, ns, name))
                    except TypeError:
                        return lister(kind)
                return lister(kind)
            return self.api.list(kind), 0

        exempt = getattr(self.api, "fault_exempt", None)
        if exempt is not None:
            with exempt():
                return do()
        return do()

    def _sync_kind(self, kind: str, prune: bool) -> list[tuple[str, str]]:
        """Merge a live list snapshot into the kind map.  Watch events keep
        flowing while the list is in flight: newer stored versions win by
        resourceVersion, and deletions observed mid-sync are tombstoned so
        the snapshot cannot resurrect them.  `prune=True` (relist after
        410) additionally drops entries absent from the snapshot, unless
        they are provably newer than it.  Returns the keys the merge
        newly admitted."""
        with self._lock:
            self._tombstones.setdefault(kind, set())
        try:
            objs, snapshot_rv = self._list_live(kind)
        except Exception:
            with self._lock:
                self._tombstones.pop(kind, None)
            raise
        fresh = {(o.namespace, o.name): o for o in objs}
        if self._key_filter is not None:
            fresh = {k: o for k, o in fresh.items()
                     if self._key_filter(kind, k[0], k[1])}
        with self._lock:
            tombstones = self._tombstones.pop(kind, set())
            store = self._objects.setdefault(kind, {})
            if prune:
                for key in [k for k in store if k not in fresh]:
                    cur = store[key]
                    owned = self._key_filter is None or \
                        self._key_filter(kind, key[0], key[1])
                    if owned and snapshot_rv and _rv_int(cur) > snapshot_rv:
                        continue  # created after the snapshot; event is live
                    # a key that moved to another shard drops regardless of
                    # its resourceVersion: not owned is not stored
                    del store[key]
                    self._deindex(kind, key, cur)
            added: list[tuple[str, str]] = []
            for key, obj in fresh.items():
                if key in tombstones:
                    continue  # deleted while the snapshot was in flight
                cur = store.get(key)
                if cur is not None and _rv_int(cur) >= _rv_int(obj):
                    continue
                if cur is None:
                    added.append(key)
                self._reindex(kind, key, cur, obj)
                store[key] = obj
            return added


__all__ = ["InformerCache"]
