"""Convergence benchmark: N notebooks -> all Ready, deterministically.

`start_notebooks.py` measures wall-clock readiness latency — useful, but
noisy and machine-dependent, so CI cannot assert on it.  This benchmark
measures what IS deterministic on the FakeClock: how much work the control
plane does to converge a fleet, and whether it then goes quiet.

    python loadtest/convergence.py --count 200 --compare-workers 8 \
        --check-budget ci/apiserver_call_budget.json

Per run it reports:
  - wall time and reconciles/sec (informational by default; a budget may
    pin a generous wall-clock ceiling as a regression backstop);
  - reconciles per notebook, per controller (Manager reconcile counters);
  - event->reconcile-start reaction latency: exact p50/p99 over every
    event-caused reconcile (Manager.event_latency_samples), the
    control-plane reaction number NotebookOS says interactive platforms
    live or die on;
  - API verbs by (verb, kind) from the ApiServer's top-level verb counters
    (reads included; the fault-exempt FakeCluster data plane is excluded)
    — per-kind write totals come from these, not the bounded audit ring,
    so they stay exact at 10k+ notebooks;
  - steady-state probe: after convergence, a full resync (`enqueue_all`)
    must complete with ZERO write verbs (verb-counter-verified — the
    counters share the audit's client-boundary gate and never wrap) —
    proving the no-op write suppression end to end — and at most one
    reconcile per (controller, object);
  - per-key serialization: the flight recorder's attempt-overlap check
    must come back empty (no two concurrent reconciles of one key);
  - SLO verdicts (utils/slo.py): each standing objective's met/violated
    state and end-of-run burn rate, recorded into the `--out` trajectory
    JSON — the same engine the manager serves at /debug/alerts.

`--compare-workers W` runs the same fleet again with W parallel workers
and asserts the normalized final cluster state (resourceVersions, uids,
timestamps, pod IPs scrubbed; uids rewritten to stable object references)
is identical to the single-worker run.

`--check-budget FILE` compares writes-per-notebook and
reconciles-per-notebook against the committed budget and fails on >
`tolerance` regression — the deterministic CI perf gate.  Regenerate an
intentionally-changed budget with `--write-budget FILE`.

Bursty mode (`--bursty N`) drives the slice scheduler + warm pool
(core/scheduler.py) with a bursty arrival trace instead of one flood:
`--bursts` waves of N TPU notebooks, each wave stopped (the cull analog)
before the next so culling->reclamation resells the same slices, with a
manager failover injected mid-run (pool bookkeeping and placement intents
must survive it).  It runs the trace twice — warm pool on
(`--warm-size`) and off — and prints p50/p99 notebook-ready time and
slice utilization for both; `--check-warm-budget FILE` gates the
comparison (warm p50 strictly below cold, minimum hit rate) for CI.
Gang atomicity (never a partially placed slice; every slice co-located
on one node pool) is asserted at every wave's convergence.

Tenants mode (`--tenants N --noisy T`) runs N namespaces of placed TPU
notebooks and floods spec churn from tenant T while the others tick over
fairly: the metering ledger (utils/metering.py) must attribute the flood
to the exact namespace, fire exactly one deduped NoisyNeighbor Warning,
clear the flag after the flood stops, and keep chip-second conservation
at zero violations; `--check-budget` gates the victim tenants' p99
event->reconcile against the `tenants` section of the budget JSON.
"""

from __future__ import annotations

import argparse
import json
import resource
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from kubeflow_tpu.api.types import Notebook, TPUSpec  # noqa: E402
from kubeflow_tpu.core.metrics import NotebookMetrics  # noqa: E402
from kubeflow_tpu.core.notebook_controller import (  # noqa: E402
    setup_core_controllers,
)
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager  # noqa: E402
from kubeflow_tpu.utils import tracing  # noqa: E402
from kubeflow_tpu.utils.clock import FakeClock  # noqa: E402
from kubeflow_tpu.utils.config import CoreConfig  # noqa: E402
from kubeflow_tpu.utils.flightrecorder import FlightRecorder  # noqa: E402
from kubeflow_tpu.utils.lifecycle import LifecycleLedger  # noqa: E402
from kubeflow_tpu.utils.metering import (  # noqa: E402
    REASON_NOISY,
    TenantMeteringLedger,
)
from kubeflow_tpu.utils.slo import (  # noqa: E402
    SLOEngine,
    default_objectives,
)
from kubeflow_tpu.utils.tsdb import TimeSeriesStore  # noqa: E402

NAMESPACE = "loadtest"

# non-deterministic or server-managed fields scrubbed before comparing the
# final cluster state of two runs (uids are MAPPED, not dropped — ownership
# topology must still match)
_SCRUB_KEYS = frozenset({
    "resourceVersion", "creationTimestamp", "managedFields",
    "lastTransitionTime", "lastProbeTime", "startedAt", "startTime",
    "time", "podIP",
})


def normalized_state(api: ApiServer) -> dict:
    """api.dump() with volatile fields scrubbed and every uid replaced by
    the stable identity of the object it names, so two runs of the same
    fleet compare equal iff they converged to the same semantic state."""
    dump = api.dump()
    uid_names: dict[str, str] = {}
    for kind, objs in dump.items():
        for o in objs:
            meta = o.get("metadata", {})
            if meta.get("uid"):
                uid_names[meta["uid"]] = "%s/%s/%s" % (
                    kind, meta.get("namespace", ""), meta.get("name", ""))

    def scrub(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in _SCRUB_KEYS:
                    continue
                if k == "uid" and isinstance(v, str):
                    out[k] = uid_names.get(v, v)
                else:
                    out[k] = scrub(v)
            return out
        if isinstance(node, list):
            return [scrub(x) for x in node]
        return node

    out = {}
    for kind, objs in sorted(dump.items()):
        if kind == "Event":
            continue  # event names/counts are sequencing artifacts
        out[kind] = sorted(
            (scrub(o) for o in objs),
            key=lambda o: (o["metadata"].get("namespace", ""),
                           o["metadata"]["name"]))
    return out


def _reconciles_per_controller(mgr: Manager) -> dict[str, int]:
    out: dict[str, int] = {}
    for key, v in mgr.reconcile_total.collect().items():
        out[key[0]] = out.get(key[0], 0) + int(v)
    return out


_WRITE_VERBS = ("create", "update", "patch", "delete")


def run_fleet(count: int, workers: int, tpu: str = "",
              compute_state: bool = True) -> dict:
    clock = FakeClock()
    # span/recorder timestamps must share the manager's FakeClock, or the
    # lifecycle ledger would attribute the wall-vs-fake clock skew to
    # queue_wait (cause stamps come from the manager clock, span times
    # from the tracer clock)
    tracing.set_clock(clock)
    try:
        return _run_fleet(count, workers, tpu, compute_state, clock)
    finally:
        tracing.set_clock(None)


def _run_fleet(count: int, workers: int, tpu: str,
               compute_state: bool, clock: FakeClock) -> dict:
    api = ApiServer()
    cluster = FakeCluster(api)
    recorder = FlightRecorder(capacity=max(4096, count * 8),
                              max_objects=max(2048, count * 4))
    mgr = Manager(api, clock=clock, workers=workers,
                  flight_recorder=recorder)
    cfg = CoreConfig.from_env({})  # hermetic: culling off, defaults only
    metrics = NotebookMetrics(api, manager=mgr)
    setup_core_controllers(mgr, cfg, metrics)
    # standing SLO verdicts ride the trajectory record (--out): the same
    # engine production runs under /debug/alerts, evaluated at run end
    slo_engine = SLOEngine(
        default_objectives(cfg),
        registries=[metrics.registry, mgr.metrics_registry],
        clock=clock, recorder=recorder)
    mgr.slo_engine = slo_engine
    metrics.attach_slo(slo_engine)
    # lifecycle stage ledger (critical-path attribution, conservation-
    # gated below) + in-process TSDB (the p99-vs-time curve a diagnose
    # bundle reconstructs offline); sized so EVERY notebook of the run is
    # conservation-checked, not just an LRU window
    ledger = LifecycleLedger(registry=metrics.registry,
                             max_notebooks=max(4096, count),
                             keep_conservation=max(4096, count))
    mgr.lifecycle = ledger
    metrics.attach_lifecycle(ledger)
    tsdb = TimeSeriesStore()
    mgr.tsdb = tsdb
    metrics.attach_tsdb(tsdb, clock=clock)
    # tenant metering ledger: one-tenant fleet here, but the dispatch
    # attribution + conservation contract is gated at 10k scale exactly
    # like the lifecycle ledger's (the --tenants mode covers multi-tenant)
    metering = TenantMeteringLedger(clock, registry=metrics.registry,
                                    max_notebooks=max(4096, count),
                                    keep_conservation=max(4096, count))
    mgr.metering = metering
    metrics.attach_metering(metering)

    spec = None
    if tpu:
        accel, topology = tpu.split(":")
        spec = TPUSpec(accel, topology)
        shape = spec.validate()
        cluster.add_tpu_slice_nodes(
            shape.accelerator.gke_label, shape.topology,
            shape.num_hosts * count, shape.chips_per_host)
    cluster.add_node("cpu-node", allocatable={"cpu": str(count * 8),
                                              "memory": "8192Gi"})
    expected_ready = spec.shape.num_hosts if spec else 1

    api.clear_audit_log()
    api.clear_verb_counts()
    # the flood arrives in batches, each settled and scraped, so the TSDB
    # holds a p99-vs-time curve (ready p99 climbing batch over batch) a
    # diagnose bundle can reconstruct offline — one monolithic settle
    # would leave a single point and no history
    n_batches = min(8, count) or 1
    t0 = time.perf_counter()
    rollout_reconciles_total = 0
    created = 0
    for b in range(n_batches):
        batch = count // n_batches + (1 if b < count % n_batches else 0)
        for i in range(created, created + batch):
            api.create(Notebook.new(f"nb-{i:04d}", NAMESPACE, tpu=spec).obj)
        created += batch
        rollout_reconciles_total += mgr.settle(max_seconds=7200.0)
        metrics.scrape()  # feeds one TSDB sample at this FakeClock instant
        if b < n_batches - 1:
            clock.advance(10.0)  # distinct timestamps across batches
    wall_s = time.perf_counter() - t0

    not_ready = []
    for i in range(count):
        status = api.get("Notebook", NAMESPACE,
                         f"nb-{i:04d}").body.get("status") or {}
        if status.get("readyReplicas") != expected_ready:
            not_ready.append(f"nb-{i:04d}")
    if not_ready:
        raise AssertionError(
            f"{len(not_ready)} notebooks never converged "
            f"(first: {not_ready[:3]})")
    if mgr.dropped_errors:
        raise AssertionError(f"retry budget exhausted: {mgr.dropped_errors}")

    rollout_reconciles = _reconciles_per_controller(mgr)
    rollout_verb_counts = api.verb_counts()
    rollout_verbs = {f"{verb}:{kind}": n
                     for (verb, kind), n in sorted(rollout_verb_counts.items())}
    # per-kind writes off the verb counters: the audit ring is bounded
    # (detail for chaos forensics), the counters are exact at any scale
    rollout_writes: dict[str, int] = {}
    for (verb, kind), n in rollout_verb_counts.items():
        if verb in _WRITE_VERBS:
            rollout_writes[kind] = rollout_writes.get(kind, 0) + n

    # steady-state probe: a full resync of a converged fleet must be
    # all-reads — zero write verbs (the counters share the audit's
    # client-boundary gate, so this is the same proof without the ring
    # bound) — and at most one reconcile per (controller, object) since
    # nothing re-triggers
    api.clear_verb_counts()
    before = _reconciles_per_controller(mgr)
    mgr.enqueue_all()
    mgr.settle(max_seconds=7200.0)
    after = _reconciles_per_controller(mgr)
    steady_write_verbs = {
        f"{verb}:{kind}": n
        for (verb, kind), n in sorted(api.verb_counts().items())
        if verb in _WRITE_VERBS}
    if steady_write_verbs:
        raise AssertionError(
            f"write verbs issued by a converged fleet: {steady_write_verbs}")
    steady_reconciles = {c: after.get(c, 0) - before.get(c, 0) for c in after}
    for controller, n in steady_reconciles.items():
        if n > count:
            raise AssertionError(
                f"steady-state resync re-reconciled {controller} {n} times "
                f"for {count} objects — the fleet is not quiet")

    overlaps = recorder.overlapping_attempts()
    if overlaps:
        a, b = overlaps[0]
        raise AssertionError(
            f"per-key serialization violated: {len(overlaps)} overlapping "
            f"attempt pairs (first: {a.controller} {a.object_key})")

    # conservation gate: every notebook's attributed stage durations must
    # sum to its measured event->ready wall time within tolerance — the
    # falsifiable contract of the lifecycle ledger (a double-count, gap
    # misclassification, or leak across retries breaks the equality)
    metrics.scrape()
    cons = ledger.conservation()
    if cons["finalized"] != count:
        raise AssertionError(
            f"lifecycle ledger finalized {cons['finalized']}/{count} "
            "notebooks — some never saw a ready event or were evicted")
    if cons["violations"]:
        first = ledger.violations()[:3]
        raise AssertionError(
            f"stage attribution broke conservation for "
            f"{cons['violations']}/{cons['checked']} notebooks "
            f"(tolerance {cons['tolerance']:.0%}, first: {first})")

    # tenant metering gate: the bucketed chip-second partition must
    # conserve (zero violations), and the workqueue attribution must have
    # actually landed on the owning namespace — a silent attribution miss
    # would leave the tenant table empty while everything else passes
    mcons = metering.conservation()
    if mcons["violations"]:
        raise AssertionError(
            f"tenant metering broke conservation for "
            f"{mcons['violations']}/{mcons['checked']} placement intervals "
            f"(first: {metering.violations()[:3]})")
    mtable = metering.tenant_table()
    if mtable.get(NAMESPACE, {}).get("dispatches", 0) <= 0:
        raise AssertionError(
            "tenant metering attributed no workqueue dispatches to the "
            f"{NAMESPACE!r} namespace")

    # event->reconcile-start reaction latency (wall clock; the FakeClock
    # collapses the deterministic histogram to ~0 in this harness): exact
    # percentiles over every event-caused reconcile of the run
    latency = mgr.event_latency_samples()
    dispatch = {f"{kind}:{result}": n
                for (kind, result), n in
                sorted(api.watch_dispatch_counts().items())}

    result = {
        "count": count,
        "notebooks": count,
        "workers": workers,
        "tpu": tpu or "cpu",
        "wall_s": round(wall_s, 3),
        "rollout_reconciles_total": rollout_reconciles_total,
        "reconciles_per_sec": round(rollout_reconciles_total / wall_s, 1)
        if wall_s > 0 else 0.0,
        "reconciles_per_notebook": {
            c: round(n / count, 3) for c, n in rollout_reconciles.items()},
        "writes_per_notebook": {
            k: round(n / count, 3) for k, n in sorted(rollout_writes.items())},
        "p50_event_to_reconcile_s": round(_percentile(latency, 0.50), 6),
        "p99_event_to_reconcile_s": round(_percentile(latency, 0.99), 6),
        "event_to_reconcile_samples": len(latency),
        "api_verbs": rollout_verbs,
        "watch_dispatch": dispatch,
        "steady_reconciles": steady_reconciles,
        "steady_write_verbs": 0,
        "cache": mgr.cache.stats() if mgr.cache is not None else {},
        # objective -> met/violated + burn rate at end of run (utils/slo):
        # the trajectory record carries a standing SLO verdict, not just
        # raw percentiles
        "slo": slo_engine.verdicts(),
        # per-stage critical-path attribution + the conservation verdict
        # (utils/lifecycle): where event->ready time actually went
        "criticalpath": {
            "ranking": ledger.ranking(),
            "conservation": cons,
        },
        # the diagnosis engine's sweep contract: each point names the
        # stage that dominates its event->ready attribution
        "binding_stage": (ledger.ranking()[0]["stage"]
                          if ledger.ranking() else ""),
        # tenant metering verdict (utils/metering): the chip-second
        # partition's conservation summary + attribution totals
        "tenants": {
            "conservation": mcons,
            "attributed_dispatches":
                mtable.get(NAMESPACE, {}).get("dispatches", 0),
            "attributed_apiserver":
                mtable.get(NAMESPACE, {}).get("apiserver_total", 0),
        },
        # TSDB inventory: the per-batch p99-vs-time history a diagnose
        # bundle captures in full (/debug/timeline?dump=1)
        "timeline_series": sorted(tsdb.series_names()),
    }
    _print_criticalpath(f"{count} notebooks ({tpu or 'cpu'})",
                        ledger.ranking())
    if compute_state:
        result["_state"] = normalized_state(api)
    mgr.stop()
    return result


def _print_criticalpath(tag: str, ranking: list) -> None:
    """The fleet-wide critical-path table (stderr; stdout carries the
    machine-readable result JSON): which lifecycle stage the fleet
    actually spent its event->ready time in, ranked."""
    print(f"critical path [{tag}]:", file=sys.stderr)
    if not ranking:
        print("  (no stage time attributed — instantaneous "
              "convergence on the fake clock)", file=sys.stderr)
        return
    print(f"  {'stage':<16} {'count':>7} {'total_s':>10} {'mean_s':>9} "
          f"{'p99_s':>9} {'share':>7}", file=sys.stderr)
    for r in ranking:
        print(f"  {r['stage']:<16} {r['count']:>7} {r['total_s']:>10.3f} "
              f"{r['mean_s']:>9.4f} {r['p99_s']:>9.4f} "
              f"{r['share']:>6.1%}", file=sys.stderr)


def _percentile(values: list[float], q: float) -> float:
    """Exact q-percentile (nearest-rank) of measured ready times."""
    if not values:
        return 0.0
    ordered = sorted(values)
    import math

    idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
    return ordered[idx]


def _audit_gang(api: ApiServer, shape) -> None:
    """Gang atomicity + co-location: every TPU slice has either all of its
    workers bound or none, and all bound workers of one slice sit on nodes
    of ONE node pool (the scheduler's placement intent, honored)."""
    from kubeflow_tpu.core import constants as C

    by_slice: dict[tuple[str, str, str], list] = {}
    for pod in api.list("Pod"):
        nb = pod.metadata.labels.get(C.NOTEBOOK_NAME_LABEL)
        if nb is None:
            continue
        slice_id = pod.metadata.labels.get(C.TPU_SLICE_LABEL, "0")
        by_slice.setdefault((pod.namespace, nb, slice_id), []).append(pod)
    for (ns, nb, slice_id), pods in sorted(by_slice.items()):
        bound = [p for p in pods if p.spec.get("nodeName")]
        if len(bound) not in (0, shape.num_hosts):
            raise AssertionError(
                f"gang atomicity violated: {ns}/{nb} slice {slice_id} has "
                f"{len(bound)}/{shape.num_hosts} workers bound")
        pools = set()
        for p in bound:
            node = api.try_get("Node", "", p.spec["nodeName"])
            pools.add(None if node is None
                      else node.metadata.labels.get(C.GKE_NODEPOOL_LABEL))
        if bound and len(pools) != 1:
            raise AssertionError(
                f"co-location violated: {ns}/{nb} slice {slice_id} spans "
                f"pools {sorted(str(p) for p in pools)}")


def _audit_pool_bookkeeping(api: ApiServer) -> None:
    """Claim consistency: no slice entry claimed twice for one notebook
    slice, no claim pointing at a missing notebook, and every placed
    (annotated) notebook backed by exactly its claims."""
    from kubeflow_tpu.core import constants as C
    from kubeflow_tpu.core.scheduler import placement_of

    claims: dict[tuple[str, int], str] = {}
    for pool in api.list(C.WARMPOOL_KIND):
        slices = pool.body.get("status", {}).get("slices") or {}
        for sid, e in slices.items():
            claimant = e.get("claimedBy")
            if not claimant:
                continue
            ckey = (claimant, e.get("claimedSlice"))
            if ckey in claims:
                raise AssertionError(
                    f"double claim: {ckey} held by {claims[ckey]} and {sid}")
            claims[ckey] = sid
            ns, _, name = claimant.partition("/")
            if api.try_get("Notebook", ns, name) is None:
                raise AssertionError(f"orphan claim {sid} -> {claimant}")
    for nb in api.list("Notebook"):
        tpu = nb.spec.get("tpu")
        if not tpu:
            continue
        placed = placement_of(nb.metadata.annotations)
        if not placed:
            continue
        key = f"{nb.namespace}/{nb.name}"
        for i in range(int(tpu.get("slices", 1))):
            if (key, i) not in claims:
                raise AssertionError(
                    f"placement intent of {key} slice {i} has no backing "
                    "claim")


def run_bursty(count: int, bursts: int, gap_s: float, tpu: str,
               warm_size: int, provision_s: float = 120.0,
               failover: bool = True) -> dict:
    """One bursty-arrival run of the slice scheduler: `bursts` waves of
    `count` TPU notebooks, each wave stopped (culled) before the next so
    reclamation resells its slices, a manager failover between waves 1
    and 2, and exact per-notebook ready-time measurement off the
    FakeClock."""
    clock = FakeClock()
    tracing.set_clock(clock)  # span times share the harness clock
    try:
        return _run_bursty(count, bursts, gap_s, tpu, warm_size,
                           provision_s, failover, clock)
    finally:
        tracing.set_clock(None)


def _run_bursty(count: int, bursts: int, gap_s: float, tpu: str,
                warm_size: int, provision_s: float, failover: bool,
                clock: FakeClock) -> dict:
    from kubeflow_tpu.core import constants as C
    from kubeflow_tpu.core.metrics import NotebookMetrics
    from kubeflow_tpu.kube import retry_on_conflict

    accel, topology = tpu.split(":")
    spec = TPUSpec(accel, topology)
    shape = spec.validate()
    env = {
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": str(warm_size),
        "WARMPOOL_SHAPES": f"{accel}:{topology}" if warm_size else "",
        "WARMPOOL_PROVISION_S": f"{provision_s:g}",
    }
    api = ApiServer()
    cluster = FakeCluster(api)
    # ONE ledger across the failover: the replacement manager adopts the
    # same stage history, so conservation must survive the handoff (a
    # leaked or double-counted stage across managers breaks the gate)
    ledger = LifecycleLedger(max_notebooks=max(4096, count * bursts),
                             keep_conservation=max(4096, count * bursts))

    def build() -> tuple[Manager, NotebookMetrics]:
        mgr = Manager(api, clock=clock,
                      flight_recorder=FlightRecorder(
                          capacity=max(4096, count * bursts * 8),
                          max_objects=max(2048, count * bursts * 4)))
        cfg = CoreConfig.from_env(env)
        metrics = NotebookMetrics(api, manager=mgr)
        mgr.lifecycle = ledger
        metrics.attach_lifecycle(ledger)
        setup_core_controllers(mgr, cfg, metrics, provisioner=cluster)
        return mgr, metrics

    mgr, metrics = build()
    mgr.settle(max_seconds=provision_s * 4 + 60)  # pre-warm the pool

    expected_ready = shape.num_hosts * spec.slices
    ready_s: dict[str, float] = {}
    utilization: list[float] = []

    def drain_until_ready(pending: dict[str, float],
                          deadline_s: float) -> None:
        deadline = clock.now() + deadline_s
        while True:
            mgr.run_until_idle()
            for name in list(pending):
                status = api.get("Notebook", NAMESPACE,
                                 name).body.get("status") or {}
                if status.get("readyReplicas") == expected_ready:
                    ready_s[name] = clock.now() - pending.pop(name)
            if not pending:
                return
            due = [d for (_, _, d) in mgr.pending_delayed()]
            if not due or min(due) > deadline:
                raise AssertionError(
                    f"{len(pending)} notebooks unready past the deadline "
                    f"(first: {sorted(pending)[:3]})")
            delta = min(due) - clock.now()
            if delta > 0:
                clock.advance(delta)

    def stop_and_release(names: list[str]) -> None:
        for name in names:
            def stop() -> None:
                live = api.get("Notebook", NAMESPACE, name)
                live.metadata.annotations[C.STOP_ANNOTATION] = "true"
                api.update(live)
            retry_on_conflict(stop)
        mgr.settle(max_seconds=gap_s)
        for name in names:
            live = api.get("Notebook", NAMESPACE, name)
            health = (live.body.get("status") or {}).get("sliceHealth")
            if health != "Stopped":
                raise AssertionError(f"{name} failed to stop: {health}")
            if C.ANNOTATION_PLACEMENT in live.metadata.annotations:
                raise AssertionError(
                    f"{name} stopped but its slice was never reclaimed")

    for b in range(bursts):
        if b == 1 and failover:
            # manager failover mid-run: a fresh manager over the same
            # store must resume claims/intents, never re-derive them
            mgr.stop()
            mgr, metrics = build()
            mgr.enqueue_all()
            mgr.settle(max_seconds=60)
        names = [f"nb-b{b}-{i:04d}" for i in range(count)]
        t0 = clock.now()
        for name in names:
            api.create(Notebook.new(name, NAMESPACE, tpu=spec).obj)
        drain_until_ready({n: t0 for n in names},
                          deadline_s=provision_s * 4 + 600)
        _audit_gang(api, shape)
        _audit_pool_bookkeeping(api)
        # slice utilization at wave convergence: claimed warm slices over
        # warm slices currently up (Ready or Claimed)
        claimed = up = 0
        for pool in api.list(C.WARMPOOL_KIND):
            for e in (pool.body.get("status", {}).get("slices")
                      or {}).values():
                if e.get("external"):
                    continue
                if e.get("state") == C.WARMSLICE_CLAIMED:
                    claimed += 1
                    up += 1
                elif e.get("state") == C.WARMSLICE_READY:
                    up += 1
        utilization.append(round(claimed / up, 3) if up else 1.0)
        stop_and_release(names)
        _audit_pool_bookkeeping(api)

    hits = misses = bypass = 0
    for pool in api.list(C.WARMPOOL_KIND):
        st = pool.body.get("status") or {}
        hits += int(st.get("hits", 0))
        misses += int(st.get("misses", 0))
        bypass += int(st.get("bypass", 0))
    served = hits + misses + bypass
    values = list(ready_s.values())
    cons = ledger.conservation()
    if cons["violations"]:
        raise AssertionError(
            f"bursty stage attribution broke conservation for "
            f"{cons['violations']}/{cons['checked']} notebooks "
            f"(first: {ledger.violations()[:3]})")
    _print_criticalpath(
        "%d notebooks %s (%s)" % (count * bursts, tpu,
                                  "warm" if warm_size else "cold"),
        ledger.ranking())
    mgr.stop()
    return {
        "mode": "warm" if warm_size else "cold",
        "notebooks": count * bursts,
        "bursts": bursts,
        "warm_size": warm_size,
        "failover": failover,
        "hits": hits,
        "misses": misses,
        "bypass": bypass,
        "hit_rate": round(hits / served, 3) if served else 0.0,
        "ready_p50_s": round(_percentile(values, 0.50), 3),
        "ready_p99_s": round(_percentile(values, 0.99), 3),
        "ready_max_s": round(max(values), 3) if values else 0.0,
        "slice_utilization": utilization,
        "ready_histogram_count":
            metrics.notebook_ready_seconds.count_value(NAMESPACE),
        "criticalpath": {
            "ranking": ledger.ranking(),
            "conservation": cons,
        },
    }


def run_sharded_fleet(count: int, shards: int = 3,
                      kill_shard: bool = True) -> dict:
    """Active-active convergence benchmark: `count` notebooks over a
    `shards`-replica sharded control plane (kube/shard.py via
    main.build_sharded_fleet), then a kill + rejoin cycle mid-run.
    Measures rollout wall time, merged p99 event->reconcile-start,
    merged reconciles/notebook, ring balance, and handoff durations —
    and PROVES the run: zero cross-process overlapping reconciles over
    the merged flight-recorder histories, zero data-plane writes from a
    converged fleet (shard-map lease renewals are the protocol's
    heartbeat and are accounted separately), every zombie write
    fenced."""
    from kubeflow_tpu.kube.shard import SHARD_MAP_KIND
    from kubeflow_tpu.main import build_sharded_fleet

    clock = FakeClock()
    tracing.set_clock(clock)  # align span times with the fleet clock
    try:
        return _run_sharded_fleet(count, shards, kill_shard, clock)
    finally:
        tracing.set_clock(None)


def _shard_namespace_count(count: int, shards: int) -> int:
    """Tenant namespaces for the sharded benchmark.  Ring placement is
    namespace-affine (kube/shard.py), and the Kubeflow deployment model
    is a namespace per user profile — so the keyspace must arrive as
    many namespaces for the ring to spread it: enough that balance noise
    stays small (>= 8 per shard), capped so namespace bookkeeping never
    dominates a 100k run."""
    return max(8 * shards, min(1024, count // 8)) or 1


def _run_sharded_fleet(count: int, shards: int, kill_shard: bool,
                       clock: FakeClock) -> dict:
    from kubeflow_tpu.kube.shard import SHARD_MAP_KIND
    from kubeflow_tpu.main import build_sharded_fleet

    cfg = CoreConfig.from_env({})  # hermetic: culling off, defaults only
    # the shared lifecycle ledger must hold every pending notebook of the
    # flood, or conservation can't be checked fleet-wide
    cfg.lifecycle_max_notebooks = max(cfg.lifecycle_max_notebooks, count)
    fleet, api, cluster, metrics = build_sharded_fleet(
        core_cfg=cfg, count=shards, clock=clock)
    ledger = metrics.lifecycle  # ONE ledger shared across all replicas
    cluster.add_node("cpu-node", allocatable={"cpu": str(count * 8),
                                              "memory": "8192Gi"})

    n_ns = _shard_namespace_count(count, shards)
    nb_keys = [(f"u{i % n_ns:04d}", f"nb-{i:04d}") for i in range(count)]

    def assert_converged(tag: str) -> None:
        not_ready = [name for ns, name in nb_keys
                     if (api.get("Notebook", ns,
                                 name).body.get("status") or {}
                         ).get("readyReplicas") != 1]
        if not_ready:
            raise AssertionError(
                f"{tag}: {len(not_ready)} notebooks never converged "
                f"(first: {not_ready[:3]})")

    # the flood arrives in batches, and each batch sits in the queue for
    # a deterministic beat before the fleet drains it — the only
    # fake-clock duration a hermetic rollout accrues, so the lifecycle
    # ledger has stage time to attribute and the sweep's binding_stage
    # contract has a stage to name (queue_wait, by construction)
    n_batches = min(4, count) or 1
    t0 = time.perf_counter()
    rollout_reconciles_total = 0
    created = 0
    for b in range(n_batches):
        batch = count // n_batches + (1 if b < count % n_batches else 0)
        for i in range(created, created + batch):
            ns, name = nb_keys[i]
            api.create(Notebook.new(name, ns).obj)
        created += batch
        clock.advance(2.0)  # queue dwell (well under the shard lease)
        rollout_reconciles_total += fleet.settle()
        metrics.scrape()  # one TSDB sample per batch at this instant
    rollout_wall_s = time.perf_counter() - t0
    assert_converged("rollout")

    # conservation gate over the SHARED ledger: attempts from every
    # replica (and handoff waits between them) must still partition each
    # notebook's event->ready window exactly
    cons = ledger.conservation()
    if cons["finalized"] != count:
        raise AssertionError(
            f"sharded lifecycle ledger finalized {cons['finalized']}/"
            f"{count} notebooks")
    if cons["violations"]:
        raise AssertionError(
            f"sharded stage attribution broke conservation for "
            f"{cons['violations']}/{cons['checked']} notebooks "
            f"(first: {ledger.violations()[:3]})")

    snap = fleet.shard_snapshot()
    owned = {sid: r["keys_owned"]
             for sid, r in snap["replicas"].items() if r["alive"]}
    if sum(owned.values()) != count:
        raise AssertionError(
            f"ring does not partition the keyspace: {owned} "
            f"(want sum == {count})")

    # kill one replica, let survivors evict + adopt, then rejoin it —
    # the handoff path under the same measurement harness
    killed = ""
    handoff_wall_s = 0.0
    if kill_shard and shards > 1:
        killed = sorted(owned)[0]
        t1 = time.perf_counter()
        fleet.kill(killed)
        for _ in range(3):  # sub-lease steps: only the dead lease ages
            clock.advance(fleet.lease_duration_s * 0.55)
            fleet.settle()
        if killed in fleet.shard_snapshot()["members"]:
            raise AssertionError(f"dead shard {killed} never evicted")
        fleet.rejoin(killed)
        fleet.settle()
        handoff_wall_s = time.perf_counter() - t1
        assert_converged("kill/rejoin")

    # steady-state probe: a converged sharded fleet must issue ZERO
    # data-plane writes on a full resync — only the shard map moves
    # (member lease renewals), and that traffic is reported, not hidden
    api.clear_verb_counts()
    for r in fleet.alive_replicas():
        r.manager.enqueue_all()
    fleet.settle()
    steady_writes = {
        f"{verb}:{kind}": n
        for (verb, kind), n in sorted(api.verb_counts().items())
        if verb in _WRITE_VERBS or verb.endswith("_status")}
    heartbeat = {k: n for k, n in steady_writes.items()
                 if k.endswith(":" + SHARD_MAP_KIND)}
    data_plane = {k: n for k, n in steady_writes.items()
                  if not k.endswith(":" + SHARD_MAP_KIND)}
    if data_plane:
        raise AssertionError(
            f"write verbs issued by a converged sharded fleet: "
            f"{data_plane}")

    overlaps = fleet.cross_process_overlaps()
    if overlaps:
        a, b = overlaps[0]
        raise AssertionError(
            f"cross-process serialization violated: {len(overlaps)} "
            f"overlapping pairs (first: {a.controller} {a.object_key})")

    reconciles: dict[str, int] = {}
    latency: list[float] = []
    for r in fleet.replicas.values():
        for ctrl, n in _reconciles_per_controller(r.manager).items():
            reconciles[ctrl] = reconciles.get(ctrl, 0) + n
        latency.extend(r.manager.event_latency_samples())
    final = fleet.shard_snapshot()
    result = {
        "count": count,
        "notebooks": count,
        "namespaces": n_ns,
        "shards": shards,
        "wall_s": round(rollout_wall_s, 3),
        "handoff_wall_s": round(handoff_wall_s, 3),
        "killed_shard": killed,
        # process high-water RSS (ru_maxrss is KB on Linux).  Monotone
        # over the process lifetime: in a sweep, each point's figure
        # includes every smaller point before it — the trend to read is
        # the growth between points, not the absolute per point.
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0,
            1),
        # shard-map RMW optimistic-concurrency losses (409s retried with
        # backoff) — membership contention, the livelock trend
        "shard_map_rmw_conflicts": fleet.rmw_conflicts(),
        "epoch": final["epoch"],
        "rollout_reconciles_total": rollout_reconciles_total,
        "reconciles_per_notebook": {
            c: round(n / count, 3) for c, n in sorted(reconciles.items())},
        "keys_owned": owned,
        "p50_event_to_reconcile_s": round(_percentile(latency, 0.50), 6),
        "p99_event_to_reconcile_s": round(_percentile(latency, 0.99), 6),
        "event_to_reconcile_samples": len(latency),
        "handoff_durations_s": [
            round(d, 3) for r in fleet.replicas.values()
            for d in r.handoff_durations],
        "fenced_rejections": sum(
            r["fenced_rejections"]
            for r in final["replicas"].values()),
        "cross_process_overlaps": 0,
        "steady_data_plane_writes": 0,
        "steady_heartbeat_writes": sum(heartbeat.values()),
        "criticalpath": {
            "ranking": ledger.ranking(),
            "conservation": ledger.conservation(),
        },
        # the diagnosis engine's sweep contract: each point names the
        # stage that dominates its event->ready attribution
        "binding_stage": (ledger.ranking()[0]["stage"]
                          if ledger.ranking() else ""),
    }
    metrics.scrape()  # post-kill/rejoin TSDB sample (clock moved on)
    _print_criticalpath(f"{count} notebooks x {shards} shards",
                        ledger.ranking())
    for r in fleet.replicas.values():
        r.manager.stop()
    return result


_TOUCH_ANNOTATION = "loadtest.kubeflow.org/touch"


def run_tenants(tenants: int, per_tenant: int, noisy: int, tpu: str,
                baseline_rounds: int = 18, flood_rounds: int = 6,
                flood_factor: int = 50, victim_delay_s: float = 2.5,
                recovery_rounds: int = 18,
                provision_s: float = 60.0) -> dict:
    """Adversarial multi-tenant run: `tenants` namespaces of `per_tenant`
    placed TPU notebooks each, tenant index `noisy` floods the control
    plane with spec churn while every other tenant's events queue behind
    the backlog.  Asserts the metering ledger's verdict end to end: the
    flood is attributed to the EXACT flooding namespace, exactly one
    deduped Warning event fires naming it, the flag clears once the flood
    stops, and chip-second conservation holds for every tenant
    throughout."""
    clock = FakeClock()
    tracing.set_clock(clock)  # span times share the harness clock
    try:
        return _run_tenants(tenants, per_tenant, noisy, tpu,
                            baseline_rounds, flood_rounds, flood_factor,
                            victim_delay_s, recovery_rounds, provision_s,
                            clock)
    finally:
        tracing.set_clock(None)


def _run_tenants(tenants: int, per_tenant: int, noisy: int, tpu: str,
                 baseline_rounds: int, flood_rounds: int, flood_factor: int,
                 victim_delay_s: float, recovery_rounds: int,
                 provision_s: float, clock: FakeClock) -> dict:
    from kubeflow_tpu.kube import EventRecorder, retry_on_conflict

    if tenants < 2:
        raise ValueError("--tenants needs at least 2 namespaces "
                         "(fair share is undefined for one tenant)")
    accel, topology = tpu.split(":")
    spec = TPUSpec(accel, topology)
    shape = spec.validate()
    total = tenants * per_tenant
    env = {
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": str(total),
        "WARMPOOL_SHAPES": f"{accel}:{topology}",
        "WARMPOOL_PROVISION_S": f"{provision_s:g}",
    }
    api = ApiServer()
    cluster = FakeCluster(api)
    mgr = Manager(api, clock=clock,
                  flight_recorder=FlightRecorder(
                      capacity=max(4096, total * 8),
                      max_objects=max(2048, total * 4)))
    cfg = CoreConfig.from_env(env)
    metrics = NotebookMetrics(api, manager=mgr)
    setup_core_controllers(mgr, cfg, metrics, provisioner=cluster)
    slo_engine = SLOEngine(
        default_objectives(cfg),
        registries=[metrics.registry, mgr.metrics_registry],
        clock=clock)
    mgr.slo_engine = slo_engine
    metrics.attach_slo(slo_engine)
    mgr.settle(max_seconds=provision_s * 4 + 60)  # pre-warm the pool

    namespaces = [f"tenant-{i}" for i in range(tenants)]
    noisy_ns = namespaces[noisy % tenants]
    victims = [ns for ns in namespaces if ns != noisy_ns]
    names = [f"nb-{i:03d}" for i in range(per_tenant)]
    expected_ready = shape.num_hosts * spec.slices

    pending: dict[tuple[str, str], float] = {}
    t0 = clock.now()
    for ns in namespaces:
        for name in names:
            api.create(Notebook.new(name, ns, tpu=spec).obj)
            pending[(ns, name)] = t0
    deadline = clock.now() + provision_s * 4 + 600
    while pending:
        mgr.run_until_idle()
        for ns, name in list(pending):
            status = api.get("Notebook", ns, name).body.get("status") or {}
            if status.get("readyReplicas") == expected_ready:
                pending.pop((ns, name))
        if not pending:
            break
        due = [d for (_, _, d) in mgr.pending_delayed()]
        if not due or min(due) > deadline:
            raise AssertionError(
                f"{len(pending)} tenant notebooks unready past the "
                f"deadline (first: {sorted(pending)[:3]})")
        delta = min(due) - clock.now()
        if delta > 0:
            clock.advance(delta)

    # attach metering only NOW: the detector's baselines must latch from
    # post-convergence benign traffic, not the provisioning transient
    # (whose requeue waits would inflate every tenant's "normal" p99)
    metering = TenantMeteringLedger(
        clock, registry=metrics.registry,
        recorder=EventRecorder(api, "tenant-metering"),
        max_tenants=max(tenants + 8, 16),
        max_notebooks=max(4096, total),
        keep_conservation=max(4096, total),
        slo_engine=slo_engine)
    mgr.metering = metering
    metrics.attach_metering(metering)

    def touch(ns: str) -> None:
        """One spec-churn tick for every notebook of `ns` (annotation
        bump -> update -> event -> reconcile: the smallest unit of
        attributable control-plane work)."""
        for name in names:
            def bump() -> None:
                live = api.get("Notebook", ns, name)
                n = int(live.metadata.annotations.get(_TOUCH_ANNOTATION,
                                                      "0"))
                live.metadata.annotations[_TOUCH_ANNOTATION] = str(n + 1)
                api.update(live)
            retry_on_conflict(bump)

    # benign phase: every tenant ticks over at the same rate — baselines
    # latch low, the rolling control-plane windows fill with fair traffic
    for _ in range(baseline_rounds):
        for ns in namespaces:
            touch(ns)
        mgr.settle(max_seconds=60)
        clock.advance(10.0)  # chip-seconds accrue between samples
        metrics.scrape()     # sample + ingest + evaluate (fair verdict)
    if metering.flagged():
        raise AssertionError(
            f"fair traffic flagged tenants {metering.flagged()} — the "
            "detector fired with no noisy neighbor")

    # flood phase: victims' events are stamped, then the clock advances by
    # the backlog delay before the queue drains (their e2r degrades), and
    # the noisy tenant churns specs flood_factor times per round
    for _ in range(flood_rounds):
        for ns in victims:
            touch(ns)
        clock.advance(victim_delay_s)
        mgr.settle(max_seconds=60)
        for _ in range(flood_factor):
            touch(noisy_ns)
            mgr.settle(max_seconds=60)
        metrics.scrape()
    flagged_flood = metering.flagged()
    if flagged_flood != [noisy_ns]:
        raise AssertionError(
            f"flood attribution wrong: flagged {flagged_flood}, "
            f"want exactly [{noisy_ns!r}]")
    warnings = [e for e in api.list("Event")
                if e.body.get("reason") == REASON_NOISY]
    if len(warnings) != 1:
        raise AssertionError(
            f"{len(warnings)} {REASON_NOISY} Warning events exist, want "
            "exactly one (EventRecorder dedup must aggregate re-fires)")
    involved = (warnings[0].body.get("involvedObject") or {}).get("name")
    if involved != noisy_ns:
        raise AssertionError(
            f"{REASON_NOISY} warning names {involved!r}, want {noisy_ns!r}")

    # recovery phase: the flood stops; once its deltas roll out of the
    # control-plane window the tenant's share drops and the flag clears
    for _ in range(recovery_rounds):
        for ns in namespaces:
            touch(ns)
        mgr.settle(max_seconds=60)
        clock.advance(10.0)
        metrics.scrape()
    if metering.flagged():
        raise AssertionError(
            f"flag never cleared after the flood stopped: "
            f"{metering.flagged()}")

    table = metering.tenant_table()
    cons = metering.conservation()
    if cons["violations"]:
        raise AssertionError(
            f"tenant metering broke conservation for "
            f"{cons['violations']}/{cons['checked']} placement intervals "
            f"(first: {metering.violations()[:3]})")
    if cons["checked"] < total:
        raise AssertionError(
            f"metering conservation checked only {cons['checked']}/{total} "
            "placement intervals — some placed notebooks were never "
            "metered")
    if not table.get(noisy_ns, {}).get("last_trace"):
        raise AssertionError(
            f"no exemplar trace latched for {noisy_ns} — a fired fairness "
            "alert would not resolve at /debug/traces")
    _print_tenants(table, noisy_ns)
    mgr.stop()
    victim_p99s = {ns: table[ns]["e2r_p99_recent_s"] for ns in victims}
    return {
        "mode": "tenants",
        "tenants": tenants,
        "per_tenant": per_tenant,
        "notebooks": total,
        "tpu": tpu,
        "noisy_tenant": noisy_ns,
        "flagged_during_flood": flagged_flood,
        "flagged_final": metering.flagged(),
        "noisy_warning_events": len(warnings),
        "noisy_fired_total": table[noisy_ns]["fired_total"],
        "victim_p99_event_to_reconcile_s":
            round(max(victim_p99s.values()), 6),
        "per_tenant_p99_s": {
            ns: round(table[ns]["e2r_p99_recent_s"], 6)
            for ns in namespaces},
        "chip_seconds": {
            ns: round(table[ns]["chip_seconds_total"], 3)
            for ns in namespaces},
        "control_units": {
            ns: table[ns]["apiserver_total"] + table[ns]["dispatches"]
            for ns in namespaces},
        "conservation": cons,
        "slo": slo_engine.verdicts(),
    }


def _print_tenants(table: dict, noisy_ns: str) -> None:
    """The per-tenant usage table (stderr; stdout carries the result
    JSON): who used the chips and the control plane, and who got flagged."""
    print("tenant usage:", file=sys.stderr)
    print(f"  {'tenant':<12} {'chip_s':>10} {'dispatches':>10} "
          f"{'api_reqs':>9} {'p99_e2r_s':>10} {'baseline_s':>10} "
          f"{'flagged':>8}", file=sys.stderr)
    for ns, row in sorted(table.items()):
        mark = " <- noisy" if ns == noisy_ns else ""
        baseline = row["e2r_p99_baseline_s"]
        print(f"  {ns:<12} {row['chip_seconds_total']:>10.1f} "
              f"{row['dispatches']:>10} {row['apiserver_total']:>9} "
              f"{row['e2r_p99_recent_s']:>10.4f} "
              f"{(baseline if baseline is not None else -1.0):>10.4f} "
              f"{str(row['flagged']):>8}{mark}", file=sys.stderr)


def run_priorities(high_gangs: int, benign: int, per_tenant: int,
                   flood: int, tpu: str,
                   provision_s: float = 3600.0) -> dict:
    """Adversarial tenancy run (ISSUE-19): a low-priority batch tenant
    floods an oversubscribed fleet past its chip quota, then a
    high-priority burst arrives with zero free capacity.  The flood must
    queue (never place, never hold claims), the burst must land within
    the time-to-placement ceiling by evicting ONLY checkpointed
    low-priority victims — benign standard tenants untouched, zero
    checkpointless teardowns — and once the burst drains every victim
    must restore its session byte-for-byte (digest) from the secured
    checkpoint: preemption moves work, it never loses state."""
    clock = FakeClock()
    tracing.set_clock(clock)
    try:
        return _run_priorities(high_gangs, benign, per_tenant, flood,
                               tpu, provision_s, clock)
    finally:
        tracing.set_clock(None)


def _run_priorities(high_gangs: int, benign: int, per_tenant: int,
                    flood: int, tpu: str, provision_s: float,
                    clock: FakeClock) -> dict:
    from kubeflow_tpu.core import constants as CC
    from kubeflow_tpu.core.preemption import new_quota_object
    from kubeflow_tpu.core.sessionstate import InMemorySessionStore

    if high_gangs < 1 or benign < 1 or flood < 1:
        raise ValueError("--priorities needs >=1 high gang, >=1 benign "
                         "tenant and >=1 flood gang")
    accel, topology = tpu.split(":")
    spec = TPUSpec(accel, topology)
    shape = spec.validate()
    # capacity fits the benign tenants plus exactly high_gangs
    # low-priority victims-in-waiting: the burst can ONLY land by
    # evicting; cold provisioning (1h) never bails it out in-run
    capacity_slices = benign * per_tenant + high_gangs
    env = {
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": "0",
        "WARMPOOL_PROVISION_S": f"{provision_s:g}",
        "SLO_PLACEMENT_P99_S": "120",
    }
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_tpu_slice_nodes(
        shape.accelerator.gke_label, topology,
        capacity_slices * shape.num_hosts, shape.chips_per_host)
    mgr = Manager(api, clock=clock,
                  flight_recorder=FlightRecorder(capacity=8192,
                                                 max_objects=2048))
    cfg = CoreConfig.from_env(env)
    metrics = NotebookMetrics(api, manager=mgr)
    store = InMemorySessionStore(clock=clock)
    cluster.attach_session_store(store)
    setup_core_controllers(mgr, cfg, metrics, session=store,
                           provisioner=cluster)
    slo_engine = SLOEngine(
        default_objectives(cfg),
        registries=[metrics.registry, mgr.metrics_registry],
        clock=clock)
    mgr.slo_engine = slo_engine
    metrics.attach_slo(slo_engine)

    # hard chip quota pins the batch tenant to its placed share: the
    # flood queues on quota, not on a capacity accident
    quota = new_quota_object()
    quota.body["spec"] = {
        "tenants": {"batch": {
            "chipQuota": float(high_gangs * shape.chips * spec.slices),
            "priority": "low"}},
        "defaults": {},
    }
    api.create(quota)

    def drive_until(cond, deadline_s: float, what: str) -> None:
        deadline = clock.now() + deadline_s
        while True:
            mgr.run_until_idle()
            if cond():
                return
            due = [d for (_, _, d) in mgr.pending_delayed()]
            if not due or min(due) > deadline:
                raise AssertionError(f"{what}: not reached within "
                                     f"{deadline_s:g}s modeled seconds")
            delta = min(due) - clock.now()
            if delta > 0:
                clock.advance(delta)

    def healthy(ns: str, name: str) -> bool:
        st = api.get("Notebook", ns, name).body.get("status") or {}
        return st.get("sliceHealth") == "Healthy"

    # phase 1 — fill: benign standard tenants + the batch tenant's
    # placed (victim-eligible) gangs converge on the whole capacity
    benign_nbs = [(f"team-{i}", f"team-{i}-nb-{j:02d}")
                  for i in range(benign) for j in range(per_tenant)]
    batch_placed = [f"bat-{i:03d}" for i in range(high_gangs)]
    for ns, name in benign_nbs:
        api.create(Notebook.new(name, ns, tpu=spec).obj)
    for name in batch_placed:
        nb = Notebook.new(name, "batch", tpu=spec)
        nb.obj.spec["priority"] = "low"
        api.create(nb.obj)
    drive_until(
        lambda: all(healthy(ns, n) for ns, n in benign_nbs)
        and all(healthy("batch", n) for n in batch_placed),
        provision_s * 4 + 600, "fill phase")
    digests = {}
    for name in batch_placed:
        cluster.set_session_payload("batch", name,
                                    b"kernel-" + name.encode())
        (snap,) = cluster.snapshot_sessions("batch", name)
        digests[name] = snap.digest

    # phase 2 — oversubscribe: the flood must queue behind the quota
    # with sliceHealth Queued and zero claims
    flood_names = [f"flood-{i:03d}" for i in range(flood)]
    for name in flood_names:
        nb = Notebook.new(name, "batch", tpu=spec)
        nb.obj.spec["priority"] = "low"
        api.create(nb.obj)
    for _ in range(3):
        mgr.run_until_idle()
        clock.advance(20.0)
    mgr.run_until_idle()
    for name in flood_names:
        obj = api.get("Notebook", "batch", name)
        st = obj.body.get("status") or {}
        if CC.ANNOTATION_PLACEMENT in obj.metadata.annotations or \
                st.get("sliceHealth") != "Queued":
            raise AssertionError(
                f"flood gang batch/{name} broke containment: "
                f"placement={CC.ANNOTATION_PLACEMENT in obj.metadata.annotations} "
                f"sliceHealth={st.get('sliceHealth')!r}")
    tenancy = metrics.tenancy_snapshot()
    queued_depth_peak = sum(
        e.get("depth", 0) for e in (tenancy.get("queued") or {}).values())

    # phase 3 — the high-priority burst: placement only via
    # checkpoint-then-preempt of the batch victims
    high_names = [f"hp-{i:02d}" for i in range(high_gangs)]
    t_burst = clock.now()
    for name in high_names:
        nb = Notebook.new(name, "urgent", tpu=spec)
        nb.obj.spec["priority"] = "high"
        api.create(nb.obj)
    placed_at: dict[str, float] = {}

    def burst_done() -> bool:
        for name in high_names:
            if name not in placed_at and healthy("urgent", name):
                placed_at[name] = clock.now()
        return len(placed_at) == len(high_names)

    drive_until(burst_done, 600.0, "high-priority burst placement")
    waits = sorted(placed_at[n] - t_burst for n in high_names)
    high_p99 = _percentile(waits, 0.99)

    evicted = [
        n for n in batch_placed
        if CC.ANNOTATION_PLACEMENT not in
        api.get("Notebook", "batch", n).metadata.annotations]
    benign_evictions = sum(
        1 for ns, n in benign_nbs if not healthy(ns, n))
    batch_sts_deletes = {
        n: len([r for r in api.audit_log(verb="delete",
                                         kind="StatefulSet")
                if r.ok and r.name == n])
        for n in batch_placed}
    checkpointless = 0
    for name, count in batch_sts_deletes.items():
        if count == 0:
            continue
        sess = (api.get("Notebook", "batch", name)
                .body.get("status") or {}).get("sessionState") or {}
        entry = sess.get("0") or {}
        if entry.get("trigger") != "preempt" or \
                entry.get("digest") != digests[name]:
            checkpointless += 1
    if any(count > 1 for count in batch_sts_deletes.values()):
        raise AssertionError(
            f"victim torn down more than once: {batch_sts_deletes}")

    # phase 4 — drain and restore: the flood withdraws, the burst
    # finishes; every evicted victim must restore its checkpoint
    for name in flood_names:
        api.delete("Notebook", "batch", name)
    for name in high_names:
        live = api.get("Notebook", "urgent", name)
        live.metadata.annotations[CC.STOP_ANNOTATION] = "true"
        api.update(live)
    restored_at: dict[str, float] = {}

    def victims_back() -> bool:
        for name in evicted:
            if name in restored_at:
                continue
            sess = (api.get("Notebook", "batch", name)
                    .body.get("status") or {}).get("sessionState") or {}
            if healthy("batch", name) and \
                    (sess.get("0") or {}).get("phase") == "restored":
                restored_at[name] = clock.now()
        return len(restored_at) == len(evicted)

    drive_until(victims_back, provision_s * 2 + 1200,
                "preempted victims restored")
    state_loss = 0
    for name in evicted:
        sess = (api.get("Notebook", "batch", name)
                .body.get("status") or {}).get("sessionState") or {}
        if (sess.get("0") or {}).get("digest") != digests[name]:
            state_loss += 1

    mgr.stop()
    result = {
        "mode": "priorities",
        "tpu": tpu,
        "capacity_slices": capacity_slices,
        "benign_tenants": benign,
        "per_tenant": per_tenant,
        "flood_gangs": flood,
        "high_gangs": high_gangs,
        "queued_depth_peak": queued_depth_peak,
        "high_p99_placement_s": round(high_p99, 3),
        "high_max_placement_s": round(waits[-1], 3),
        "evicted_victims": len(evicted),
        "benign_evictions": benign_evictions,
        "checkpointless_teardowns": checkpointless,
        "preempted_state_loss": state_loss,
        "restored_victims": len(restored_at),
        "queue_wait_counts": {
            p: metrics.queue_wait_seconds.count_value(p)
            for p in ("low", "standard", "high")},
        "preemptions_evicted_low":
            metrics.preemptions.value("evicted", "low"),
        "slo": slo_engine.verdicts(),
    }
    _print_priorities(result)
    return result


def _print_priorities(result: dict) -> None:
    print("tenancy run:", file=sys.stderr)
    for k in ("capacity_slices", "queued_depth_peak",
              "high_p99_placement_s", "evicted_victims",
              "benign_evictions", "checkpointless_teardowns",
              "preempted_state_loss", "restored_victims"):
        print(f"  {k:<26} {result[k]}", file=sys.stderr)


def check_priorities_budget(result: dict, budget: dict) -> list[str]:
    """CI gate over the adversarial tenancy run (ci/fleet_budget.json
    "tenancy" section): high-priority time-to-placement ceiling, zero
    state loss, zero benign evictions, zero checkpointless teardowns,
    and the lane must actually have exercised preemption."""
    failures = []
    max_p99 = budget.get("max_high_p99_placement_s")
    if max_p99 is not None and \
            result["high_p99_placement_s"] > max_p99:
        failures.append(
            f"high-priority p99 time-to-placement "
            f"{result['high_p99_placement_s']}s > ceiling {max_p99}s")
    if result["preempted_state_loss"] > \
            int(budget.get("max_preempted_state_loss", 0)):
        failures.append(
            f"{result['preempted_state_loss']} preempted victims lost "
            "session state")
    if result["benign_evictions"] > \
            int(budget.get("max_benign_evictions", 0)):
        failures.append(
            f"{result['benign_evictions']} benign-tenant gangs evicted")
    if result["checkpointless_teardowns"] > \
            int(budget.get("max_checkpointless_teardowns", 0)):
        failures.append(
            f"{result['checkpointless_teardowns']} teardowns without a "
            "secured checkpoint")
    min_evict = int(budget.get("min_evictions", 1))
    if result["evicted_victims"] < min_evict:
        failures.append(
            f"only {result['evicted_victims']} evictions — the lane "
            f"never exercised preemption (want >= {min_evict})")
    if result["restored_victims"] < result["evicted_victims"]:
        failures.append(
            f"{result['evicted_victims'] - result['restored_victims']} "
            "evicted victims never restored")
    return failures


def check_tenant_budget(result: dict, budget: dict) -> list[str]:
    """CI gate over the adversarial tenants run (ci/fleet_budget.json
    "tenants" section): victim p99 ceiling under flood, exactly-one
    deduped warning, zero conservation violations."""
    failures = []
    max_p99 = budget.get("max_victim_p99_event_to_reconcile_s")
    if max_p99 is not None and \
            result["victim_p99_event_to_reconcile_s"] > max_p99:
        failures.append(
            f"victim p99 event->reconcile "
            f"{result['victim_p99_event_to_reconcile_s']}s > ceiling "
            f"{max_p99}s")
    if result["noisy_warning_events"] != 1:
        failures.append(
            f"{result['noisy_warning_events']} noisy-neighbor warnings, "
            "want exactly 1")
    max_viol = int(budget.get("max_conservation_violations", 0))
    if result["conservation"]["violations"] > max_viol:
        failures.append(
            f"metering conservation violations "
            f"{result['conservation']['violations']} > {max_viol}")
    return failures


def check_shard_budget(result: dict, budget: dict) -> list[str]:
    """CI gate over the sharded-fleet run (ci/fleet_budget.json
    "sharded" section): wall-clock + p99 ceilings like the flat fleet,
    plus ring balance — no live shard may own more than
    `max_owned_fraction` of the keyspace."""
    failures = check_budget(result, budget)
    max_frac = budget.get("max_owned_fraction")
    if max_frac is not None and result["keys_owned"]:
        worst = max(result["keys_owned"].values())
        if worst > result["count"] * max_frac:
            failures.append(
                f"ring imbalance: one shard owns {worst}/{result['count']} "
                f"keys (> {max_frac:.0%})")
    return failures


def check_warm_budget(warm: dict, cold: dict, budget: dict) -> list[str]:
    """CI gate over the warm-vs-cold comparison: warm-pool-on p50 ready
    time strictly below the cold path, a minimum warm hit rate, and a
    minimum converged slice utilization."""
    failures = []
    if not warm["ready_p50_s"] < cold["ready_p50_s"]:
        failures.append(
            f"warm p50 {warm['ready_p50_s']}s not strictly below cold p50 "
            f"{cold['ready_p50_s']}s")
    max_frac = budget.get("max_warm_p50_fraction_of_cold")
    if max_frac is not None and cold["ready_p50_s"] > 0 and \
            warm["ready_p50_s"] > cold["ready_p50_s"] * max_frac:
        failures.append(
            f"warm p50 {warm['ready_p50_s']}s above "
            f"{max_frac:.0%} of cold p50 {cold['ready_p50_s']}s")
    min_hit = budget.get("min_hit_rate")
    if min_hit is not None and warm["hit_rate"] < min_hit:
        failures.append(
            f"warm hit rate {warm['hit_rate']} < {min_hit}")
    min_util = budget.get("min_slice_utilization")
    if min_util is not None:
        worst = min(warm["slice_utilization"] or [0.0])
        if worst < min_util:
            failures.append(
                f"converged slice utilization {worst} < {min_util}")
    return failures


def check_budget(result: dict, budget: dict) -> list[str]:
    """Failures (empty = within budget).  A measurement may regress at
    most `tolerance` (fraction) over the committed per-notebook budget."""
    tol = 1.0 + float(budget.get("tolerance", 0.10))
    failures = []
    for kind, allowed in budget.get("writes_per_notebook", {}).items():
        got = result["writes_per_notebook"].get(kind, 0.0)
        if got > allowed * tol:
            failures.append(
                f"writes/notebook[{kind}]: {got} > {allowed} (+{tol - 1:.0%})")
    for ctrl, allowed in budget.get("reconciles_per_notebook", {}).items():
        got = result["reconciles_per_notebook"].get(ctrl, 0.0)
        if got > allowed * tol:
            failures.append(
                f"reconciles/notebook[{ctrl}]: {got} > {allowed} "
                f"(+{tol - 1:.0%})")
    hard_cap = budget.get("max_reconciles_per_notebook")
    if hard_cap is not None:
        got = result["reconciles_per_notebook"].get("notebook", 0.0)
        if got > hard_cap:
            failures.append(
                f"reconciles/notebook[notebook]: {got} > hard cap {hard_cap}")
    # fleet-scale regression backstops (ci/fleet_budget.json): generous
    # wall-clock ceiling and an event->reconcile-start p99 ceiling — wide
    # enough to absorb machine variance, tight enough that an O(N^2)
    # regression (the pre-shard apiserver) blows straight through them
    max_wall = budget.get("max_wall_s")
    if max_wall is not None and result["wall_s"] > max_wall:
        failures.append(
            f"wall time {result['wall_s']}s > ceiling {max_wall}s")
    max_p99 = budget.get("max_p99_event_to_reconcile_s")
    if max_p99 is not None and \
            result.get("p99_event_to_reconcile_s", 0.0) > max_p99:
        failures.append(
            f"p99 event->reconcile-start "
            f"{result['p99_event_to_reconcile_s']}s > ceiling {max_p99}s")
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-l", "--count", type=int, default=200)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--compare-workers", type=int, default=0,
                        help="re-run with N workers and require an "
                        "identical normalized final state")
    parser.add_argument("--tpu", default="",
                        help="accelerator:topology, e.g. v5e:2x4 "
                        "(default CPU)")
    parser.add_argument("--check-budget", default="",
                        help="budget JSON; fail on >tolerance regression")
    parser.add_argument("--write-budget", default="",
                        help="write the measured result as the new budget")
    parser.add_argument("--out", default="",
                        help="also write the machine-readable result JSON "
                        "to this file (fleet-scale trajectory tracking)")
    parser.add_argument("--profile-on-fail", default="", metavar="FILE",
                        help="on budget failure, re-run the fleet under "
                        "cProfile and write the top-25 cumulative listing "
                        "to FILE (and stderr) so the regression is "
                        "diagnosable from CI output alone")
    parser.add_argument("--bursty", type=int, default=0, metavar="N",
                        help="bursty slice-scheduler mode: N TPU notebooks "
                        "per wave, warm-pool-on vs off comparison")
    parser.add_argument("--bursts", type=int, default=3)
    parser.add_argument("--burst-gap-s", type=float, default=300.0)
    parser.add_argument("--warm-size", type=int, default=8,
                        help="warm-pool base target for the warm run")
    parser.add_argument("--provision-s", type=float, default=120.0,
                        help="modeled cold slice-provision latency")
    parser.add_argument("--check-warm-budget", default="",
                        help="warm-vs-cold budget JSON (min hit rate, p50 "
                        "ratio); fail on regression")
    parser.add_argument("--shards", type=int, default=0, metavar="N",
                        help="sharded mode: run --count notebooks over an "
                        "N-replica active-active fleet with a kill+rejoin "
                        "cycle; --check-budget reads the 'sharded' section "
                        "of the budget JSON")
    parser.add_argument("--budget-section", default="", metavar="NAME",
                        help="budget JSON section for sharded runs "
                        "(default 'sharded').  A section carrying a "
                        "'points' map gates EVERY sweep point listed in "
                        "it — base ceilings overridden per point — "
                        "instead of only the largest")
    parser.add_argument("--tenants", type=int, default=0, metavar="N",
                        help="adversarial multi-tenant mode: N namespaces "
                        "of --per-tenant TPU notebooks, tenant --noisy "
                        "floods spec churn; asserts metering attribution, "
                        "exactly-one warning, and conservation; "
                        "--check-budget reads the 'tenants' section")
    parser.add_argument("--per-tenant", type=int, default=4,
                        help="notebooks per tenant in --tenants mode")
    parser.add_argument("--noisy", type=int, default=0, metavar="T",
                        help="index of the flooding tenant in --tenants "
                        "mode")
    parser.add_argument("--priorities", type=int, default=0, metavar="N",
                        help="adversarial tenancy mode: a low-priority "
                        "flood oversubscribes the fleet, then an "
                        "N-gang high-priority burst must land via "
                        "checkpoint-then-preempt with zero state loss; "
                        "--check-budget reads the 'tenancy' section")
    parser.add_argument("--flood", type=int, default=6,
                        help="queued low-priority gangs in --priorities "
                        "mode")
    parser.add_argument("--benign", type=int, default=2,
                        help="untouchable standard-priority tenants in "
                        "--priorities mode")
    parser.add_argument("--sweep", default="", metavar="N1,N2,...",
                        help="scale sweep: run the fleet (sharded when "
                        "--shards is set) at each point, print the "
                        "per-stage critical-path table per point, record "
                        "per-point stage attribution into --out, and "
                        "budget-check the largest point — the "
                        "where-does-it-bend curve")
    args = parser.parse_args(argv)

    if args.sweep:
        return _run_sweep(args)

    if args.priorities:
        result = run_priorities(args.priorities, args.benign,
                                args.per_tenant, args.flood,
                                args.tpu or "v5e:2x2")
        rc = 0
        if args.check_budget:
            budget = json.loads(Path(args.check_budget).read_text())
            failures = check_priorities_budget(
                result, budget.get("tenancy", budget))
            result["budget_ok"] = not failures
            for f in failures:
                print(f"TENANCY BUDGET FAIL: {f}", file=sys.stderr)
                rc = 1
        print(json.dumps(result))
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=2,
                                                 sort_keys=True) + "\n")
        return rc

    if args.tenants:
        result = run_tenants(args.tenants, args.per_tenant, args.noisy,
                             args.tpu or "v5e:2x2")
        rc = 0
        if args.check_budget:
            budget = json.loads(Path(args.check_budget).read_text())
            failures = check_tenant_budget(result,
                                           budget.get("tenants", budget))
            result["budget_ok"] = not failures
            for f in failures:
                print(f"TENANT BUDGET FAIL: {f}", file=sys.stderr)
                rc = 1
        print(json.dumps(result))
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=2,
                                                 sort_keys=True) + "\n")
        return rc

    if args.shards:
        result = run_sharded_fleet(args.count, args.shards)
        rc = 0
        if args.check_budget:
            budget = json.loads(Path(args.check_budget).read_text())
            section = budget.get(args.budget_section or "sharded", budget)
            failures = check_shard_budget(
                result, _point_budget(section, result["count"]))
            result["budget_ok"] = not failures
            for f in failures:
                print(f"SHARD BUDGET FAIL: {f}", file=sys.stderr)
                rc = 1
        print(json.dumps(result))
        if args.out:
            Path(args.out).write_text(json.dumps(result, indent=2,
                                                 sort_keys=True) + "\n")
        return rc

    if args.bursty:
        tpu = args.tpu or "v5e:4x4"
        warm = run_bursty(args.bursty, args.bursts, args.burst_gap_s, tpu,
                          warm_size=args.warm_size,
                          provision_s=args.provision_s)
        cold = run_bursty(args.bursty, args.bursts, args.burst_gap_s, tpu,
                          warm_size=0, provision_s=args.provision_s)
        out = {"tpu": tpu, "warm": warm, "cold": cold}
        rc = 0
        budget = {}
        if args.check_warm_budget:
            budget = json.loads(Path(args.check_warm_budget).read_text())
        failures = check_warm_budget(warm, cold, budget)
        out["warm_budget_ok"] = not failures
        for f in failures:
            print(f"WARM BUDGET FAIL: {f}", file=sys.stderr)
            rc = 1
        print(json.dumps(out))
        return rc

    # the normalized-state scrub is O(cluster) and only needed for the
    # 1-vs-N worker equivalence comparison — skip it on plain (10k-scale)
    # runs so the wall-clock ceiling measures the control plane, not the
    # harness
    result = run_fleet(args.count, args.workers, tpu=args.tpu,
                       compute_state=bool(args.compare_workers))
    state = result.pop("_state", None)
    rc = 0

    if args.compare_workers:
        other = run_fleet(args.count, args.compare_workers, tpu=args.tpu)
        other_state = other.pop("_state")
        result["compare"] = {
            "workers": other["workers"],
            "wall_s": other["wall_s"],
            "reconciles_per_notebook": other["reconciles_per_notebook"],
            "state_identical": other_state == state,
        }
        if other_state != state:
            print("FAIL: final cluster state differs between "
                  f"{args.workers}-worker and {args.compare_workers}-worker "
                  "runs", file=sys.stderr)
            rc = 1

    if args.check_budget:
        budget = json.loads(Path(args.check_budget).read_text())
        failures = check_budget(result, budget)
        result["budget_ok"] = not failures
        if failures:
            for f in failures:
                print(f"BUDGET FAIL: {f}", file=sys.stderr)
            rc = 1
            if args.profile_on_fail:
                _profile_fleet(args, args.profile_on_fail)

    if args.write_budget:
        Path(args.write_budget).write_text(json.dumps({
            "notebooks": args.count,
            "tolerance": 0.10,
            "max_reconciles_per_notebook": 3.0,
            "reconciles_per_notebook": result["reconciles_per_notebook"],
            "writes_per_notebook": result["writes_per_notebook"],
        }, indent=2, sort_keys=True) + "\n")

    print(json.dumps(result))
    if args.out:
        Path(args.out).write_text(json.dumps(result, indent=2,
                                             sort_keys=True) + "\n")
    return rc


def _point_budget(budget: dict, count: int) -> dict:
    """A budget section scaled to one sweep point: the section's base
    ceilings with the `points[str(count)]` overrides folded in.  A
    section without a `points` map (or without this count) gates with
    its base ceilings unchanged."""
    sub = (budget.get("points") or {}).get(str(count)) or {}
    merged = {k: v for k, v in budget.items() if k != "points"}
    merged.update(sub)
    return merged


def _run_sweep(args) -> int:
    """`--sweep N1,N2,...`: the same fleet at increasing scale, one
    critical-path table + attribution record per point.  The per-point
    records land in --out so CI archives where each stage's contribution
    starts to bend.  A budget section with a `points` map gates every
    point it lists against scaled sub-budgets; without one the budget
    gates only the LARGEST point (the smaller ones exist for the curve,
    not the ceiling)."""
    points = sorted({int(x) for x in args.sweep.split(",") if x.strip()})
    if not points:
        print("SWEEP: no scale points parsed", file=sys.stderr)
        return 1
    sweep = []
    for n in points:
        if args.shards:
            r = run_sharded_fleet(n, args.shards)
        else:
            r = run_fleet(n, args.workers, tpu=args.tpu,
                          compute_state=False)
            r.pop("_state", None)
        sweep.append(r)
    rc = 0
    if args.check_budget:
        budget = json.loads(Path(args.check_budget).read_text())
        if args.shards:
            section = budget.get(args.budget_section or "sharded", budget)
        else:
            section = budget
        point_budgets = section.get("points") or {}
        for rec in sweep:
            if point_budgets:
                if str(rec["count"]) not in point_budgets:
                    continue  # runs for the curve, not the ceiling
            elif rec is not sweep[-1]:
                continue
            merged = _point_budget(section, rec["count"])
            failures = (check_shard_budget(rec, merged) if args.shards
                        else check_budget(rec, merged))
            rec["budget_ok"] = not failures
            for f in failures:
                print(f"SWEEP BUDGET FAIL (count={rec['count']}): {f}",
                      file=sys.stderr)
                rc = 1
    out = {
        "mode": "sweep",
        "points": points,
        "shards": args.shards or 0,
        "tpu": args.tpu or "cpu",
        "sweep": sweep,
        # where the wall-time curve bends: the point with the largest
        # slope increase (per-notebook cost), plus what binds there —
        # ROADMAP item 1's "name the binding stage at each point"
        "knee": _sweep_knee(points, sweep),
    }
    print(json.dumps(out))
    if args.out:
        Path(args.out).write_text(json.dumps(out, indent=2,
                                             sort_keys=True) + "\n")
    return rc


def _sweep_knee(points: list[int], sweep: list[dict]) -> dict:
    """Name the knee of the wall-time curve: per segment the marginal
    cost (wall seconds per added notebook); the knee is the point whose
    segment's marginal cost grows the most over the previous segment's.
    With fewer than 3 points there is no curvature — the largest point
    stands in."""
    knee_idx = len(points) - 1
    if len(points) >= 3:
        slopes = []
        for i in range(1, len(points)):
            dn = points[i] - points[i - 1]
            slopes.append((sweep[i]["wall_s"] - sweep[i - 1]["wall_s"])
                          / dn if dn else 0.0)
        growth = [slopes[i] - slopes[i - 1] for i in range(1, len(slopes))]
        knee_idx = growth.index(max(growth)) + 2  # segment i ends at i+1
    at = sweep[knee_idx]
    return {
        "count": points[knee_idx],
        "wall_s": at["wall_s"],
        "binding_stage": at.get("binding_stage", ""),
    }


def _profile_fleet(args, out_path: str) -> None:
    """Budget-failure forensics: re-run the same fleet under cProfile and
    dump the top-25 cumulative functions, so a CI regression names its hot
    path without anyone having to reproduce locally."""
    import cProfile
    import io
    import pstats

    print(f"profiling {args.count}-notebook fleet for the failure "
          f"artifact...", file=sys.stderr)
    profile = cProfile.Profile()
    profile.enable()
    try:
        run_fleet(args.count, args.workers, tpu=args.tpu,
                  compute_state=False)
    finally:
        profile.disable()
        buf = io.StringIO()
        pstats.Stats(profile, stream=buf).sort_stats(
            "cumulative").print_stats(25)
        listing = buf.getvalue()
        Path(out_path).write_text(listing)
        print(listing, file=sys.stderr)


if __name__ == "__main__":
    raise SystemExit(main())
