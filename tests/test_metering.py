"""Tenant metering ledger: chip-second accrual + conservation,
control-plane attribution, and the noisy-neighbor detector.

Three layers, mirroring how the ledger is fed in production:

* direct unit tests drive ``TenantMeteringLedger`` with a ``FakeClock``
  and hand-built census dicts — interval accrual across bucket
  transitions, finalization on release/eviction, the conservation
  contract (including that a tampered meter IS flagged — the check must
  be falsifiable), and apiserver delta semantics;
* detector tests latch baselines from benign dispatch streams, then
  flood one tenant while degrading another's event->reconcile p99 and
  assert exactly-once firing, Warning-event dedup through a real
  EventRecorder, SLO exemplar latching, and flag clearance;
* integration tests run the real census pipeline — placement-annotated
  Notebooks, the InformerCache ``tenant-metering`` aggregate, and
  ``NotebookMetrics.scrape()`` — and check the incremental cache census
  stays equal to a brute-force api.list scan under seeded churn.
"""

import json
import random

import pytest

from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.metering import (BUCKET_IDLE, BUCKET_READY,
                                         BUCKET_RECOVERING,
                                         BUCKET_SCHEDULING, BUCKETS,
                                         OTHER_TENANT, REASON_NOISY,
                                         TenantMeteringLedger,
                                         register_metering_metrics)
from kubeflow_tpu.utils.metrics import Registry


def _ledger(clock=None, **kw):
    return TenantMeteringLedger(clock or FakeClock(), **kw)


class TestChipSecondAccrual:
    """sample() accrues wall time into the bucket observed at the
    PREVIOUS sample, per placement interval, conserving exactly."""

    def test_buckets_partition_measured_wall_time(self):
        clock = FakeClock()
        led = _ledger(clock)
        key = ("team-a", "nb-0")

        led.sample({key: (BUCKET_SCHEDULING, 8.0)})      # t=0: meter opens
        clock.advance(5)
        led.sample({key: (BUCKET_READY, 8.0)})           # 5s of scheduling
        clock.advance(10)
        led.sample({key: (BUCKET_READY, 8.0)})           # 10s of ready
        clock.advance(3)
        led.sample({key: (BUCKET_RECOVERING, 8.0)})      # 3s more of ready
        clock.advance(2)
        led.sample({key: (BUCKET_RECOVERING, 8.0)})      # 2s of recovering
        led.sample({})                                   # released: finalize

        assert led.finalized_total == 1
        cons = led.conservation()
        assert cons["violations"] == 0 and cons["checked"] == 1
        assert cons["max_rel_err"] < 1e-9
        rec = led.violations() or None
        assert rec is None
        row = led.tenant_table()["team-a"]
        assert row["chip_seconds"] == pytest.approx({
            BUCKET_SCHEDULING: 8.0 * 5,
            BUCKET_READY: 8.0 * 13,
            BUCKET_RECOVERING: 8.0 * 2,
        })
        assert row["chip_seconds_total"] == pytest.approx(8.0 * 20)
        assert row["notebooks_metered"] == 1

    def test_idle_bucket_accrues_for_stopped_chips(self):
        clock = FakeClock()
        led = _ledger(clock)
        key = ("team-a", "nb-idle")
        led.sample({key: (BUCKET_READY, 4.0)})
        clock.advance(10)
        led.sample({key: (BUCKET_IDLE, 4.0)})            # 10s ready
        clock.advance(30)
        led.sample({key: (BUCKET_IDLE, 4.0)})            # 30s idle
        led.sample({})                                   # release
        row = led.tenant_table()["team-a"]
        assert row["chip_seconds"][BUCKET_IDLE] == pytest.approx(120.0)
        assert led.conservation()["violations"] == 0

    def test_replacement_opens_a_fresh_interval(self):
        clock = FakeClock()
        led = _ledger(clock)
        key = ("team-a", "nb-0")
        led.sample({key: (BUCKET_READY, 4.0)})
        clock.advance(7)
        led.sample({key: (BUCKET_READY, 4.0)})
        led.sample({})                                   # interval 1 closes
        clock.advance(100)                               # gap: not metered
        led.sample({key: (BUCKET_SCHEDULING, 4.0)})      # interval 2 opens
        clock.advance(3)
        led.sample({key: (BUCKET_SCHEDULING, 4.0)})      # 3s scheduling
        led.sample({})
        assert led.finalized_total == 2
        recs = led.conservation()
        assert recs["checked"] == 2 and recs["violations"] == 0
        # the 100s gap between intervals must NOT have been accrued
        row = led.tenant_table()["team-a"]
        assert row["chip_seconds_total"] == pytest.approx(4.0 * 10)
        assert row["notebooks_metered"] == 2

    def test_zero_chip_notebook_still_meters_wall_time(self):
        clock = FakeClock()
        led = _ledger(clock)
        key = ("team-a", "cpu-nb")
        led.sample({key: (BUCKET_READY, 0.0)})
        clock.advance(42)
        led.sample({key: (BUCKET_READY, 0.0)})
        led.sample({})
        cons = led.conservation()
        assert cons["checked"] == 1 and cons["violations"] == 0
        [rec] = [r for r in led._conservation]
        assert rec["wall_s"] == pytest.approx(42.0)
        assert led.tenant_table()["team-a"]["chip_seconds_total"] == 0.0

    def test_conservation_flags_a_tampered_meter(self):
        """Falsifiability: a double-counted bucket breaks the equality
        and surfaces as a violation — the check is not vacuous."""
        clock = FakeClock()
        led = _ledger(clock, tolerance=0.05)
        key = ("team-a", "nb-0")
        led.sample({key: (BUCKET_READY, 4.0)})
        clock.advance(10)
        led.sample({key: (BUCKET_READY, 4.0)})
        # white-box: inject a double-count into the live meter
        led._meters[key].buckets[BUCKET_READY] += 5.0
        assert led.conservation()["violations"] == 1   # live meter checked
        [v] = led.violations()
        assert v["live"] is True and v["rel_err"] > 0.05
        led.sample({})                                  # finalize it
        assert led.conservation()["violations"] == 1
        [v] = led.violations()
        assert "live" not in v and v["namespace"] == "team-a"

    def test_lru_eviction_finalizes_oldest_meter(self):
        clock = FakeClock()
        led = _ledger(clock, max_notebooks=2)
        a, b, c = [("ns", f"nb-{i}") for i in range(3)]
        led.sample({a: (BUCKET_READY, 1.0)})
        clock.advance(1)
        led.sample({a: (BUCKET_READY, 1.0), b: (BUCKET_READY, 1.0)})
        clock.advance(1)
        led.sample({a: (BUCKET_READY, 1.0), b: (BUCKET_READY, 1.0),
                    c: (BUCKET_READY, 1.0)})
        # cap is 2: the least-recently-sampled meter was evicted+finalized
        assert led.finalized_total == 1
        assert len(led._meters) == 2
        assert led.conservation()["violations"] == 0

    def test_chip_seconds_counter_exported_per_bucket(self):
        reg = Registry()
        fams = register_metering_metrics(reg)
        clock = FakeClock()
        led = _ledger(clock, registry=reg)
        key = ("team-a", "nb-0")
        led.sample({key: (BUCKET_READY, 2.0)})
        clock.advance(10)
        led.sample({key: (BUCKET_READY, 2.0)})
        assert fams["chip_seconds"].value("team-a", BUCKET_READY) \
            == pytest.approx(20.0)
        text = reg.render()
        assert "# TYPE notebook_tenant_chip_seconds_total counter" in text


class TestControlPlaneAttribution:
    def test_dispatch_observations_accumulate_per_tenant(self):
        reg = Registry()
        fams = register_metering_metrics(reg)
        led = _ledger(registry=reg)
        led.observe_dispatch("team-a", queue_s=0.5, e2r_s=1.5)
        led.observe_dispatch("team-a", queue_s=0.25, e2r_s=0.75)
        led.observe_dispatch("team-b", queue_s=0.1, e2r_s=0.1)
        tbl = led.tenant_table()
        assert tbl["team-a"]["dispatches"] == 2
        assert tbl["team-a"]["queue_s"] == pytest.approx(0.75)
        assert tbl["team-a"]["event_to_reconcile_s"] == pytest.approx(2.25)
        assert fams["queue"].value("team-a", "queue_wait") \
            == pytest.approx(0.75)
        assert fams["queue"].value("team-b", "event_to_reconcile") \
            == pytest.approx(0.1)

    def test_apiserver_snapshot_deltas_are_idempotent(self):
        led = _ledger()
        snap = {("update", "Notebook", "team-a"): 5,
                ("get", "Notebook", "team-a"): 2}
        led.ingest_apiserver(snap)
        led.ingest_apiserver(snap)      # same snapshot: no double count
        row = led.tenant_table()["team-a"]
        assert row["apiserver"] == {"get": 2, "update": 5}
        led.ingest_apiserver({("update", "Notebook", "team-a"): 8,
                              ("get", "Notebook", "team-a"): 2})
        assert led.tenant_table()["team-a"]["apiserver"]["update"] == 8

    def test_cluster_scoped_requests_have_no_owning_tenant(self):
        led = _ledger()
        led.ingest_apiserver({("list", "Node", ""): 50})
        assert led.tenant_table() == {}

    def test_tenants_past_cap_fold_into_other(self):
        led = _ledger(max_tenants=2)
        led.observe_dispatch("team-a", 0.0, 0.0)
        led.observe_dispatch("team-b", 0.0, 0.0)
        led.observe_dispatch("team-c", 0.0, 0.0)   # over cap: folds
        led.observe_dispatch("team-d", 0.0, 0.0)   # folds too
        tbl = led.tenant_table()
        assert sorted(tbl) == [OTHER_TENANT, "team-a", "team-b"]
        assert tbl[OTHER_TENANT]["dispatches"] == 2

    def test_empty_namespace_dispatch_folds_into_other(self):
        led = _ledger()
        led.observe_dispatch("", 0.1, 0.1)
        assert led.tenant_table()[OTHER_TENANT]["dispatches"] == 1

    def test_attempt_stream_latches_last_trace(self):
        class Rec:
            trace_id = "trace-xyz"
            object_key = "team-a/nb-0"

        led = _ledger()
        led.observe_attempt(Rec())
        assert led.tenant_table()["team-a"]["last_trace"] == "trace-xyz"
        led.observe_attempt(None)            # feed path never raises

        class ClusterRec:
            trace_id = "t2"
            object_key = "no-namespace"

        led.observe_attempt(ClusterRec())    # cluster-scoped: ignored
        assert "no-namespace" not in led.tenant_table()


class _SLOStub:
    def __init__(self):
        self.latched = []

    def latch_exemplar(self, objective, exemplar):
        self.latched.append((objective, exemplar))


def _latch_baselines(led, tenants, e2r=0.01):
    """Pump enough benign dispatches through each tenant to latch its
    baseline p99 (ledger latches at >= baseline_samples observations)."""
    for ns in tenants:
        for _ in range(led.baseline_samples):
            led.observe_dispatch(ns, 0.0, e2r)


class TestNoisyNeighborDetector:
    def _detector(self, **kw):
        # with only two tenants, factor 3 would need a >150% share —
        # 1.5 keeps the threshold reachable (share > 75% of the window)
        kw.setdefault("fairshare_factor", 1.5)
        kw.setdefault("window_evals", 4)
        return _ledger(slo_engine=_SLOStub(), **kw)

    def _flood(self, led, noisy, victims, rounds=3, flood=100,
               degraded_e2r=5.0):
        """Drive flood rounds: the noisy tenant issues `flood` dispatches
        per round while every victim sees a few degraded dispatches."""
        out = {}
        for _ in range(rounds):
            for _ in range(flood):
                led.observe_dispatch(noisy, 0.0, 0.0)
            for v in victims:
                for _ in range(3):
                    led.observe_dispatch(v, 0.0, degraded_e2r)
            out = led.evaluate()
        return out

    def test_flood_with_degraded_victim_fires_exactly_once(self):
        led = self._detector()

        class Rec:
            trace_id = "noisy-trace"
            object_key = "team-noisy/nb-0"

        _latch_baselines(led, ["team-noisy", "team-quiet"])
        led.observe_attempt(Rec())
        # benign rounds: balanced shares, nothing fires
        for _ in range(3):
            for ns in ("team-noisy", "team-quiet"):
                for _ in range(10):
                    led.observe_dispatch(ns, 0.0, 0.01)
            verdict = led.evaluate()
            assert verdict["noisy"] == [] and verdict["fired"] == []

        verdict = self._flood(led, "team-noisy", ["team-quiet"])
        assert verdict["noisy"] == ["team-noisy"]
        assert led.flagged() == ["team-noisy"]
        row = led.tenant_table()["team-noisy"]
        assert row["flagged"] is True and row["fired_total"] == 1
        # firing is once per episode even though the flood spans rounds
        assert led.checks["noisy"] >= 1
        # the SLO exemplar carries the latched trace of the noisy tenant
        assert led.slo_engine.latched == [
            ("tenant_fairness",
             {"trace_id": "noisy-trace", "tenant": "team-noisy"})]

    def test_victim_not_degraded_means_no_flag(self):
        led = self._detector()
        _latch_baselines(led, ["team-noisy", "team-quiet"])
        # flood, but the quiet tenant's p99 stays at baseline
        verdict = self._flood(led, "team-noisy", ["team-quiet"],
                              degraded_e2r=0.01)
        assert verdict["noisy"] == [] and led.flagged() == []
        assert led.checks["noisy"] == 0

    def test_single_tenant_is_never_its_own_neighbor(self):
        led = self._detector()
        _latch_baselines(led, ["team-solo"])
        for _ in range(3):
            for _ in range(200):
                led.observe_dispatch("team-solo", 0.0, 5.0)
            verdict = led.evaluate()
            assert verdict["noisy"] == []

    def test_near_idle_window_is_not_judged(self):
        """Below _MIN_WINDOW_UNITS total traffic, shares are all noise
        and no verdict may fire even on a 100% share."""
        led = self._detector()
        _latch_baselines(led, ["team-a", "team-b"], e2r=0.01)
        led.evaluate()  # roll the baseline burst out of the window
        for _ in range(led.window_evals):
            led.evaluate()
        led.observe_dispatch("team-a", 0.0, 0.0)
        led.observe_dispatch("team-b", 0.0, 5.0)   # degraded, tiny traffic
        verdict = led.evaluate()
        assert verdict["noisy"] == []

    def test_flag_clears_when_share_drops_back(self):
        led = self._detector()
        _latch_baselines(led, ["team-noisy", "team-quiet"])
        self._flood(led, "team-noisy", ["team-quiet"])
        assert led.flagged() == ["team-noisy"]
        # recovery: balanced traffic rolls the flood out of the window
        cleared = []
        for _ in range(led.window_evals + 1):
            for ns in ("team-noisy", "team-quiet"):
                for _ in range(10):
                    led.observe_dispatch(ns, 0.0, 0.01)
            cleared.extend(led.evaluate()["cleared"])
        assert cleared == ["team-noisy"]
        assert led.flagged() == []

    def test_refire_after_clear_is_a_new_episode(self):
        led = self._detector()
        _latch_baselines(led, ["team-noisy", "team-quiet"])
        self._flood(led, "team-noisy", ["team-quiet"])
        for _ in range(led.window_evals + 1):
            for ns in ("team-noisy", "team-quiet"):
                for _ in range(10):
                    led.observe_dispatch(ns, 0.0, 0.01)
            led.evaluate()
        assert led.flagged() == []
        self._flood(led, "team-noisy", ["team-quiet"])
        assert led.tenant_table()["team-noisy"]["fired_total"] == 2

    def test_other_tenant_is_excluded_from_verdicts(self):
        """The fold target aggregates many namespaces — flagging it
        would name nobody, so it neither fires nor counts as a victim."""
        led = self._detector(max_tenants=1)
        _latch_baselines(led, ["team-a"])
        # these two fold into "other", which then floods
        for _ in range(3):
            for _ in range(200):
                led.observe_dispatch("team-x", 0.0, 0.0)
            for _ in range(3):
                led.observe_dispatch("team-a", 0.0, 5.0)
            verdict = led.evaluate()
        assert verdict["noisy"] == []
        assert OTHER_TENANT in led.tenant_table()

    def test_warning_event_dedupes_through_real_recorder(self):
        from kubeflow_tpu.kube import ApiServer, EventRecorder

        api = ApiServer()
        led = self._detector()
        led.recorder = EventRecorder(api, "tenant-metering")
        _latch_baselines(led, ["team-noisy", "team-quiet"])
        self._flood(led, "team-noisy", ["team-quiet"])
        # clear, then refire: the second Warning must dedupe into the
        # same Event object (stable message), not create a second one
        for _ in range(led.window_evals + 1):
            for ns in ("team-noisy", "team-quiet"):
                for _ in range(10):
                    led.observe_dispatch(ns, 0.0, 0.01)
            led.evaluate()
        self._flood(led, "team-noisy", ["team-quiet"])
        events = [e for e in api.list("Event")
                  if e.body.get("reason") == REASON_NOISY]
        assert len(events) == 1, [e.body for e in events]
        ev = events[0].body
        assert ev["type"] == "Warning"
        assert ev["involvedObject"]["name"] == "team-noisy"
        assert ev["count"] == 2

    def test_fairness_counter_and_snapshot_shape(self):
        reg = Registry()
        fams = register_metering_metrics(reg)
        led = self._detector(registry=reg)
        _latch_baselines(led, ["team-a", "team-b"])
        led.evaluate()
        snap = led.snapshot()
        assert snap["enabled"] is True
        assert snap["buckets"] == list(BUCKETS)
        assert snap["fairness"]["evaluations"] == 1
        assert snap["fairness"]["flagged"] == []
        assert snap["conservation"]["violations"] == 0
        assert fams["fairness"].value("ok") == 1.0
        assert json.dumps(snap)  # the /debug/tenants body serializes

    def test_clear_resets_all_state(self):
        led = self._detector()
        _latch_baselines(led, ["team-a", "team-b"])
        led.sample({("team-a", "nb"): (BUCKET_READY, 2.0)})
        led.evaluate()
        led.clear()
        assert led.tenant_table() == {}
        assert led.conservation()["checked"] == 0
        assert led.evaluations_total == 0


class TestBucketMapping:
    """The pure census classifiers in core/metrics.py."""

    def _nb(self, tpu=None):
        from kubeflow_tpu.api.types import Notebook, TPUSpec
        spec = TPUSpec(*tpu) if tpu else None
        return Notebook.new("nb", "ns", tpu=spec).obj

    def test_placement_chips_resolves_topology(self):
        from kubeflow_tpu.core.metrics import placement_chips
        assert placement_chips(self._nb(("v5e", "2x2"))) == 4.0
        nb = self._nb(("v5e", "2x4"))
        nb.spec["tpu"]["slices"] = 2
        assert placement_chips(nb) == 16.0
        assert placement_chips(self._nb()) == 0.0
        bad = self._nb(("v5e", "2x2"))
        bad.spec["tpu"]["topology"] = "not-a-shape"
        assert placement_chips(bad) == 0.0  # invalid spec: wall-time only

    def test_metering_bucket_partitions_slice_health(self):
        from kubeflow_tpu.core import constants as C
        from kubeflow_tpu.core.metrics import metering_bucket
        nb = self._nb(("v5e", "2x2"))
        assert metering_bucket(nb) == BUCKET_SCHEDULING  # no status yet
        for health, want in (("Healthy", BUCKET_READY),
                             ("Unhealthy", BUCKET_RECOVERING),
                             ("Degraded", BUCKET_RECOVERING),
                             ("Stopping", BUCKET_IDLE),
                             ("Stopped", BUCKET_IDLE),
                             ("Scheduling", BUCKET_SCHEDULING)):
            nb.body["status"] = {"sliceHealth": health}
            assert metering_bucket(nb) == want, health
        # the stop annotation wins over a healthy slice: chips held past
        # the cull decision are idle
        nb.body["status"] = {"sliceHealth": "Healthy"}
        nb.metadata.annotations[C.STOP_ANNOTATION] = "2026-08-07T00:00:00Z"
        assert metering_bucket(nb) == BUCKET_IDLE


class TestCensusIntegration:
    """The real pipeline: placement-annotated Notebooks -> InformerCache
    aggregate -> NotebookMetrics scrape -> ledger."""

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.notebook_controller import \
            setup_core_controllers
        from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
        from kubeflow_tpu.utils.config import CoreConfig

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node", allocatable={"cpu": "64",
                                                  "memory": "256Gi"})
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        metrics = NotebookMetrics(api, manager=mgr)
        setup_core_controllers(mgr, CoreConfig(), metrics)
        led = TenantMeteringLedger(clock, registry=metrics.registry)
        mgr.metering = led
        metrics.attach_metering(led)
        return api, mgr, metrics, clock, led

    def _place(self, api, mgr, name, ns, tpu=None):
        from kubeflow_tpu.api.types import Notebook, TPUSpec
        from kubeflow_tpu.core import constants as C
        spec = TPUSpec(*tpu) if tpu else None
        api.create(Notebook.new(name, ns, tpu=spec).obj)
        mgr.run_until_idle()
        nb = api.get("Notebook", ns, name)
        nb.metadata.annotations[C.ANNOTATION_PLACEMENT] = json.dumps(
            {"pool": "pool-0"})
        api.update(nb)
        mgr.run_until_idle()
        return api.get("Notebook", ns, name)

    def test_scrape_meters_placed_notebooks_and_attributes_dispatches(self):
        api, mgr, metrics, clock, led = self._env()
        self._place(api, mgr, "metered", "team-a", tpu=("v5e", "2x2"))

        metrics.scrape()                  # meter opens
        clock.advance(30)
        metrics.scrape()                  # 30s accrued
        row = led.tenant_table()["team-a"]
        assert row["chip_seconds_total"] == pytest.approx(4.0 * 30)
        assert row["notebooks_metered"] == 1
        # the reconciles that created the notebook were attributed
        assert row["dispatches"] > 0
        assert row["apiserver_total"] > 0
        assert "update" in row["apiserver"] or "create" in row["apiserver"]
        assert led.conservation()["violations"] == 0

    def test_release_finalizes_conserving_interval(self):
        from kubeflow_tpu.core import constants as C
        api, mgr, metrics, clock, led = self._env()
        self._place(api, mgr, "short", "team-a", tpu=("v5e", "2x2"))
        metrics.scrape()
        clock.advance(15)
        metrics.scrape()
        nb = api.get("Notebook", "team-a", "short")
        del nb.metadata.annotations[C.ANNOTATION_PLACEMENT]  # released
        api.update(nb)
        mgr.run_until_idle()
        metrics.scrape()
        cons = led.conservation()
        assert cons["finalized"] == 1 and cons["violations"] == 0
        assert led.snapshot()["live_meters"] == 0

    def test_deletion_finalizes_the_meter(self):
        api, mgr, metrics, clock, led = self._env()
        self._place(api, mgr, "doomed", "team-a", tpu=("v5e", "2x2"))
        metrics.scrape()
        clock.advance(5)
        api.delete("Notebook", "team-a", "doomed")
        mgr.run_until_idle()
        metrics.scrape()
        assert led.conservation()["finalized"] == 1
        assert led.conservation()["violations"] == 0

    def test_cache_census_matches_bruteforce_under_seeded_churn(self):
        """The incremental cache aggregate must stay equal to a full
        api.list scan through placements, health flips, stop/unstop,
        releases, deletes, and creates."""
        from kubeflow_tpu.api.types import Notebook, TPUSpec
        from kubeflow_tpu.core import constants as C
        from kubeflow_tpu.core.metrics import NotebookMetrics

        api, mgr, metrics, clock, led = self._env()
        metrics.scrape()   # registers the tenant-metering aggregate
        rng = random.Random(1337)
        names = []
        for i in range(8):
            ns = f"team-{i % 3}"
            api.create(Notebook.new(f"nb-{i}", ns,
                                    tpu=TPUSpec("v5e", "2x2")).obj)
            names.append((ns, f"nb-{i}"))
        mgr.run_until_idle()

        def decode(pairs):
            out = {}
            for key, chips in pairs:
                p = key.split(NotebookMetrics._SEP)
                out[(p[0], p[1])] = (p[2], chips)
            return out

        def bruteforce():
            acc = {}
            for nb in api.list("Notebook"):
                acc.update(NotebookMetrics._metering_census(nb).items())
            return decode(acc.items())

        next_id = 8
        for _ in range(40):
            ns, name = rng.choice(names)
            nb = api.get("Notebook", ns, name)
            op = rng.randrange(6)
            if nb is None or op == 5:
                if nb is not None:
                    api.delete("Notebook", ns, name)
                    names.remove((ns, name))
                new = (f"team-{next_id % 3}", f"nb-{next_id}")
                next_id += 1
                api.create(Notebook.new(new[1], new[0],
                                        tpu=TPUSpec("v5e", "2x2")).obj)
                names.append(new)
            elif op == 0:
                nb.metadata.annotations[C.ANNOTATION_PLACEMENT] = \
                    json.dumps({"pool": "p"})
                api.update(nb)
            elif op == 1:
                nb.metadata.annotations.pop(C.ANNOTATION_PLACEMENT, None)
                api.update(nb)
            elif op == 2:
                nb.body.setdefault("status", {})["sliceHealth"] = \
                    rng.choice(["Healthy", "Unhealthy", "Degraded",
                                "Scheduling", "Stopping"])
                api.update(nb)
            elif op == 3:
                nb.metadata.annotations[C.STOP_ANNOTATION] = "stamp"
                api.update(nb)
            else:
                nb.metadata.annotations.pop(C.STOP_ANNOTATION, None)
                api.update(nb)
            mgr.run_until_idle()
            clock.advance(1)
            metrics.scrape()
            cached = decode(
                mgr.cache.aggregate("Notebook", "tenant-metering").items())
            assert cached == bruteforce()
        assert led.conservation()["violations"] == 0

    def test_shared_ledger_survives_manager_failover(self):
        """One ledger serving successive managers (the sharded-fleet
        wiring): accrual continues across the handoff and the interval
        still conserves when it finally closes."""
        from kubeflow_tpu.core import constants as C
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.notebook_controller import \
            setup_core_controllers
        from kubeflow_tpu.kube import Manager
        from kubeflow_tpu.utils.config import CoreConfig

        api, mgr, metrics, clock, led = self._env()
        self._place(api, mgr, "durable", "team-a", tpu=("v5e", "2x2"))
        metrics.scrape()
        clock.advance(10)
        metrics.scrape()

        # "failover": a fresh manager + metrics attach the SAME ledger
        mgr2 = Manager(api, clock=clock)
        metrics2 = NotebookMetrics(api, manager=mgr2)
        setup_core_controllers(mgr2, CoreConfig(), metrics2)
        mgr2.metering = led
        metrics2.attach_metering(led)
        mgr2.run_until_idle()
        metrics2.scrape()
        clock.advance(20)
        metrics2.scrape()

        row = led.tenant_table()["team-a"]
        assert row["chip_seconds_total"] == pytest.approx(4.0 * 30)
        nb = api.get("Notebook", "team-a", "durable")
        del nb.metadata.annotations[C.ANNOTATION_PLACEMENT]
        api.update(nb)
        mgr2.run_until_idle()
        metrics2.scrape()
        cons = led.conservation()
        assert cons["finalized"] == 1 and cons["violations"] == 0
        [rec] = list(led._conservation)
        assert rec["wall_s"] == pytest.approx(30.0)

    def test_tenant_families_render_in_the_exposition(self):
        api, mgr, metrics, clock, led = self._env()
        self._place(api, mgr, "vis", "team-a", tpu=("v5e", "2x2"))
        metrics.scrape()
        clock.advance(5)
        text = metrics.scrape()
        assert ('notebook_tenant_chip_seconds_total{namespace="team-a",'
                'bucket="') in text
        assert "notebook_tenant_queue_seconds_total" in text
        assert "notebook_tenant_fairness_checks_total" in text
