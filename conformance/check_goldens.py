"""Rendered-object goldens: the conformance contract for what a conformant
controller must create.

For two canonical Notebook inputs (a CPU workbench with auth, and a 2-slice
TPU workbench), the COMMITTED goldens record the full normalized object set
a conformant implementation renders — names, labels, ports, env injection,
topology wiring, network policy shape — plus the deployment manifests for
every profile.  `python conformance/check_goldens.py` re-renders with the
current implementation and diffs; any drift fails.  `--update` regenerates
(a contract change, to be reviewed like one).  Reference analog:
conformance/1.7/Makefile:16-30 (an external expected-artifact contract, not
a re-run of the implementation's own tests).
"""

from __future__ import annotations

import argparse
import difflib
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GOLDEN_DIR = Path(__file__).resolve().parent / "goldens"

# dynamic/server-assigned fields stripped before comparison
VOLATILE_META = ("uid", "resourceVersion", "creationTimestamp", "generation",
                 "deletionTimestamp")


def normalize(obj: dict) -> dict:
    obj = json.loads(json.dumps(obj))  # deep copy
    meta = obj.get("metadata", {})
    for k in VOLATILE_META:
        meta.pop(k, None)
    for ref in meta.get("ownerReferences", []) or []:
        ref.pop("uid", None)
    obj.pop("status", None)
    # annotations stamped with wall-clock times
    ann = meta.get("annotations") or {}
    for k in list(ann):
        if "last-activity" in k or "last_activity" in k:
            ann[k] = "<timestamp>"
    return obj


def sort_key(obj: dict) -> tuple:
    return (obj.get("kind", ""), obj.get("metadata", {}).get("namespace", ""),
            obj.get("metadata", {}).get("name", ""))


def render_workbench_objects() -> dict[str, list[dict]]:
    """Drive the full manager over the two canonical inputs and collect
    every object the controllers render."""
    from kubeflow_tpu.api.types import Notebook, TPUSpec
    from kubeflow_tpu.main import build_manager
    from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

    out: dict[str, list[dict]] = {}
    scenarios = {
        "cpu-auth-workbench": dict(
            name="wb-cpu", tpu=None,
            annotations={"notebooks.opendatahub.io/inject-auth": "true"},
        ),
        "tpu-multislice-workbench": dict(
            name="wb-tpu", tpu=TPUSpec("v5e", "2x4", slices=2),
            annotations={},
        ),
    }
    for label, sc in scenarios.items():
        core_cfg = CoreConfig.from_env({})
        odh_cfg = OdhConfig.from_env({})
        mgr, api, cluster, _ = build_manager(core_cfg, odh_cfg)
        if sc["tpu"] is not None:
            shape = sc["tpu"].shape
            cluster.add_tpu_slice_nodes(
                shape.accelerator.gke_label, shape.topology,
                shape.num_hosts * sc["tpu"].slices, shape.chips_per_host)
        else:
            cluster.add_node("n1", allocatable={"cpu": "8", "memory": "32Gi"})
        nb = Notebook.new(sc["name"], "user-ns", tpu=sc["tpu"],
                          annotations=sc["annotations"])
        api.create(nb.obj)
        mgr.run_until_idle()
        objects = []
        for kind, items in api.dump().items():
            for item in items:
                if kind in ("Node", "Namespace", "Event", "Lease"):
                    continue  # infrastructure, not rendered contract
                if kind == "Pod":
                    continue  # kubelet's output, not the controller's
                objects.append(normalize(item))
        out[label] = sorted(objects, key=sort_key)
    return out


def render_manifests() -> dict[str, list[dict]]:
    from kubeflow_tpu.deploy.manifests import render_profile

    return {profile: [normalize(d) for d in render_profile(profile)]
            for profile in ("standalone", "kubeflow", "openshift")}


def collect() -> dict[str, dict]:
    return {
        "workbench_objects.json": render_workbench_objects(),
        "deploy_manifests.json": render_manifests(),
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--update", action="store_true",
                        help="regenerate the goldens (contract change)")
    args = parser.parse_args()
    GOLDEN_DIR.mkdir(exist_ok=True)
    failures = 0
    for fname, data in collect().items():
        rendered = json.dumps(data, indent=1, sort_keys=True) + "\n"
        path = GOLDEN_DIR / fname
        if args.update:
            path.write_text(rendered)
            print(f"UPDATED {fname}")
            continue
        if not path.exists():
            print(f"FAIL {fname}: golden missing (run with --update)")
            failures += 1
            continue
        golden = path.read_text()
        if golden != rendered:
            failures += 1
            diff = difflib.unified_diff(
                golden.splitlines(), rendered.splitlines(),
                fromfile=f"goldens/{fname}", tofile="rendered", lineterm="", n=2)
            print(f"FAIL {fname}: rendered objects drifted from the contract:")
            for line in list(diff)[:60]:
                print("  " + line)
        else:
            print(f"PASS {fname}")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
