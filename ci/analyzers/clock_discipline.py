"""Clock discipline: direct time reads/sleeps outside utils/clock.py.

Everything that observes or spends time must go through an injected
`Clock` (utils/clock.py) so FakeClock suites and loadtests control the
timeline.  Flagged call forms (module aliases resolved per file):

  - time.time() / time.monotonic() / time.monotonic_ns() /
    time.perf_counter() / time.sleep()
  - datetime.now() / datetime.utcnow() / date.today()
    (datetime module or class spelling)
  - argless time.gmtime() / time.localtime() (implicit "now" reads)

`time.time` referenced WITHOUT a call (e.g. a `time_fn=time.time`
injectable default) is deliberately not flagged — that is the injection
idiom, not a hardwired read.
"""

from __future__ import annotations

import ast

from . import Module, Violation, dotted

CHECK = "clock"

_TIME_FNS = {"time", "monotonic", "monotonic_ns", "perf_counter", "sleep"}
_IMPLICIT_NOW = {"gmtime", "localtime"}
_DT_FNS = {"now", "utcnow", "today"}


def _import_aliases(tree: ast.AST) -> tuple[set, set, set]:
    """(names bound to the time module, names bound to the datetime
    module, names bound to the datetime/date classes)."""
    time_mods, dt_mods, dt_classes = set(), set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_mods.add(a.asname or "time")
                elif a.name == "datetime":
                    dt_mods.add(a.asname or "datetime")
        elif isinstance(node, ast.ImportFrom) and node.module == "datetime":
            for a in node.names:
                if a.name in ("datetime", "date"):
                    dt_classes.add(a.asname or a.name)
    return time_mods, dt_mods, dt_classes


def analyze(mod: Module) -> list[Violation]:
    time_mods, dt_mods, dt_classes = _import_aliases(mod.tree)
    if not (time_mods or dt_mods or dt_classes):
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        func = node.func
        recv = dotted(func.value)
        attr = func.attr
        flagged = None
        if recv in time_mods:
            if attr in _TIME_FNS:
                flagged = f"{recv}.{attr}()"
            elif attr in _IMPLICIT_NOW and not node.args \
                    and not node.keywords:
                flagged = f"{recv}.{attr}() with no argument (implicit now)"
        elif attr in _DT_FNS:
            if recv in dt_classes or \
                    any(recv in (f"{m}.datetime", f"{m}.date")
                        for m in dt_mods):
                flagged = f"{recv}.{attr}()"
        if flagged:
            out.append(Violation(
                CHECK, mod.rel, node.lineno, mod.qualname_at(node.lineno),
                f"direct time call {flagged} — route through the injected "
                "Clock (utils/clock.py) or allowlist with a reason"))
    return out
