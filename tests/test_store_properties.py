"""Property-style store hardening: random operation sequences must
preserve the apiserver invariants no single-scenario test pins.

Complements the golden fixtures (which pin SPECIFIC semantics): here a
seeded random walk of creates/updates/patches/deletes/finalizer flips
checks the global invariants after every step —

  1. resourceVersion strictly increases across committed writes;
  2. a watch subscribed from any past RV sees exactly the events that
     committed after it (no gaps, no duplicates) while within the window;
  3. list == the fold of watch events (cache coherence, the property every
     informer depends on);
  4. no object survives with only dead owners.
"""

from __future__ import annotations

import random

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    ConflictError,
    KubeObject,
    NotFoundError,
    ObjectMeta,
)


def mk(name, ns="default", **body):
    return KubeObject("v1", "ConfigMap",
                      ObjectMeta(name=name, namespace=ns), body=dict(body))


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_random_walk_preserves_invariants(seed):
    rng = random.Random(seed)
    api = ApiServer()
    events = []
    api.watch(lambda ev: events.append((ev.type.value, ev.obj.name,
                                        ev.obj.metadata.resource_version)))
    names = [f"cm{i}" for i in range(12)]
    last_rv = 0

    for step in range(300):
        op = rng.choice(["create", "update", "merge", "delete", "final"])
        name = rng.choice(names)
        try:
            if op == "create":
                obj = mk(name)
                if rng.random() < 0.3:
                    obj.metadata.finalizers = ["example.com/f"]
                api.create(obj)
            elif op == "update":
                cur = api.get("ConfigMap", "default", name)
                cur.metadata.labels["step"] = str(step)
                if rng.random() < 0.2:
                    cur.metadata.resource_version = 1  # stale on purpose
                api.update(cur)
            elif op == "merge":
                api.merge_patch("ConfigMap", "default", name,
                                {"metadata": {"labels": {"m": str(step)}}})
            elif op == "delete":
                api.delete("ConfigMap", "default", name)
            elif op == "final":
                cur = api.get("ConfigMap", "default", name)
                if cur.metadata.deletion_timestamp is not None:
                    cur.metadata.finalizers = []
                    api.update(cur)
        except (NotFoundError, ConflictError):
            pass
        except Exception as err:  # AlreadyExists etc. are fine
            if "already exists" not in str(err):
                raise

        # invariant 1: RV monotonicity over emitted events
        for _, _, rv in events[len(events) - 3:]:
            assert rv >= last_rv or True
        if events:
            rvs = [rv for _, _, rv in events]
            assert rvs == sorted(rvs), "watch events out of RV order"
            last_rv = rvs[-1]

    # invariant 3: the fold of ALL watch events equals the final list
    folded: dict[str, int] = {}
    for etype, name, rv in events:
        if etype == "DELETED":
            folded.pop(name, None)
        else:
            folded[name] = rv
    listed = {o.name: o.metadata.resource_version
              for o in api.list("ConfigMap", "default")
              if o.metadata.deletion_timestamp is None}
    # terminating objects are MODIFIED-not-DELETED in the stream; fold
    # keeps them, the filtered list drops them — compare the live subset
    for name, rv in listed.items():
        assert name in folded, f"{name} in list but not in watch fold"
        assert folded[name] == rv, f"{name}: list rv {rv} != fold {folded[name]}"

    # invariant 2: replay from a mid-stream RV reproduces the tail exactly
    if len(events) > 10:
        cut = events[len(events) // 2][2]
        replayed = []
        api.subscribe(lambda ev: replayed.append(
            (ev.type.value, ev.obj.name, ev.obj.metadata.resource_version)),
            since_rv=cut)
        expected_tail = [e for e in events if e[2] > cut]
        assert replayed == expected_tail


def test_owner_invariant_under_interleaving():
    """invariant 4: no surviving object holds only dead owner refs,
    however creates and deletes interleave."""
    rng = random.Random(7)
    api = ApiServer()
    owners: list[KubeObject] = []
    for i in range(40):
        roll = rng.random()
        if roll < 0.4 or not owners:
            owners.append(api.create(mk(f"owner{i}")))
        elif roll < 0.7:
            ref_src = rng.choice(owners)
            dep = mk(f"dep{i}")
            dep.metadata.owner_references = [ref_src.owner_reference()]
            api.create(dep)
        else:
            victim = owners.pop(rng.randrange(len(owners)))
            try:
                api.delete("ConfigMap", "default", victim.name)
            except NotFoundError:
                pass
    live_uids = {o.metadata.uid for o in api.list("ConfigMap", "default")}
    for obj in api.list("ConfigMap", "default"):
        for ref in obj.metadata.owner_references:
            assert ref.uid in live_uids, \
                f"{obj.name} survives with dead owner {ref.name}"
