"""Fleet tenancy under oversubscription: priority classes, quota /
fair-share admission (core/scheduler.py `_admission`), and
checkpoint-then-preempt (core/preemption.py).

Covers the tenancy invariants end to end on the in-memory control plane:
queued gangs read sliceHealth "Queued" and never hold claims, dequeue
order is deterministic and starvation-free (aged weighted fair share),
preemption never tears down an unsecured or equal-or-higher-priority
victim, the write-ahead record resumes exactly once across a manager
crash, and the cull/preempt precedence holds in BOTH orderings."""

from __future__ import annotations

import json

import pytest

from kubeflow_tpu.api.types import PRIORITY_RANK, Notebook, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.culling_controller import CullingReconciler
from kubeflow_tpu.core.jupyter import FakeJupyterState
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.core.preemption import (
    PREEMPT_RESULT_EVICTED,
    PREEMPT_RESULT_NO_VICTIM,
    PREEMPT_RESULT_RESUMED,
    new_quota_object,
    pending_preemption,
)
from kubeflow_tpu.core.scheduler import (
    queued_info,
    rank_of,
    resolve_priority,
    tenant_policy,
)
from kubeflow_tpu.core.sessionstate import InMemorySessionStore
from kubeflow_tpu.kube import (
    ApiServer,
    FakeCluster,
    InvalidError,
    Manager,
    Request,
)
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig

HOSTS = 4                      # v5e 4x4: 4 hosts x 4 chips = 16 chips
GKE_LABEL = "tpu-v5-lite-podslice"


def make_env(extra=None, nodes=0, provisioner=True):
    """Scheduler + notebook controller + session store, cold provisioning
    effectively disabled (1h) so capacity scarcity is real."""
    api = ApiServer()
    cluster = FakeCluster(api)
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    env = {
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": "0",
        "WARMPOOL_PROVISION_S": "3600",
    }
    env.update(extra or {})
    cfg = CoreConfig.from_env(env)
    metrics = NotebookMetrics(api, manager=mgr)
    store = InMemorySessionStore(clock=clock)
    cluster.attach_session_store(store)
    setup_core_controllers(mgr, cfg, metrics, session=store,
                           provisioner=cluster if provisioner else None)
    if nodes:
        cluster.add_tpu_slice_nodes(GKE_LABEL, "4x4", nodes, 4)
    return api, cluster, clock, mgr, metrics, store


def create_nb(api, name, ns, priority=None, slices=1, annotations=None):
    nb = Notebook.new(name, ns, tpu=TPUSpec("v5e", "4x4", slices),
                      annotations=annotations)
    if priority is not None:
        nb.obj.spec["priority"] = priority
    api.create(nb.obj)
    return nb


def set_quota(api, tenants=None, defaults=None):
    if api.try_get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME) is None:
        api.create(new_quota_object())
    live = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
    live.body["spec"] = {"tenants": tenants or {},
                         "defaults": defaults or {}}
    api.update(live)


def queued_stamp(since, priority="standard", reason="quota"):
    return {C.ANNOTATION_QUEUED: json.dumps(
        {"since": since, "priority": priority, "reason": reason})}


def queue_of(api, ns, name):
    return queued_info(api.get("Notebook", ns, name).metadata.annotations)


def placed(api, ns, name):
    return C.ANNOTATION_PLACEMENT in \
        api.get("Notebook", ns, name).metadata.annotations


def health(api, ns, name):
    return (api.get("Notebook", ns, name).body.get("status") or {}) \
        .get("sliceHealth")


def victim_sts_deletes(api, name):
    """Client-side deletes against the victim's gang STS.  Pods cascade
    through the apiserver's owner-ref GC, so slice-atomicity reads as:
    exactly one whole-StatefulSet delete, ZERO pod-level client deletes
    (a pod-by-pod teardown would be a partial eviction in flight)."""
    return [r for r in api.audit_log(verb="delete", kind="StatefulSet")
            if r.name == name and r.ok]


def victim_pod_deletes(api, name):
    return [r for r in api.audit_log(verb="delete", kind="Pod")
            if r.name.startswith(name + "-")]


# -- priority classes ----------------------------------------------------------
class TestPriorityClass:
    def test_invalid_priority_rejected(self):
        nb = Notebook.new("nb", "t1", tpu=TPUSpec("v5e", "4x4"))
        nb.obj.spec["priority"] = "urgent"
        with pytest.raises(InvalidError):
            nb.validate()

    def test_valid_classes_pass_validation(self):
        for p in PRIORITY_RANK:
            nb = Notebook.new("nb", "t1", tpu=TPUSpec("v5e", "4x4"))
            nb.obj.spec["priority"] = p
            nb.validate()

    def test_resolution_explicit_beats_tenant_default(self):
        quota = new_quota_object()
        quota.body["spec"] = {"defaults": {"priority": "low"},
                              "tenants": {"vip": {"priority": "high"}}}
        anon = Notebook.new("a", "t1", tpu=TPUSpec("v5e", "4x4"))
        assert resolve_priority(anon, quota) == "low"
        viper = Notebook.new("b", "vip", tpu=TPUSpec("v5e", "4x4"))
        assert resolve_priority(viper, quota) == "high"
        viper.obj.spec["priority"] = "standard"
        assert resolve_priority(viper, quota) == "standard"
        # no quota object at all: the module default
        assert resolve_priority(anon, None) == "standard"

    def test_tenant_policy_merging_and_clamping(self):
        quota = new_quota_object()
        quota.body["spec"] = {
            "defaults": {"chipQuota": 32, "weight": 2},
            "tenants": {"t1": {"chipQuota": "garbage", "weight": -5},
                        "t2": {"weight": 4}},
        }
        p1 = tenant_policy(quota, "t1")
        assert p1["chip_quota"] == 32.0      # garbage -> default kept
        assert p1["weight"] > 0              # clamped positive
        p2 = tenant_policy(quota, "t2")
        assert (p2["chip_quota"], p2["weight"]) == (32.0, 4.0)
        assert tenant_policy(None, "t3") == {
            "chip_quota": 0.0, "weight": 1.0, "priority": "standard"}
        assert rank_of("high") > rank_of("standard") > rank_of("low")


# -- quota / fair-share admission ----------------------------------------------
class TestAdmissionGate:
    def test_over_quota_gang_queues_then_admits_on_quota_raise(self):
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=8)
        set_quota(api, tenants={"ta": {"chipQuota": 16}})
        create_nb(api, "a1", "ta")
        mgr.run_until_idle()
        assert placed(api, "ta", "a1")
        create_nb(api, "a2", "ta")
        mgr.run_until_idle()
        assert not placed(api, "ta", "a2")
        info = queue_of(api, "ta", "a2")
        assert info["reason"] == "quota"
        assert info["priority"] == "standard"
        assert health(api, "ta", "a2") == "Queued"
        # a queued gang holds NO pool claims
        for pool in api.list(C.WARMPOOL_KIND):
            claims = (pool.body.get("status", {}).get("slices") or {})
            assert not any(e.get("claimedBy") == "ta/a2"
                           for e in claims.values())
        # the /debug/fleet tenancy section sees the queue
        tenancy = metrics.tenancy_snapshot()
        assert tenancy["queued"]["ta"]["depth"] == 1
        # raising the quota wakes every queued gang (TenantQuota watch)
        clock.advance(30.0)
        set_quota(api, tenants={"ta": {"chipQuota": 32}})
        mgr.run_until_idle()
        assert placed(api, "ta", "a2")
        assert C.ANNOTATION_QUEUED not in \
            api.get("Notebook", "ta", "a2").metadata.annotations
        # queue wait observed, labeled by priority: EVERY placement is
        # observed (0s for gangs that never queued) so the distribution's
        # p99 is the time-to-placement SLO — a1 and a2 make two samples
        assert metrics.queue_wait_seconds.count_value("standard") == 2

    def test_stopped_while_queued_leaves_the_line(self):
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=4)
        set_quota(api, tenants={"ta": {"chipQuota": 16}})
        create_nb(api, "a1", "ta")
        create_nb(api, "a2", "ta")
        mgr.run_until_idle()
        assert queue_of(api, "ta", "a2") or queue_of(api, "ta", "a1")
        queued_name = "a2" if queue_of(api, "ta", "a2") else "a1"
        live = api.get("Notebook", "ta", queued_name)
        live.metadata.annotations[C.STOP_ANNOTATION] = "true"
        api.update(live)
        mgr.run_until_idle()
        assert C.ANNOTATION_QUEUED not in \
            api.get("Notebook", "ta", queued_name).metadata.annotations

    def test_fair_share_parks_tenant_over_its_share(self):
        """Capacity 32, two tenants, equal weights -> 16-chip shares.
        With tb's gang waiting mid-provision, ta (already at 16 placed)
        may not claim MORE; once the contention clears and capacity
        frees, the parked gang admits and places."""
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=8)
        create_nb(api, "a1", "ta")
        mgr.run_until_idle()
        create_nb(api, "b1", "tb")
        mgr.run_until_idle()
        assert placed(api, "ta", "a1") and placed(api, "tb", "b1")
        create_nb(api, "b2", "tb")    # no capacity left: cold reservation
        mgr.run_until_idle()
        assert not placed(api, "tb", "b2")
        create_nb(api, "a2", "ta")
        mgr.run_until_idle()
        assert queue_of(api, "ta", "a2").get("reason") == "fair-share"
        assert health(api, "ta", "a2") == "Queued"
        # contention ends: a1 and b2 stop; a2 takes the freed capacity
        for ns, name in (("ta", "a1"), ("tb", "b2")):
            live = api.get("Notebook", ns, name)
            live.metadata.annotations[C.STOP_ANNOTATION] = "true"
            api.update(live)
        mgr.run_until_idle()
        for _ in range(3):
            mgr.advance(20.0)
        assert placed(api, "ta", "a2")

    def test_quota_counts_inflight_reservations(self):
        """A burst of concurrent cold reservations must not oversubscribe
        the quota: the second gang queues even though the first has not
        PLACED yet (its reservation already spends the quota)."""
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=0)
        set_quota(api, tenants={"ta": {"chipQuota": 16}})
        create_nb(api, "a1", "ta")
        mgr.run_until_idle()          # no capacity: a1 -> reservation
        assert not placed(api, "ta", "a1")
        create_nb(api, "a2", "ta")
        mgr.run_until_idle()
        assert queue_of(api, "ta", "a2").get("reason") == "quota"


# -- deterministic, starvation-free dequeue order ------------------------------
class TestDequeueOrder:
    def _race(self, api, clock, mgr, winner, loser):
        mgr.run_until_idle()
        for _ in range(4):
            mgr.advance(20.0)
        (wns, wname), (lns, lname) = winner, loser
        assert placed(api, wns, wname), f"{wname} should have won"
        assert not placed(api, lns, lname)

    def test_older_gang_dequeues_first(self):
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=4)
        t0 = clock.now()
        clock.advance(100.0)
        create_nb(api, "old", "ta", annotations=queued_stamp(t0))
        create_nb(api, "young", "ta", annotations=queued_stamp(t0 + 90.0))
        self._race(api, clock, mgr, ("ta", "old"), ("ta", "young"))

    def test_priority_outranks_small_age_gap(self):
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=4)
        t0 = clock.now()
        clock.advance(100.0)
        create_nb(api, "lo", "ta",
                  annotations=queued_stamp(t0, priority="standard"))
        create_nb(api, "hi", "tb", priority="high",
                  annotations=queued_stamp(t0 + 90.0, priority="high"))
        self._race(api, clock, mgr, ("tb", "hi"), ("ta", "lo"))

    def test_aging_eventually_beats_priority(self):
        """Starvation-freedom: age grows without bound, so a low-priority
        gang queued long enough outranks a fresh high-priority one.
        Preemption is off so the dequeue order is observable in
        isolation — with it on, the high gang would (correctly) admit
        second and then evict the placed low gang."""
        api, cluster, clock, mgr, metrics, _ = make_env(
            nodes=4, extra={"QUEUE_AGING_S": "1",
                            "ENABLE_PREEMPTION": "false"})
        t0 = clock.now()
        clock.advance(1000.0)
        create_nb(api, "lo", "ta", priority="low",
                  annotations=queued_stamp(t0, priority="low"))
        create_nb(api, "hi", "tb", priority="high",
                  annotations=queued_stamp(t0 + 990.0, priority="high"))
        self._race(api, clock, mgr, ("ta", "lo"), ("tb", "hi"))


# -- checkpoint-then-preempt ---------------------------------------------------
class TestPreemption:
    def _place_victim(self, api, cluster, mgr, name="victim", ns="t-low",
                      priority="low", payload=b"kernel-state-A"):
        create_nb(api, name, ns, priority=priority)
        mgr.run_until_idle()
        assert placed(api, ns, name)
        cluster.set_session_payload(ns, name, payload)

    def test_checkpoint_then_preempt_happy_path(self):
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr)
        create_nb(api, "ben", "t-hi", priority="high")
        mgr.run_until_idle()
        # beneficiary holds the freed capacity
        assert placed(api, "t-hi", "ben")
        assert health(api, "t-hi", "ben") == "Healthy"
        # victim: evicted, re-queued at its OWN priority, fenced on the
        # beneficiary, session secured
        assert not placed(api, "t-low", "victim")
        info = queue_of(api, "t-low", "victim")
        assert info["reason"] == "preempted"
        assert info["priority"] == "low"
        assert info["beneficiary"] == "t-hi/ben"
        session = (api.get("Notebook", "t-low", "victim")
                   .body["status"]["sessionState"])
        assert session["0"]["trigger"] == "preempt"
        assert session["0"]["digest"]
        # write-ahead record reached its terminal state
        quota = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        st = quota.body.get("status") or {}
        assert not (st.get("preemptions") or {})
        (rec,) = st["recentPreemptions"]
        assert rec["victim"] == "t-low/victim"
        assert rec["phase"] == C.PREEMPTION_DONE
        assert metrics.preemptions.value(
            PREEMPT_RESULT_EVICTED, "low") == 1
        # teardown was slice-atomic: one whole-STS delete, no pod-level
        # client deletes (pods cascade via owner-ref GC), nothing left
        assert len(victim_sts_deletes(api, "victim")) == 1
        assert victim_pod_deletes(api, "victim") == []
        assert api.list("Pod", namespace="t-low") == []
        # events on both sides
        reasons = {e.body.get("reason") for e in api.list("Event")}
        assert {"NotebookPreempted", "PreemptionIssued"} <= reasons

    def test_victim_restores_from_checkpoint_on_replacement(self):
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr)
        create_nb(api, "ben", "t-hi", priority="high")
        mgr.run_until_idle()
        assert placed(api, "t-hi", "ben")
        # beneficiary leaves; the victim's fence lifts and its cold
        # reservation eventually provisions; the migrate-verb restore
        # machinery carries the secured checkpoint back
        live = api.get("Notebook", "t-hi", "ben")
        live.metadata.annotations[C.STOP_ANNOTATION] = "true"
        api.update(live)
        mgr.run_until_idle()
        for _ in range(4):
            mgr.advance(20.0)
        mgr.advance(3700.0)
        for _ in range(3):
            mgr.advance(20.0)
        assert placed(api, "t-low", "victim")
        session = (api.get("Notebook", "t-low", "victim")
                   .body["status"]["sessionState"])
        assert session["0"]["phase"] == "restored"
        assert metrics.migrations.value("preempt", "restored") == 1

    def test_never_evicts_equal_or_higher_priority(self):
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr, ns="t-std",
                           priority="standard")
        create_nb(api, "ben", "t-hi", priority="standard")
        mgr.run_until_idle()
        assert placed(api, "t-std", "victim")      # untouched
        assert not placed(api, "t-hi", "ben")
        for result in (PREEMPT_RESULT_EVICTED, PREEMPT_RESULT_NO_VICTIM):
            for p in PRIORITY_RANK:
                assert metrics.preemptions.value(result, p) == 0
        assert api.try_get(
            C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME) is None

    def test_no_secured_checkpoint_means_no_eviction(self):
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr)
        # sever the checkpoint path: no final-snapshot handler, nothing
        # stored -> the victim's state cannot be secured
        store.set_final_snapshot_handler(None)
        create_nb(api, "ben", "t-hi", priority="high")
        mgr.run_until_idle()
        assert placed(api, "t-low", "victim")      # never torn down
        assert not placed(api, "t-hi", "ben")
        assert metrics.preemptions.value(
            PREEMPT_RESULT_NO_VICTIM, "high") >= 1
        assert victim_sts_deletes(api, "victim") == []
        assert len(api.list("Pod", namespace="t-low")) == HOSTS

    def test_partial_coverage_evicts_nobody(self):
        """The victim frees 16 chips but the beneficiary needs 32: evict
        NOBODY (a partial eviction destroys a session without unblocking
        anyone)."""
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr)
        create_nb(api, "ben", "t-hi", priority="high", slices=2)
        mgr.run_until_idle()
        assert placed(api, "t-low", "victim")
        assert not placed(api, "t-hi", "ben")
        assert metrics.preemptions.value(
            PREEMPT_RESULT_NO_VICTIM, "high") >= 1
        assert victim_sts_deletes(api, "victim") == []
        assert len(api.list("Pod", namespace="t-low")) == HOSTS

    def test_preemption_fence_holds_until_beneficiary_places(self):
        """A victim re-queued by an eviction must NOT reclaim the freed
        capacity while its beneficiary still waits for it."""
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        # beneficiary of a DIFFERENT shape: it can never place here, so
        # the fence (not capacity) is what holds the victim out
        ben = Notebook.new("ben", "t-hi", tpu=TPUSpec("v5p", "2x2x2"))
        ben.obj.spec["priority"] = "high"
        api.create(ben.obj)
        stamp = queued_stamp(0.0, priority="low", reason="preempted")
        info = json.loads(stamp[C.ANNOTATION_QUEUED])
        info["beneficiary"] = "t-hi/ben"
        create_nb(api, "victim", "t-low", priority="low",
                  annotations={C.ANNOTATION_QUEUED: json.dumps(info)})
        mgr.run_until_idle()
        for _ in range(3):
            mgr.advance(20.0)
        # capacity for the victim is RIGHT THERE, but the fence holds
        assert not placed(api, "t-low", "victim")
        assert queue_of(api, "t-low", "victim")["reason"] == "preempted"
        # beneficiary gives up -> fence lifts -> victim places
        live = api.get("Notebook", "t-hi", "ben")
        live.metadata.annotations[C.STOP_ANNOTATION] = "true"
        api.update(live)
        for _ in range(3):
            mgr.advance(20.0)
        assert placed(api, "t-low", "victim")

    def test_resume_after_crash_exactly_once(self):
        """A write-ahead record whose manager died before teardown is
        re-driven by the next manager — exactly once: a second sweep
        neither re-deletes pods nor double-counts."""
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        self._place_victim(api, cluster, mgr)
        (snap,) = cluster.snapshot_sessions("t-low", "victim")
        # the record's beneficiary exists but cannot place here (wrong
        # accelerator) — the fence must keep the resumed victim from
        # snatching its own freed capacity back
        ben = Notebook.new("ben", "t-hi", tpu=TPUSpec("v5p", "2x2x2"))
        ben.obj.spec["priority"] = "high"
        api.create(ben.obj)
        api.create(new_quota_object())
        live = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        live.status = {"preemptions": {"t-low/victim": {
            "victim": "t-low/victim", "victimPriority": "low",
            "beneficiary": "t-hi/ben", "beneficiaryPriority": "high",
            "chips": 16.0, "phase": C.PREEMPTION_PENDING,
            "createdAt": clock.now_iso(),
            "restore": {"0": {
                "restoreGeneration": snap.generation,
                "restoreUri": snap.uri, "digest": snap.digest,
                "savedAt": clock.now_iso()}}}}}
        api.update_status(live)
        assert pending_preemption(api, "t-low", "victim")
        mgr.run_until_idle()   # TenantQuota watch drives the resume
        assert not placed(api, "t-low", "victim")
        assert not pending_preemption(api, "t-low", "victim")
        assert metrics.preemptions.value(
            PREEMPT_RESULT_RESUMED, "low") == 1
        assert len(victim_sts_deletes(api, "victim")) == 1
        assert victim_pod_deletes(api, "victim") == []
        session = (api.get("Notebook", "t-low", "victim")
                   .body["status"]["sessionState"])
        assert session["0"]["digest"] == snap.digest
        # second sweep: idempotent no-op
        mgr.enqueue_all()
        mgr.run_until_idle()
        assert metrics.preemptions.value(
            PREEMPT_RESULT_RESUMED, "low") == 1
        assert len(victim_sts_deletes(api, "victim")) == 1


# -- cull <-> preempt precedence (both orderings) ------------------------------
class TestCullPreemptPrecedence:
    def test_mid_cull_victim_never_selected(self):
        """Cull first: a stop-annotated victim is already being parked —
        the preemption engine must not double-handle it (the freed
        capacity arrives through the ordinary release path instead)."""
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        create_nb(api, "victim", "t-low", priority="low")
        mgr.run_until_idle()
        cluster.set_session_payload("t-low", "victim", b"s")
        live = api.get("Notebook", "t-low", "victim")
        live.metadata.annotations[C.STOP_ANNOTATION] = "true"
        api.update(live)
        create_nb(api, "ben", "t-hi", priority="high")
        mgr.run_until_idle()
        for _ in range(3):
            mgr.advance(20.0)
        # the beneficiary got the capacity via release, NOT preemption
        assert placed(api, "t-hi", "ben")
        for p in PRIORITY_RANK:
            assert metrics.preemptions.value(
                PREEMPT_RESULT_EVICTED, p) == 0
        quota = api.try_get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        assert quota is None or not (
            (quota.body.get("status") or {}).get("recentPreemptions"))
        session = (api.get("Notebook", "t-low", "victim")
                   .body.get("status") or {}).get("sessionState") or {}
        assert all(e.get("trigger") != "preempt"
                   for e in session.values())

    def test_pending_preemption_blocks_culler(self):
        """Preempt first: while a write-ahead record owns the victim's
        teardown, the culler must hold its stop annotation — a cull
        landing mid-eviction would race the engine for the claims."""
        api, cluster, clock, mgr, metrics, store = make_env(nodes=4)
        create_nb(api, "victim", "t-low", priority="low")
        mgr.run_until_idle()
        api.create(new_quota_object())
        live = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        live.status = {"preemptions": {"t-low/victim": {
            "victim": "t-low/victim", "phase": C.PREEMPTION_PENDING}}}
        api.update_status(live)
        jupyter = FakeJupyterState()
        cull_cfg = CoreConfig(enable_culling=True, cull_idle_time_min=60,
                              idleness_check_period_min=1)
        culler_rec = CullingReconciler(api, cull_cfg, jupyter, metrics,
                                       clock=clock)
        req = Request("t-low", "victim")
        culler_rec.reconcile(req)      # initializes activity annotations
        clock.advance(61 * 60)
        culler_rec.reconcile(req)      # idle — but the record holds it
        nb = api.get("Notebook", "t-low", "victim")
        assert C.STOP_ANNOTATION not in nb.metadata.annotations
        # record closes -> the very next check culls normally
        live = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        live.status = {"preemptions": {}}
        api.update_status(live)
        clock.advance(2 * 60)
        culler_rec.reconcile(req)
        nb = api.get("Notebook", "t-low", "victim")
        assert C.STOP_ANNOTATION in nb.metadata.annotations


# -- observability satellites --------------------------------------------------
class TestTenancyObservability:
    def test_new_metric_families_registered(self):
        api = ApiServer()
        metrics = NotebookMetrics(api)
        fams = dict(metrics.families())
        assert fams["notebook_preemptions_total"] == "counter"
        assert fams["notebook_queue_wait_seconds"] == "histogram"

    def test_fleet_snapshot_has_tenancy_section(self):
        api, cluster, clock, mgr, metrics, _ = make_env(nodes=4)
        set_quota(api, tenants={"ta": {"chipQuota": 16}})
        create_nb(api, "a1", "ta")
        create_nb(api, "a2", "ta")
        mgr.run_until_idle()
        snap = metrics.fleet_snapshot()
        tenancy = snap["tenancy"]
        assert tenancy["queued"]["ta"]["depth"] == 1
        assert tenancy["usage_chips"]["ta"] == 16.0
        assert tenancy["quota"]["ta"]["chipQuota"] == 16
        assert tenancy["pending_preemptions"] == 0

    def test_placement_slo_objective_gated_on_knob(self):
        from kubeflow_tpu.utils.slo import default_objectives

        on = default_objectives(CoreConfig(slo_placement_p99_s=300.0))
        assert any(o.name == "time_to_placement" for o in on)
        off = default_objectives(CoreConfig())
        assert not any(o.name == "time_to_placement" for o in off)

    def test_quota_wait_is_a_lifecycle_stage(self):
        from kubeflow_tpu.utils import lifecycle

        assert lifecycle.STAGE_QUOTA_WAIT in lifecycle.STAGES
