"""Distributed-runtime environment wiring for TPU notebook workers.

The TPU-native analog of the reference's `NB_PREFIX` injection and Service
generation (notebook-controller/controllers/notebook_controller.go:417-431,
525-552): every worker pod gets the coordination env that
`jax.distributed.initialize()` (and MaxText/libtpu) read, derived from the
indexed StatefulSet + headless Service identity:

- TPU_WORKER_ID        — pod ordinal, via downward API from the pod-index label
- TPU_WORKER_HOSTNAMES — comma list of stable per-worker DNS names
- JAX_COORDINATOR_ADDRESS / COORDINATOR_ADDRESS — worker 0 of slice 0
- TPU_ACCELERATOR_TYPE, TPU_TOPOLOGY, TPU_HOSTS_PER_SLICE - slice geometry
- MEGASCALE_* — multi-slice (DCN data-parallel) coordination

The hostnames list is ordered by ordinal: its index MUST equal TPU_WORKER_ID
or jax.distributed mis-assigns process ids (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from .topology import SliceShape

JAX_COORDINATOR_PORT = 8471
MEGASCALE_PORT = 8080

POD_INDEX_LABEL = "apps.kubernetes.io/pod-index"


def headless_service_name(notebook_name: str) -> str:
    return f"{notebook_name}-workers"


def worker_hostname(
    notebook_name: str, slice_id: int, num_slices: int, ordinal: int,
    replica: int = 0,
) -> str:
    """Short DNS name of one worker through the headless Service.

    Resolvable cluster-wide as {pod}.{svc}.{ns}.svc via the pod's
    subdomain; we emit the svc-qualified short form GKE uses.  All
    replica gangs share the notebook's one headless Service — follower
    pods carry the same notebook-name label, so their names resolve
    through the same subdomain.
    """
    sts = statefulset_name(notebook_name, slice_id, num_slices, replica)
    return f"{sts}-{ordinal}.{headless_service_name(notebook_name)}"


def statefulset_name(notebook_name: str, slice_id: int, num_slices: int,
                     replica: int = 0) -> str:
    """Slice 0 of a single-slice notebook keeps the bare CR name so the
    CPU-path naming contract (STS == notebook name, reference
    notebook_controller.go:433-447) holds; multi-slice appends -slice-N.
    Replica 0 (the boot-time primary) keeps the unreplicated names —
    turning replication on never renames a running workload; follower
    gangs append -rN."""
    base = notebook_name if num_slices <= 1 \
        else f"{notebook_name}-slice-{slice_id}"
    return base if replica <= 0 else f"{base}-r{replica}"


def worker_hostnames(notebook_name: str, shape: SliceShape, slice_id: int,
                     num_slices: int, replica: int = 0) -> list[str]:
    return [
        worker_hostname(notebook_name, slice_id, num_slices, i, replica)
        for i in range(shape.num_hosts)
    ]


def tpu_env_vars(
    notebook_name: str,
    shape: SliceShape,
    slice_id: int,
    num_slices: int,
    replica: int = 0,
) -> list[dict]:
    """corev1.EnvVar list (dict form) for every worker container in a slice.

    TPU_WORKER_ID comes from the downward API so one pod template serves all
    ordinals — the same property the reference exploits for NB_PREFIX being
    identical across the (single) replica.
    """
    # each replica gang is its own coordination domain: followers run a
    # full jax.distributed world of their own, continuously restoring the
    # primary's delta stream — so every address below stays intra-replica
    hostnames = ",".join(
        worker_hostnames(notebook_name, shape, slice_id, num_slices, replica))
    coordinator = (
        f"{worker_hostname(notebook_name, 0, num_slices, 0, replica)}"
        f":{JAX_COORDINATOR_PORT}"
    )
    env: list[dict] = [
        {
            "name": "TPU_WORKER_ID",
            "valueFrom": {
                "fieldRef": {
                    "fieldPath": f"metadata.labels['{POD_INDEX_LABEL}']"
                }
            },
        },
        {"name": "TPU_WORKER_HOSTNAMES", "value": hostnames},
        {"name": "TPU_ACCELERATOR_TYPE", "value": shape.accelerator.name},
        {"name": "TPU_TOPOLOGY", "value": shape.topology},
        {"name": "TPU_HOSTS_PER_SLICE", "value": str(shape.num_hosts)},
        {"name": "TPU_CHIPS_PER_HOST_BOUNDS", "value": str(shape.chips_per_host)},
        {"name": "JAX_COORDINATOR_ADDRESS", "value": coordinator},
        {"name": "COORDINATOR_ADDRESS", "value": coordinator},
    ]
    if num_slices > 1:
        megascale_coord = worker_hostname(
            notebook_name, 0, num_slices, 0, replica)
        env += [
            {"name": "MEGASCALE_COORDINATOR_ADDRESS", "value": megascale_coord},
            {"name": "MEGASCALE_NUM_SLICES", "value": str(num_slices)},
            {"name": "MEGASCALE_SLICE_ID", "value": str(slice_id)},
            {"name": "MEGASCALE_PORT", "value": str(MEGASCALE_PORT)},
        ]
    return env


def upsert_by_name(items: list[dict], item: dict) -> None:
    """Replace the entry with the same `name`, or append.  The idempotent
    mutation primitive every webhook injection (volumes, volumeMounts,
    containers) is built on — mirrors the reference's replace-or-append loops
    (e.g. notebook_mutating_webhook.go:283-307)."""
    for i, existing in enumerate(items):
        if existing.get("name") == item.get("name"):
            items[i] = item
            return
    items.append(item)


def merge_env(existing: list[dict], injected: list[dict]) -> list[dict]:
    """Inject env vars, keeping user-provided values for colliding names
    (same precedence rule as the reference's setPrefixEnvVar, which leaves a
    user NB_PREFIX in place — notebook_controller.go:417-431)."""
    have = {e.get("name") for e in existing}
    return list(existing) + [e for e in injected if e["name"] not in have]
