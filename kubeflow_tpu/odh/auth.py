"""kube-rbac-proxy auth-mode resources.

Port of odh notebook_kube_rbac_auth.go: a per-notebook ServiceAccount, a
Service on :8443 carrying the OpenShift serving-cert annotation, a ConfigMap
with the SubjectAccessReview the proxy performs (`get` on this specific
notebook), and a per-notebook ClusterRoleBinding to system:auth-delegator —
cluster-scoped, so cleaned up manually via finalizer
(notebook_kube_rbac_auth.go:48-368).  The sidecar container itself is
injected by the mutating webhook (webhook.py).
"""

from __future__ import annotations

from ..api.types import GROUP, Notebook
from ..common import reconcilehelper as rh
from ..core.constants import STATEFULSET_LABEL
from ..kube import ApiServer, KubeObject, NotFoundError, ObjectMeta, set_controller_reference
from ..tpu import env as tpuenv
from . import constants as C


def cluster_role_binding_name(nb: Notebook) -> str:
    # includes the namespace: CRB names are cluster-scoped
    # (notebook_kube_rbac_auth.go:290)
    return f"{nb.name}-rbac-{nb.namespace}-auth-delegator"


def new_notebook_service_account(nb: Notebook) -> KubeObject:
    """Dedicated SA the proxy runs as (notebook_kube_rbac_auth.go:48-92)."""
    return KubeObject(
        api_version="v1",
        kind="ServiceAccount",
        metadata=ObjectMeta(name=nb.name, namespace=nb.namespace),
        body={},
    )


def new_kube_rbac_proxy_service(nb: Notebook) -> KubeObject:
    """Service :8443 -> sidecar port; the serving-cert annotation makes
    OpenShift mint the TLS secret the sidecar mounts
    (notebook_kube_rbac_auth.go:95-159)."""
    return KubeObject(
        api_version="v1",
        kind="Service",
        metadata=ObjectMeta(
            name=nb.name + C.KUBE_RBAC_PROXY_SERVICE_SUFFIX,
            namespace=nb.namespace,
            annotations={
                C.SERVING_CERT_ANNOTATION: nb.name + C.KUBE_RBAC_PROXY_TLS_SECRET_SUFFIX
            },
        ),
        body={
            "spec": {
                "type": "ClusterIP",
                # select only slice 0's StatefulSet pods — the workers where
                # JupyterLab runs — matching the plain notebook Service; the
                # notebook-name label would catch every TPU worker of every
                # slice and round-robin auth traffic across them
                "selector": {
                    STATEFULSET_LABEL: tpuenv.statefulset_name(
                        nb.name, 0, nb.tpu.slices if nb.tpu else 1
                    )
                },
                "ports": [
                    {
                        "name": C.KUBE_RBAC_PROXY_PORT_NAME,
                        "port": C.KUBE_RBAC_PROXY_PORT,
                        "targetPort": C.KUBE_RBAC_PROXY_PORT_NAME,
                        "protocol": "TCP",
                    }
                ],
            }
        },
    )


def new_kube_rbac_proxy_configmap(nb: Notebook) -> KubeObject:
    """Proxy config: authorize by SubjectAccessReview `get
    notebooks.kubeflow.org/{name}` in the notebook namespace
    (notebook_kube_rbac_auth.go:180-282)."""
    config = (
        "authorization:\n"
        "  resourceAttributes:\n"
        "    apiGroup: " + GROUP + "\n"
        "    apiVersion: v1\n"
        "    resource: notebooks\n"
        "    verb: get\n"
        f"    namespace: {nb.namespace}\n"
        f"    name: {nb.name}\n"
    )
    return KubeObject(
        api_version="v1",
        kind="ConfigMap",
        metadata=ObjectMeta(
            name=nb.name + C.KUBE_RBAC_PROXY_CONFIG_SUFFIX, namespace=nb.namespace
        ),
        body={"data": {C.KUBE_RBAC_PROXY_CONFIG_FILE: config}},
    )


def new_cluster_role_binding(nb: Notebook) -> KubeObject:
    """Grants the notebook SA the TokenReview/SubjectAccessReview powers the
    proxy needs (system:auth-delegator).  Cluster-scoped: modeled with an
    empty namespace; no owner ref possible
    (notebook_kube_rbac_auth.go:287-311)."""
    return KubeObject(
        api_version="rbac.authorization.k8s.io/v1",
        kind="ClusterRoleBinding",
        metadata=ObjectMeta(
            name=cluster_role_binding_name(nb),
            labels={
                C.NOTEBOOK_NAME_LABEL: nb.name,
                C.NOTEBOOK_NAMESPACE_LABEL: nb.namespace,
            },
        ),
        body={
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "system:auth-delegator",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": nb.name,
                    "namespace": nb.namespace,
                }
            ],
        },
    )


def reconcile_auth_resources(api: ApiServer, nb: Notebook) -> None:
    """Auth-mode object set, ordered as the reference's auth branch
    (odh notebook_controller.go:443-497): SA -> CRB -> ConfigMap -> Service.
    The HTTPRoute variant is reconciled by the caller via routing.py."""
    sa = new_notebook_service_account(nb)
    set_controller_reference(nb.obj, sa)
    found = api.try_get("ServiceAccount", nb.namespace, sa.name)
    if found is None:
        api.create(sa)

    crb = new_cluster_role_binding(nb)
    if api.try_get("ClusterRoleBinding", "", crb.name) is None:
        api.create(crb)

    cm = new_kube_rbac_proxy_configmap(nb)
    set_controller_reference(nb.obj, cm)
    rh.reconcile_object(api, cm, rh.copy_data)

    svc = new_kube_rbac_proxy_service(nb)
    set_controller_reference(nb.obj, svc)
    rh.reconcile_object(api, svc, rh.copy_service_fields)


def cleanup_cluster_role_binding(api: ApiServer, nb: Notebook) -> None:
    """Manual CRB deletion — no GC for cluster-scoped dependents of a
    namespaced owner (notebook_kube_rbac_auth.go:346-368)."""
    try:
        api.delete("ClusterRoleBinding", "", cluster_role_binding_name(nb))
    except NotFoundError:
        pass
