"""KV-cache generation: decode must agree with teacher forcing.

The load-bearing check: greedy decode built token-by-token through the
cache must reproduce exactly the tokens obtained by re-running the FULL
prefix through the training forward at every step (no cache).  A stale
cache slot, a wrong rope position, or a mask off-by-one diverges the two
within a few tokens.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.generate import decode_config, generate, sample_token
from kubeflow_tpu.models.transformer import Transformer


def _init_params(cfg, rng=0):
    import flax.linen as nn

    from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
    from kubeflow_tpu.parallel.sharding import rules_for_mesh

    mesh = make_mesh(MeshConfig(data=8))
    model = Transformer(decode_config(cfg))
    with nn.logical_axis_rules(list(rules_for_mesh(mesh))):
        return model.init(jax.random.PRNGKey(rng),
                          jnp.ones((1, 8), jnp.int32))["params"]


class TestGenerate:
    def test_greedy_decode_matches_teacher_forcing(self):
        cfg = TINY
        params = _init_params(cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                    cfg.vocab_size)
        n_new = 6
        out = generate(cfg, params, prompt, max_new_tokens=n_new)
        assert out.shape == (2, 5 + n_new)
        np.testing.assert_array_equal(np.asarray(out[:, :5]),
                                      np.asarray(prompt))

        # teacher forcing: rebuild the same continuation with full forwards
        model = Transformer(decode_config(cfg))
        seq = prompt
        for _ in range(n_new):
            logits = model.apply({"params": params}, seq)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)
            seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(seq))

    def test_accepts_stacked_training_params(self):
        """Train-then-serve: params from a scan_layers=True TRAINING run
        (stacked 'layers' subtree) must decode identically to the unrolled
        decode layout — generate converts the tree on the fly."""
        cfg = TINY  # scan_layers=True: the training layout
        train_model = Transformer(cfg)
        stacked = train_model.init(jax.random.PRNGKey(0),
                                   jnp.ones((1, 8), jnp.int32))["params"]
        assert "layers" in stacked  # really the stacked layout
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg, stacked, prompt, max_new_tokens=4)
        assert out.shape == (2, 9)

        # the same weights pre-unrolled give the same tokens
        from kubeflow_tpu.models.generate import unroll_params

        unrolled = unroll_params(stacked, cfg.num_layers)
        out2 = generate(cfg, unrolled, prompt, max_new_tokens=4)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))

    def test_moe_config_decodes(self):
        """The KV-cache decode path composes with MoE layers (DecoderLayer
        returns (x, aux) there; the unrolled decode stack must thread it)."""
        cfg = TINY.with_(moe_experts=4, moe_top_k=2, moe_capacity_factor=4.0)
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg, params, prompt, max_new_tokens=4)
        assert out.shape == (2, 9)
        assert jnp.isfinite(out).sum() == out.size  # int tokens, all valid
        assert int(out.max()) < cfg.vocab_size

    def test_single_new_token(self):
        cfg = TINY
        params = _init_params(cfg)
        prompt = jnp.ones((1, 4), jnp.int32)
        out = generate(cfg, params, prompt, max_new_tokens=1)
        assert out.shape == (1, 5)

    def test_temperature_sampling_reproducible_and_in_range(self):
        cfg = TINY
        params = _init_params(cfg)
        prompt = jnp.ones((2, 4), jnp.int32)
        a = generate(cfg, params, prompt, max_new_tokens=5, temperature=1.0,
                     top_k=8, rng=jax.random.PRNGKey(7))
        b = generate(cfg, params, prompt, max_new_tokens=5, temperature=1.0,
                     top_k=8, rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(jnp.max(a)) < cfg.vocab_size and int(jnp.min(a)) >= 0

    def test_length_guard(self):
        cfg = TINY
        params = _init_params(cfg)
        prompt = jnp.ones((1, cfg.max_seq_len - 2), jnp.int32)
        import pytest

        with pytest.raises(ValueError, match="max_seq_len"):
            generate(cfg, params, prompt, max_new_tokens=8)

    def test_sample_token_greedy_vs_topk(self):
        logits = jnp.array([[0.0, 5.0, 1.0, 2.0]])
        assert int(sample_token(logits, None, 0.0)[0]) == 1
        # top-1 sampling degenerates to greedy regardless of rng
        tok = sample_token(logits, jax.random.PRNGKey(0), 1.0, top_k=1)
        assert int(tok[0]) == 1

    def test_works_with_gqa_and_tied_embeddings(self):
        cfg = TINY.with_(tie_embeddings=True, logits_softcap=30.0)
        params = _init_params(cfg, rng=3)
        prompt = jnp.ones((1, 4), jnp.int32)
        out = generate(cfg, params, prompt, max_new_tokens=4)
        assert out.shape == (1, 8)


class TestFusedProjections:
    """decode_config fuses q/k/v and gate/up into single matmuls (launch-
    overhead cut); the fused tree must produce IDENTICAL decode output to
    the unfused layout, raw and quantized."""

    def test_fused_matches_unfused_decode(self):
        from kubeflow_tpu.models.configs import TINY

        cfg = TINY
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0,
                                    cfg.vocab_size)
        # fused (the default path: generate fuses the training tree)
        out_fused = generate(cfg, params, prompt, max_new_tokens=8)
        # unfused decode: same decode semantics, training param layout
        from kubeflow_tpu.models.generate import unroll_params

        ucfg = decode_config(cfg).with_(fused_projections=False)
        uparams = unroll_params(params, cfg.num_layers)
        out_unfused = generate(ucfg, uparams, prompt, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(out_fused),
                                      np.asarray(out_unfused))

    def test_fused_then_quantized_tracks_unfused_quantized(self):
        from kubeflow_tpu.models.configs import TINY
        from kubeflow_tpu.models.generate import (
            fuse_decode_params,
            unroll_params,
        )
        from kubeflow_tpu.models.quant import quantize_params

        cfg = TINY
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0,
                                    cfg.vocab_size)
        dcfg = decode_config(cfg)
        fused_q = quantize_params(
            fuse_decode_params(unroll_params(params, cfg.num_layers), dcfg))
        out_fq = generate(dcfg.with_(weight_dtype="int8"), fused_q, prompt,
                          max_new_tokens=8)
        # the unfused-quantized fallback (old pipeline)
        unfused_q = quantize_params(unroll_params(params, cfg.num_layers))
        out_uq = generate(
            dcfg.with_(weight_dtype="int8", fused_projections=False),
            unfused_q, prompt, max_new_tokens=8)
        assert out_fq.shape == out_uq.shape == (2, 14)
        # int8 scale granularity differs slightly between layouts (fused
        # shares scales across q/k/v); greedy tokens still agree on the
        # easy TINY margin
        agree = float(np.mean(np.asarray(out_fq) == np.asarray(out_uq)))
        assert agree > 0.9, agree


class TestStagedKv:
    """Staged KV writes (decode_config default) must be token-identical
    to the unstaged path across prompt tail alignments and enough steps
    to cross several 8-row flush boundaries."""

    @pytest.mark.parametrize("prompt_len", [8, 10, 13])
    def test_staged_matches_unstaged(self, prompt_len):
        from kubeflow_tpu.models.configs import TINY

        cfg = TINY
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, prompt_len),
                                    0, cfg.vocab_size)
        n_new = 21  # crosses >=2 flush boundaries from any tail offset
        staged = generate(cfg, params, prompt, max_new_tokens=n_new)
        ucfg = decode_config(cfg).with_(staged_kv=False)
        from kubeflow_tpu.models.generate import prepare_decode

        _, uparams = prepare_decode(cfg, params)
        unstaged = generate(ucfg, uparams, prompt, max_new_tokens=n_new)
        # the staged softmax reduces over an S+8 score axis (split p@V
        # sums), so bitwise equality is reassociation luck on some
        # backends; near-tie argmax flips are the only tolerated diffs
        agree = float(np.mean(np.asarray(staged) == np.asarray(unstaged)))
        assert agree >= 0.95, agree

    def test_multi_token_decode_at_nonzero_cur_matches_unstaged(self):
        """Chunked prefill / verify-style multi-token calls at cur>0: rows
        [flushed, cur) live only in the stage, and the multi-token branch
        must flush them into the main cache before attending — they used
        to silently read as zeros (ADVICE round 5)."""
        from kubeflow_tpu.models.configs import TINY

        cfg = decode_config(TINY)
        assert cfg.staged_kv
        ucfg = cfg.with_(staged_kv=False)
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        toks = jax.random.randint(jax.random.PRNGKey(5), (2, 15),
                                  0, cfg.vocab_size)
        # single-token steps leave a live stage (cur=7, slots [0,7));
        # the 6-token chunk at cur=7 is the hazard case, and the single
        # steps after it verify the re-seeded stage invariant holds
        chunks = [5, 1, 1, 6, 1, 1]

        def run(c):
            model = Transformer(c)
            cache: dict = {}
            outs = []
            pos = 0
            for n in chunks:
                seg = toks[:, pos:pos + n]
                kw = {}
                if pos:
                    kw["positions"] = jnp.broadcast_to(
                        pos + jnp.arange(n)[None, :], (2, n))
                (logits, _), cache = model.apply(
                    {"params": params, **cache}, seg, return_aux=True,
                    decode=True, mutable=["cache"], **kw)
                outs.append(np.asarray(logits))
                pos += n
            return outs

        staged_outs = run(cfg)
        unstaged_outs = run(ucfg)
        # reading the stage rows as zeros collapses agreement to chance;
        # correct flushing leaves only reassociation-level argmax flips
        for i, (s, u) in enumerate(zip(staged_outs, unstaged_outs)):
            agree = float(np.mean(s.argmax(-1) == u.argmax(-1)))
            assert agree >= 0.95, (i, agree)

    def test_staged_kv_requires_aligned_max_seq_len(self):
        from kubeflow_tpu.models.configs import TINY

        cfg = decode_config(TINY).with_(max_seq_len=30)
        with pytest.raises(ValueError, match="max_seq_len"):
            Transformer(cfg).init(
                jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32),
                decode=True)

    def test_decode_marker_preserves_explicit_choices(self):
        """already_decode keys on the explicit decode marker: a training
        config that merely looks decode-ish (remat off, xla attention)
        still gets the decode defaults, while a decode_config product
        keeps its explicit overrides (ADVICE round 5)."""
        from kubeflow_tpu.models.configs import TINY

        trainish = TINY.with_(remat=False, attention_impl="xla")
        d = decode_config(trainish)
        assert d.decode and d.fused_projections and d.staged_kv
        # explicit opt-outs on a decode-shaped config survive re-entry
        explicit = d.with_(staged_kv=False, fused_projections=False)
        d2 = decode_config(explicit)
        assert not d2.staged_kv and not d2.fused_projections
