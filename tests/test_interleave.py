"""Bounded-exhaustive model checking of the three core concurrency
protocols (testing/interleave.py): warm-pool claim/release under racing
schedulers, workqueue park/re-dispatch under racing workers, and the
self-healing write-ahead restore protocol under manager failover.

Each protocol test enumerates thousands of DISTINCT schedules (CHESS
iterative preemption bounding + sleep-set pruning over the
INVARIANTS_STRICT yield points) and asserts its invariant holds on every
one.  The seeded-mutant tests then prove the harness can actually FAIL:
a textual mutant deleting the write-ahead bookkeeping (selfheal) or
reordering the claim commit after the intent write (scheduler) must be
caught by a failing schedule that shrinks to a handful of preemption
directives — the same mutants ci/analyzers/write_ahead.py flags
statically.

The suite is control-plane only (no jax import) and honours the CI
budget knobs INTERLEAVE_MAX_SCHEDULES / INTERLEAVE_BUDGET_S
(utils/config.py); ci/chaos_soak.sh raises them for deep exploration.
"""

from __future__ import annotations

import importlib
import json
import os
import sys
import types
from collections import Counter

import pytest

from kubeflow_tpu.api.types import Notebook, ReplicationSpec, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.scheduler import SliceScheduler, pool_object_name
from kubeflow_tpu.core.selfheal import RecoveryEngine
from kubeflow_tpu.core.sessionstate import (
    InMemorySessionStore,
    StaleWriterError,
)
from kubeflow_tpu.kube import (
    ApiServer,
    KubeObject,
    Manager,
    ObjectMeta,
    Request,
)
from kubeflow_tpu.kube.events import EventRecorder
from kubeflow_tpu.testing.interleave import InterleavingExplorer, await_cond
from kubeflow_tpu.utils import invariants
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig

SPEC = TPUSpec("v5e", "4x4")
POOL_NAME = pool_object_name("v5e", "4x4")

# acceptance floor: every protocol test must cover at least this many
# distinct schedules inside the CI budget
MIN_SCHEDULES = 1000


@pytest.fixture(autouse=True)
def _strict(monkeypatch):
    """The yield points the explorer schedules on only exist when the
    sanitizer substrate is armed (invariants.tracked returns the raw lock
    otherwise).  Scenario factories construct their ApiServer/Manager
    inside the fixture's scope, so the flag is read when it is set."""
    monkeypatch.setenv("INVARIANTS_STRICT", "1")


def _budget():
    """CI budget knobs, via the same env parsing production uses."""
    cfg = CoreConfig.from_env(dict(os.environ))
    return cfg.interleave_max_schedules, cfg.interleave_budget_s


def _explore(scenario, *, max_preemptions=2, min_schedules=MIN_SCHEDULES):
    max_schedules, budget_s = _budget()
    ex = InterleavingExplorer(
        scenario, max_preemptions=max_preemptions,
        max_schedules=max_schedules, budget_s=budget_s)
    res = ex.explore()
    assert res.ok, "invariant violated:\n%s" % res.failure.narrative
    assert res.schedules >= min_schedules, (
        "explored only %d distinct schedules (%s after %d runs; floor %d)"
        % (res.schedules, res.stopped, res.runs, min_schedules))
    return ex, res


# -- protocol A: warm-pool claim/release ---------------------------------------
def _scheduler_cfg():
    return CoreConfig.from_env({
        "ENABLE_SLICE_SCHEDULER": "true",
        "WARMPOOL_SIZE": "0",
        "WARMPOOL_PROVISION_S": "120",
    })


def warmpool_scenario():
    """Two schedulers race to claim from a 2-slice Ready pool for two
    notebooks.  Every schedule must end with the two claims DISJOINT
    (chips never double-sold) and both PRESENT (claims never lost across
    conflict retries), each matching its notebook's placement intent."""
    api = ApiServer()
    clock = FakeClock()
    cfg = _scheduler_cfg()
    metrics = NotebookMetrics(api)
    api.create(KubeObject(
        api_version="kubeflow.org/v1", kind=C.WARMPOOL_KIND,
        metadata=ObjectMeta(name=POOL_NAME),
        body={"spec": {"accelerator": "v5e", "topology": "4x4"},
              "status": {"slices": {
                  "ws-0001": {"state": "Ready", "pool": "warm-a"},
                  "ws-0002": {"state": "Ready", "pool": "warm-b"},
              }}}))
    names = ("nb-a", "nb-b")
    for name in names:
        api.create(Notebook.new(name, "default", tpu=SPEC).obj)
    scheds = {name: SliceScheduler(api, cfg, metrics, clock=clock)
              for name in names}

    def reconciler(name):
        def run():
            scheds[name].reconcile(Request("default", name))
        return run

    def check():
        pool = api.get(C.WARMPOOL_KIND, "", POOL_NAME)
        slices = (pool.body.get("status") or {}).get("slices") or {}
        owners: dict[str, list[str]] = {}
        for sid, e in slices.items():
            if e.get("claimedBy"):
                owners.setdefault(e["claimedBy"], []).append(sid)
        intent_pools = {}
        for name in names:
            ann = api.get("Notebook", "default", name) \
                .metadata.annotations.get(C.ANNOTATION_PLACEMENT)
            assert ann, f"{name}: placement intent lost"
            intent_pools[name] = {
                e["pool"]
                for e in json.loads(ann)["slices"].values()}
        # never double-sold: the two intents reference disjoint capacity
        assert not (intent_pools["nb-a"] & intent_pools["nb-b"]), (
            "double-sold: %r" % intent_pools)
        # never lost: both notebooks hold exactly one persisted claim
        assert sorted(owners) == ["default/nb-a", "default/nb-b"], (
            "claims lost or leaked: %r" % owners)
        for name in names:
            sids = owners[f"default/{name}"]
            assert len(sids) == 1, (name, sids)
            assert slices[sids[0]]["pool"] in intent_pools[name], (
                "claim/intent mismatch for %s: %r vs %r"
                % (name, slices[sids[0]]["pool"], intent_pools[name]))

    return [(name, reconciler(name)) for name in names], check


def test_warmpool_claims_hold_under_all_schedules():
    _explore(warmpool_scenario)


# -- protocol B: workqueue park / re-dispatch ----------------------------------
def workqueue_scenario():
    """A producer enqueues keys (including a re-enqueue of a key that may
    be in flight) while two workers pop/process/done.  Every schedule
    must keep the per-key serialization contract: no key is ever
    processed by two workers at once (park, don't double-dispatch) and no
    dirty key is dropped (re-queue on done)."""
    api = ApiServer()
    clock = FakeClock()
    mgr = Manager(api, clock=clock)
    mgr.register("wq", lambda req: None, "Notebook")
    keys = ("k1", "k2")
    done = [False]
    inflight: set = set()
    processed: list[str] = []

    def has_work():
        return done[0] or any(mgr._queues.values())

    def producer():
        for name in keys:
            mgr.enqueue("wq", Request("ns", name))
        # dirty re-add: if k1 is mid-flight this must PARK and re-queue
        # on _done, never dispatch a second concurrent reconcile
        mgr.enqueue("wq", Request("ns", keys[0]))
        done[0] = True

    def worker():
        while True:
            await_cond("work-available", has_work)
            item = mgr._pop()
            if item is None:
                if done[0] and not any(mgr._queues.values()):
                    return
                continue
            assert item not in inflight, (
                "duplicate in-flight key: %r" % (item,))
            inflight.add(item)
            processed.append(item[1].name)
            inflight.discard(item)
            mgr._done(item)

    def check():
        assert not mgr._queued, "dirty keys dropped: %r" % mgr._queued
        assert not mgr._processing, (
            "in-flight keys leaked: %r" % mgr._processing)
        assert not any(mgr._queues.values()), "queued work left behind"
        counts = Counter(processed)
        for name in keys:
            assert counts[name] >= 1, (
                "key %s never processed: %r" % (name, processed))
        # the re-enqueue is processed at most once more (dedup while
        # queued, park+redispatch while in flight)
        assert counts[keys[0]] <= 2, processed

    return [("producer", producer), ("worker-1", worker),
            ("worker-2", worker)], check


def test_workqueue_park_redispatch_under_all_schedules():
    _explore(workqueue_scenario)


# -- protocol C: write-ahead restore vs manager failover -----------------------
def _failed_pod(name):
    return KubeObject(
        api_version="v1", kind="Pod",
        metadata=ObjectMeta(name=name, namespace="u1"),
        body={"spec": {}, "status": {"phase": "Failed"}})


def _selfheal_scenario(engine_cls):
    """Two recovery engines (the manager and its failover twin) race
    maybe_recover for the same disrupted slice.  The write-ahead protocol
    must guarantee, on EVERY schedule: no pod restart before the restore
    intent and the attempt charge are persisted, the restored generation
    is never a retired one, and no engine restores twice."""
    api = ApiServer()
    clock = FakeClock()
    cfg = CoreConfig()
    metrics = NotebookMetrics(api)
    store = InMemorySessionStore(clock=clock)
    snap = store.put("u1", "heal", 0, b"session", trigger="interval")
    nb = Notebook.new("heal", "u1", tpu=SPEC)
    api.create(nb.obj)
    pods = [_failed_pod("heal-0-0")]
    restarts: list[str] = []
    stamped: list[object] = []

    def persisted_session():
        status = api.get("Notebook", "u1", "heal").body.get("status") or {}
        return ((status.get("sessionState") or {}).get("0") or {},
                (status.get("sliceRecovery") or {}).get("0") or {})

    def make_callbacks(mgr_name):
        def restart_slice(live_name):
            sess, rec = persisted_session()
            # the write-ahead core: by the time any pod dies, failover
            # can resume the migration from status alone
            assert sess.get("phase") == "migrating", (
                "%s: restart before the restore intent was persisted "
                "(sessionState=%r)" % (mgr_name, sess))
            assert sess.get("restoreGeneration") == snap.generation, (
                "%s: restoring retired generation %r (live is %d)"
                % (mgr_name, sess.get("restoreGeneration"),
                   snap.generation))
            assert rec.get("attempts"), (
                "%s: restart before the attempt charge was persisted"
                % mgr_name)
            restarts.append(mgr_name)

        def stamp_restore(live_name, idx):
            sess, _rec = persisted_session()
            stamped.append(sess.get("restoreGeneration"))

        return restart_slice, stamp_restore

    engines = {}
    for mgr_name in ("mgr-a", "mgr-b"):
        engines[mgr_name] = engine_cls(
            api, cfg, metrics, EventRecorder(api, mgr_name),
            clock=clock, session=store)

    def recover(mgr_name):
        restart_slice, stamp_restore = make_callbacks(mgr_name)

        def run():
            engines[mgr_name].maybe_recover(
                Notebook(api.get("Notebook", "u1", "heal")),
                ["heal-0"], lambda live_name: pods,
                restart_slice, stamp_restore=stamp_restore)
        return run

    def check():
        sess, rec = persisted_session()
        assert sess.get("phase") == "migrating", sess
        assert sess.get("restoreGeneration") == snap.generation, sess
        assert rec.get("attempts"), rec
        assert 1 <= len(restarts) <= 2, restarts
        # never restore twice: each engine executes at most one restart,
        # and every stamped restore targets the one live generation
        assert all(n == 1 for n in Counter(restarts).values()), restarts
        assert stamped and all(g == snap.generation for g in stamped), (
            stamped)

    return [("mgr-a", recover("mgr-a")), ("mgr-b", recover("mgr-b"))], check


def migrate_scenario():
    return _selfheal_scenario(RecoveryEngine)


def test_write_ahead_restore_under_all_schedules():
    _explore(migrate_scenario)


# -- protocol E: epoch-fenced primary promotion --------------------------------
def _promote_scenario(engine_cls):
    """Two recovery engines (the manager and its failover twin) race the
    promote verb for a replicated notebook whose primary gang died, while
    a zombie primary (gated to fire only after a promotion completed)
    keeps appending deltas with the OLD epoch.  Every schedule must keep
    the fenced-election contract: the write-ahead promotion record is
    persisted before the store fence ever rises (asserted at the fence
    call itself), the membership change is exactly one epoch bump with a
    completed promotion record, and every zombie write is rejected with
    StaleWriterError — no kernel-state write can land after demotion."""
    api = ApiServer()
    clock = FakeClock()
    cfg = CoreConfig()
    metrics = NotebookMetrics(api)

    class _WriteAheadCheckedStore(InMemorySessionStore):
        def fence(self, namespace, notebook, epoch):
            status = api.get("Notebook", namespace, notebook) \
                .body.get("status") or {}
            promo = (status.get("replication") or {}).get("promotion") or {}
            assert promo.get("epoch") == epoch and \
                promo.get("phase") in ("promoting", "promoted"), (
                    "fence raised to %d before the promotion record was "
                    "persisted (promotion=%r)" % (epoch, promo))
            return super().fence(namespace, notebook, epoch)

    store = _WriteAheadCheckedStore(clock=clock)
    store.put("u1", "rep", 0, b"base", writer_epoch=1)
    store.append_delta("u1", "rep", 0, b"+d1", writer_epoch=1)
    store.append_delta("u1", "rep", 0, b"+d2", writer_epoch=1)
    head_gen, head_seq, head_digest = store.chain_head("u1", "rep", 0)

    nb = Notebook.new("rep", "u1", tpu=SPEC,
                      replication=ReplicationSpec(replicas=2))
    created = api.create(nb.obj)
    created.status = {"replication": {"epoch": 1, "primary": 0}}
    api.update_status(created)

    follower_pods = [
        KubeObject(
            api_version="v1", kind="Pod",
            metadata=ObjectMeta(
                name="rep-r1-%d" % i, namespace="u1",
                annotations={
                    C.ANNOTATION_REPLICA_GENERATION: str(head_gen),
                    C.ANNOTATION_REPLICA_SEQ: str(head_seq),
                    C.ANNOTATION_REPLICA_DIGEST: head_digest,
                }),
            body={"spec": {}, "status": {
                "phase": "Running",
                "conditions": [{"type": "Ready", "status": "True"}],
            }})
        for i in range(SPEC.shape.num_hosts)
    ]
    gang_pods = {"rep": [_failed_pod("rep-0")], "rep-r1": follower_pods}

    promoted_seen = [False]
    restarts: list[tuple[str, str]] = []
    zombie_attempts: list[str] = []
    zombie_successes: list[str] = []

    engines = {}
    for mgr_name in ("mgr-a", "mgr-b"):
        engines[mgr_name] = engine_cls(
            api, cfg, metrics, EventRecorder(api, mgr_name),
            clock=clock, session=store)

    def recover(mgr_name):
        def run():
            engines[mgr_name].maybe_recover(
                Notebook(api.get("Notebook", "u1", "rep")),
                ["rep", "rep-r1"],
                lambda live_name: gang_pods.get(live_name, []),
                lambda live_name: restarts.append((mgr_name, live_name)),
                stamp_restore=lambda live_name, idx: None)
            status = api.get("Notebook", "u1", "rep") \
                .body.get("status") or {}
            promo = (status.get("replication") or {}).get("promotion") or {}
            if promo.get("phase") == "promoted":
                promoted_seen[0] = True
        return run

    def zombie():
        # fires only after a promotion completed (plain-flag gate: the
        # await_cond predicate runs on the scheduler thread and may not
        # touch the store or apiserver) — by then the fence MUST hold
        await_cond("promoted", lambda: promoted_seen[0])
        zombie_attempts.append("d3")
        try:
            store.append_delta("u1", "rep", 0, b"+zombie", writer_epoch=1)
            zombie_successes.append("d3")
        except StaleWriterError:
            pass

    def check():
        status = api.get("Notebook", "u1", "rep").body.get("status") or {}
        rep = status.get("replication") or {}
        # exactly one committed epoch bump, promotion record terminal
        assert rep.get("epoch") == 2, (
            "epoch must bump exactly once: %r" % rep)
        assert rep.get("primary") == 1, rep
        promo = rep.get("promotion") or {}
        assert promo.get("phase") == "promoted", promo
        assert promo.get("from") == 0 and promo.get("to") == 1, promo
        assert store.fence_epoch("u1", "rep") == 2
        # the zombie primary got fenced, never through
        assert zombie_attempts and not zombie_successes, (
            "zombie write landed after demotion: %r" % zombie_successes)
        assert store.fenced_rejections.get(("u1", "rep"), 0) >= 1
        # at least one engine promoted; a racer resuming the in-flight
        # record may legitimately complete it too (idempotent flip)
        promoted = metrics.promotions.value("u1", "promoted")
        lost = metrics.promotions.value("u1", "lost-race")
        assert promoted >= 1, (promoted, lost)
        assert promoted + lost <= 2, (promoted, lost)
        # the chain head the election keyed on was never corrupted
        assert store.chain_head("u1", "rep", 0) == \
            (head_gen, head_seq, head_digest)

    return [("mgr-a", recover("mgr-a")), ("mgr-b", recover("mgr-b")),
            ("zombie", zombie)], check


def promote_scenario():
    return _promote_scenario(RecoveryEngine)


def test_promotion_fencing_under_all_schedules():
    _explore(promote_scenario)


# -- protocol D: sharded control-plane handoff ---------------------------------
def shard_handoff_scenario(shard_mod=None):
    """Replica A owns the whole keyspace; replica B joins after A's lease
    lapses (evicting it) while A's zombie threads keep writing.  Every
    schedule must keep the single-owner contract: each SUCCESSFUL
    notebook write was issued by the key's committed owner at the time
    the map was last read, every zombie write fences with StaleEpochError
    (and is counted), no key is dropped (B rewrites all of them), and the
    membership change is exactly one committed epoch bump whose handoff
    record completes.

    A's churn finishes before B's takeover begins — that sequencing is
    the renew-deadline contract from kube/leader.py, not a test
    convenience: a live member stops writing at its renew deadline,
    strictly before any peer may evict it, so check-then-write fencing
    never races a legitimate writer."""
    if shard_mod is None:
        shard_mod = importlib.import_module("kubeflow_tpu.kube.shard")
    api = ApiServer()
    clock = FakeClock()
    names = ("nb-a", "nb-b", "nb-c")
    for name in names:
        api.create(Notebook.new(name, "default").obj)
    a = shard_mod.ShardedReplica(api, "shard-a", clock=clock)
    b = shard_mod.ShardedReplica(api, "shard-b", clock=clock)
    a.join_fleet()
    a_quiet = [False]
    b_committed = [False]
    owner_log: list[tuple] = []        # (writer, key, committed owner)
    zombie_attempts: list[str] = []
    zombie_successes: list[str] = []

    def touch(replica, writer):
        for name in names:
            obj = api.get("Notebook", "default", name)
            obj.metadata.annotations["touched-by"] = writer
            replica.fenced.update(obj)
            members = sorted(
                replica.member.read_status().get("members") or {})
            owner_log.append((
                writer, name,
                shard_mod.HashRing(members).owner_of("default", name)))

    def a_churn():
        touch(a, "shard-a")
        a_quiet[0] = True

    def b_join():
        await_cond("a-quiet", lambda: a_quiet[0])
        clock.advance(a.member.lease_duration_s + 1)
        b.join_fleet()      # ONE commit: eviction + admission + handoff
        b_committed[0] = True
        touch(b, "shard-b")

    def a_zombie():
        # await_cond predicates run on the scheduler thread, so they may
        # only read plain Python state published by logical threads —
        # touching the store here would deadlock against a paused thread
        # holding a store lock.
        await_cond("deposed", lambda: b_committed[0])
        for name in names:
            zombie_attempts.append(name)
            try:
                obj = api.get("Notebook", "default", name)
                obj.metadata.annotations["touched-by"] = "zombie"
                a.fenced.update(obj)
                zombie_successes.append(name)
            except shard_mod.StaleEpochError:
                pass

    def check():
        assert not zombie_successes, (
            "stale-epoch writes landed: %r" % zombie_successes)
        assert a.fenced.rejected_total == len(zombie_attempts) == \
            len(names), (a.fenced.rejected_total, zombie_attempts)
        for writer, name, owner in owner_log:
            assert writer == owner, (
                "successful write by a non-owner: %s wrote %s owned by %s"
                % (writer, name, owner))
        status = a.member.read_status()
        assert sorted(status.get("members") or {}) == ["shard-b"], status
        assert status.get("epoch") == 2, (
            "membership change must be exactly one epoch bump: %r"
            % status.get("epoch"))
        assert not status.get("handoffs"), (
            "handoff record left open: %r" % status.get("handoffs"))
        assert (status.get("lastHandoff") or {}).get("epoch") == 2, status
        for name in names:                    # no key dropped
            ann = api.get("Notebook", "default", name) \
                .metadata.annotations.get("touched-by")
            assert ann == "shard-b", (name, ann)

    return [("a-churn", a_churn), ("b-join", b_join),
            ("a-zombie", a_zombie)], check


def test_shard_handoff_single_owner_under_all_schedules():
    _explore(shard_handoff_scenario)


def shard_concurrent_join_scenario(shard_mod=None):
    """TWO replicas join an established fleet simultaneously — both
    per-change handoff records are pending at once, which is exactly the
    case the stable-ring drain gate exists for (a single previous-ring
    snapshot is the wrong gate when changes overlap).

    Every schedule must keep the INSTANTANEOUS single-owner contract:
    the dispatch filter never admits a key on two replicas at once.
    Each thread models reconcile windows explicitly — a key the filter
    admits is held in a shared map across a preemption point; a second
    holder is an overlap.  The schedule-independent end state: both
    records complete, and ownership is an exact partition by the final
    ring."""
    if shard_mod is None:
        shard_mod = importlib.import_module("kubeflow_tpu.kube.shard")
    api = ApiServer()
    clock = FakeClock()
    # one namespace per final owner (a: team-3, b: team-0, c: team-1
    # on the shard-a/b/c ring) — the smallest keyspace where BOTH
    # joiners gain keys and the survivor keeps one, kept small so the
    # bounded DFS covers the run's opening steps within its budget
    keys = [("team-0", "nb-0"), ("team-1", "nb-1"), ("team-3", "nb-3")]
    for ns, name in keys:
        api.create(Notebook.new(name, ns).obj)
    replicas = {sid: shard_mod.ShardedReplica(api, sid, clock=clock)
                for sid in ("shard-a", "shard-b", "shard-c")}
    a = replicas["shard-a"]
    a.join_fleet()
    joined = {"shard-b": False, "shard-c": False}
    holders: dict = {}
    # the committed pending-record list, published to plain Python state
    # at every map commit (the in-process watch fires on the committing
    # thread) so await_cond predicates may read it without touching the
    # apiserver from the scheduler thread.  Commit fan-out happens
    # outside the store lock, so two writers' events can arrive out of
    # commit order — mirror by resourceVersion, exactly like the
    # replicas' own rv-guarded _install_status.
    records_view: list = [list(a.member.read_status().get("handoffs")
                               or []), 0]

    def mirror_map(ev):
        rv = ev.obj.metadata.resource_version
        if rv <= records_view[1]:
            return
        records_view[1] = rv
        records_view[0] = list(
            (ev.obj.body.get("status") or {}).get("handoffs") or [])

    api.watch(mirror_map, kinds=[shard_mod.SHARD_MAP_KIND])

    def dispatch_pass(sid):
        replica = replicas[sid]
        for key in keys:
            if replica.owns_key(*key):
                cur = holders.setdefault(key, set())
                assert not cur, (
                    "single-owner violation: %s dispatched %r while %r "
                    "held it" % (sid, key, sorted(cur)))
                cur.add(sid)
                invariants.yield_point("shard.window", (sid,) + key)
                cur.discard(sid)

    def run_survivor():
        dispatch_pass("shard-a")
        await_cond("a-sees-joins",
                   lambda: joined["shard-b"] and joined["shard-c"])
        # one RMW acks shard-a out of EVERY pending record's drains
        a.sync()
        dispatch_pass("shard-a")

    def run_joiner(sid):
        replica = replicas[sid]
        view = replica.member.join()
        replica._install_status(view, rv=replica.member.last_commit_rv)
        joined[sid] = True
        dispatch_pass(sid)
        await_cond(sid + "-sees-joins",
                   lambda: joined["shard-b"] and joined["shard-c"])
        replica.sync()  # ack own drains for the other joiner's record
        dispatch_pass(sid)
        await_cond(sid + "-grants-drained", lambda: not any(
            h.get("drains") for h in records_view[0]
            if sid in (h.get("adopters") or ())))
        replica.sync()  # adopt the gained keys, ack out of the record
        dispatch_pass(sid)

    def check():
        status = a.member.read_status()
        assert sorted(status.get("members") or {}) == \
            ["shard-a", "shard-b", "shard-c"], status
        assert status.get("epoch") == 3, status
        assert not status.get("handoffs"), (
            "a per-change record was left open: %r"
            % status.get("handoffs"))
        assert status.get("lastHandoff"), status
        ring = shard_mod.HashRing(sorted(status["members"]))
        for key in keys:
            owners = [sid for sid, r in replicas.items()
                      if r.owns_key(*key)]
            assert owners == [ring.owner_of(*key)], (key, owners)

    return [("a-run", run_survivor),
            ("b-join", lambda: run_joiner("shard-b")),
            ("c-join", lambda: run_joiner("shard-c"))], check


def test_shard_concurrent_joins_single_owner_under_all_schedules():
    _explore(shard_concurrent_join_scenario)


# -- byte-exact replay ---------------------------------------------------------
def test_replay_is_byte_identical():
    ex = InterleavingExplorer(warmpool_scenario)
    base = ex.replay(())          # the default run-until-blocked schedule
    again = ex.replay(base.choices)
    assert not base.failed and not again.failed
    assert again.choices == base.choices
    assert ex.render(again.trace) == ex.render(base.trace)
    # a schedule that DIVERGES from the default at the first branchy step
    # must also replay byte-identically
    for i, (enabled, _ops, chosen) in enumerate(base.nodes):
        alts = [t for t in enabled if t != chosen]
        if alts:
            forked = tuple(base.choices[:i]) + (alts[0],)
            break
    else:
        pytest.skip("scenario never had two enabled threads")
    r1 = ex.replay(forked)
    r2 = ex.replay(r1.choices)
    assert r1.choices == r2.choices
    assert ex.render(r1.trace) == ex.render(r2.trace)
    assert ex.render(r1.trace) != ex.render(base.trace)


# -- seeded mutants: the harness must be falsifiable ---------------------------
def _load_mutant(module: str, mutations, name: str):
    """Compile a textually mutated copy of `module` under a fresh module
    name (same package, so relative imports resolve)."""
    src_path = importlib.import_module(module).__file__
    with open(src_path, encoding="utf-8") as fh:
        src = fh.read()
    for old, new in mutations:
        assert src.count(old) == 1, (
            "mutation anchor not unique in %s: %r" % (module, old))
        src = src.replace(old, new)
    mod = types.ModuleType(name)
    mod.__package__ = module.rsplit(".", 1)[0]
    mod.__file__ = src_path
    sys.modules[name] = mod
    try:
        exec(compile(src, src_path, "exec"), mod.__dict__)
    finally:
        sys.modules.pop(name, None)
    return mod


# Mutant A: delete the write-ahead bookkeeping in maybe_recover — the
# budget charge and restore intent no longer persist before pod deletes.
MUTANT_A = [(
    """            self._write_bookkeeping(nb, recovery, exhausted, session_state,
                                    replication=replication,
                                    skip_if_unchanged=(prev_recovery,
                                                       prev_session,
                                                       prev_replication))""",
    "            pass  # MUTANT A: write-ahead bookkeeping dropped",
)]

# Mutant B: reorder the claim commit after the intent write in _place —
# the pool status claim is no longer persisted ahead of the annotation.
MUTANT_B = [
    (
        """            if st != before:
                live.status = st
                self.api.update_status(live)
            out.update(waiting=waiting, assignments=assignments,
                       slices=copy.deepcopy(slices), claims=claims)""",
        """            out.update(waiting=waiting, assignments=assignments,
                       slices=copy.deepcopy(slices), claims=claims,
                       _commit=(live, st, before))""",
    ),
    (
        """        retry_on_conflict(write_intent)
        if wrote[0]:""",
        """        retry_on_conflict(write_intent)

        def late_commit() -> None:
            live, st, before = out["_commit"]
            if st != before:
                live.status = st
                self.api.update_status(live)

        retry_on_conflict(late_commit)
        if wrote[0]:""",
    ),
]


def _explore_mutant(scenario, *, max_preemptions=2, max_schedules=600):
    ex = InterleavingExplorer(scenario, max_preemptions=max_preemptions,
                              max_schedules=max_schedules, budget_s=120.0)
    res = ex.explore()
    assert res.failure is not None, (
        "mutant survived %d schedules — the harness cannot falsify"
        % res.schedules)
    fail = res.failure
    # acceptance: the shrunk repro needs at most 4 preemptions
    assert fail.preemptions <= 4, fail.narrative
    assert len(fail.directives) <= 4, fail.narrative
    # regression artifact: the shrunk schedule replays byte-identically
    r1 = ex.replay(fail.choices)
    r2 = ex.replay(fail.choices)
    assert r1.failed and r2.failed
    assert ex.render(r1.trace) == ex.render(r2.trace)
    return fail


def test_mutant_dropped_write_ahead_is_caught():
    mod = _load_mutant("kubeflow_tpu.core.selfheal", MUTANT_A,
                       "kubeflow_tpu.core._selfheal_mutant_a")

    fail = _explore_mutant(lambda: _selfheal_scenario(mod.RecoveryEngine))
    # pinned shrunk schedule: the very first (sequential, zero-preemption)
    # schedule already restarts pods with nothing persisted
    assert fail.preemptions == 0, fail.narrative
    assert fail.directives == {}, fail.narrative
    assert "restore intent was persisted" in fail.message \
        or "attempt charge" in fail.message, fail.message


# Mutant P: delete the fence raise between the write-ahead promotion
# record and the primary flip — the linearization point of the election is
# gone, so a demoted zombie primary can keep acking session writes with
# its stale epoch after the new primary took over.
MUTANT_PROMOTE = [(
    """            if self.session is not None:
                self.session.fence(nb.namespace, nb.name, entry["epoch"])
                span.add_event("promote.fenced", {
                    "epoch": entry["epoch"]})""",
    "            pass  # MUTANT P: promotion no longer fences the store",
)]


def test_mutant_unfenced_promotion_is_caught():
    mod = _load_mutant("kubeflow_tpu.core.selfheal", MUTANT_PROMOTE,
                       "kubeflow_tpu.core._selfheal_mutant_promote")

    fail = _explore_mutant(lambda: _promote_scenario(mod.RecoveryEngine))
    # pinned shrunk schedule: even the sequential zero-preemption schedule
    # lets the zombie's stale-epoch delta land once the fence is gone
    assert fail.preemptions == 0, fail.narrative
    assert fail.directives == {}, fail.narrative


# Mutant C: adopt from the join PREVIEW instead of the commit — the map
# write is no longer ahead of adoption, so the joiner acts on membership
# nobody committed (and its token never activates off a committed view).
MUTANT_SHARD = [(
    "        view = self.member.join()",
    "        view = self.member.preview_join()"
    "  # MUTANT C: adopt before the commit",
)]


def test_mutant_adopt_before_commit_is_caught():
    mod = _load_mutant("kubeflow_tpu.kube.shard", MUTANT_SHARD,
                       "kubeflow_tpu.kube._shard_mutant_c")

    _explore_mutant(lambda: shard_handoff_scenario(mod))


def test_mutant_adopt_before_commit_fails_writeahead_analyzer():
    """The same mutant must also trip the STATIC half of the gate: with
    the commit gone from join_fleet, the destructive drain/adopt call has
    no persist dominator on the CFG (ci/analyzers/write_ahead.py)."""
    import ast as _ast
    from pathlib import Path

    from ci.analyzers import Module
    from ci.analyzers import write_ahead as wa

    src_path = importlib.import_module("kubeflow_tpu.kube.shard").__file__
    rel = "kubeflow_tpu/kube/shard.py"
    src = Path(src_path).read_text()
    clean = Module(Path(src_path), rel, src,
                   _ast.parse(src, filename=rel))
    assert [v for v in wa.analyze(clean)
            if v.context == "ShardedReplica.join_fleet"] == [], \
        "the committed order must satisfy the analyzer"
    old, new = MUTANT_SHARD[0]
    assert src.count(old) == 1
    mutated_src = src.replace(old, new)
    mutated = Module(Path(src_path), rel, mutated_src,
                     _ast.parse(mutated_src, filename=rel))
    found = [v for v in wa.analyze(mutated)
             if v.context == "ShardedReplica.join_fleet"]
    assert found, "analyzer missed the commit-after-adopt reorder"


# Mutant O: drop the stable-ring drain gate in owns_key — a shard starts
# dispatching keys it GAINED in a still-draining handoff while the
# previous owner may have one inside an open reconcile window.
MUTANT_OVERLAP = [(
    """        if gated:
            if not stable.members or \\
                    stable.owner_of(namespace, name) != self.shard_id:
                return False
        return True""",
    """        del gated, stable  # MUTANT O: drain gate dropped
        return True""",
)]


def test_mutant_dropped_drain_gate_is_caught():
    """Deleting the drain gate must be caught by a shrunk schedule of
    the concurrent-join scenario: a joiner dispatches a gained key
    inside the previous owner's still-open window."""
    mod = _load_mutant("kubeflow_tpu.kube.shard", MUTANT_OVERLAP,
                       "kubeflow_tpu.kube._shard_mutant_o")

    # bound 1: the overlap needs exactly one preemption (into the
    # survivor's open window), and the bound-2 DFS burns its schedule
    # budget in deep suffix subtrees before reaching the run's opening
    # steps, where the survivor still owns the whole keyspace.  The
    # deepest-first sweep reaches those steps around schedule ~800, so
    # the cap gets headroom over the default 600.
    fail = _explore_mutant(lambda: shard_concurrent_join_scenario(mod),
                           max_preemptions=1, max_schedules=1500)
    assert "single-owner violation" in fail.message, fail.message


def test_mutant_reordered_claim_commit_is_caught():
    mod = _load_mutant("kubeflow_tpu.core.scheduler", MUTANT_B,
                       "kubeflow_tpu.core._scheduler_mutant_b")

    # the warmpool scenario, but with the mutated scheduler class
    def mutant_scenario():
        api = ApiServer()
        clock = FakeClock()
        cfg = _scheduler_cfg()
        metrics = NotebookMetrics(api)
        api.create(KubeObject(
            api_version="kubeflow.org/v1", kind=C.WARMPOOL_KIND,
            metadata=ObjectMeta(name=POOL_NAME),
            body={"spec": {"accelerator": "v5e", "topology": "4x4"},
                  "status": {"slices": {
                      "ws-0001": {"state": "Ready", "pool": "warm-a"},
                      "ws-0002": {"state": "Ready", "pool": "warm-b"},
                  }}}))
        names = ("nb-a", "nb-b")
        for name in names:
            api.create(Notebook.new(name, "default", tpu=SPEC).obj)
        scheds = {name: mod.SliceScheduler(api, cfg, metrics, clock=clock)
                  for name in names}

        def run(name):
            def go():
                scheds[name].reconcile(Request("default", name))
            return go

        def check():
            pool = api.get(C.WARMPOOL_KIND, "", POOL_NAME)
            slices = (pool.body.get("status") or {}).get("slices") or {}
            intent_pools = {}
            for name in names:
                ann = api.get("Notebook", "default", name) \
                    .metadata.annotations.get(C.ANNOTATION_PLACEMENT)
                assert ann, f"{name}: placement intent lost"
                intent_pools[name] = {
                    e["pool"]
                    for e in json.loads(ann)["slices"].values()}
            assert not (intent_pools["nb-a"] & intent_pools["nb-b"]), (
                "double-sold: %r" % intent_pools)

        return [(name, run(name)) for name in names], check

    fail = _explore_mutant(mutant_scenario)
    # pinned shrunk schedule: one scheduler's claim read slips between
    # the other's in-memory claim and its (now too-late) commit
    assert 1 <= fail.preemptions <= 4, fail.narrative
    assert fail.directives, fail.narrative
    assert "double-sold" in fail.message or "Conflict" in fail.message, (
        fail.message)


# -- protocol F: checkpoint-then-preempt vs failover ---------------------------
class _NullSpan:
    def add_event(self, *a, **k):
        pass

    def set_attribute(self, *a, **k):
        pass


def _preemption_scenario(engine_cls):
    """A preemption engine evicts a placed low-priority victim for a
    high-priority beneficiary while its failover twin re-drives the
    write-ahead record and the victim's own scheduler reconcile races
    both (claim / evict / restore).  Every schedule must keep the
    checkpoint-then-preempt contract: by the time ANY victim teardown
    runs, the Pending record, its restore manifest (digest included) and
    the victim's sessionState intent are all persisted (so a crash at
    any point resumes — never repeats — the eviction); the record
    reaches its terminal phase exactly once; the claims drain and the
    placement retires; and the victim is never resurrected onto the
    freed capacity while the beneficiary still waits for it."""
    from kubeflow_tpu.core import constants as CC

    api = ApiServer()
    clock = FakeClock()
    cfg = _scheduler_cfg()
    metrics = NotebookMetrics(api)
    store = InMemorySessionStore(clock=clock)
    snap = store.put("t-low", "victim", 0, b"kernel-state",
                     trigger="interval")

    victim = Notebook.new("victim", "t-low", tpu=SPEC)
    victim.obj.spec["priority"] = "low"
    victim.obj.metadata.annotations[C.ANNOTATION_PLACEMENT] = json.dumps(
        {"slices": {"0": {"pool": "warm-a"}}, "v": 1},
        sort_keys=True, separators=(",", ":"))
    api.create(victim.obj)
    ben = Notebook.new("ben", "t-hi", tpu=SPEC)
    ben.obj.spec["priority"] = "high"
    api.create(ben.obj)
    api.create(KubeObject(
        api_version="kubeflow.org/v1", kind=C.WARMPOOL_KIND,
        metadata=ObjectMeta(name=POOL_NAME),
        body={"spec": {"accelerator": "v5e", "topology": "4x4"},
              "status": {"slices": {
                  "ws-0001": {"state": CC.WARMSLICE_CLAIMED,
                              "pool": "warm-a",
                              "claimedBy": "t-low/victim",
                              "claimedSlice": 0}}}}))

    teardowns: list[str] = []

    class _Checked(engine_cls):
        def _teardown_victim(self, victim_rec):
            quota = api.try_get(C.TENANTQUOTA_KIND, "",
                                C.TENANTQUOTA_NAME)
            st = {} if quota is None else (quota.body.get("status") or {})
            rec = (st.get("preemptions") or {}).get(victim_rec["key"])
            if rec is None:
                # a racing manager may have finished this victim while
                # we were paused — legitimate ONLY if the record folded
                # to its terminal phase (the in-engine duplicate guard
                # then makes super() a no-op); a teardown with no record
                # trace at all is the write-ahead violation
                recents = st.get("recentPreemptions") or []
                assert any(r.get("victim") == victim_rec["key"]
                           for r in recents), (
                    "teardown with no write-ahead record trace "
                    "(neither Pending nor terminal): %r" % st)
            else:
                assert rec.get("phase") == C.PREEMPTION_PENDING, (
                    "teardown before the write-ahead record persisted: "
                    "%r" % rec)
                restore = rec.get("restore") or {}
                assert restore.get("0", {}).get("digest") \
                    == snap.digest, (
                    "teardown before the restore manifest persisted: %r"
                    % restore)
                sess = (api.get("Notebook", "t-low", "victim")
                        .body.get("status") or {}) \
                    .get("sessionState") or {}
                assert (sess.get("0") or {}).get("trigger") \
                    == "preempt", (
                    "teardown before the victim intent persisted: %r"
                    % sess)
                teardowns.append(victim_rec["key"])
            super()._teardown_victim(victim_rec)

    engines = {
        n: _Checked(api, cfg, metrics, EventRecorder(api, n),
                    clock=clock, session=store)
        for n in ("mgr-a", "mgr-b")}

    def preempt():
        engines["mgr-a"].maybe_preempt(
            Notebook(api.get("Notebook", "t-hi", "ben")),
            SPEC.shape, float(SPEC.shape.chips), _NullSpan())

    def resume():
        engines["mgr-b"].reconcile(Request("", C.TENANTQUOTA_NAME))

    def victim_sched():
        SliceScheduler(api, cfg, metrics, clock=clock).reconcile(
            Request("t-low", "victim"))

    def check():
        assert teardowns, "eviction never ran"
        quota = api.get(C.TENANTQUOTA_KIND, "", C.TENANTQUOTA_NAME)
        st = quota.body.get("status") or {}
        assert not (st.get("preemptions") or {}), (
            "record left Pending: %r" % st)
        recents = st.get("recentPreemptions") or []
        mine = [r for r in recents if r.get("victim") == "t-low/victim"]
        assert len(mine) == 1 and mine[0]["phase"] == C.PREEMPTION_DONE, (
            "record must fold to terminal exactly once: %r" % recents)
        vobj = api.get("Notebook", "t-low", "victim")
        assert C.ANNOTATION_PLACEMENT not in vobj.metadata.annotations, (
            "victim resurrected onto the freed capacity: %r"
            % vobj.metadata.annotations)
        info = json.loads(
            vobj.metadata.annotations[C.ANNOTATION_QUEUED])
        assert info.get("reason") == "preempted", info
        assert info.get("beneficiary") == "t-hi/ben", info
        sess = (vobj.body.get("status") or {}).get("sessionState") or {}
        assert sess.get("0", {}).get("digest") == snap.digest, sess
        assert sess.get("0", {}).get("restoreGeneration") \
            == snap.generation, sess
        pool = api.get(C.WARMPOOL_KIND, "", POOL_NAME)
        slices = (pool.body.get("status") or {}).get("slices") or {}
        assert not any(e.get("claimedBy") == "t-low/victim"
                       for e in slices.values()), (
            "victim claims never drained (or were re-taken): %r" % slices)

    return [("preempt", preempt), ("resume", resume),
            ("victim-sched", victim_sched)], check


def preemption_scenario():
    from kubeflow_tpu.core.preemption import PreemptionEngine

    return _preemption_scenario(PreemptionEngine)


def test_preemption_write_ahead_under_all_schedules():
    _explore(preemption_scenario)


# Mutant D: delete the write-ahead record commit in preempt — victims are
# torn down with no persisted record, so a crash mid-plan strands
# half-evicted gangs no successor knows to finish.
MUTANT_PREEMPT = [(
    """        self._commit_record(nb, plan)
        for victim in plan:""",
    "        for victim in plan:"
    "  # MUTANT D: teardown before the record",
)]


def test_mutant_preempt_before_record_is_caught():
    mod = _load_mutant("kubeflow_tpu.core.preemption", MUTANT_PREEMPT,
                       "kubeflow_tpu.core._preemption_mutant_d")

    fail = _explore_mutant(
        lambda: _preemption_scenario(mod.PreemptionEngine))
    # pinned shrunk schedule: the very first (sequential, zero-preemption)
    # schedule already tears the victim down with nothing persisted
    assert fail.preemptions == 0, fail.narrative
    assert fail.directives == {}, fail.narrative
    assert "write-ahead record" in fail.message, fail.message


def test_mutant_preempt_before_record_fails_writeahead_analyzer():
    """The same mutant must also trip the STATIC half of the gate: with
    the commit gone from preempt, the destructive teardown call has no
    persist dominator on the CFG (ci/analyzers/write_ahead.py)."""
    import ast as _ast
    from pathlib import Path

    from ci.analyzers import Module
    from ci.analyzers import write_ahead as wa

    src_path = importlib.import_module(
        "kubeflow_tpu.core.preemption").__file__
    rel = "kubeflow_tpu/core/preemption.py"
    src = Path(src_path).read_text()
    clean = Module(Path(src_path), rel, src,
                   _ast.parse(src, filename=rel))
    assert [v for v in wa.analyze(clean)
            if v.context == "PreemptionEngine.preempt"] == [], \
        "the committed order must satisfy the analyzer"
    old, new = MUTANT_PREEMPT[0]
    assert src.count(old) == 1
    mutated_src = src.replace(old, new)
    mutated = Module(Path(src_path), rel, mutated_src,
                     _ast.parse(mutated_src, filename=rel))
    found = [v for v in wa.analyze(mutated)
             if v.context == "PreemptionEngine.preempt"]
    assert found, "analyzer missed the record-after-teardown reorder"
    assert "not dominated" in found[0].message
