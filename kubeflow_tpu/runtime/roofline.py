"""Analytic roofline model: step-time floors, MFU, and bound attribution.

The repo measured MFU in two places with two code paths (bench.py through
models/train.mfu, the StepTimer through the same function but its own
call graph) and attributed nothing: a 0.39-MFU run never said whether the
chip was compute-starved or bandwidth-starved.  This module is the ONE
definition both planes share:

  - **FLOPs per step** come from the model config's own accounting
    (`TransformerConfig.flops_per_token`: 6x activated matmul params +
    causal attention; MoE counts top-k experts only), so the MFU
    numerator here is byte-identical to what bench.py always reported.
  - **HBM bytes per step** are a first-order traffic model (weights
    streamed fwd+bwd, fp32 master + Adam moments read/written, remat
    layer-boundary activations stashed+read for training; matmul weights
    streamed once + the full static-shape staged-KV cache read once for
    decode — the same formula bench.py --decode derived empirically in
    round 4).  These are *floors, not simulations*: real steps add
    attention traffic and collective overhead on top.
  - the chip table is `tpu.topology.ACCELERATORS` (per-chip bf16 peak
    TFLOPs and HBM GB/s for v4/v5e/v5p/v6e) — no second spec table.

A `RooflineEstimate` answers the questions telemetry needs: the
compute-bound and memory-bound step-time floors, which one *binds*
(`bound`: compute | memory), achieved MFU at a measured step time, and
the roofline fraction (floor / measured — 1.0 means running at the
analytic limit).  Pure stdlib math, importable jax-free from the
control plane, the workbench image, and CI alike.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..tpu.topology import ACCELERATORS

# bytes per element by dtype name (TransformerConfig dtype fields);
# int4 is nibble-packed (models.quant)
DTYPE_BYTES = {
    "float32": 4.0,
    "float16": 2.0,
    "bfloat16": 2.0,
    "int8": 1.0,
    "int4": 0.5,
}

# Adam keeps two fp32 moments per parameter; each is read and written
# once per step (mu_dtype="bfloat16" shaves the first moment — ignored
# here, the floor stays a floor)
_ADAM_MOMENT_BYTES = 2 * 2 * 4.0


def dtype_bytes(name: str, default: float = 2.0) -> float:
    return DTYPE_BYTES.get(name, default)


def matmul_params(config) -> float:
    """Parameters that participate in matmuls — the weights decode must
    stream.  The untied embedding table is a per-token row lookup and
    never streams; tied, it doubles as the LM-head weight and does
    (the same convention as `flops_per_token`)."""
    p = float(config.num_params)
    if not config.tie_embeddings:
        p -= config.vocab_size * config.embed_dim
    return p


# -- per-step work ------------------------------------------------------------


def train_step_flops(config, batch: int, seq_len: int) -> float:
    """Fwd+bwd matmul FLOPs per training step — the MFU numerator, one
    definition with `TransformerConfig.flops_per_token`."""
    return config.flops_per_token(seq_len) * batch * seq_len


def train_step_hbm_bytes(config, batch: int, seq_len: int) -> float:
    """First-order HBM traffic per training step:

      - every parameter's compute copy read by fwd AND bwd (2x act
        bytes), the fp32 master read + written by the optimizer (2x
        param bytes), and both Adam moments read + written;
      - the remat activation stash: one [B, S, D] residual per layer
        boundary written by fwd and read back by bwd.

    Attention score traffic and collectives ride on top of this floor.
    """
    ab = dtype_bytes(config.dtype)
    pb = dtype_bytes(config.param_dtype, 4.0)
    weights = config.num_params * (2 * ab + 2 * pb + _ADAM_MOMENT_BYTES)
    stash = 2.0 * batch * seq_len * config.embed_dim * config.num_layers * ab
    return weights + stash


def decode_weight_stream_bytes(config) -> float:
    """Bytes of weights one decode step streams: every matmul weight
    once, in the decode streaming dtype (bf16 unless `weight_dtype`
    says the kernels are int8/int4-quantized)."""
    wb = dtype_bytes(config.weight_dtype or "bfloat16")
    return matmul_params(config) * wb


def decode_kv_bytes(config, batch: int) -> float:
    """The full static-shape KV cache read once per decode step: K and V,
    [B, max_seq, kv_heads, head_dim] bf16 per layer.  The cache is
    allocated (and with staged-KV, flushed in aligned 8-row tiles) to
    max_seq_len, so it reads to max_seq_len regardless of fill — the
    round-4 empirical finding bench.py --decode codified."""
    return (2.0 * batch * config.max_seq_len * config.num_kv_heads
            * config.head_dim * 2.0 * config.num_layers)


def decode_step_flops(config, batch: int) -> float:
    """Matmul FLOPs per single-token decode step: 2 FLOPs per streamed
    weight per token, plus the QK^T/AV attention reads over the cache."""
    attn = (4.0 * config.num_layers * config.num_heads * config.head_dim
            * config.max_seq_len)
    return (2.0 * matmul_params(config) + attn) * batch


# -- MFU (the one definition) -------------------------------------------------


def mfu_from_flops(tokens_per_second: float, flops_per_token: float,
                   num_chips: int, accelerator: str = "v5e") -> float:
    """Achieved fraction of the slice's bf16 peak.  EVERY MFU the repo
    reports funnels through here: bench.py and models/train.mfu via
    `mfu()`, the TelemetryAgent/StepTimer via the same — so the headline
    number has exactly one definition."""
    peak = ACCELERATORS[accelerator].bf16_peak_tflops * 1e12 * num_chips
    return tokens_per_second * flops_per_token / peak


def mfu(tokens_per_second: float, config, seq_len: int, num_chips: int,
        accelerator: str = "v5e") -> float:
    return mfu_from_flops(tokens_per_second, config.flops_per_token(seq_len),
                          num_chips, accelerator)


# -- the estimate -------------------------------------------------------------


@dataclass(frozen=True)
class RooflineEstimate:
    """Analytic floors for one (config, batch, seq) workload on a slice."""

    mode: str                 # train | decode
    accelerator: str
    num_chips: int
    flops: float              # matmul FLOPs per step
    hbm_bytes: float          # HBM bytes per step (first-order floor)
    tokens: int               # tokens produced/consumed per step

    @property
    def peak_flops_per_s(self) -> float:
        return (ACCELERATORS[self.accelerator].bf16_peak_tflops * 1e12
                * self.num_chips)

    @property
    def peak_hbm_bytes_per_s(self) -> float:
        return (ACCELERATORS[self.accelerator].hbm_gbps * 1e9
                * self.num_chips)

    @property
    def compute_floor_s(self) -> float:
        return self.flops / self.peak_flops_per_s

    @property
    def memory_floor_s(self) -> float:
        return self.hbm_bytes / self.peak_hbm_bytes_per_s

    @property
    def step_floor_s(self) -> float:
        return max(self.compute_floor_s, self.memory_floor_s)

    @property
    def bound(self) -> str:
        """Which resource the analytic floor says binds this workload."""
        return ("compute" if self.compute_floor_s >= self.memory_floor_s
                else "memory")

    @property
    def tokens_per_s_ceiling(self) -> float:
        return self.tokens / self.step_floor_s if self.step_floor_s else 0.0

    def mfu_at(self, step_time_s: float) -> float:
        """MFU at a measured step time — identical to
        `mfu_from_flops(tokens/step_time, flops/tokens, ...)`."""
        if step_time_s <= 0:
            return 0.0
        return self.flops / step_time_s / self.peak_flops_per_s

    def roofline_fraction(self, step_time_s: float) -> float:
        """Fraction of the analytic limit achieved: floor / measured.
        1.0 = running at the floor; >1.0 means the first-order model
        under-counts this workload (worth knowing, not clamped)."""
        if step_time_s <= 0:
            return 0.0
        return self.step_floor_s / step_time_s

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "accelerator": self.accelerator,
            "num_chips": self.num_chips,
            "flops_per_step": self.flops,
            "hbm_bytes_per_step": self.hbm_bytes,
            "tokens_per_step": self.tokens,
            "compute_floor_s": self.compute_floor_s,
            "memory_floor_s": self.memory_floor_s,
            "step_floor_s": self.step_floor_s,
            "bound": self.bound,
        }


def train_estimate(config, batch: int, seq_len: int, num_chips: int = 1,
                   accelerator: str = "v5e") -> RooflineEstimate:
    return RooflineEstimate(
        mode="train", accelerator=accelerator, num_chips=num_chips,
        flops=train_step_flops(config, batch, seq_len),
        hbm_bytes=train_step_hbm_bytes(config, batch, seq_len),
        tokens=batch * seq_len)


def decode_estimate(config, batch: int, num_chips: int = 1,
                    accelerator: str = "v5e",
                    param_bytes: float = 0.0) -> RooflineEstimate:
    """Single-token decode step.  `param_bytes` overrides the analytic
    weight-stream bytes with measured ones (bench.py --decode passes
    `quantized_bytes(params, ...)` off the real tree, which knows the
    exact quantization group scales)."""
    stream = param_bytes or decode_weight_stream_bytes(config)
    return RooflineEstimate(
        mode="decode", accelerator=accelerator, num_chips=num_chips,
        flops=decode_step_flops(config, batch),
        hbm_bytes=stream + decode_kv_bytes(config, batch),
        tokens=batch)


__all__ = [
    "DTYPE_BYTES", "RooflineEstimate", "decode_estimate", "decode_kv_bytes",
    "decode_step_flops", "decode_weight_stream_bytes", "dtype_bytes",
    "matmul_params", "mfu", "mfu_from_flops", "train_estimate",
    "train_step_flops", "train_step_hbm_bytes",
]
