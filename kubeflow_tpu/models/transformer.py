"""Llama/Gemma-family decoder, TPU-first.

Design choices (vs a torch translation):
  - flax.linen with *logical* axis metadata on every parameter
    (nn.with_logical_partitioning); physical placement comes from
    parallel.sharding rules at jit boundary — one table controls
    dp/fsdp/tp/sp.
  - layers run under `nn.scan` (one compiled layer body, rolled over a
    leading "layers" param axis) + per-layer `nn.remat` — compile time and
    HBM both scale to 7B+ on a notebook chip.
  - attention dispatches to the Pallas flash kernel on TPU, ring attention
    when the mesh has a populated "sequence" axis (long context), and the
    einsum reference elsewhere (ops/attention.py, ops/ring_attention.py).
  - bf16 activations, fp32 master weights and norm/softmax accumulation.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..ops.attention import attention
from ..ops.ring_attention import ring_attention
from .configs import TransformerConfig

# What each layer's checkpoint may keep across fwd->bwd (HBM-for-FLOPs
# dial; MaxText exposes the same choice as remat_policy):
#   nothing — recompute everything (min HBM, max recompute)
#   dots    — keep matmul outputs with no batch dims (weights-side products)
#   none    — save all residuals (no recompute; only fits small models)
_REMAT_POLICIES = {
    "nothing": lambda: jax.checkpoint_policies.nothing_saveable,
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    # save ONLY the attention outputs (checkpoint_name in Attention): the
    # per-layer backward recompute then skips re-running the flash kernel —
    # the one fwd op whose wall share beats its HBM share ([B,S,H,D] bf16
    # per layer) — while everything else still remats
    "attn": lambda: jax.checkpoint_policies.save_only_these_names("attn_out"),
    "none": lambda: jax.checkpoint_policies.everything_saveable,
}


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones_init(), ("norm",)),
            (x.shape[-1],),
            jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (norm * scale).astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding on [B, S, H, D]; fp32 trig, split-half convention.

    The frequency table is a trace-time numpy constant, not a traced iota
    chain: a traced rank-1 freq gets closure-captured as an operand of the
    ring-attention manual computation when rope runs inside the pipeline's
    shard_map, and sdy propagation assigns it an inconsistent sharding
    (manual_computation verifier failure with check_vma=True).  A constant
    inlines into each region instead.
    """
    import numpy as np

    half = x.shape[-1] // 2
    freq = jnp.asarray(
        theta ** (-np.arange(0, half, dtype=np.float32) / half))
    angle = positions[..., None].astype(jnp.float32) * freq  # [B, S, half]
    cos = jnp.cos(angle)[:, :, None, :]
    sin = jnp.sin(angle)[:, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


def _dense(
    features,
    axes,
    name=None,
    dtype=jnp.bfloat16,
    param_dtype=jnp.float32,
    contract_axes=(-1,),
    weight_dtype="",
):
    if weight_dtype in ("int8", "int4"):
        # decode-time quantized weight streaming (models.quant): params
        # come from quantize_params/_int4, upcast fused into the matmul
        # operand load; same logical axes as the dense kernel
        from .quant import Int4DenseGeneral, Int8DenseGeneral

        cls = Int8DenseGeneral if weight_dtype == "int8" else Int4DenseGeneral
        return cls(features, axis=contract_axes, dtype=dtype,
                   logical_axes=tuple(axes), name=name)
    return nn.DenseGeneral(
        features,
        axis=contract_axes,
        use_bias=False,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), axes
        ),
        name=name,
    )


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, positions, decode: bool = False):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        if cfg.fused_projections:
            # decode fusion: one matmul for q|k|v along the heads axis —
            # small-batch decode pays ~10-15us of launch overhead PER
            # KERNEL (ci/kv_cache_probe.py), so 3 projections -> 1 is a
            # direct step-time cut.  models.generate.fuse_decode_params
            # concatenates a training tree's q/k/v kernels into this
            # layout before quantization.
            fused_heads = cfg.num_heads + 2 * cfg.num_kv_heads
            qkv = _dense(
                (fused_heads, cfg.head_dim), ("embed", "heads", "kv"),
                "qkv", dtype, _dtype(cfg.param_dtype),
                weight_dtype=cfg.weight_dtype,
            )(x)
            q = qkv[..., :cfg.num_heads, :]
            k = qkv[..., cfg.num_heads:cfg.num_heads + cfg.num_kv_heads, :]
            v = qkv[..., cfg.num_heads + cfg.num_kv_heads:, :]
        else:
            q = _dense(
                (cfg.num_heads, cfg.head_dim), ("embed", "heads", "kv"), "q",
                dtype, _dtype(cfg.param_dtype), weight_dtype=cfg.weight_dtype,
            )(x)
            k = _dense(
                (cfg.num_kv_heads, cfg.head_dim), ("embed", "heads", "kv"),
                "k", dtype, _dtype(cfg.param_dtype),
                weight_dtype=cfg.weight_dtype,
            )(x)
            v = _dense(
                (cfg.num_kv_heads, cfg.head_dim), ("embed", "heads", "kv"),
                "v", dtype, _dtype(cfg.param_dtype),
                weight_dtype=cfg.weight_dtype,
            )(x)
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "kv"))
        k = nn.with_logical_constraint(k, ("batch", "seq", "heads", "kv"))
        v = nn.with_logical_constraint(v, ("batch", "seq", "heads", "kv"))
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)

        if decode:
            # KV cache (flax "cache" collection): static ring written with
            # dynamic_update_slice — XLA-friendly in-place updates, no
            # growing shapes.  Layout is [B, kvH, S, D], what the decode
            # dots consume directly (ops/attention.decode_attention): the
            # [B, S, kvH, D] activation layout would cost a full-cache
            # transpose copy per step; here only the new token's slab is
            # transposed.  rope was applied with GLOBAL positions above,
            # so cached keys need no re-rotation.
            from ..ops.attention import (
                decode_attention,
                decode_attention_staged,
            )

            batch = x.shape[0]
            cached_k = self.variable(
                "cache", "cached_key", jnp.zeros,
                (batch, cfg.num_kv_heads, cfg.max_seq_len, cfg.head_dim),
                k.dtype)
            cached_v = self.variable(
                "cache", "cached_value", jnp.zeros,
                (batch, cfg.num_kv_heads, cfg.max_seq_len, cfg.head_dim),
                v.dtype)
            index = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((), jnp.int32))
            cur = index.value
            kt = k.transpose(0, 2, 1, 3)
            vt = v.transpose(0, 2, 1, 3)
            q_len = x.shape[1]
            staged = cfg.staged_kv and q_len == 1
            if cfg.staged_kv:
                if cfg.max_seq_len % 8:
                    raise ValueError(
                        "staged_kv requires max_seq_len % 8 == 0 (the "
                        f"stage flushes aligned 8-row tiles); got "
                        f"{cfg.max_seq_len}")
                # 8-row staging (ci/kv_cache_probe.py: a 1-row DUS
                # read-modify-writes a whole (8,128) tile row per buffer;
                # staging flushes aligned full tiles instead).  Invariant:
                # main cache = rows [0, flushed), flushed 8-aligned;
                # stage slots [0, fill-flushed) = rows [flushed, fill).
                stage_k = self.variable(
                    "cache", "stage_key", jnp.zeros,
                    (batch, cfg.num_kv_heads, 8, cfg.head_dim), k.dtype)
                stage_v = self.variable(
                    "cache", "stage_value", jnp.zeros,
                    (batch, cfg.num_kv_heads, 8, cfg.head_dim), v.dtype)
            if staged:
                slot = jnp.mod(cur, 8)
                stage_k.value = jax.lax.dynamic_update_slice(
                    stage_k.value, kt, (0, 0, slot, 0))
                stage_v.value = jax.lax.dynamic_update_slice(
                    stage_v.value, vt, (0, 0, slot, 0))
                fill = cur + 1
                flushed = fill - jnp.mod(fill, 8)

                def flush(main, stage):
                    return jax.lax.dynamic_update_slice(
                        main, stage, (0, 0, cur - 7, 0))

                do_flush = slot == 7
                cached_k.value = jax.lax.cond(
                    do_flush, flush, lambda m, _s: m,
                    cached_k.value, stage_k.value)
                cached_v.value = jax.lax.cond(
                    do_flush, flush, lambda m, _s: m,
                    cached_v.value, stage_v.value)
                index.value = fill
                out = decode_attention_staged(
                    q, cached_k.value, cached_v.value,
                    stage_k.value, stage_v.value, flushed, fill)
            else:
                if cfg.staged_kv:
                    # multi-token write with a possibly-live stage (cur > 0:
                    # chunked prefill, verify-style passes): flush the stage
                    # into the main cache FIRST so rows [flushed, cur) —
                    # which live only in the stage — are visible to the
                    # attention below (they used to silently read as
                    # zeros, ADVICE round 5).  Stale stage rows past `cur`
                    # are overwritten by the new kt/vt or sit beyond the
                    # visibility mask.
                    aligned = cur - jnp.mod(cur, 8)

                    def flush_stage(main, stage):
                        return jax.lax.dynamic_update_slice(
                            main, stage, (0, 0, aligned, 0))

                    has_stage = jnp.mod(cur, 8) > 0
                    cached_k.value = jax.lax.cond(
                        has_stage, flush_stage, lambda m, _s: m,
                        cached_k.value, stage_k.value)
                    cached_v.value = jax.lax.cond(
                        has_stage, flush_stage, lambda m, _s: m,
                        cached_v.value, stage_v.value)
                cached_k.value = jax.lax.dynamic_update_slice(
                    cached_k.value, kt, (0, 0, cur, 0))
                cached_v.value = jax.lax.dynamic_update_slice(
                    cached_v.value, vt, (0, 0, cur, 0))
                if cfg.staged_kv:
                    # re-seed the stage so later single-token staged steps
                    # continue the invariant: slots [0, fill%8) must hold
                    # rows [fill - fill%8, fill) — all valid in the main
                    # cache now, so slice them straight back out (max_seq
                    # is 8-aligned, checked above, so the slice never
                    # clamps).
                    fill = cur + q_len
                    new_aligned = fill - jnp.mod(fill, 8)

                    def reseed(main, stage):
                        return jax.lax.dynamic_slice(
                            main, (0, 0, new_aligned, 0),
                            (batch, cfg.num_kv_heads, 8, cfg.head_dim))

                    needs_stage = jnp.mod(fill, 8) > 0
                    stage_k.value = jax.lax.cond(
                        needs_stage, reseed, lambda _m, s: s,
                        cached_k.value, stage_k.value)
                    stage_v.value = jax.lax.cond(
                        needs_stage, reseed, lambda _m, s: s,
                        cached_v.value, stage_v.value)
                index.value = cur + q_len
                # the visibility mask with q at global offset `cur` covers
                # both the unwritten tail (kv_pos > q_pos) and causality
                out = decode_attention(q, cached_k.value, cached_v.value,
                                       q_offset=cur)
            out = nn.with_logical_constraint(
                out, ("batch", "seq", "heads", "kv"))
            return _dense(
                cfg.embed_dim, ("heads", "kv", "embed"), "out",
                dtype, _dtype(cfg.param_dtype), contract_axes=(-2, -1),
                weight_dtype=cfg.weight_dtype,
            )(out)

        use_ring = (
            cfg.attention_impl == "ring"
            or (
                cfg.attention_impl == "auto"
                and self.mesh is not None
                and "sequence" in self.mesh.shape
                and self.mesh.shape["sequence"] > 1
            )
        )
        if use_ring:
            if self.mesh is None:
                raise ValueError("ring attention requires a mesh")
            out = ring_attention(q, k, v, self.mesh, causal=True,
                                 positions=positions)
        else:
            impl = cfg.attention_impl if cfg.attention_impl != "ring" else "auto"
            out = attention(q, k, v, causal=True, impl=impl,
                            block_q=cfg.flash_block_q,
                            block_k=cfg.flash_block_k)
        from jax.ad_checkpoint import checkpoint_name

        out = checkpoint_name(out, "attn_out")
        out = nn.with_logical_constraint(out, ("batch", "seq", "heads", "kv"))
        return _dense(
            cfg.embed_dim, ("heads", "kv", "embed"), "out",
            dtype, _dtype(cfg.param_dtype), contract_axes=(-2, -1),
            weight_dtype=cfg.weight_dtype,
        )(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        wd = cfg.weight_dtype
        if cfg.fused_projections:
            # decode fusion twin of Attention's qkv (launch-overhead cut)
            gu = _dense((2, cfg.mlp_dim), ("embed", None, "mlp"),
                        "gate_up", dtype, pdtype, weight_dtype=wd)(x)
            gate, up = gu[..., 0, :], gu[..., 1, :]
        else:
            gate = _dense(cfg.mlp_dim, ("embed", "mlp"), "gate", dtype,
                          pdtype, weight_dtype=wd)(x)
            up = _dense(cfg.mlp_dim, ("embed", "mlp"), "up", dtype, pdtype,
                        weight_dtype=wd)(x)
        hidden = nn.silu(gate) * up
        hidden = nn.with_logical_constraint(hidden, ("batch", "seq", "mlp"))
        return _dense(cfg.embed_dim, ("mlp", "embed"), "down", dtype, pdtype,
                      weight_dtype=wd)(hidden)


class DecoderLayer(nn.Module):
    """One decoder block.  Dense configs return the residual stream; MoE
    configs (cfg.moe_experts > 0) return (stream, aux_loss) — run_stack
    accumulates the aux term across layers."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x, positions, decode: bool = False):
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        h = RMSNorm(cfg.norm_eps, dtype, name="attn_norm")(x)
        x = x + Attention(cfg, self.mesh, name="attn")(h, positions, decode)
        h = RMSNorm(cfg.norm_eps, dtype, name="mlp_norm")(x)
        if cfg.moe_experts > 0:
            from .moe import MoEMLP

            mlp_out, aux = MoEMLP(cfg, self.mesh, name="moe")(h)
            x = x + mlp_out
            return (
                nn.with_logical_constraint(x, ("batch", "seq", "embed")),
                aux,
            )
        x = x + MLP(cfg, name="mlp")(h)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class Transformer(nn.Module):
    """Decoder-only LM: tokens [B, S] int32 -> logits [B, S, V].

    setup-style with separately callable phases (embed_tokens / run_stack /
    head) so the pipeline-parallel path (parallel.pipeline.gpipe, driven
    from models.train) can run the layer stack itself while reusing the
    exact same parameters; __call__ composes the three and is the
    single-program path.  The parameter tree is identical either way
    ("embed", "layers"/"layer_i", "final_norm", "lm_head")."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    def setup(self):
        cfg = self.cfg
        if cfg.moe_experts > 0 and cfg.weight_dtype == "int4":
            # quantize_params_int4 skips expert kernels (its flat packed
            # layout does not survive nn.vmap stacking), but _ExpertFFN
            # would still build Int4DenseGeneral for them — apply would
            # fail deep inside flax with a missing-kernel_q4 error.  Fail
            # loudly here instead; int8 covers MoE serving (moe.py).
            raise ValueError(
                "weight_dtype='int4' does not support MoE configs "
                "(moe_experts > 0): int4 packing covers dense kernels "
                "only.  Use weight_dtype='int8' for quantized MoE serving."
            )
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        self.embed = nn.Embed(
            cfg.vocab_size,
            cfg.embed_dim,
            dtype=dtype,
            param_dtype=pdtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(1.0), ("vocab", "embed")
            ),
            name="embed",
        )
        layer_cls = DecoderLayer
        if cfg.remat:
            layer_cls = nn.remat(
                DecoderLayer,
                prevent_cse=not cfg.scan_layers,
                static_argnums=(),
                policy=_REMAT_POLICIES[cfg.remat_policy](),
            )
        if cfg.scan_layers:
            self.layers = layer_cls(cfg, self.mesh, name="layers")
        else:
            self.layer_list = [
                layer_cls(cfg, self.mesh, name=f"layer_{i}")
                for i in range(cfg.num_layers)
            ]
        self.final_norm = RMSNorm(cfg.norm_eps, dtype, name="final_norm")
        if not cfg.tie_embeddings:
            self.lm_head = _dense(
                cfg.vocab_size, ("embed", "vocab"), "lm_head", dtype, pdtype,
                weight_dtype=cfg.weight_dtype,
            )

    def embed_tokens(self, tokens):
        x = self.embed(tokens)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))

    def run_stack(self, x, positions, decode: bool = False):
        """Apply the layer stack; returns (x, aux) where aux is the summed
        MoE load-balance loss (0.0 for dense configs).  decode=True runs
        the KV-cache path (the "cache" collection gains a stacked layer
        axis under scan)."""
        cfg = self.cfg
        moe = cfg.moe_experts > 0
        if cfg.scan_layers:
            def body(mdl, carry, _):
                x, aux = carry
                # pass `decode` only when set: the remat wrapper treats
                # call args as dynamic, and a traced boolean would break
                # the layer's Python-level branch (decode configs run with
                # remat=False; models.generate enforces that)
                out = mdl(x, positions, True) if decode else mdl(x, positions)
                if moe:
                    x, layer_aux = out
                    return (x, aux + layer_aux), None
                return (out, aux), None

            (x, aux), _ = nn.scan(
                body,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(self.layers, (x, jnp.float32(0.0)), None)
        else:
            aux = jnp.float32(0.0)
            for layer in self.layer_list:
                out = layer(x, positions, True) if decode \
                    else layer(x, positions)
                if moe:
                    x, layer_aux = out
                    aux = aux + layer_aux
                else:
                    x = out
        return x, aux

    def head(self, x, return_hidden: bool = False):
        cfg = self.cfg
        pdtype = _dtype(cfg.param_dtype)
        x = self.final_norm(x)
        if return_hidden:
            # chunked-loss path: the caller applies the LM head per chunk
            # (train.chunked_cross_entropy) so [tokens, vocab] fp32 logits
            # are never resident all at once
            return x
        if cfg.tie_embeddings:
            logits = self.embed.attend(x.astype(pdtype))
        else:
            logits = self.lm_head(x)
        if cfg.logits_softcap > 0.0:
            cap = cfg.logits_softcap
            logits = jnp.tanh(logits.astype(jnp.float32) / cap) * cap
        return nn.with_logical_constraint(
            logits.astype(jnp.float32), ("batch", "seq", "vocab")
        )

    def __call__(self, tokens, return_hidden: bool = False,
                 return_aux: bool = False, decode: bool = False,
                 positions=None):
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]),
                                         tokens.shape)
        x = self.embed_tokens(tokens)
        x, aux = self.run_stack(x, positions, decode)
        out = self.head(x, return_hidden)
        return (out, aux) if return_aux else out
