"""API error model mirroring apimachinery's StatusError reasons.

The reference leans on k8s error predicates (apierrs.IsNotFound,
retry.RetryOnConflict) throughout, e.g.
components/notebook-controller/controllers/culling_controller.go:107,125,144.
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar


class ApiError(Exception):
    reason = "Unknown"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    reason = "AlreadyExists"


class ConflictError(ApiError):
    reason = "Conflict"


class InvalidError(ApiError):
    reason = "Invalid"


class ForbiddenError(ApiError):
    reason = "Forbidden"


class GoneError(ApiError):
    """HTTP 410: requested watch resourceVersion fell out of the history
    window — the client must relist (client-go reflector does the same)."""

    reason = "Expired"


class ServerError(ApiError):
    """Transport/5xx failure talking to a real apiserver."""

    reason = "InternalError"


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ConflictError)


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, AlreadyExistsError)


T = TypeVar("T")


def retry_on_conflict(
    fn: Callable[[], T],
    steps: int = 5,
    initial_backoff_s: float = 0.0,
    factor: float = 2.0,
) -> T:
    """Equivalent of retry.RetryOnConflict(retry.DefaultRetry, fn).

    The in-memory API server is synchronous so the default backoff is zero;
    steps mirror client-go's DefaultRetry (5 attempts).
    """
    backoff = initial_backoff_s
    last: Exception | None = None
    for _ in range(steps):
        try:
            return fn()
        except ConflictError as err:
            last = err
            if backoff:
                time.sleep(backoff)
                backoff *= factor
    assert last is not None
    raise last
