"""Model configurations for the BASELINE.md workload matrix.

Presets map to the baseline configs: MNIST MLP (v5e-1), ViT-B/16 (v5e-8),
Llama-2-7B (v5e-16 MaxText config), Gemma-7B (v5p-128 two-slice pretrain).
`llama2_350m` is the single-chip bench proxy: same architecture family,
sized so weights + Adam state fit one v5e chip's 16 GiB HBM.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    num_layers: int = 32
    embed_dim: int = 4096
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int = 128
    mlp_dim: int = 11_008
    max_seq_len: int = 4096
    rope_theta: float = 10_000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"          # activation/compute dtype
    param_dtype: str = "float32"     # master weights
    weight_dtype: str = ""           # decode-time weight streaming format:
                                     # "" = param_dtype as-is; "int8" /
                                     # "int4" = quantized kernels
                                     # (models.quant) — halve/quarter the
                                     # HBM traffic decode is bound by
    attention_impl: str = "auto"     # auto | flash | xla | ring
    remat: bool = True               # checkpoint each layer (HBM for FLOPs)
    scan_layers: bool = True         # lax.scan over layers (compile time)
    tie_embeddings: bool = False
    logits_softcap: float = 0.0      # gemma-style tanh softcap; 0 = off
    loss_chunks: int = 0             # >0: chunked CE — never materializes
                                     # the full [tokens, vocab] fp32 logits
    remat_policy: str = "nothing"    # nothing|dots|attn|none — what the
                                     # per-layer checkpoint may keep (see
                                     # models.transformer._REMAT_POLICIES)
    flash_block_q: int = 0           # Pallas flash tile sizes; 0 = kernel
    flash_block_k: int = 0           # defaults (tuned per-chip in bench)
    moe_experts: int = 0             # >0: MLPs become MoE (models.moe)
    moe_top_k: int = 2               # experts per token
    moe_capacity_factor: float = 1.25
    moe_mlp_dim: int = 0             # per-expert hidden; 0 = mlp_dim
    moe_aux_weight: float = 0.01     # load-balance loss weight
    decode: bool = False             # decode-shaped marker, set by
                                     # models.generate.decode_config: a cfg
                                     # carrying it keeps its explicit
                                     # fused_projections/staged_kv choices
                                     # through prepare_decode instead of
                                     # being re-defaulted (a training cfg
                                     # that merely looks decode-ish —
                                     # remat off + xla attention — no
                                     # longer masks the decode defaults)
    staged_kv: bool = False          # decode-path KV write staging: single
                                     # -token cache writes land in a small
                                     # [B,kvH,8,D] stage and flush to the
                                     # main cache as ALIGNED 8-row tiles —
                                     # the per-step dynamic_update_slice
                                     # otherwise read-modify-writes a full
                                     # (8,128) tile row per buffer
                                     # (ci/kv_cache_probe.py).  Multi-token
                                     # decode calls (chunked prefill,
                                     # verify passes) flush the stage
                                     # first, so any cur/q_len mix is
                                     # exact; requires max_seq_len % 8 ==
                                     # 0.  The speculative rewind path
                                     # still keeps this off (rewinds move
                                     # the fill index backwards)
    fused_projections: bool = False  # decode-path op-count fusion: one
                                     # qkv matmul + one gate_up matmul per
                                     # layer instead of five (decode is
                                     # launch-overhead-bound at small
                                     # batch; ci/kv_cache_probe.py).  The
                                     # param tree changes (qkv/gate_up
                                     # kernels) — models.generate fuses a
                                     # training tree on the way in
    moe_dispatch: str = "einsum"     # einsum (GShard one-hot) | hybrid
                                     # (einsum dispatch + gather combine —
                                     # halves the O(E*C*D) overhead) | sort
                                     # (argsort scatter/gather — skips it
                                     # entirely, loses on TPU at small E)

    def with_(self, **kw) -> "TransformerConfig":
        return replace(self, **kw)

    @property
    def num_params(self) -> int:
        """Parameter count (embed + per-layer attn/mlp/norms + final norm
        [+ untied output head]); MoE multiplies the MLP by the expert count
        and adds the router."""
        d, l = self.embed_dim, self.num_layers
        attn = d * self.num_heads * self.head_dim * 2  # q + out
        attn += d * self.num_kv_heads * self.head_dim * 2  # k + v
        if self.moe_experts > 0:
            expert_mlp = 3 * d * (self.moe_mlp_dim or self.mlp_dim)
            mlp = self.moe_experts * expert_mlp + d * self.moe_experts
        else:
            mlp = 3 * d * self.mlp_dim  # gate, up, down
        norms = 2 * d
        per_layer = attn + mlp + norms
        embed = self.vocab_size * d
        head = 0 if self.tie_embeddings else self.vocab_size * d
        return embed + l * per_layer + d + head

    def flops_per_token(self, seq_len: int) -> float:
        """Training (fwd+bwd) matmul FLOPs per token: 6x ACTIVATED matmul
        params plus the causal attention term 12*L*S*(H*Dh)/2 (QK^T and AV,
        halved for causality) — the standard MFU accounting (PaLM appendix
        B).  For MoE only the top-k activated experts count (the honest
        sparse-FLOPs convention).

        The embedding table is a lookup (no matmul) when untied, so it is
        excluded; when tied it doubles as the logits matmul weight and
        counts."""
        matmul_params = self.num_params - (
            0 if self.tie_embeddings else self.vocab_size * self.embed_dim
        )
        if self.moe_experts > 0:
            expert_mlp = 3 * self.embed_dim * (self.moe_mlp_dim or self.mlp_dim)
            inactive = self.moe_experts - min(self.moe_top_k, self.moe_experts)
            matmul_params -= self.num_layers * inactive * expert_mlp
            if self.moe_capacity_factor < 1.0:
                # capacity < 1 structurally DROPS routed tokens: the
                # hardware executes at most cf * top_k expert passes per
                # token, so counting the nominal top_k would inflate MFU
                # by 1/cf on the expert share — scale the numerator to
                # what can actually run
                active_mlp = min(self.moe_top_k, self.moe_experts) * expert_mlp
                matmul_params -= self.num_layers * active_mlp * (
                    1.0 - self.moe_capacity_factor)
        attn = 12 * self.num_layers * seq_len * self.num_heads * self.head_dim / 2
        return 6.0 * matmul_params + attn


LLAMA2_7B = TransformerConfig()  # the MaxText v5e-16 headline config

# 13B-class: the int4 single-chip capacity demo (ci/llama13b_decode.py) —
# bf16 weights are 26 GiB (two chips' worth); int4 packs them into ~6.8
# GiB, KV-cache room included on one 16-GiB v5e
LLAMA2_13B = TransformerConfig(
    num_layers=40,
    embed_dim=5120,
    num_heads=40,
    num_kv_heads=40,
    head_dim=128,
    mlp_dim=13_824,
)

GEMMA_7B = TransformerConfig(
    vocab_size=256_128,
    num_layers=28,
    embed_dim=3072,
    num_heads=16,
    num_kv_heads=16,
    head_dim=256,
    mlp_dim=24_576,
    max_seq_len=8192,
    tie_embeddings=True,
    logits_softcap=30.0,
)

# single-chip bench proxy (~0.4B params)
LLAMA2_350M = TransformerConfig(
    num_layers=24,
    embed_dim=1024,
    num_heads=16,
    num_kv_heads=16,
    head_dim=64,
    mlp_dim=2816,
    max_seq_len=2048,
)

# tuned single-chip bench config (~0.47B params): wider layers (K=1536)
# keep the MXU fed — measured ~1.7x the MFU of the 1024-wide proxy on one
# v5e through this image's remote-compile path.  The round-3 sweep
# (ci/mfu_sweep.py, results in ci/sweep_results.jsonl) settled the rest:
#   - chunked CE (loss_chunks=32) never materializes the [B*S, 32k] fp32
#     logits (~6 GiB at batch 48) — the single knob that moves the batch
#     from 24 to 48;
#   - Pallas flash tiles 256x256 beat the kernel's 512 defaults by 39% at
#     batch 48 (0.3196 vs 0.2303 MFU) — smaller tiles double-buffer better
#     in VMEM at this head_dim;
#   - bf16 first-moment (bench.py passes mu_dtype) frees ~0.9 GiB;
#   - batch 50+ and every larger tile combination OOM 16 GiB HBM.
BENCH_CHIP = TransformerConfig(
    num_layers=10,
    embed_dim=1536,
    num_heads=12,
    num_kv_heads=12,
    head_dim=128,
    mlp_dim=6144,
    max_seq_len=2048,
    attention_impl="flash",
    loss_chunks=32,
    # round-5 re-sweep (ci/mfu_sweep_r5.py, ci/sweep_r5_results.jsonl):
    # batch 40 with 1024x512 flash tiles sustains 0.475 MFU / 34.0k tok/s
    # (5 agreeing bench windows) vs the round-3 batch-48/256x256 config's
    # 0.391 best-of-windows — the bigger kv tile is what the 4k config
    # already proved out (flash efficiency, not batch, was the 2k
    # bottleneck); at batch 48 the 256x512/512x512 pairs OOM and 512x256
    # measures ~0.34
    flash_block_q=1024,
    flash_block_k=512,
)

# single-chip MoE bench config: BENCH_CHIP's trunk with the dense MLP
# replaced by 4 experts of half the hidden (top-2 routing) — ~0.76B total
# params, ~0.48B activated, sized so fp32 master + Adam second moment +
# bf16 first moment (~7.5 GiB) leave room for the expert dispatch buffers
# in 16 GiB.  MFU uses the activated-FLOPs convention (configs.py
# flops_per_token), so the one-hot dispatch/combine einsums GShard-style
# dense dispatch pays are honest overhead, not numerator.
BENCH_MOE = BENCH_CHIP.with_(
    moe_experts=4,
    moe_top_k=2,
    moe_mlp_dim=3072,
    # capacity 1.0 measured ~8% faster than 1.25 (round 4) and honest:
    # cf < 1 reads higher raw (0.75 probed +10% in round 5) but executes
    # proportionally fewer expert FLOPs than the numerator counts —
    # flops_per_token scales the expert share by cf when cf < 1, under
    # which 0.75 LOSES (0.227 effective vs 0.255)
    moe_capacity_factor=1.0,
    # round-5 MoE tile x dispatch matrix (ci/sweep_r5 probes): 512x512
    # beats 256x256 (+12%) and 1024x512 at batch 16; hybrid gather-
    # combine beats einsum +8-15% at these tiles
    flash_block_q=512,
    flash_block_k=512,
    moe_dispatch="hybrid",
)

# CI/test config: tiny but structurally identical (GQA, scan, remat)
TINY = TransformerConfig(
    vocab_size=256,
    num_layers=2,
    embed_dim=64,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    mlp_dim=128,
    max_seq_len=128,
    dtype="float32",
    param_dtype="float32",
)

PRESETS = {
    "llama2-7b": LLAMA2_7B,
    "llama2-13b": LLAMA2_13B,
    "gemma-7b": GEMMA_7B,
    "llama2-350m": LLAMA2_350M,
    "bench-chip": BENCH_CHIP,
    "bench-moe": BENCH_MOE,
    "tiny": TINY,
}
