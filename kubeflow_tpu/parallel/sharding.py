"""Logical-axis sharding rules.

Models annotate arrays with *logical* axis names ("batch", "embed", "mlp",
...); these rules bind logical names to the physical mesh axes from
parallel.mesh.  Sharding thereby lives in one table instead of being wired
through every layer — the idiomatic jax/flax pattern (equivalent to MaxText's
logical_axis_rules), and the in-notebook counterpart of the controller's
topology plumbing.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# logical name -> mesh axis (or tuple of axes); None = replicated
DEFAULT_RULES: tuple[tuple[str, object], ...] = (
    ("layers", None),                # nn.scan's stacked-layer axis
    ("batch", ("data", "fsdp")),     # activation batch over all DP-ish axes
    ("seq", "sequence"),             # activation sequence (context parallel)
    ("embed", "fsdp"),               # parameter embed dim (ZeRO-3)
    ("heads", "tensor"),             # attention heads (Megatron)
    ("kv", None),                    # per-head dim stays local
    ("mlp", "tensor"),               # MLP hidden (Megatron)
    ("vocab", "tensor"),             # embedding/logits vocab dim
    ("norm", None),
)


def rules_for_mesh(mesh: Mesh) -> tuple[tuple[str, object], ...]:
    """DEFAULT_RULES specialized to the mesh's populated axes:

    - a populated "pipeline" axis shards the stacked "layers" param axis
      stage-wise (parallel.pipeline's GPipe engine consumes exactly that
      layout);
    - the "expert" logical axis (MoE expert stack + dispatched token
      buffers, models.moe) shards over the mesh's "expert" axis; XLA
      inserts the dispatch/combine all-to-alls the einsum shardings imply
      (the GShard recipe);
    - everything else is DEFAULT_RULES.
    """
    rules = [(name, ax) for name, ax in DEFAULT_RULES if name != "layers"]
    if mesh.shape.get("pipeline", 1) > 1:
        rules.insert(0, ("layers", "pipeline"))
    else:
        rules.insert(0, ("layers", None))
    rules.append(("expert", "expert" if mesh.shape.get("expert", 1) > 1
                  else None))
    return tuple(rules)


def rules_dict(
    rules: Optional[Sequence[tuple[str, object]]] = None,
) -> dict[str, object]:
    return dict(rules if rules is not None else DEFAULT_RULES)


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Sequence[tuple[str, object]]] = None,
) -> PartitionSpec:
    """("batch", "seq", "embed") -> PartitionSpec(("data","fsdp"),
    "sequence", None).

    A physical mesh axis may shard only one dimension; later logical axes
    skip mesh axes already claimed by earlier ones (the same
    first-come-first-served resolution flax's rule engine applies), so e.g.
    "embed" -> "fsdp" yields None here because "batch" already took fsdp."""
    table = rules_dict(rules)
    used: set[str] = set()
    entries = []
    for axis in logical_axes:
        mapped = table.get(axis) if axis is not None else None
        if mapped is None:
            entries.append(None)
            continue
        axes = mapped if isinstance(mapped, tuple) else (mapped,)
        free = tuple(a for a in axes if a not in used)
        used.update(free)
        if not free:
            entries.append(None)
        elif len(free) == 1:
            entries.append(free[0])
        else:
            entries.append(free)
    return PartitionSpec(*entries)


def logical_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Sequence[tuple[str, object]]] = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def constrain(
    x: jax.Array,
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Sequence[tuple[str, object]]] = None,
) -> jax.Array:
    """with_sharding_constraint by logical names — the hint that keeps XLA
    from resharding activations mid-layer."""
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical_axes, rules)
    )


def tree_shardings(mesh: Mesh, logical_tree, rules=None):
    """Map a pytree of logical-axis tuples to NamedShardings (for jit
    in_shardings/out_shardings of whole parameter trees)."""
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        logical_tree,
        is_leaf=lambda v: isinstance(v, tuple),
    )
