"""Field selectors on list/watch (kube/wire.py parse_field_selector).

The reference's culler and event plumbing rely on the apiserver's field
selectors (e.g. client-go listing Events by involvedObject).  The wire
server evaluates dotted-path terms server-side; unset fields compare as
"" per apiserver convention.
"""

from __future__ import annotations

import threading

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.kube import ApiServer, KubeObject, ObjectMeta
from kubeflow_tpu.kube.client import KubeClient, RestConfig
from kubeflow_tpu.kube.wire import (
    KubeApiWireServer,
    match_fields,
    parse_field_selector,
)


class TestParser:
    def test_equality_and_inequality(self):
        sel = parse_field_selector(
            "metadata.name=wb,status.phase==Running,spec.nodeName!=n1")
        assert sel == [("metadata.name", True, "wb"),
                       ("status.phase", True, "Running"),
                       ("spec.nodeName", False, "n1")]

    def test_invalid_segment_raises(self):
        with pytest.raises(ValueError):
            parse_field_selector("metadata.name")

    def test_empty_is_noop(self):
        assert parse_field_selector("") == []


class TestMatcher:
    def test_dotted_path(self):
        obj = {"metadata": {"name": "wb"},
               "involvedObject": {"kind": "Notebook", "name": "wb"}}
        assert match_fields(obj, parse_field_selector(
            "involvedObject.kind=Notebook,involvedObject.name=wb"))
        assert not match_fields(obj, parse_field_selector(
            "involvedObject.kind=Pod"))

    def test_unset_field_matches_empty(self):
        assert match_fields({}, parse_field_selector("spec.nodeName="))
        assert match_fields({}, parse_field_selector("spec.nodeName!=n1"))

    def test_numbers_and_bools_stringify(self):
        obj = {"status": {"readyReplicas": 3, "ready": True}}
        assert match_fields(obj, parse_field_selector(
            "status.readyReplicas=3,status.ready=true"))

    def test_non_scalar_never_matches(self):
        obj = {"spec": {"containers": [{"name": "a"}]}}
        assert not match_fields(obj, parse_field_selector("spec.containers=x"))


class TestOverTheWire:
    @pytest.fixture()
    def wire(self):
        api = ApiServer()
        srv = KubeApiWireServer(api).start()
        client = KubeClient(RestConfig(server=srv.url))
        yield api, client
        client.stop_informers()
        srv.stop()

    def test_list_filters_by_name(self, wire):
        _, client = wire
        for name in ("a", "b", "c"):
            client.create(Notebook.new(name, "default").obj)
        got = client.list("Notebook", "default",
                          field_selector="metadata.name=b")
        assert [o.name for o in got] == ["b"]
        got = client.list("Notebook", "default",
                          field_selector="metadata.name!=b")
        assert [o.name for o in got] == ["a", "c"]

    def test_list_events_by_involved_object(self, wire):
        _, client = wire
        for nb, reason in [("wb1", "Created"), ("wb2", "Failed")]:
            client.create(KubeObject(
                "v1", "Event",
                ObjectMeta(name=f"ev-{nb}", namespace="default"),
                body={"involvedObject": {"kind": "Notebook", "name": nb},
                      "reason": reason, "type": "Normal"}))
        got = client.list(
            "Event", "default",
            field_selector="involvedObject.name=wb2,involvedObject.kind=Notebook")
        assert [o.name for o in got] == ["ev-wb2"]

    def test_delete_collection_with_selectors(self, wire):
        """DELETE on the collection path (kubectl delete --all): only
        selector matches go, and the deleted items come back as a List."""
        import json
        import urllib.request
        api, client = wire
        for name, team in [("a", "ml"), ("b", "web"), ("c", "ml")]:
            nb = Notebook.new(name, "default").obj
            nb.metadata.labels["team"] = team
            client.create(nb)
        req = urllib.request.Request(
            client.config.server
            + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
            + "?labelSelector=team%3Dml&fieldSelector=metadata.name%21%3Da",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.load(resp)
        assert body["kind"] == "NotebookList"
        assert [i["metadata"]["name"] for i in body["items"]] == ["c"]
        assert sorted(o.name for o in client.list("Notebook", "default")) \
            == ["a", "b"]

    def test_delete_collection_cluster_scope_spans_namespaces(self, wire):
        """A cluster-scope collection DELETE (no namespace segment) must
        delete each item in its OWN namespace, not silently no-op."""
        import json
        import urllib.request
        api, client = wire
        for ns in ("team-a", "team-b"):
            client.create(Notebook.new("wb", ns).obj)
        req = urllib.request.Request(
            client.config.server + "/apis/kubeflow.org/v1/notebooks",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.load(resp)
        assert len(body["items"]) == 2
        assert client.list("Notebook") == []

    def test_invalid_selector_answers_400(self, wire):
        import urllib.error
        import urllib.request
        api, client = wire
        url = (client.config.server
               + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
               + "?fieldSelector=bogus")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(url)
        assert exc.value.code == 400

    def test_watch_respects_field_selector(self, wire):
        api, client = wire
        import json
        import urllib.request
        url = (client.config.server
               + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
               + "?watch=true&fieldSelector=metadata.name%3Dwanted")
        seen: list[str] = []
        ready = threading.Event()

        def consume():
            req = urllib.request.urlopen(url, timeout=10)
            ready.set()
            for line in req:
                seen.append(json.loads(line)["object"]["metadata"]["name"])
                if seen:
                    break

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        ready.wait(5)
        api.create(Notebook.new("other", "default").obj)
        api.create(Notebook.new("wanted", "default").obj)
        t.join(timeout=10)
        assert seen == ["wanted"], "filtered watch only streams matches"

    def test_watch_synthesizes_transitions(self, wire):
        """An object editing out of the selected set must stream a
        synthetic DELETED (and editing in, an ADDED) — the apiserver's
        cacher semantics; plain skipping strands informer caches."""
        import json
        import urllib.request
        api, client = wire
        url = (client.config.server
               + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
               + "?watch=true&fieldSelector="
               + "metadata.annotations.tier%3Dgold")
        seen: list[tuple[str, str]] = []
        ready = threading.Event()

        def consume():
            req = urllib.request.urlopen(url, timeout=10)
            ready.set()
            for line in req:
                ev = json.loads(line)
                seen.append((ev["type"], ev["object"]))
                if len(seen) >= 3:
                    break

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        ready.wait(5)
        nb = Notebook.new("wb", "default").obj
        api.create(nb)                       # no annotation: outside the set
        cur = api.get("Notebook", "default", "wb")
        cur.metadata.annotations["tier"] = "gold"
        cur = api.update(cur)                # edits IN  -> ADDED
        cur.metadata.annotations["note"] = "x"
        cur = api.update(cur)                # stays in  -> MODIFIED
        cur.metadata.annotations["tier"] = "bronze"
        final = api.update(cur)              # edits OUT -> synthetic DELETED
        t.join(timeout=10)
        assert [(t_, o["metadata"]["name"]) for t_, o in seen] == [
            ("ADDED", "wb"), ("MODIFIED", "wb"), ("DELETED", "wb")], seen
        # the synthetic DELETED carries the LAST IN-SET state (the cacher's
        # shape), stamped with the event's resourceVersion
        deleted = seen[2][1]
        assert deleted["metadata"]["annotations"]["tier"] == "gold"
        assert deleted["metadata"]["resourceVersion"] == \
            str(final.metadata.resource_version)

    def test_resumed_watch_replays_transitions(self, wire):
        """A watch resuming from an older resourceVersion must still see
        the synthetic DELETED for an edit-out that happened while it was
        away — history replay carries the pre-update state too."""
        import json
        import urllib.request
        api, client = wire
        nb = Notebook.new("wb", "default").obj
        nb.metadata.labels["team"] = "ml"
        created = api.create(nb)
        rv = created.metadata.resource_version
        # while "away": the label is removed (edit-out), then a decoy update
        cur = api.get("Notebook", "default", "wb")
        del cur.metadata.labels["team"]
        api.update(cur)
        url = (client.config.server
               + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
               + f"?watch=true&labelSelector=team%3Dml&resourceVersion={rv}")
        req = urllib.request.urlopen(url, timeout=10)
        line = next(iter(req))
        ev = json.loads(line)
        assert ev["type"] == "DELETED", ev
        assert ev["object"]["metadata"]["labels"]["team"] == "ml", \
            "replayed synthetic DELETED carries the last in-set state"

    def test_label_selector_watch_synthesizes_transitions(self, wire):
        """Label selectors get the same selected-set semantics as field
        selectors — removing a watched label must stream a DELETED."""
        import json
        import urllib.request
        api, client = wire
        url = (client.config.server
               + "/apis/kubeflow.org/v1/namespaces/default/notebooks"
               + "?watch=true&labelSelector=team%3Dml")
        seen: list[tuple[str, str]] = []
        ready = threading.Event()

        def consume():
            req = urllib.request.urlopen(url, timeout=10)
            ready.set()
            for line in req:
                ev = json.loads(line)
                seen.append((ev["type"], ev["object"]["metadata"]["name"]))
                if len(seen) >= 2:
                    break

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        ready.wait(5)
        nb = Notebook.new("wb", "default").obj
        nb.metadata.labels["team"] = "ml"
        api.create(nb)                         # in set -> ADDED
        cur = api.get("Notebook", "default", "wb")
        del cur.metadata.labels["team"]
        api.update(cur)                        # label removed -> DELETED
        t.join(timeout=10)
        assert seen == [("ADDED", "wb"), ("DELETED", "wb")], seen
