"""Chaos-model validation + fault-injection drills.

Two halves, mirroring the reference's shift-left chaos CI (SURVEY.md §4.6):
1. the knowledge model (chaos/knowledge/workbenches.yaml) must stay in sync
   with what the controllers actually create — a drift check;
2. the declared fault injections actually hold: kill/fail a worker, delete a
   route, and watch level-triggered reconciliation restore steady state.
"""

from pathlib import Path

import pytest
import yaml

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.odh import constants as OC
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

KNOWLEDGE = Path(__file__).parent.parent / "chaos" / "knowledge" / "workbenches.yaml"
CENTRAL_NS = "opendatahub"


@pytest.fixture()
def env():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 4, 4)
    mgr = Manager(api, clock=FakeClock())
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
    return api, cluster, mgr


def knowledge():
    return yaml.safe_load(KNOWLEDGE.read_text())


class TestKnowledgeModel:
    def test_model_parses_and_names_controllers(self):
        model = knowledge()
        names = {c["name"] for c in model["controllers"]}
        assert names == {
            "notebook-controller", "culling-controller", "odh-notebook-controller",
        }
        assert all(c["primary"] == "Notebook" for c in model["controllers"])

    def test_managed_kinds_match_reality(self, env):
        """Drift check: every kind the stack creates for a TPU+auth notebook
        is declared in the model, and vice versa for non-optional kinds."""
        api, _, mgr = env
        nb = Notebook.new(
            "drift", "user1", tpu=TPUSpec("v5e", "4x4"),
            annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
        )
        api.create(nb.obj)
        mgr.run_until_idle()
        created_kinds = {
            kind
            for kind, objs in api.dump().items()
            if kind not in ("Notebook", "Node", "Pod", "Event")
            and any(
                o["metadata"].get("namespace") in ("user1", CENTRAL_NS, "")
                for o in objs
            )
        }
        model = knowledge()
        declared = {
            m["kind"]
            for c in model["controllers"]
            for m in c["manages"]
        }
        undeclared = created_kinds - declared
        assert not undeclared, f"created but not in chaos model: {undeclared}"

    def test_steady_state_timeout_declared(self):
        model = knowledge()
        assert all(s["timeout_seconds"] <= 60 for s in model["steady_state"])


class TestFaultInjection:
    def _healthy_tpu_nb(self, api, mgr, name="chaos-nb"):
        nb = Notebook.new(name, "user1", tpu=TPUSpec("v5e", "4x4"))
        api.create(nb.obj)
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        return name

    def test_kill_worker_pod_recovers(self, env):
        api, cluster, mgr = env
        name = self._healthy_tpu_nb(api, mgr)
        api.delete("Pod", "user1", f"{name}-2")
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        assert status["readyReplicas"] == 4

    def test_failed_worker_degrades_then_restart_recovers(self, env):
        api, cluster, mgr = env
        name = self._healthy_tpu_nb(api, mgr)
        cluster.fail_pod("user1", f"{name}-1")
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Degraded"
        # slice-atomic restart via the restart annotation
        live = api.get("Notebook", "user1", name)
        live.metadata.annotations["notebooks.opendatahub.io/notebook-restart"] = "true"
        api.update(live)
        mgr.run_until_idle()
        status = api.get("Notebook", "user1", name).body["status"]
        assert status["sliceHealth"] == "Healthy"
        live = api.get("Notebook", "user1", name)
        assert "notebooks.opendatahub.io/notebook-restart" not in (
            live.metadata.annotations
        )

    def test_delete_route_recreated(self, env):
        api, _, mgr = env
        name = self._healthy_tpu_nb(api, mgr)
        route_name = f"nb-user1-{name}"
        api.delete("HTTPRoute", CENTRAL_NS, route_name)
        mgr.run_until_idle()
        assert api.try_get("HTTPRoute", CENTRAL_NS, route_name) is not None
