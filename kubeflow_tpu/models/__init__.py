"""Model zoo for the BASELINE workload matrix: MNIST MLP, ViT, and the
Llama/Gemma decoder family with sharded training (models.train).

Exports are lazy (PEP 562, same pattern as ops/__init__): configs.py is
pure dataclasses, and the control plane (telemetry stamping, roofline
math, the --demo manager) imports `models.configs` without dragging
jax/flax in; `from kubeflow_tpu.models import Transformer` still
resolves exactly as before."""

import importlib

_LAZY = {
    "GEMMA_7B": ".configs",
    "LLAMA2_7B": ".configs",
    "LLAMA2_350M": ".configs",
    "PRESETS": ".configs",
    "TINY": ".configs",
    "TransformerConfig": ".configs",
    "MLP": ".mlp",
    "Transformer": ".transformer",
    "VIT_B16": ".vit",
    "VIT_TINY": ".vit",
    "ViT": ".vit",
    "ViTConfig": ".vit",
}

__all__ = [
    "GEMMA_7B", "LLAMA2_7B", "LLAMA2_350M", "MLP", "PRESETS", "TINY",
    "Transformer", "TransformerConfig", "VIT_B16", "VIT_TINY", "ViT",
    "ViTConfig",
]


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(target, __name__)
    value = getattr(mod, name)
    globals()[name] = value  # cache: resolve each export once
    return value
