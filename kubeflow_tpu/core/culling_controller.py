"""Culling controller: idle detection -> scale-to-zero.

Port of CullingReconciler
(components/notebook-controller/controllers/culling_controller.go:73-588)
with two TPU extensions: culling is slice-atomic by construction (the stop
annotation scales every slice StatefulSet to zero — partial stops cannot
exist), and an optional checkpoint-before-cull handshake gives the
in-notebook runtime a grace window to snapshot JAX state before the slice
goes away (SURVEY.md §5 'Checkpoint/resume')."""

from __future__ import annotations

import logging
from typing import Optional

from ..api.types import Notebook
from ..kube import ApiServer, Manager, Request, Result, retry_on_conflict
from ..tpu import env as tpuenv
from ..utils import tracing
from ..utils.clock import Clock
from ..utils.config import CoreConfig
from . import constants as C
from . import culler
from .jupyter import JupyterAPI
from .metrics import NotebookMetrics
from .preemption import pending_preemption

logger = logging.getLogger("kubeflow_tpu.culling")

_TRACER = tracing.get_tracer("kubeflow_tpu.core.culling")

# annotation the in-notebook runtime sets once its pre-cull checkpoint is done
CHECKPOINT_COMPLETE_ANNOTATION = C.ANNOTATION_CHECKPOINT_COMPLETE


class CullingReconciler:
    def __init__(
        self,
        api: ApiServer,
        cfg: CoreConfig,
        jupyter: JupyterAPI,
        metrics: NotebookMetrics,
        clock: Optional[Clock] = None,
        cache=None,
    ):
        self.api = api
        self.cfg = cfg
        self.jupyter = jupyter
        self.metrics = metrics
        self.clock = clock or Clock()
        # informer cache for probe-path reads (pod-0 existence, period
        # gate); annotation writes still read-modify-write the live object
        self.cache = cache

    def _requeue(self) -> Result:
        return Result(requeue_after=self.cfg.idleness_check_period_min * 60)

    def reconcile(self, req: Request) -> Result:
        obj = self.api.try_get("Notebook", req.namespace, req.name)
        if obj is None:
            return Result()
        nb = Notebook(obj)

        # already stopping: drop activity annotations, no requeue (:105-118)
        if culler.stop_annotation_is_set(obj.metadata):
            self._mutate(req, culler.remove_activity_annotations)
            return Result()

        # worker-0 pod of slice 0 runs the Jupyter server; without it there
        # is nothing to probe (:121-136)
        num_slices = nb.tpu.slices if nb.tpu else 1
        sts0 = tpuenv.statefulset_name(nb.name, 0, num_slices)
        reader = self.cache if self.cache is not None else self.api
        pod0 = reader.try_get("Pod", req.namespace, f"{sts0}-0")
        if pod0 is None:
            self._mutate(req, culler.remove_activity_annotations)
            return Result()

        # initialize annotations (:142-154)
        if not culler.annotations_exist(obj.metadata):
            self._mutate(
                req, lambda meta: culler.initialize_annotations(meta, self.clock)
            )

        # period gate (:157-160) — cache read: the common case is "period
        # not passed yet", which must not cost an API round trip
        live = reader.try_get("Notebook", req.namespace, req.name)
        if live is None:
            return Result()
        if not culler.culling_check_period_has_passed(
            live.metadata, self.clock, self.cfg.idleness_check_period_min
        ):
            return self._requeue()

        # idle probe + cull decision under a 'culling' phase span, so a
        # trace shows whether an idle notebook was culled, held for a
        # checkpoint, or found active again
        with _TRACER.start_span(
            "culling", {"phase": "culling", "namespace": req.namespace,
                        "notebook": req.name}
        ) as span:
            # probe Jupyter outside the retry loop (:163-169)
            kernels = self.jupyter.get_kernels(req.name, req.namespace)
            terminals = self.jupyter.get_terminals(req.name, req.namespace)

            def apply(meta) -> None:
                culler.update_last_activity_from_kernels(meta, kernels, self.clock)
                culler.update_last_activity_from_terminals(meta, terminals, self.clock)
                culler.update_last_culling_check_timestamp(meta, self.clock)
                if not culler.notebook_is_idle(
                    meta, self.clock, self.cfg.cull_idle_time_min
                ):
                    # activity resumed: reset the checkpoint handshake so the
                    # next idle period gets a fresh request + grace window
                    culler.remove_checkpoint_annotations(meta)
                    self._clear_cull_signal(nb)
                else:
                    if self._should_wait_for_checkpoint(nb, meta):
                        span.add_event("culling.checkpoint_wait")
                        return
                    if pending_preemption(self.api, req.namespace, req.name):
                        # a write-ahead preemption record owns this
                        # notebook's teardown and claim release; a stop
                        # annotation landing mid-eviction would race the
                        # engine for the pool claims.  Hold the cull —
                        # the requeue re-checks after the record closes.
                        span.add_event("culling.preemption_wait")
                        return
                    logger.info("culling notebook %s/%s", req.namespace, req.name)
                    span.add_event("notebook.culled")
                    self._clear_cull_signal(nb)
                    culler.set_stop_annotation(meta, self.clock)
                    self.metrics.culling.labels(req.namespace, req.name).inc()
                    self.metrics.last_culling_timestamp.labels(
                        req.namespace, req.name
                    ).set(self.clock.now())

            self._mutate(req, apply)
        return self._requeue()

    def _should_wait_for_checkpoint(self, nb: Notebook, meta) -> bool:
        """Checkpoint-before-cull handshake (TPU extension, off by default):
        on the first idle verdict, stamp checkpoint-requested — and, when a
        signal root is configured (CHECKPOINT_SIGNAL_ROOT), write the
        actual cull-signal request file the in-pod CullSignalWatcher
        polls, so checkpoint-on-cull genuinely fires.  The cull then holds
        until the runtime acknowledges (ack file or checkpoint-complete
        annotation) or the grace window (one idleness period) expires —
        only after that does the stop annotation land and the slice
        transition toward Stopping."""
        if not (self.cfg.checkpoint_before_cull and nb.tpu is not None):
            return False
        requested = meta.annotations.get(C.ANNOTATION_CHECKPOINT_REQUESTED)
        if requested is None:
            meta.annotations[C.ANNOTATION_CHECKPOINT_REQUESTED] = (
                self.clock.now_iso()
            )
            self._write_cull_signal(nb)
            return True
        if self._checkpoint_acknowledged(nb, meta):
            return False
        from ..utils.clock import parse_iso

        try:
            grace_end = parse_iso(requested) + self.cfg.idleness_check_period_min * 60
        except ValueError:
            return False
        return self.clock.now() < grace_end

    # -- cull-signal file transport (runtime/checkpoint.py contract) -----------
    def _signal_dir(self, nb: Notebook):
        if not self.cfg.checkpoint_signal_root:
            return None
        from pathlib import Path

        return Path(self.cfg.checkpoint_signal_root) / nb.namespace / nb.name

    def _write_cull_signal(self, nb: Notebook) -> None:
        d = self._signal_dir(nb)
        if d is None:
            return
        from ..runtime.checkpoint import REQUEST_FILE

        try:
            d.mkdir(parents=True, exist_ok=True)
            (d / REQUEST_FILE).write_text("true")
        except OSError:
            logger.warning("could not write cull signal under %s", d)

    def _checkpoint_acknowledged(self, nb: Notebook, meta) -> bool:
        """Either side of the transport counts: the checkpoint-complete
        annotation (downward-API-less runtimes PATCH it directly) or the
        ack file next to the signal request."""
        if C.ANNOTATION_CHECKPOINT_COMPLETE in meta.annotations:
            return True
        d = self._signal_dir(nb)
        if d is None:
            return False
        from ..runtime.checkpoint import ACK_FILE

        if not (d / ACK_FILE).exists():
            return False
        # mirror the ack into the annotation so the decision is visible on
        # the CR (and survives signal-dir cleanup), and account the
        # snapshot exactly once
        meta.annotations[C.ANNOTATION_CHECKPOINT_COMPLETE] = \
            self.clock.now_iso()
        self.metrics.checkpoint_snapshots.labels(
            nb.namespace, "cull").inc()
        return True

    def _clear_cull_signal(self, nb: Notebook) -> None:
        """Activity resumed (or the cull completed): retire both signal
        files so a stale request/ack never leaks into the next idle
        cycle — the file-transport twin of remove_checkpoint_annotations."""
        d = self._signal_dir(nb)
        if d is None:
            return
        from ..runtime.checkpoint import ACK_FILE, REQUEST_FILE

        for name in (REQUEST_FILE, ACK_FILE):
            try:
                (d / name).unlink()
            except OSError:
                pass

    def _mutate(self, req: Request, fn) -> None:
        """Read-modify-write on the CR metadata with conflict retry — the
        reference wraps every annotation write the same way
        (culling_controller.go:107,125,144,172)."""

        def attempt() -> None:
            live = self.api.get("Notebook", req.namespace, req.name)
            before = dict(live.metadata.annotations)
            fn(live.metadata)
            if live.metadata.annotations != before:
                self.api.update(live)

        retry_on_conflict(attempt)


def setup_culling(
    mgr: Manager,
    cfg: Optional[CoreConfig] = None,
    jupyter: Optional[JupyterAPI] = None,
    metrics: Optional[NotebookMetrics] = None,
) -> Optional[CullingReconciler]:
    """Register the culler, gated on ENABLE_CULLING (main.go:111-123)."""
    cfg = cfg or CoreConfig.from_env()
    if not cfg.enable_culling:
        return None
    if jupyter is None:
        from .jupyter import HttpJupyterClient

        jupyter = HttpJupyterClient(cfg.cluster_domain, cfg.dev)
    metrics = metrics or NotebookMetrics(mgr.api)
    rec = CullingReconciler(mgr.api, cfg, jupyter, metrics, clock=mgr.clock,
                            cache=mgr.cache)
    from ..kube import suppress_status_only

    # the culler keys off annotations + pod liveness, never Notebook
    # status: the notebook controller's status writes must not wake it
    mgr.register("culling", rec, for_kind="Notebook",
                 for_predicate=suppress_status_only)
    return rec
