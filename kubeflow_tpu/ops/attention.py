"""Attention ops: causal multi-head/GQA attention for TPU.

Three execution paths, chosen by `attention()`:
  - "flash": the Pallas TPU flash-attention kernel (jax.experimental.pallas
    .ops.tpu) — VMEM-blocked online softmax, the MXU-friendly hot path.
  - "xla": plain einsum attention. XLA fuses the softmax chain well on TPU;
    also the numerics reference for tests and the CPU fallback.
  - ring attention lives in ops.ring_attention (sequence-parallel shard_map).

Shapes follow the [batch, seq, heads, head_dim] convention throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def causal_mask_bias(q_len: int, kv_len: int, q_offset: int = 0, dtype=jnp.float32):
    """Additive -inf bias above the causal diagonal.  q_offset shifts query
    positions for ring/blockwise variants where the local q block starts at a
    global position > 0."""
    q_pos = jnp.arange(q_len)[:, None] + q_offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return jnp.where(q_pos >= kv_pos, 0.0, -jnp.inf).astype(dtype)


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """GQA: tile kv heads up to the query head count."""
    num_kv = k.shape[2]
    if num_kv == num_q_heads:
        return k
    return jnp.repeat(k, num_q_heads // num_kv, axis=2)


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    q_offset: int = 0,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference einsum attention in fp32 accumulation."""
    *_, head_dim = q.shape
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        scores = scores + causal_mask_bias(q.shape[1], k.shape[1], q_offset)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    q_offset,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """KV-cache attention with the cache in [B, kvH, S, D] layout.

    Decode is HBM-bound: every step reads the full static cache, so the
    cache layout must be what the dots consume DIRECTLY.  The [B,S,H,D]
    activation layout xla_attention takes needs a [B,H,S,D] transpose of
    both K and V per step — XLA materializes that as a copy, roughly
    1.5x-ing the KV traffic the roofline counts once (measured on the
    470M decode bench: 60% -> see BASELINE.md round-5 row).  Here the
    caches arrive pre-transposed (the per-step write transposes only the
    NEW token's [B,1,kvH,D] slab) and grouped-query heads fold into the
    q reshape instead of a materialized _repeat_kv.

    q: [B, Q, H, D] (Q = 1, or gamma+1 in speculative verify);
    k_cache/v_cache: [B, kvH, S, D]; q_offset: global position of q[0]
    (traced scalar) — masks unwritten/future cache slots."""
    batch, q_len, num_heads, head_dim = q.shape
    kv_heads, kv_len = k_cache.shape[1], k_cache.shape[2]
    groups = num_heads // kv_heads
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    qg = q.reshape(batch, q_len, kv_heads, groups, head_dim)
    scores = jnp.einsum(
        "bqkgd,bksd->bkgqs", qg, k_cache,
        preferred_element_type=jnp.float32,
    ) * scale
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    visible = jnp.arange(kv_len)[None, :] <= q_pos        # [Q, S]
    scores = jnp.where(visible[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("bkgqs,bksd->bqkgd", probs, v_cache)
    return out.reshape(batch, q_len, num_heads, head_dim)


def decode_attention_staged(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    k_stage: jax.Array,
    v_stage: jax.Array,
    flushed,
    fill,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """decode_attention over a main cache + an 8-row staging buffer.

    Invariant (transformer.py staged_kv path): the main cache holds
    global rows [0, flushed) with `flushed` 8-aligned; the stage holds
    rows [flushed, fill) at slots [0, fill-flushed).  One softmax spans
    both (concatenated score axis), so the result is exactly
    decode_attention over the logically-merged cache.  Single-token
    queries only (q_len == 1 — multi-token prefill writes the main cache
    directly and uses decode_attention)."""
    batch, q_len, num_heads, head_dim = q.shape
    if q_len != 1:
        raise ValueError("staged decode attention is single-token only")
    kv_heads, kv_len = k_cache.shape[1], k_cache.shape[2]
    stage_len = k_stage.shape[2]
    groups = num_heads // kv_heads
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    qg = q.reshape(batch, q_len, kv_heads, groups, head_dim)
    s_main = jnp.einsum(
        "bqkgd,bksd->bkgqs", qg, k_cache,
        preferred_element_type=jnp.float32) * scale
    s_stage = jnp.einsum(
        "bqkgd,bksd->bkgqs", qg, k_stage,
        preferred_element_type=jnp.float32) * scale
    vis_main = jnp.arange(kv_len) < flushed                 # [S]
    vis_stage = (flushed + jnp.arange(stage_len)) < fill    # [8]
    s_main = jnp.where(vis_main[None, None, None, None], s_main, -1e30)
    s_stage = jnp.where(vis_stage[None, None, None, None], s_stage, -1e30)
    scores = jnp.concatenate([s_main, s_stage], axis=-1)
    probs = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    p_main, p_stage = probs[..., :kv_len], probs[..., kv_len:]
    out = (jnp.einsum("bkgqs,bksd->bqkgd", p_main, v_cache)
           + jnp.einsum("bkgqs,bksd->bqkgd", p_stage, v_stage))
    return out.reshape(batch, q_len, num_heads, head_dim)


@functools.cache
def _pallas_flash():
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as kernel,
    )

    return kernel


@functools.cache
def _block_sizes(block_q: int, block_k: int, q_len: int, kv_len: int):
    """Pallas tile config; clamped to the sequence so short sequences and
    tuned tiles compose.  The same tiling is used for the dq/dkv backward
    passes — one knob pair, applied consistently."""
    if not block_q and not block_k:
        return None
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    bq = min(block_q or 512, q_len)
    bk = min(block_k or 512, kv_len)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_k_dkv=bk, block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq,
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int = 0,
    block_k: int = 0,
) -> jax.Array:
    """Pallas TPU flash attention (expects [b, h, s, d]; we carry
    [b, s, h, d] and transpose at the boundary — XLA folds the transposes
    into the surrounding copies).  block_q/block_k override the kernel's
    default VMEM tiling (0 = kernel default)."""
    *_, head_dim = q.shape
    scale = softmax_scale if softmax_scale is not None else head_dim**-0.5
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    out = _pallas_flash()(
        q.transpose(0, 2, 1, 3),
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        causal=causal,
        sm_scale=scale,
        block_sizes=_block_sizes(block_q, block_k, q.shape[1], k.shape[1]),
    )
    return out.transpose(0, 2, 1, 3)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    impl: str = "auto",
    softmax_scale: Optional[float] = None,
    block_q: int = 0,
    block_k: int = 0,
    q_offset=0,
) -> jax.Array:
    """Dispatch: flash on TPU when the shape fits the kernel's tiling
    (seq multiple of the 128-lane block, head_dim >= 128-friendly), else XLA.
    q_offset (global position of the first query; may be traced) forces the
    XLA path — the decode KV-cache reads use it.
    """
    offset = q_offset is not None and (
        not isinstance(q_offset, int) or q_offset != 0)
    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        seq_ok = q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
        impl = "flash" if (on_tpu and seq_ok and not offset) else "xla"
    if impl == "flash":
        if offset:
            raise ValueError("flash attention path has no q_offset support")
        return flash_attention(q, k, v, causal=causal,
                               softmax_scale=softmax_scale,
                               block_q=block_q, block_k=block_k)
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal,
                             softmax_scale=softmax_scale,
                             q_offset=q_offset)
    raise ValueError(f"unknown attention impl {impl!r}")
