"""Convergence benchmark: N notebooks -> all Ready, deterministically.

`start_notebooks.py` measures wall-clock readiness latency — useful, but
noisy and machine-dependent, so CI cannot assert on it.  This benchmark
measures what IS deterministic on the FakeClock: how much work the control
plane does to converge a fleet, and whether it then goes quiet.

    python loadtest/convergence.py --count 200 --compare-workers 8 \
        --check-budget ci/apiserver_call_budget.json

Per run it reports:
  - wall time (informational only — never asserted);
  - reconciles per notebook, per controller (Manager reconcile counters);
  - API verbs by (verb, kind) from the ApiServer's top-level verb counters
    (reads included; the fault-exempt FakeCluster data plane is excluded);
  - steady-state probe: after convergence, a full resync (`enqueue_all`)
    must complete with ZERO write verbs in the audit log — proving the
    no-op write suppression end to end — and at most one reconcile per
    (controller, object);
  - per-key serialization: the flight recorder's attempt-overlap check
    must come back empty (no two concurrent reconciles of one key).

`--compare-workers W` runs the same fleet again with W parallel workers
and asserts the normalized final cluster state (resourceVersions, uids,
timestamps, pod IPs scrubbed; uids rewritten to stable object references)
is identical to the single-worker run.

`--check-budget FILE` compares writes-per-notebook and
reconciles-per-notebook against the committed budget and fails on >
`tolerance` regression — the deterministic CI perf gate.  Regenerate an
intentionally-changed budget with `--write-budget FILE`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from kubeflow_tpu.api.types import Notebook, TPUSpec  # noqa: E402
from kubeflow_tpu.core.notebook_controller import (  # noqa: E402
    setup_core_controllers,
)
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager  # noqa: E402
from kubeflow_tpu.utils.clock import FakeClock  # noqa: E402
from kubeflow_tpu.utils.config import CoreConfig  # noqa: E402
from kubeflow_tpu.utils.flightrecorder import FlightRecorder  # noqa: E402

NAMESPACE = "loadtest"

# non-deterministic or server-managed fields scrubbed before comparing the
# final cluster state of two runs (uids are MAPPED, not dropped — ownership
# topology must still match)
_SCRUB_KEYS = frozenset({
    "resourceVersion", "creationTimestamp", "managedFields",
    "lastTransitionTime", "lastProbeTime", "startedAt", "startTime",
    "time", "podIP",
})


def normalized_state(api: ApiServer) -> dict:
    """api.dump() with volatile fields scrubbed and every uid replaced by
    the stable identity of the object it names, so two runs of the same
    fleet compare equal iff they converged to the same semantic state."""
    dump = api.dump()
    uid_names: dict[str, str] = {}
    for kind, objs in dump.items():
        for o in objs:
            meta = o.get("metadata", {})
            if meta.get("uid"):
                uid_names[meta["uid"]] = "%s/%s/%s" % (
                    kind, meta.get("namespace", ""), meta.get("name", ""))

    def scrub(node):
        if isinstance(node, dict):
            out = {}
            for k, v in node.items():
                if k in _SCRUB_KEYS:
                    continue
                if k == "uid" and isinstance(v, str):
                    out[k] = uid_names.get(v, v)
                else:
                    out[k] = scrub(v)
            return out
        if isinstance(node, list):
            return [scrub(x) for x in node]
        return node

    out = {}
    for kind, objs in sorted(dump.items()):
        if kind == "Event":
            continue  # event names/counts are sequencing artifacts
        out[kind] = sorted(
            (scrub(o) for o in objs),
            key=lambda o: (o["metadata"].get("namespace", ""),
                           o["metadata"]["name"]))
    return out


def _reconciles_per_controller(mgr: Manager) -> dict[str, int]:
    out: dict[str, int] = {}
    for key, v in mgr.reconcile_total.collect().items():
        out[key[0]] = out.get(key[0], 0) + int(v)
    return out


def run_fleet(count: int, workers: int, tpu: str = "") -> dict:
    api = ApiServer()
    cluster = FakeCluster(api)
    clock = FakeClock()
    recorder = FlightRecorder(capacity=max(4096, count * 8),
                              max_objects=max(2048, count * 4))
    mgr = Manager(api, clock=clock, workers=workers,
                  flight_recorder=recorder)
    cfg = CoreConfig.from_env({})  # hermetic: culling off, defaults only
    setup_core_controllers(mgr, cfg)

    spec = None
    if tpu:
        accel, topology = tpu.split(":")
        spec = TPUSpec(accel, topology)
        shape = spec.validate()
        cluster.add_tpu_slice_nodes(
            shape.accelerator.gke_label, shape.topology,
            shape.num_hosts * count, shape.chips_per_host)
    cluster.add_node("cpu-node", allocatable={"cpu": str(count * 8),
                                              "memory": "8192Gi"})
    expected_ready = spec.shape.num_hosts if spec else 1

    api.clear_audit_log()
    api.clear_verb_counts()
    t0 = time.perf_counter()
    for i in range(count):
        api.create(Notebook.new(f"nb-{i:04d}", NAMESPACE, tpu=spec).obj)
    rollout_reconciles_total = mgr.settle(max_seconds=7200.0)
    wall_s = time.perf_counter() - t0

    not_ready = []
    for i in range(count):
        status = api.get("Notebook", NAMESPACE,
                         f"nb-{i:04d}").body.get("status") or {}
        if status.get("readyReplicas") != expected_ready:
            not_ready.append(f"nb-{i:04d}")
    if not_ready:
        raise AssertionError(
            f"{len(not_ready)} notebooks never converged "
            f"(first: {not_ready[:3]})")
    if mgr.dropped_errors:
        raise AssertionError(f"retry budget exhausted: {mgr.dropped_errors}")

    rollout_reconciles = _reconciles_per_controller(mgr)
    rollout_verbs = {f"{verb}:{kind}": n
                     for (verb, kind), n in sorted(api.verb_counts().items())}
    rollout_writes: dict[str, int] = {}
    for rec in api.audit_log(ok=True):
        rollout_writes[rec.kind] = rollout_writes.get(rec.kind, 0) + 1

    # steady-state probe: a full resync of a converged fleet must be
    # all-reads — zero write verbs (audit log is the proof) — and at most
    # one reconcile per (controller, object) since nothing re-triggers
    audit_before = len(api.audit_log())
    api.clear_verb_counts()
    before = _reconciles_per_controller(mgr)
    mgr.enqueue_all()
    mgr.settle(max_seconds=7200.0)
    after = _reconciles_per_controller(mgr)
    steady_writes = api.audit_log()[audit_before:]
    if steady_writes:
        first = steady_writes[0]
        raise AssertionError(
            f"{len(steady_writes)} write verbs issued by a converged fleet "
            f"(first: {first.verb} {first.kind} "
            f"{first.namespace}/{first.name})")
    steady_reconciles = {c: after.get(c, 0) - before.get(c, 0) for c in after}
    for controller, n in steady_reconciles.items():
        if n > count:
            raise AssertionError(
                f"steady-state resync re-reconciled {controller} {n} times "
                f"for {count} objects — the fleet is not quiet")

    overlaps = recorder.overlapping_attempts()
    if overlaps:
        a, b = overlaps[0]
        raise AssertionError(
            f"per-key serialization violated: {len(overlaps)} overlapping "
            f"attempt pairs (first: {a.controller} {a.object_key})")

    state = normalized_state(api)
    mgr.stop()
    return {
        "count": count,
        "workers": workers,
        "tpu": tpu or "cpu",
        "wall_s": round(wall_s, 3),
        "rollout_reconciles_total": rollout_reconciles_total,
        "reconciles_per_notebook": {
            c: round(n / count, 3) for c, n in rollout_reconciles.items()},
        "writes_per_notebook": {
            k: round(n / count, 3) for k, n in sorted(rollout_writes.items())},
        "api_verbs": rollout_verbs,
        "steady_reconciles": steady_reconciles,
        "steady_write_verbs": 0,
        "cache": mgr.cache.stats() if mgr.cache is not None else {},
        "_state": state,
    }


def check_budget(result: dict, budget: dict) -> list[str]:
    """Failures (empty = within budget).  A measurement may regress at
    most `tolerance` (fraction) over the committed per-notebook budget."""
    tol = 1.0 + float(budget.get("tolerance", 0.10))
    failures = []
    for kind, allowed in budget.get("writes_per_notebook", {}).items():
        got = result["writes_per_notebook"].get(kind, 0.0)
        if got > allowed * tol:
            failures.append(
                f"writes/notebook[{kind}]: {got} > {allowed} (+{tol - 1:.0%})")
    for ctrl, allowed in budget.get("reconciles_per_notebook", {}).items():
        got = result["reconciles_per_notebook"].get(ctrl, 0.0)
        if got > allowed * tol:
            failures.append(
                f"reconciles/notebook[{ctrl}]: {got} > {allowed} "
                f"(+{tol - 1:.0%})")
    hard_cap = budget.get("max_reconciles_per_notebook")
    if hard_cap is not None:
        got = result["reconciles_per_notebook"].get("notebook", 0.0)
        if got > hard_cap:
            failures.append(
                f"reconciles/notebook[notebook]: {got} > hard cap {hard_cap}")
    return failures


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-l", "--count", type=int, default=200)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--compare-workers", type=int, default=0,
                        help="re-run with N workers and require an "
                        "identical normalized final state")
    parser.add_argument("--tpu", default="",
                        help="accelerator:topology, e.g. v5e:2x4 "
                        "(default CPU)")
    parser.add_argument("--check-budget", default="",
                        help="budget JSON; fail on >tolerance regression")
    parser.add_argument("--write-budget", default="",
                        help="write the measured result as the new budget")
    args = parser.parse_args(argv)

    result = run_fleet(args.count, args.workers, tpu=args.tpu)
    state = result.pop("_state")
    rc = 0

    if args.compare_workers:
        other = run_fleet(args.count, args.compare_workers, tpu=args.tpu)
        other_state = other.pop("_state")
        result["compare"] = {
            "workers": other["workers"],
            "wall_s": other["wall_s"],
            "reconciles_per_notebook": other["reconciles_per_notebook"],
            "state_identical": other_state == state,
        }
        if other_state != state:
            print("FAIL: final cluster state differs between "
                  f"{args.workers}-worker and {args.compare_workers}-worker "
                  "runs", file=sys.stderr)
            rc = 1

    if args.check_budget:
        budget = json.loads(Path(args.check_budget).read_text())
        failures = check_budget(result, budget)
        result["budget_ok"] = not failures
        if failures:
            for f in failures:
                print(f"BUDGET FAIL: {f}", file=sys.stderr)
            rc = 1

    if args.write_budget:
        Path(args.write_budget).write_text(json.dumps({
            "notebooks": args.count,
            "tolerance": 0.10,
            "max_reconciles_per_notebook": 3.0,
            "reconciles_per_notebook": result["reconciles_per_notebook"],
            "writes_per_notebook": result["writes_per_notebook"],
        }, indent=2, sort_keys=True) + "\n")

    print(json.dumps(result))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
