"""Int8 weight streaming (models.quant): the decode-time quantized model
must closely track the full-precision one — same tree shape contract,
close logits, matching greedy tokens on an easy margin."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.configs import TINY
from kubeflow_tpu.models.generate import decode_config, generate
from kubeflow_tpu.models.quant import quantize_params, quantized_bytes
from kubeflow_tpu.models.transformer import Transformer


def _params(cfg):
    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(0),
                      jnp.ones((1, 8), jnp.int32))["params"]


class TestQuantizeParams:
    def test_tree_matches_int8_model_and_shrinks(self):
        cfg = TINY
        params = _params(cfg)
        import flax.linen as nn

        qcfg = cfg.with_(weight_dtype="int8")
        qmodel = Transformer(qcfg)
        ref = nn.unbox(jax.eval_shape(
            lambda: qmodel.init(jax.random.PRNGKey(0),
                                jnp.ones((1, 8), jnp.int32))["params"]))
        qparams = quantize_params(params)

        ref_paths = {jax.tree_util.keystr(p): v.shape for p, v in
                     jax.tree_util.tree_flatten_with_path(ref)[0]}
        got_paths = {jax.tree_util.keystr(p): v.shape for p, v in
                     jax.tree_util.tree_flatten_with_path(qparams)[0]}
        assert ref_paths == got_paths

        import flax.linen as nn

        full = sum(v.size * 4 for v in
                   jax.tree_util.tree_leaves(nn.unbox(params)))
        assert quantized_bytes(qparams) < 0.45 * full  # ~int8 + scales

    def test_logits_track_full_precision(self):
        cfg = TINY
        params = _params(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        dense = Transformer(cfg).apply({"params": params}, tokens)
        q = Transformer(cfg.with_(weight_dtype="int8")).apply(
            {"params": quantize_params(params)}, tokens)
        a = np.asarray(dense, np.float32).ravel()
        b = np.asarray(q, np.float32).ravel()
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        assert cos > 0.999, cos

    def test_int8_generate_runs(self):
        cfg = TINY
        params = _params(cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg.with_(weight_dtype="int8"),
                       quantize_params(params), prompt, max_new_tokens=4)
        assert out.shape == (2, 9)
        # greedy decode of the quantized model mostly agrees with dense
        ref = generate(cfg, params, prompt, max_new_tokens=4)
        agree = float(np.mean(np.asarray(out[:, 5:]) == np.asarray(ref[:, 5:])))
        assert agree >= 0.5, agree

    def test_decode_config_preserves_weight_dtype(self):
        assert decode_config(
            TINY.with_(weight_dtype="int8")).weight_dtype == "int8"

    def test_moe_int8_tracks_full_precision(self):
        """Expert FFNs quantize per expert (stacked lead axis from
        nn.vmap); the router stays fp32 so routing is UNCHANGED and the
        whole MoE model tracks full precision."""
        cfg = TINY.with_(moe_experts=4, moe_top_k=2, moe_capacity_factor=4.0)
        params = _params(cfg)
        q = quantize_params(params)
        # router kernel untouched; expert kernels quantized per expert
        layers = q["layers"] if "layers" in q else q["layer_0"]
        assert "kernel" in layers["moe"]["router"]
        ek = layers["moe"]["experts"]["gate"]
        assert ek["kernel_q"].dtype == jnp.int8
        # scales keep the (layers, experts) lead axes per-slice
        assert ek["kernel_scale"].shape[:2] == ek["kernel_q"].shape[:2]

        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        dense, _ = Transformer(cfg).apply({"params": params}, tokens,
                                          return_aux=True)
        qout, _ = Transformer(cfg.with_(weight_dtype="int8")).apply(
            {"params": q}, tokens, return_aux=True)
        a = np.asarray(dense, np.float32).ravel()
        b = np.asarray(qout, np.float32).ravel()
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        assert cos > 0.999, cos

        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg.with_(weight_dtype="int8"), q, prompt,
                       max_new_tokens=4)
        assert out.shape == (2, 9)


class TestInt4:
    """Nibble-packed int4 with group scales: decode must still track the
    full-precision model (coarser than int8, so a looser cosine bar)."""

    def _cfg(self):
        # contract dims must divide 2*INT4_GROUP=128: widen TINY
        return TINY.with_(embed_dim=256, mlp_dim=512, num_heads=4,
                          num_kv_heads=2, head_dim=64, scan_layers=False)

    def test_pack_unpack_roundtrip(self):
        from kubeflow_tpu.models.quant import (
            Int4DenseGeneral,
            _quantize_kernel_int4,
        )

        k = jax.random.normal(jax.random.PRNGKey(0), (256, 32)) * 0.05
        packed = _quantize_kernel_int4(k)
        assert packed["kernel_q4"].shape == (128, 32)
        assert packed["kernel_q4"].dtype == jnp.int8
        mod = Int4DenseGeneral(32, axis=-1, dtype=jnp.float32)
        x = jnp.eye(256, dtype=jnp.float32)
        w = mod.apply({"params": packed}, x)  # identity input -> dequant w
        err = np.max(np.abs(np.asarray(w) - np.asarray(k)))
        # int4 with group-128 scales: |err| <= absmax/7 per group
        assert err < float(np.max(np.abs(np.asarray(k)))) / 6.0

    def test_int4_accepts_stacked_training_params(self):
        """The default scan_layers=True training tree quantizes directly
        (unrolled internally — decode always unrolls)."""
        from kubeflow_tpu.models.quant import quantize_params_int4

        cfg = self._cfg().with_(scan_layers=True)
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        q = quantize_params_int4(params)
        assert "layers" not in q and "layer_0" in q
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg.with_(weight_dtype="int4"), q, prompt,
                       max_new_tokens=3)
        assert out.shape == (1, 8)

    def test_int4_generate_tracks_dense(self):
        from kubeflow_tpu.models.quant import quantize_params_int4

        cfg = self._cfg()
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.ones((1, 8), jnp.int32))["params"]
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        dense = Transformer(cfg).apply({"params": params}, tokens)
        q = Transformer(cfg.with_(weight_dtype="int4")).apply(
            {"params": quantize_params_int4(params)}, tokens)
        a = np.asarray(dense, np.float32).ravel()
        b = np.asarray(q, np.float32).ravel()
        cos = float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-9))
        # random init weights are int4's worst case (no structure for the
        # group scales to exploit — every weight ~absmax/7 error); trained
        # weights track tighter.  0.984 measured here; the bar catches
        # sign/packing bugs, not quantization noise
        assert cos > 0.97, cos

        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0,
                                    cfg.vocab_size)
        out = generate(cfg.with_(weight_dtype="int4"),
                       quantize_params_int4(params), prompt,
                       max_new_tokens=4)
        assert out.shape == (2, 9)


class TestAdvisorGuards:
    """Round-4 advisor findings: int4+MoE fails loudly, streamed-bytes
    roofline excludes the embedding lookup."""

    def test_int4_moe_config_raises(self):
        cfg = TINY.with_(moe_experts=2, weight_dtype="int4")
        try:
            Transformer(cfg).init(jax.random.PRNGKey(0),
                                  jnp.ones((1, 8), jnp.int32))
            raise AssertionError("expected ValueError for int4 MoE")
        except ValueError as e:
            assert "int4" in str(e) and "MoE" in str(e)

    def test_quantize_params_int4_rejects_expert_tree(self):
        from kubeflow_tpu.models.quant import quantize_params_int4

        cfg = TINY.with_(moe_experts=2, scan_layers=False)
        params = _params(cfg)
        try:
            quantize_params_int4(params)
            raise AssertionError("expected ValueError for expert kernels")
        except ValueError as e:
            assert "expert" in str(e)

    def test_quantized_bytes_excludes_embedding(self):
        params = _params(TINY)
        q = quantize_params(params)
        streamed = quantized_bytes(q)
        resident = quantized_bytes(q, exclude=())
        embed = TINY.vocab_size * TINY.embed_dim
        # the embed table stays unquantized (fp32 here), so the delta is
        # exactly its bytes
        assert resident - streamed == embed * 4

    def test_vit_head_flops_counted_once_per_image(self):
        from kubeflow_tpu.models.vit import VIT_TINY, vit_flops_per_image

        tokens = (VIT_TINY.image_size // VIT_TINY.patch_size) ** 2
        base = vit_flops_per_image(VIT_TINY)
        import dataclasses

        doubled = dataclasses.replace(
            VIT_TINY, num_classes=2 * VIT_TINY.num_classes)
        # doubling the head adds 6*d*num_classes ONCE, not once per token
        delta = vit_flops_per_image(doubled) - base
        assert delta == 6.0 * VIT_TINY.embed_dim * VIT_TINY.num_classes, (
            delta, tokens)
