"""Runtime concurrency sanitizer: the dynamic half of the invariant gate.

`ci/analyzers` proves the COW / clock / lock contracts statically where a
conservative analysis can; this module catches the escapes at runtime when
`INVARIANTS_STRICT=1` (the threaded suites — test_workers, the chaos and
self-healing soaks at WORKQUEUE_WORKERS=8 — run with it on):

  - **Deep-freeze.**  When the ApiServer commits an object it already marks
    it `frozen` (kube/meta.py skeleton-key guard).  Under strict mode the
    store additionally rebuilds the shared body/labels/annotations trees
    out of mutation-trapping `FrozenDict`/`FrozenList` wrappers, so ANY
    in-place write to a committed snapshot — the mutate-after-list bug
    class PR 8 fixed by hand in three places — raises `FrozenMutationError`
    AT THE MUTATION SITE, stamped with the active trace id, instead of
    silently corrupting every other reader's view.

  - **LockTracker.**  `tracked()` wraps the store/cluster/cache/manager
    locks; the tracker records each thread's acquisition stack, learns the
    global acquisition-order graph as the suite runs, and raises
    `LockInversionError` the first time two locks are taken in both
    orders — a deadlock that a real scheduler interleaving would need luck
    to hit becomes a deterministic failure.  Same-name multi-instance
    locks (the per-kind shard locks) carry a `rank` and must be acquired
    in strictly increasing rank order (the store sorts by kind).

Both hooks cost nothing when strict mode is off: `tracked()` returns the
raw lock and the store skips the wrapper rebuild.

The same instrumentation points double as the *schedule surface* for the
model checker (`kubeflow_tpu/testing/interleave.py`): when a yield hook
is installed via `set_yield_hook()`, every TrackedLock acquire/release,
store commit and workqueue add/pop/done first calls the hook, which may
suspend the calling thread and hand the schedule to another one.  With no
hook installed (the default, including all of production) `yield_point()`
is a None-check and a return.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional


def strict_enabled() -> bool:
    """True when INVARIANTS_STRICT=1 (checked once per ApiServer/Manager
    construction, not per operation)."""
    return os.environ.get("INVARIANTS_STRICT", "") == "1"


class InvariantViolation(Exception):
    """Base of every runtime invariant failure."""


class FrozenMutationError(InvariantViolation):
    """In-place write to a committed (frozen, shared) store snapshot."""


class LockInversionError(InvariantViolation):
    """Two locks observed acquired in both orders (deadlock potential)."""


def _active_trace_id() -> str:
    # lazy import: utils.tracing must stay importable without this module
    from . import tracing

    try:
        span = tracing.current_span()
    except Exception:
        return ""
    return getattr(span, "trace_id", "") or ""


def _mutation_error(op: str) -> FrozenMutationError:
    trace = _active_trace_id()
    where = f" (active trace {trace})" if trace else ""
    return FrozenMutationError(
        f"in-place {op} on a frozen store snapshot{where}: objects from "
        "list()/select()/by_index()/watch events are shared read-only "
        "copy-on-write state — get() a private copy and update() it")


class FrozenDict(dict):
    """Dict that raises on every mutator.  Subclasses dict (not a Mapping
    proxy) so isinstance checks, json serialization, kube.meta.copy_tree
    and strategic-merge walks all keep working on the same object."""

    __slots__ = ()

    def _reject(self, op):
        raise _mutation_error(op)

    def __setitem__(self, k, v):
        self._reject(f"[{k!r}] assignment")

    def __delitem__(self, k):
        self._reject(f"del [{k!r}]")

    def setdefault(self, k, default=None):
        if k in self:
            return self[k]
        self._reject(f"setdefault({k!r})")

    def update(self, *a, **kw):
        self._reject("update()")

    def pop(self, *a):
        self._reject("pop()")

    def popitem(self):
        self._reject("popitem()")

    def clear(self):
        self._reject("clear()")

    def __ior__(self, other):
        self._reject("|= merge")

    def copy(self):
        return dict(self)  # a copy is private and mutable again


class FrozenList(list):
    """List twin of FrozenDict — same dict/list-subclass rationale."""

    __slots__ = ()

    def _reject(self, op):
        raise _mutation_error(op)

    def __setitem__(self, i, v):
        self._reject(f"[{i!r}] assignment")

    def __delitem__(self, i):
        self._reject(f"del [{i!r}]")

    def __iadd__(self, other):
        self._reject("+= extend")

    def __imul__(self, n):
        self._reject("*= repeat")

    def append(self, v):
        self._reject("append()")

    def extend(self, it):
        self._reject("extend()")

    def insert(self, i, v):
        self._reject("insert()")

    def pop(self, *a):
        self._reject("pop()")

    def remove(self, v):
        self._reject("remove()")

    def clear(self):
        self._reject("clear()")

    def sort(self, **kw):
        self._reject("sort()")

    def reverse(self):
        self._reject("reverse()")

    def copy(self):
        return list(self)


#: what KubeObject.spec/.status return for a frozen object with no such
#: key under strict mode — a write to it must raise, not vanish
EMPTY_FROZEN_DICT = FrozenDict()


def freeze_tree(x):
    """Rebuild a JSON-shaped tree with mutation-trapping containers.
    Already-frozen subtrees are returned as-is (idempotent)."""
    if type(x) is FrozenDict or type(x) is FrozenList:
        return x
    if isinstance(x, dict):
        return FrozenDict((k, freeze_tree(v)) for k, v in x.items())
    if isinstance(x, list):
        return FrozenList(freeze_tree(v) for v in x)
    return x


def deep_freeze(obj) -> None:
    """Swap a KubeObject's shared mutable trees for trapping wrappers.
    Called by the store at commit time (after obj.frozen = True) under
    strict mode.  deepcopy()/get() still hand out plain mutable trees
    (kube.meta.copy_tree rebuilds builtin dicts/lists)."""
    obj.body = freeze_tree(obj.body)
    meta = obj.metadata
    meta.labels = freeze_tree(meta.labels)
    meta.annotations = freeze_tree(meta.annotations)


# -- schedule points ----------------------------------------------------------

#: Installed by the InterleavingExplorer for the duration of one explored
#: run; None in production and in every non-exploring test.  Signature:
#: hook(kind, detail, token) where `kind` is the yield-point class
#: ("lock.acquire", "lock.release", "store.commit", "queue.add",
#: "queue.pop", "queue.done", "test.point", "test.wait"), `detail` is a
#: small picklable payload naming the object (lock name, kind/ns/name
#: tuple, queue key) and `token` identifies the concrete lock instance
#: for ownership modelling (or a wait predicate for "test.wait").
_yield_hook: Optional[Callable[[str, object, object], None]] = None


def set_yield_hook(hook):
    """Install (or with None, remove) the schedule hook.  Returns the
    previous hook so explorers can nest/restore."""
    global _yield_hook
    prev = _yield_hook
    _yield_hook = hook
    return prev


def yield_point(kind: str, detail=None, token=None) -> None:
    """A point where the model checker may preempt this thread.  Callers
    pass unformatted payloads (tuples, not f-strings) so the production
    cost is one global read and a truth test."""
    hook = _yield_hook
    if hook is not None:
        hook(kind, detail, token)


# -- lock-order tracking ------------------------------------------------------

class LockTracker:
    """Global acquisition-order recorder shared by every TrackedLock.

    `_edges[a]` holds every lock name acquired while `a` was held.  A new
    acquisition of B with A held fails if B→A is already on record — the
    two orders together are a potential deadlock.  Re-entrant acquisition
    of the SAME instance is transparent (RLock semantics); acquisition of
    a same-name SIBLING instance (another kind's shard lock) must carry a
    strictly greater `rank` than the deepest held sibling, mirroring the
    store's sorted-by-kind multi-shard acquisition."""

    def __init__(self) -> None:
        self._graph_lock = threading.Lock()
        self._edges: dict[str, set[str]] = {}
        self._held = threading.local()

    def _stack(self) -> list:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def held_names(self) -> list[str]:
        return [name for (_, name, _) in self._stack()]

    def on_acquire(self, lock: "TrackedLock") -> None:
        stack = self._stack()
        for inner, _, _ in stack:
            if inner is lock:
                stack.append((lock, lock.name, lock.rank))  # re-entry
                return
        held_names = []
        for _, name, rank in stack:
            if name == lock.name:
                if lock.rank is None or rank is None or \
                        not lock.rank > rank:
                    raise LockInversionError(
                        f"same-class lock {lock.name!r} acquired out of "
                        f"rank order (held rank {rank!r}, acquiring "
                        f"{lock.rank!r}); multi-instance acquisition must "
                        "follow the canonical sort")
                continue
            if name not in held_names:
                held_names.append(name)
        with self._graph_lock:
            successors = self._edges.get(lock.name)
            if successors:
                for name in held_names:
                    if name in successors:
                        raise LockInversionError(
                            f"lock order inversion: acquiring {lock.name!r}"
                            f" while holding {name!r}, but the opposite "
                            f"order {lock.name!r} -> {name!r} was already "
                            f"observed (held: {self.held_names()})")
            for name in held_names:
                self._edges.setdefault(name, set()).add(lock.name)
        stack.append((lock, lock.name, lock.rank))

    def on_release(self, lock: "TrackedLock") -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                del stack[i]
                return

    def edges(self) -> dict[str, set[str]]:
        with self._graph_lock:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        with self._graph_lock:
            self._edges.clear()


#: process-wide tracker; tests may instantiate their own for isolation
GLOBAL_TRACKER = LockTracker()


class TrackedLock:
    """Wrapper giving a threading.Lock/RLock acquisition-order tracking.
    Order violations raise BEFORE blocking on the lock, so the sanitizer
    reports the inversion instead of deadlocking the suite."""

    __slots__ = ("_lock", "name", "rank", "_tracker")

    def __init__(self, lock, name: str, rank=None,
                 tracker: Optional[LockTracker] = None) -> None:
        self._lock = lock
        self.name = name
        self.rank = rank
        self._tracker = tracker if tracker is not None else GLOBAL_TRACKER

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _yield_hook is not None and blocking:
            # the hook must run BEFORE on_acquire/blocking so the explorer
            # can park this thread while the lock is modelled as held
            # elsewhere — a granted thread then never blocks for real
            _yield_hook("lock.acquire", self.name, self._lock)
        self._tracker.on_acquire(self)
        ok = self._lock.acquire(blocking, timeout)
        if not ok:
            self._tracker.on_release(self)
        return ok

    def release(self) -> None:
        if _yield_hook is not None:
            _yield_hook("lock.release", self.name, self._lock)
        self._lock.release()
        self._tracker.on_release(self)

    def __enter__(self) -> "TrackedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def tracked(lock, name: str, rank=None,
            tracker: Optional[LockTracker] = None):
    """Wrap `lock` for order tracking when strict mode is on; otherwise
    return it untouched (zero overhead on the production path)."""
    if not strict_enabled():
        return lock
    return TrackedLock(lock, name, rank=rank, tracker=tracker)
