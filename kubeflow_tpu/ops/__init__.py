"""TPU compute ops — flash/XLA attention and ring attention (sequence-
parallel exact attention over the ICI ring) — plus the stdlib-only
`ops.diagnose` one-shot diagnostics bundle.

The compute exports are lazy (PEP 562): `python -m kubeflow_tpu.ops.
diagnose` runs in the control-plane pod (and the fast test lane) without
dragging jax/XLA in; `from kubeflow_tpu.ops import flash_attention`
resolves exactly as before.
"""

import importlib

_LAZY = {
    "attention": ".attention",
    "flash_attention": ".attention",
    "xla_attention": ".attention",
    "ring_attention": ".ring_attention",
}

__all__ = ["attention", "flash_attention", "ring_attention", "xla_attention"]


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    mod = importlib.import_module(target, __name__)
    value = getattr(mod, name)
    globals()[name] = value  # cache: resolve each export once
    return value
