"""InformerCache correctness (kube/cache.py): index maintenance across
add/update/delete, watch relist (410 Gone), leader failover, and the
label-selector index vs brute-force equivalence on randomized fixtures."""

import random

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    InformerCache,
    KubeObject,
    ObjectMeta,
    set_controller_reference,
)
from kubeflow_tpu.utils.metrics import Registry


def mk(kind, ns, name, labels=None, spec=None):
    return KubeObject(
        "v1", kind,
        ObjectMeta(name=name, namespace=ns, labels=dict(labels or {})),
        body={"spec": dict(spec or {})})


def fresh(api=None, registry=None):
    api = api or ApiServer()
    cache = InformerCache(api, registry=registry)
    cache.add_namespace_index("ConfigMap")
    cache.add_label_index("Pod", "app")
    cache.add_owner_uid_index("Pod")
    return api, cache


class TestIndexMaintenance:
    def test_add_update_delete_consistency(self):
        api, cache = fresh()
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        api.create(mk("Pod", "ns1", "p2", labels={"app": "b"}))
        assert [p.name for p in cache.select("Pod", "ns1", {"app": "a"})] \
            == ["p1"]
        # update moves the object between index buckets
        live = api.get("Pod", "ns1", "p1")
        live.metadata.labels["app"] = "b"
        api.update(live)
        assert cache.select("Pod", "ns1", {"app": "a"}) == []
        assert sorted(p.name for p in cache.select(
            "Pod", "ns1", {"app": "b"})) == ["p1", "p2"]
        # delete drops it from every index
        api.delete("Pod", "ns1", "p2")
        assert [p.name for p in cache.select("Pod", "ns1", {"app": "b"})] \
            == ["p1"]
        assert cache.get("Pod", "ns1", "p2") is None

    def test_owner_uid_index_tracks_owner(self):
        api, cache = fresh()
        owner = api.create(mk("Notebook", "ns1", "nb"))
        pod = mk("Pod", "ns1", "w-0")
        set_controller_reference(owner, pod)
        api.create(pod)
        api.create(mk("Pod", "ns1", "loner"))
        got = cache.by_index("Pod", "owner-uid", owner.metadata.uid)
        assert [p.name for p in got] == ["w-0"]

    def test_by_index_unregistered_raises(self):
        api, cache = fresh()
        api.create(mk("Pod", "ns1", "p1"))
        with pytest.raises(KeyError):
            cache.by_index("Pod", "nope", "x")

    def test_returns_deepcopies(self):
        api, cache = fresh()
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        got = cache.get("Pod", "ns1", "p1")
        got.metadata.labels["app"] = "mutated"
        assert cache.get("Pod", "ns1", "p1").metadata.labels["app"] == "a"

    def test_priming_sees_objects_created_before_cache(self):
        api = ApiServer()
        api.create(mk("Pod", "ns1", "pre", labels={"app": "a"}))
        _, cache = fresh(api)
        assert cache.get("Pod", "ns1", "pre") is not None
        assert cache.keys("Pod") == [("ns1", "pre")]

    def test_delete_then_recreate_inside_fanout_keeps_new_incarnation(self):
        """A watcher registered BEFORE the cache may recreate an object
        while the DELETED event is still fanning out (the FakeCluster
        kubelet does exactly this for StatefulSet pods); the stale DELETED
        must not evict the newer incarnation."""
        api = ApiServer()

        recreated = []

        def recreator(ev):
            from kubeflow_tpu.kube.store import EventType

            if ev.type is EventType.DELETED and ev.obj.kind == "Pod" \
                    and not recreated:
                recreated.append(True)
                api.create(mk("Pod", "ns1", ev.obj.name,
                              labels={"app": "a"}))

        api.watch(recreator)
        _, cache = fresh(api)
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        api.delete("Pod", "ns1", "p1")
        assert recreated
        got = cache.get("Pod", "ns1", "p1")
        assert got is not None
        assert [p.name for p in cache.select("Pod", "ns1", {"app": "a"})] \
            == ["p1"]


class TestResume:
    def test_watch_drop_resumes_from_rv(self):
        api, cache = fresh()
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        assert api.drop_watch_connections() >= 1
        assert not cache.connected
        # events while disconnected
        api.create(mk("Pod", "ns1", "p2", labels={"app": "a"}))
        api.delete("Pod", "ns1", "p1")
        cache.ensure_connected()
        assert cache.connected and cache.relists == 0
        assert [p.name for p in cache.select("Pod", "ns1", {"app": "a"})] \
            == ["p2"]

    def test_410_relist_rebuilds_every_primed_kind(self):
        api, cache = fresh()
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        api.create(mk("ConfigMap", "ns1", "cm1"))
        assert cache.keys("ConfigMap")  # prime both kinds
        api.drop_watch_connections()
        api.create(mk("Pod", "ns1", "p2", labels={"app": "b"}))
        api.delete("ConfigMap", "ns1", "cm1")
        api.reset_watch_history()  # compaction: resume impossible -> 410
        cache.ensure_connected()
        assert cache.relists == 1
        assert cache.keys("Pod") == [("ns1", "p1"), ("ns1", "p2")]
        assert cache.keys("ConfigMap") == []
        assert [p.name for p in cache.select("Pod", "ns1", {"app": "b"})] \
            == ["p2"]

    def test_leader_failover_cache_matches_store(self):
        """A new leader's freshly-built cache (prime-from-list) answers
        identically to the deposed leader's event-fed one."""
        api, old = fresh()
        rng = random.Random(7)
        for i in range(30):
            api.create(mk("Pod", f"ns{rng.randrange(3)}", f"p{i:02d}",
                          labels={"app": rng.choice("abc")}))
        for i in rng.sample(range(30), 10):
            pods = [k for k in old.keys("Pod") if k[1] == f"p{i:02d}"]
            if pods:
                api.delete("Pod", pods[0][0], pods[0][1])
        _, new = fresh(api)
        assert new.keys("Pod") == old.keys("Pod")
        for app in "abc":
            for ns in ("ns0", "ns1", "ns2"):
                assert [p.name for p in new.select("Pod", ns, {"app": app})] \
                    == [p.name for p in old.select("Pod", ns, {"app": app})]


class TestSelectorEquivalence:
    def test_label_index_equals_brute_force_on_random_fleet(self):
        """The label-selector index must answer exactly what a live
        api.list() with the same selector answers, across randomized
        create/update/delete churn."""
        api, cache = fresh()
        rng = random.Random(20260804)
        names = []
        for step in range(300):
            op = rng.random()
            if op < 0.5 or not names:
                name = f"pod-{step:03d}"
                names.append(name)
                api.create(mk("Pod", f"ns{rng.randrange(2)}", name,
                              labels={"app": rng.choice("abcd"),
                                      "tier": rng.choice("xy")}))
            elif op < 0.8:
                name = rng.choice(names)
                for ns in ("ns0", "ns1"):
                    live = api.try_get("Pod", ns, name)
                    if live is not None:
                        live.metadata.labels["app"] = rng.choice("abcd")
                        api.update(live)
                        break
            else:
                name = names.pop(rng.randrange(len(names)))
                for ns in ("ns0", "ns1"):
                    try:
                        api.delete("Pod", ns, name)
                        break
                    except Exception:
                        continue
        for app in "abcd":
            for ns in (None, "ns0", "ns1"):
                want = [p.name for p in api.list(
                    "Pod", namespace=ns, label_selector={"app": app})]
                got = [p.name for p in cache.select(
                    "Pod", ns, {"app": app})]
                assert got == want, (app, ns)
        # multi-key selector has no exact index -> brute scan, same answer
        want = [p.name for p in api.list(
            "Pod", namespace="ns0",
            label_selector={"app": "a", "tier": "x"})]
        got = [p.name for p in cache.select(
            "Pod", "ns0", {"app": "a", "tier": "x"})]
        assert got == want


class TestLookupAccounting:
    def test_hit_and_miss_counted(self):
        registry = Registry()
        api, cache = fresh(registry=registry)
        api.create(mk("Pod", "ns1", "p1", labels={"app": "a"}))
        cache.select("Pod", "ns1", {"app": "a"})            # indexed: hit
        cache.select("Pod", "ns1", {"unindexed": "z"})      # no index: miss
        cache.list("ConfigMap", namespace="ns1")            # ns index: hit
        cache.list("Pod", namespace="ns1")                  # no ns index: miss
        counter = registry.get("cache_index_lookups_total")
        assert counter.value("label:app", "hit") == 1
        assert counter.value("label:unindexed", "miss") == 1
        assert counter.value("namespace", "hit") == 1
        assert counter.value("namespace", "miss") == 1
