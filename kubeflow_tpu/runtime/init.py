"""In-notebook distributed bootstrap: `tpu_init()`.

Consumes exactly the env the controller injects into every worker
(tpu/env.py: TPU_WORKER_ID from the pod-index downward API,
TPU_WORKER_HOSTNAMES ordered by ordinal, JAX_COORDINATOR_ADDRESS pinned to
slice-0 worker-0, MEGASCALE_* for multi-slice) and calls
`jax.distributed.initialize()` so `jax.devices()` shows the whole slice —
the contract SURVEY.md §7 calls out as failing only at init time when wrong.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Mapping, Optional


@dataclass(frozen=True)
class WorkerIdentity:
    """Parsed coordination env of this worker."""

    worker_id: int
    hosts_per_slice: int
    slice_id: int
    num_slices: int
    coordinator_address: str
    hostnames: tuple[str, ...]

    @property
    def process_id(self) -> int:
        # global process ids are slice-major, matching the hostname ordering
        # the controller generates (tpu/env.py worker_hostnames)
        return self.slice_id * self.hosts_per_slice + self.worker_id

    @property
    def num_processes(self) -> int:
        return self.hosts_per_slice * self.num_slices

    @property
    def is_multihost(self) -> bool:
        return self.num_processes > 1


def parse_worker_env(env: Optional[Mapping[str, str]] = None) -> WorkerIdentity:
    env = env if env is not None else os.environ
    hostnames = tuple(
        h for h in env.get("TPU_WORKER_HOSTNAMES", "").split(",") if h
    )
    hosts_per_slice = int(
        env.get("TPU_HOSTS_PER_SLICE") or len(hostnames) or 1
    )
    worker_id = int(env.get("TPU_WORKER_ID", 0) or 0)
    return WorkerIdentity(
        worker_id=worker_id,
        hosts_per_slice=hosts_per_slice,
        slice_id=int(env.get("MEGASCALE_SLICE_ID", 0) or 0),
        num_slices=int(env.get("MEGASCALE_NUM_SLICES", 1) or 1),
        coordinator_address=env.get(
            "JAX_COORDINATOR_ADDRESS", env.get("COORDINATOR_ADDRESS", "")
        ),
        hostnames=hostnames,
    )


def tpu_init(env: Optional[Mapping[str, str]] = None) -> WorkerIdentity:
    """Initialize the JAX distributed runtime from the injected env.

    Single-host (or CPU-notebook) pods are a no-op beyond parsing; multi-host
    slices block in `jax.distributed.initialize` until all workers arrive —
    the gang-startup rendezvous the headless Service's
    publishNotReadyAddresses makes resolvable (core/workload.py).
    """
    identity = parse_worker_env(env)
    if identity.is_multihost and identity.coordinator_address:
        import jax

        jax.distributed.initialize(
            coordinator_address=identity.coordinator_address,
            num_processes=identity.num_processes,
            process_id=identity.process_id,
        )
    return identity


def local_chip_count() -> int:
    import jax

    return jax.local_device_count()
