#!/usr/bin/env bash
# Seeded chaos soak (tests/test_chaos.py::TestChaosSoak): N rounds of
# random fault plans (kube/faults.py) against a TPU+auth notebook, driven
# entirely on the FakeClock so wall time stays in seconds regardless of how
# much backoff the injected faults provoke.
#
# The seed is printed up front and on failure — reproduce any run with
#   CHAOS_SOAK_SEED=<seed> CHAOS_SOAK_ROUNDS=<n> ci/chaos_soak.sh
# The default seed is date-stable (not time-derived) so CI is
# deterministic; pass CHAOS_SOAK_SEED=random for an exploratory roll.
set -euo pipefail
cd "$(dirname "$0")/.."

ROUNDS="${CHAOS_SOAK_ROUNDS:-25}"
SEED="${CHAOS_SOAK_SEED:-20260804}"
if [[ "$SEED" == "random" ]]; then
  SEED=$((RANDOM * 32768 + RANDOM))
fi

echo "== chaos soak: seed=${SEED} rounds=${ROUNDS} =="
if ! CHAOS_SOAK_SEED="$SEED" CHAOS_SOAK_ROUNDS="$ROUNDS" \
    python -m pytest tests/test_chaos.py::TestChaosSoak -q "$@"; then
  echo "chaos soak FAILED — reproduce with:" >&2
  echo "  CHAOS_SOAK_SEED=${SEED} CHAOS_SOAK_ROUNDS=${ROUNDS} ci/chaos_soak.sh" >&2
  exit 1
fi
echo "chaos soak OK (seed=${SEED}, rounds=${ROUNDS})"
