#!/usr/bin/env bash
# Build the controller image, load it into a kind cluster, deploy the
# standalone profile, and wait for the manager (reference analog: the
# integration workflow's podman build -> kind load -> make deploy,
# odh_notebook_controller_integration_test.yaml:62-90).
set -euo pipefail
cd "$(dirname "$0")/../.."
CLUSTER="${CLUSTER:-kubeflow-tpu}"
IMAGE="${IMAGE:-kubeflow-tpu-controller:kind}"
NAMESPACE="${NAMESPACE:-kubeflow-tpu-system}"

docker build -t "$IMAGE" .
kind load docker-image "$IMAGE" --name "$CLUSTER"

kubectl create namespace "$NAMESPACE" --dry-run=client -o yaml | kubectl apply -f -
# standalone profile: CRD without the conversion-webhook clause (no
# cert-manager in the minimal cluster), RBAC, manager Deployment
python -m kubeflow_tpu.deploy standalone --image "$IMAGE" \
  | sed "s/\$(NAMESPACE)/${NAMESPACE}/g" \
  | kubectl apply -n "$NAMESPACE" -f -

kubectl -n "$NAMESPACE" rollout status deployment/notebook-controller-deployment \
  --timeout=180s
echo "deploy: OK"
