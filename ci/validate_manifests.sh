#!/usr/bin/env bash
# Manifest build check for every profile (reference ci/kustomize.sh analog).
set -euo pipefail
cd "$(dirname "$0")/.."
python - <<'PY'
from kubeflow_tpu.deploy import PROFILES, render_profile, render_yaml, validate_docs
for profile in PROFILES:
    docs = render_profile(profile)
    validate_docs(docs)
    render_yaml(profile)
    print(f"profile {profile}: {len(docs)} manifests ok")
PY
