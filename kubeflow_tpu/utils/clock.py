"""Clock abstraction so culling/idleness logic is testable.

The reference manipulates time in tests by rewriting annotation timestamps
(culling_controller_test.go:95-142); we inject a clock instead.
"""

from __future__ import annotations

import time


class Clock:
    def now(self) -> float:
        return time.time()

    def now_iso(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.now()))


class FakeClock(Clock):
    def __init__(self, start: float = 1_700_000_000.0) -> None:
        self._now = start

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t


def parse_iso(ts: str) -> float:
    import calendar

    return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
