"""Active-active sharded control plane (kube/shard.py): namespace-affine
consistent-hash ownership, fenced writes, per-change write-ahead handoff
records, kill/rejoin survival.

The headline invariant — one owner per key at every instant, across
processes — is asserted three ways here: the dispatch filter agrees with
the committed ring, a deposed incarnation's writes raise StaleEpochError,
and the merged flight-recorder sweep finds zero cross-replica overlaps.
"""

from __future__ import annotations

import random
from collections import Counter

import pytest

from kubeflow_tpu.api.types import Notebook
from kubeflow_tpu.kube import ApiServer
from kubeflow_tpu.kube.controller import Result
from kubeflow_tpu.kube.shard import (
    DEFAULT_LEASE_DURATION_S,
    FencedApi,
    HashRing,
    SHARD_MAP_KIND,
    ShardMember,
    ShardedFleet,
    ShardedReplica,
    StaleEpochError,
    WRITE_VERBS,
)
from kubeflow_tpu.utils.clock import FakeClock


def nb(name, ns="default"):
    return Notebook.new(name, ns).obj


#: placement is namespace-affine (a key's ring position hashes only its
#: namespace), so fleet fixtures spread keys over several tenant
#: namespaces — these six split 2/2/2 across shard-0/1/2
NAMESPACES = [f"team-{i}" for i in range(6)]


def spread(n, nss=NAMESPACES):
    """n (namespace, name) keys spread round-robin over namespaces."""
    return [(nss[i % len(nss)], f"nb-{i}") for i in range(n)]


def make_member(api, sid, clock, lease=DEFAULT_LEASE_DURATION_S):
    return ShardMember(api, sid, clock=clock, lease_duration_s=lease)


class _Recorder:
    def __init__(self, shard_id):
        self.shard_id = shard_id
        self.seen = []

    def reconcile(self, req):
        self.seen.append((req.namespace, req.name))
        return Result()


class TestHashRing:
    def test_deterministic_across_observers(self):
        keys = [(f"ns-{i}", "nb") for i in range(200)]
        a = HashRing(["s0", "s1", "s2"])
        b = HashRing(["s2", "s0", "s1"])  # order must not matter
        assert [a.owner_of(*k) for k in keys] == [b.owner_of(*k) for k in keys]

    def test_every_member_owns_a_share(self):
        ring = HashRing(["s0", "s1", "s2"])
        owners = {ring.owner_of(f"ns-{i}", "nb") for i in range(200)}
        assert owners == {"s0", "s1", "s2"}

    def test_namespace_affinity_ignores_the_name(self):
        """All keys of one namespace share one owner — the placement
        property that keeps a tenant's churn on one shard's cache."""
        ring = HashRing(["s0", "s1", "s2"])
        for i in range(50):
            ns = f"ns-{i}"
            owners = {ring.owner_of(ns, f"nb-{j}") for j in range(25)}
            assert len(owners) == 1

    def test_join_moves_a_fraction_not_half(self):
        """Consistent hashing's point: a 4th member takes roughly 1/4 of
        the keyspace; keys that don't move to it don't move at all."""
        keys = [(f"ns-{i}", "nb") for i in range(500)]
        before = HashRing(["s0", "s1", "s2"])
        after = HashRing(["s0", "s1", "s2", "s3"])
        moved = sum(1 for k in keys
                    if before.owner_of(*k) != after.owner_of(*k))
        assert 0 < moved < len(keys) / 2
        for k in keys:
            if after.owner_of(*k) != "s3":
                assert after.owner_of(*k) == before.owner_of(*k), \
                    "a key not gained by the joiner must not move"

    def test_departure_only_moves_the_departed_keys(self):
        keys = [(f"ns-{i}", "nb") for i in range(500)]
        before = HashRing(["s0", "s1", "s2"])
        after = HashRing(["s0", "s1"])
        for k in keys:
            if before.owner_of(*k) != "s2":
                assert after.owner_of(*k) == before.owner_of(*k)

    def test_empty_ring_owns_nothing(self):
        assert HashRing(()).owner_of("default", "nb") is None


#: candidate member ids for the seeded property sweeps below
_POOL = [f"cp-{i}" for i in range(64)]


class TestRingProperties:
    """Seeded property sweeps over random membership sets — the three
    contracts the 100k-sweep placement lever rests on.  Bounds are set
    from measured worst cases (balance 1.6x fair share, movement 1.45x
    the consistent-hashing expectation) with headroom; a regression in
    vnode spreading or hash mixing trips them."""

    def test_one_owner_per_namespace_always(self):
        rng = random.Random(7)
        for _ in range(100):
            members = rng.sample(_POOL, rng.randrange(1, 9))
            ring = HashRing(members)
            for _ in range(10):
                ns = f"ns-{rng.randrange(10 ** 9)}"
                owners = {ring.owner_of(ns, f"nb-{j}") for j in range(8)}
                assert len(owners) == 1
                assert owners <= set(members)

    def test_balance_bound_over_random_membership_sets(self):
        rng = random.Random(1234)
        namespaces = [f"ns-{i}" for i in range(512)]
        for _ in range(100):
            n = rng.randrange(2, 9)
            members = rng.sample(_POOL, n)
            counts = Counter(HashRing(members).owner_of(ns, "x")
                             for ns in namespaces)
            assert set(counts) <= set(members)
            max_share = max(counts.values()) / len(namespaces)
            assert max_share <= 2.0 / n, \
                (members, dict(counts), max_share)

    def test_join_movement_bounded_and_targeted(self):
        """A join moves at most ~2x the consistent-hashing bound K/N,
        and only ever moves keys TO the joiner."""
        rng = random.Random(99)
        namespaces = [f"ns-{i}" for i in range(512)]
        for _ in range(100):
            n = rng.randrange(2, 9)
            members = rng.sample(_POOL, n)
            joiner = next(m for m in _POOL if m not in members)
            before = HashRing(members)
            after = HashRing(members + [joiner])
            moved = 0
            for ns in namespaces:
                b, a = before.owner_of(ns, "x"), after.owner_of(ns, "x")
                if b != a:
                    assert a == joiner, \
                        "a join may only move keys to the joiner"
                    moved += 1
            assert moved <= 2.0 * len(namespaces) / (n + 1), \
                (members, joiner, moved)

    def test_leave_movement_only_from_the_departed(self):
        rng = random.Random(4242)
        namespaces = [f"ns-{i}" for i in range(512)]
        for _ in range(100):
            members = rng.sample(_POOL, rng.randrange(2, 9))
            gone = rng.choice(members)
            before = HashRing(members)
            after = HashRing([m for m in members if m != gone])
            for ns in namespaces:
                if before.owner_of(ns, "x") != gone:
                    assert after.owner_of(ns, "x") == \
                        before.owner_of(ns, "x")


class TestShardMember:
    def test_first_join_creates_map_and_activates_token(self):
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)
        view = a.join()
        assert view["epoch"] == 1
        assert a.token.valid and a.token.epoch == 1
        assert api.get(SHARD_MAP_KIND, "", "control-plane") is not None
        # solo joiner: nobody to drain, self-adoption is the only ack
        (rec,) = view["handoffs"]
        assert rec["adopters"] == ["a"]
        assert rec["drains"] == []

    def test_second_join_bumps_epoch_and_writes_handoff_ahead(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join()
        a.ack_adopt()
        view = b.join()
        assert view["epoch"] == 2
        assert b.token.epoch == 2
        assert a.token.epoch == 1, "survivor incarnation must not move"
        # the SAME commit that admitted b names the key movement
        (rec,) = view["handoffs"]
        assert rec == {
            "epoch": 2, "startedAt": rec["startedAt"],
            "adopters": ["b"], "drains": ["a"]}

    def test_ack_lifecycle_completes_handoff_with_duration(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        b.join()
        clock.advance(2.5)
        view = a.ack_drain()
        (rec,) = view["handoffs"]
        assert rec["drains"] == []
        assert rec["adopters"] == ["b"]
        view, duration = b.ack_adopt()
        assert "handoffs" not in view
        assert duration == pytest.approx(2.5)
        assert view["lastHandoff"]["epoch"] == 2
        assert view["lastHandoff"]["durationSeconds"] == pytest.approx(2.5)

    def test_adopt_before_drain_does_not_complete(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        b.join()
        view, duration = b.ack_adopt()
        assert duration is None
        assert view["handoffs"][0]["drains"] == ["a"], \
            "the record must survive until the drain acks too"

    def test_two_overlapping_joins_carry_independent_records(self):
        """Per-change records: two simultaneous joins each commit their
        OWN adopter/drain lists instead of convoying through one merged
        record, and one drain-ack RMW clears a member out of every
        pending record at once."""
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)
        a.join(); a.ack_adopt()
        b, c = make_member(api, "b", clock), make_member(api, "c", clock)
        b.join()
        view = c.join()
        recs = view["handoffs"]
        assert [r["epoch"] for r in recs] == [2, 3]
        assert recs[0]["adopters"] == ["b"]
        assert recs[0]["drains"] == ["a"]
        assert recs[1]["adopters"] == ["c"]
        assert recs[1]["drains"] == ["a", "b"]
        # one ack RMW removes a from BOTH records' drains
        view = a.ack_drain()
        assert [r["drains"] for r in view["handoffs"]] == [[], ["b"]]
        b.ack_drain()
        view, duration = b.ack_adopt()
        assert duration is not None, "b's record completed"
        view, duration = c.ack_adopt()
        assert duration is not None, "c's record completed"
        assert "handoffs" not in view
        # completions land in epoch order: the highest epoch wins
        assert view["lastHandoff"]["epoch"] == 3

    def test_renew_keeps_incarnation_and_evicts_expired(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        b.join(); a.ack_drain(); b.ack_adopt()
        # b goes dark; a keeps renewing in sub-lease steps
        for _ in range(3):
            clock.advance(8)
            assert a.renew()
        status = a.read_status()
        assert sorted(status["members"]) == ["a"]
        assert status["epoch"] == 3, "eviction must bump the epoch"
        assert a.token.epoch == 1, "renewals never change the incarnation"
        # the eviction commit hands the dead member's keys to survivors
        assert status["handoffs"][0]["adopters"] == ["a"]

    def test_renew_due_coalesces_heartbeats(self):
        """renew_due gates the maintain-loop heartbeat: fresh leases are
        not re-renewed every settle round (the steady-state map write
        the 100k sweep eliminated), but a third of the lease flips it
        and a fenced or never-joined member is always due."""
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)
        assert a.renew_due(), "a member that never joined is always due"
        a.join()
        assert not a.renew_due()
        clock.advance(DEFAULT_LEASE_DURATION_S / 3 + 0.1)
        assert a.renew_due()
        assert a.renew()
        assert not a.renew_due()
        a.token.invalidate()
        assert a.renew_due(), "a fenced member is always due"

    def test_evicted_member_renew_fails_and_invalidates(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        b.join(); a.ack_drain(); b.ack_adopt()
        for _ in range(3):
            clock.advance(8)
            b.renew()  # a never renews -> b evicts it
        assert not a.renew()
        assert not a.token.valid
        with pytest.raises(StaleEpochError):
            a.verify()

    def test_leave_kills_token_before_the_commit(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        b.join(); a.ack_drain(); b.ack_adopt()
        view = a.leave()
        assert not a.token.valid
        assert sorted(view["members"]) == ["b"]
        assert view["epoch"] == 3
        assert view["handoffs"][0]["adopters"] == ["b"]

    def test_preview_join_never_writes(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        rv_before = api.get(SHARD_MAP_KIND, "", "control-plane") \
            .metadata.resource_version
        preview = b.preview_join()
        assert preview["epoch"] == 2
        assert "b" in preview["members"]
        assert api.get(SHARD_MAP_KIND, "", "control-plane") \
            .metadata.resource_version == rv_before
        assert not b.token.valid, "a preview must never activate the token"
        assert a.read_status()["epoch"] == 1


class TestFencedApi:
    def test_reads_delegate_unfenced(self):
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)  # never joined: token invalid
        fenced = FencedApi(api, a)
        api.create(nb("plain"))
        assert fenced.get("Notebook", "default", "plain") is not None
        assert fenced.rejected_total == 0

    def test_valid_member_writes_flow(self):
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)
        a.join()
        FencedApi(api, a).create(nb("ok"))
        assert api.try_get("Notebook", "default", "ok") is not None

    def test_every_write_verb_is_fenced(self):
        api, clock = ApiServer(), FakeClock()
        a = make_member(api, "a", clock)
        rejected = []
        fenced = FencedApi(api, a, on_rejected=lambda: rejected.append(1))
        for i, verb in enumerate(WRITE_VERBS):
            with pytest.raises(StaleEpochError):
                getattr(fenced, verb)(None)
            assert fenced.rejected_total == i + 1
        assert len(rejected) == len(WRITE_VERBS)

    def test_deposed_incarnation_is_rejected(self):
        api, clock = ApiServer(), FakeClock()
        a, b = make_member(api, "a", clock), make_member(api, "b", clock)
        a.join(); a.ack_adopt()
        fenced_a = FencedApi(api, a)
        b.join(); a.ack_drain(); b.ack_adopt()
        for _ in range(3):
            clock.advance(8)
            b.renew()  # evicts a
        with pytest.raises(StaleEpochError):
            fenced_a.create(nb("zombie"))
        assert fenced_a.rejected_total == 1
        assert api.try_get("Notebook", "default", "zombie") is None


def make_fleet(api, clock, count=3, recs=None):
    def factory(replica):
        rec = _Recorder(replica.shard_id)
        if recs is not None:
            recs[replica.shard_id] = rec
        replica.manager.register("nb", rec, for_kind="Notebook")
    return ShardedFleet(api, count=count, clock=clock,
                        controller_factory=factory)


def expire_dead_lease(fleet, clock, steps=3, step=8):
    """Walk time past the dead member's lease in sub-lease increments so
    survivors keep renewing (the production pattern under FakeClock)."""
    for _ in range(steps):
        clock.advance(step)
        fleet.settle()


class TestShardedFleet:
    def test_keyspace_partitions_exactly_once(self):
        api, clock = ApiServer(), FakeClock()
        recs = {}
        fleet = make_fleet(api, clock, recs=recs)
        keys = spread(20)
        for ns, name in keys:
            api.create(nb(name, ns))
        fleet.settle()
        snap = fleet.shard_snapshot()
        assert snap["members"] == ["shard-0", "shard-1", "shard-2"]
        assert snap["handoff"] is None
        assert snap["handoffs"] == []
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert sum(owned.values()) == 20
        assert all(v > 0 for v in owned.values())
        assert all(r["rmw_conflicts"] == 0
                   for r in snap["replicas"].values())
        # dispatch filter and committed ring agree, exactly one owner each
        for ns, name in keys:
            owner = fleet.owner_of(ns, name)
            claimants = [sid for sid, r in fleet.replicas.items()
                         if r.owns_key(ns, name)]
            assert claimants == [owner]
            assert recs[owner].seen.count((ns, name)) >= 1

    def test_namespace_lands_whole_on_one_shard(self):
        """The placement lever itself: every key of one namespace is
        owned — and was reconciled — by the same shard."""
        api, clock = ApiServer(), FakeClock()
        recs = {}
        fleet = make_fleet(api, clock, recs=recs)
        for ns in NAMESPACES:
            for i in range(4):
                api.create(nb(f"nb-{i}", ns))
        fleet.settle()
        for ns in NAMESPACES:
            owner = fleet.owner_of(ns, "nb-0")
            for i in range(4):
                assert fleet.owner_of(ns, f"nb-{i}") == owner
                done_by = [sid for sid, r in recs.items()
                           if (ns, f"nb-{i}") in r.seen]
                assert done_by == [owner]

    def test_kill_evicts_and_survivors_adopt(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(20):
            api.create(nb(name, ns))
        fleet.settle()
        epoch_before = fleet.shard_snapshot()["epoch"]
        fleet.kill("shard-1")
        expire_dead_lease(fleet, clock)
        snap = fleet.shard_snapshot()
        assert snap["members"] == ["shard-0", "shard-2"]
        assert snap["epoch"] > epoch_before
        assert snap["handoff"] is None, "eviction handoff must complete"
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert owned["shard-1"] == 0
        assert owned["shard-0"] + owned["shard-2"] == 20
        assert snap["lastHandoff"]["epoch"] == snap["epoch"]

    def test_zombie_write_after_eviction_is_fenced(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(10):
            api.create(nb(name, ns))
        fleet.settle()
        fleet.kill("shard-1")
        expire_dead_lease(fleet, clock)
        zombie = fleet.replicas["shard-1"]
        with pytest.raises(StaleEpochError):
            zombie.fenced.create(nb("from-the-grave"))
        assert zombie.fenced.rejected_total == 1
        assert api.try_get("Notebook", "default", "from-the-grave") is None
        assert zombie.snapshot()["fenced_rejections"] == 1

    def test_rejoin_restores_membership_with_fresh_incarnation(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(20):
            api.create(nb(name, ns))
        fleet.settle()
        old_incarnation = fleet.replicas["shard-1"].member.token.epoch
        fleet.kill("shard-1")
        expire_dead_lease(fleet, clock)
        fleet.rejoin("shard-1")
        fleet.settle()
        snap = fleet.shard_snapshot()
        assert snap["members"] == ["shard-0", "shard-1", "shard-2"]
        assert snap["handoff"] is None
        assert snap["replicas"]["shard-1"]["incarnation"] > old_incarnation
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert sum(owned.values()) == 20
        assert all(v > 0 for v in owned.values())

    def test_no_cross_process_overlaps_through_kill_and_rejoin(self):
        """The merged flight-recorder sweep: across every replica's
        history, no key was ever inside two reconcile windows at once —
        the single-owner proof the chaos soak scales up."""
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(20):
            api.create(nb(name, ns))
        fleet.settle()
        fleet.kill("shard-2")
        expire_dead_lease(fleet, clock)
        fleet.rejoin("shard-2")
        fleet.settle()
        assert len(fleet.merged_records()) > 0
        assert fleet.cross_process_overlaps() == []

    def test_two_simultaneous_joins_settle_cleanly(self):
        """Two replicas join back-to-back with NO settle in between:
        both per-change records are pending at once, and the fleet still
        converges to an exact single-owner partition (the overlapping-
        handoff case the stable-ring dispatch gate exists for)."""
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock, count=2)
        keys = spread(24)
        for ns, name in keys:
            api.create(nb(name, ns))
        fleet.settle()
        fleet.add_replica("shard-2")
        fleet.add_replica("shard-3")
        fleet.settle()
        snap = fleet.shard_snapshot()
        assert snap["members"] == \
            ["shard-0", "shard-1", "shard-2", "shard-3"]
        assert snap["handoff"] is None
        assert snap["handoffs"] == []
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert sum(owned.values()) == 24
        for ns, name in keys:
            claimants = [sid for sid, r in fleet.replicas.items()
                         if r.owns_key(ns, name)]
            assert claimants == [fleet.owner_of(ns, name)]
        assert fleet.cross_process_overlaps() == []

    def test_graceful_leave_hands_off_without_expiry(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(12):
            api.create(nb(name, ns))
        fleet.settle()
        fleet.replicas["shard-0"].leave_fleet()
        fleet.settle()  # no clock advance needed: leave commits the record
        snap = fleet.shard_snapshot()
        assert snap["members"] == ["shard-1", "shard-2"]
        assert snap["handoff"] is None
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert owned["shard-0"] == 0
        assert owned["shard-1"] + owned["shard-2"] == 12


class TestSettleSkipsIdle:
    """A settle pass costs O(active shards): replicas with nothing
    queued, no pending handoff record naming them, and a fresh lease are
    skipped entirely — at 10k+ notebooks the idle maintain+workqueue
    walks dominated the sweep's handoff-stall wall time."""

    def _count_maintains(self, fleet):
        counts = {}
        for sid, r in fleet.replicas.items():
            def wrapped(orig=r.maintain, sid=sid):
                counts[sid] = counts.get(sid, 0) + 1
                return orig()
            r.maintain = wrapped
        return counts

    def test_idle_fleet_settles_without_touching_replicas(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        for ns, name in spread(12):
            api.create(nb(name, ns))
        fleet.settle()
        counts = self._count_maintains(fleet)
        assert fleet.settle(advance_clock=False) == 0
        assert counts == {}, "idle replicas still walked in settle"

    def test_only_the_busy_shard_runs(self):
        api, clock = ApiServer(), FakeClock()
        recs = {}
        fleet = make_fleet(api, clock, recs=recs)
        for ns, name in spread(12):
            api.create(nb(name, ns))
        fleet.settle()
        counts = self._count_maintains(fleet)
        owner = fleet.owner_of("team-0", "late")
        api.create(nb("late", "team-0"))
        assert fleet.settle(advance_clock=False) >= 1
        assert set(counts) == {owner}, \
            "only the shard owning the new key should run"
        assert ("team-0", "late") in recs[owner].seen

    def test_due_renewals_still_happen_when_idle(self):
        api, clock = ApiServer(), FakeClock()
        fleet = make_fleet(api, clock)
        fleet.settle()
        clock.advance(DEFAULT_LEASE_DURATION_S / 2)
        counts = self._count_maintains(fleet)
        fleet.settle(advance_clock=False)
        assert set(counts) == set(fleet.replicas), \
            "a due lease renewal must not be skipped"


class TestDrainGate:
    def test_gained_key_not_dispatchable_until_drain_acked(self):
        """Write-ahead handoff, observable edge: the commit admitting a
        joiner grants it keys, but the joiner must not dispatch them
        while the loser is still in `drains` — the loser may have one in
        flight."""
        api, clock = ApiServer(), FakeClock()
        r0 = ShardedReplica(api, "shard-0", clock=clock)
        r0.manager.register("nb", _Recorder("shard-0"), for_kind="Notebook")
        r0.join_fleet()
        keys = [(f"team-{i}", f"nb-{i}") for i in range(20)]
        for ns, name in keys:
            api.create(nb(name, ns))
        r0.manager.run_until_idle()
        r1 = ShardedReplica(api, "shard-1", clock=clock)
        r1.manager.register("nb", _Recorder("shard-1"), for_kind="Notebook")
        # commit the join WITHOUT running r1's drain/adopt step: the
        # handoff is now pending with drains=[shard-0]
        view = r1.member.join()
        r1._install_status(view)
        ring = HashRing(["shard-0", "shard-1"])
        gained = [k for k in keys if ring.owner_of(*k) == "shard-1"]
        assert gained, "the joiner must gain part of the keyspace"
        for ns, name in gained:
            assert not r1.owns_key(ns, name), \
                "gained key dispatched before the loser drained"
            assert not r0.owns_key(ns, name), \
                "the ring moved the key: the loser must stop dispatching"
        # the loser acks its drain; the gate opens
        r0.sync()
        for ns, name in gained:
            assert r1.owns_key(ns, name)

    def test_cache_realigns_on_both_sides(self):
        api, clock = ApiServer(), FakeClock()
        r0 = ShardedReplica(api, "shard-0", clock=clock)
        r0.manager.register("nb", _Recorder("shard-0"), for_kind="Notebook")
        r0.join_fleet()
        for ns, name in [(f"team-{i}", f"nb-{i}") for i in range(20)]:
            api.create(nb(name, ns))
        r0.manager.run_until_idle()
        r0.sync()
        assert r0.keys_owned() == 20
        r1 = ShardedReplica(api, "shard-1", clock=clock)
        r1.manager.register("nb", _Recorder("shard-1"), for_kind="Notebook")
        r1.join_fleet()
        r0.sync()
        r1.sync()
        r1.alive = True
        assert r0.keys_owned() + r1.keys_owned() == 20
        assert r0.keys_owned() < 20, "the loser's cache must shed moved keys"


def make_adoption_fleet(cfg, count=2, session=False, tpu_nodes=4):
    """A 2-shard fleet running the full core controller set over a fake
    cluster — the cross-process bookkeeping-adoption harness."""
    from kubeflow_tpu.core.metrics import NotebookMetrics
    from kubeflow_tpu.core.notebook_controller import setup_core_controllers
    from kubeflow_tpu.core.sessionstate import InMemorySessionStore
    from kubeflow_tpu.kube import FakeCluster

    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node",
                     allocatable={"cpu": "64", "memory": "256Gi"})
    if tpu_nodes:
        cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4",
                                    tpu_nodes, 4)
    clock = FakeClock()
    metrics = NotebookMetrics(api)
    store = None
    if session:
        store = InMemorySessionStore(clock=clock)
        cluster.attach_session_store(store)

    def factory(replica):
        setup_core_controllers(replica.manager, cfg, metrics,
                               provisioner=cluster, session=store)

    fleet = ShardedFleet(api, count=count, clock=clock,
                         controller_factory=factory)
    return api, cluster, clock, fleet, store


def recovery_state(api, ns="u1", name="heal", slice_id="0"):
    status = api.get("Notebook", ns, name).body.get("status", {})
    return (status.get("sliceRecovery") or {}).get(slice_id)


def session_entry(api, ns="u1", name="heal", slice_id="0"):
    status = api.get("Notebook", ns, name).body.get("status", {})
    return (status.get("sessionState") or {}).get(slice_id)


def pod_delete_groups(api, name, hosts=4):
    """Audited worker-pod delete attempts, partitioned into consecutive
    whole-slice groups (slice-atomicity assert from test_selfheal.py)."""
    recs = [r for r in api.audit_log(verb="delete", kind="Pod")
            if r.name.startswith(name + "-")]
    expected = {f"{name}-{i}" for i in range(hosts)}
    groups = 0
    for i in range(0, len(recs), hosts):
        chunk = {r.name for r in recs[i:i + hosts]}
        assert chunk == expected, (
            "partial-slice pod deletion observed",
            [(r.name, r.ok) for r in recs])
        groups += 1
    return groups


class TestCrossProcessAdoption:
    """A shard replica dies mid-recovery/mid-migration; the adopter must
    resume from status alone — the in-flight budget never resets, the
    warm-pool claim never moves, the restore intent is never replayed.
    This is the cross-process proof of the write-ahead bookkeeping
    claims in core/selfheal.py and core/scheduler.py."""

    def test_recovery_budget_adopted_not_reset(self):
        from kubeflow_tpu.api.types import TPUSpec
        from kubeflow_tpu.utils.config import CoreConfig

        cfg = CoreConfig(recovery_backoff_base_s=10.0,
                         recovery_backoff_max_s=300.0,
                         recovery_max_attempts=4,
                         recovery_window_s=100000.0)
        api, cluster, clock, fleet, _ = make_adoption_fleet(cfg)
        api.create(Notebook.new("heal", "u1",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        fleet.settle()
        owner = fleet.owner_of("u1", "heal")
        adopter_id = next(s for s in fleet.replicas if s != owner)
        victim, adopter = fleet.replicas[owner], fleet.replicas[adopter_id]
        cluster.poison_statefulset("u1", "heal")  # permanently broken
        victim.manager.enqueue_all()
        victim.manager.run_until_idle(advance_clock=False)  # attempt 1
        st = recovery_state(api)
        assert len(st["attempts"]) == 1
        first_charge = st["attempts"][0]
        assert pod_delete_groups(api, "heal") == 1

        fleet.kill(owner)
        for _ in range(3):
            clock.advance(8)
            fleet.settle()
        assert fleet.shard_snapshot()["members"] == sorted([adopter_id])
        # the adopter resumed A's ledger: the original charge survives
        st = recovery_state(api)
        assert st["attempts"][0] == first_charge, \
            "adoption reset the in-flight recovery budget"
        # drive to exhaustion: the cap holds EXACTLY across processes
        for _ in range(6):
            adopter.manager.advance(300)
        st = recovery_state(api)
        assert st["exhausted"] is True
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts
        assert st["attempts"][0] == first_charge
        adopter.manager.advance(10000)  # still capped after the handoff
        assert pod_delete_groups(api, "heal") == cfg.recovery_max_attempts

    def test_warmpool_claim_adopted_not_reclaimed(self):
        from kubeflow_tpu.api.types import TPUSpec
        from kubeflow_tpu.core import constants as C
        from kubeflow_tpu.core.scheduler import pool_object_name
        from kubeflow_tpu.kube import KubeObject, ObjectMeta
        from kubeflow_tpu.utils.config import CoreConfig

        cfg = CoreConfig.from_env({
            "ENABLE_SLICE_SCHEDULER": "true",
            "WARMPOOL_SIZE": "0",
            "WARMPOOL_PROVISION_S": "120",
            "ENABLE_SELF_HEALING": "false",
        })
        api, cluster, clock, fleet, _ = make_adoption_fleet(cfg)
        pool_name = pool_object_name("v5e", "4x4")
        api.create(KubeObject(
            api_version="kubeflow.org/v1", kind=C.WARMPOOL_KIND,
            metadata=ObjectMeta(name=pool_name),
            body={"spec": {"accelerator": "v5e", "topology": "4x4"},
                  "status": {"slices": {
                      "ws-0001": {"state": "Ready", "pool": "warm-a"},
                      "ws-0002": {"state": "Ready", "pool": "warm-b"},
                  }}}))
        api.create(Notebook.new("heal", "u1",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        fleet.settle()

        def claims():
            pool = api.get(C.WARMPOOL_KIND, "", pool_name)
            slices = (pool.body.get("status") or {}).get("slices") or {}
            return {sid: e["claimedBy"] for sid, e in slices.items()
                    if e.get("claimedBy")}

        before = claims()
        assert list(before.values()) == ["u1/heal"]
        intent_before = api.get("Notebook", "u1", "heal") \
            .metadata.annotations.get(C.ANNOTATION_PLACEMENT)
        assert intent_before

        owner = fleet.owner_of("u1", "heal")
        fleet.kill(owner)
        for _ in range(3):
            clock.advance(8)
            fleet.settle()
        # the adopter reconciled the notebook: the persisted claim is the
        # ground truth it resumes from — same slice, never re-sold
        assert claims() == before, "warm-pool claim moved across the handoff"
        assert api.get("Notebook", "u1", "heal") \
            .metadata.annotations.get(C.ANNOTATION_PLACEMENT) \
            == intent_before, "placement intent rewritten by the adopter"

    def test_migrate_intent_resumed_never_replayed(self):
        from kubeflow_tpu.api.types import TPUSpec
        from kubeflow_tpu.core import constants as C
        from kubeflow_tpu.kube import FaultPlan, FaultRule
        from kubeflow_tpu.utils.config import CoreConfig

        cfg = CoreConfig(checkpoint_store_uri="mem://session-state",
                         checkpoint_max_age_s=1e6,
                         recovery_backoff_base_s=5.0,
                         recovery_max_attempts=6,
                         recovery_window_s=100000.0)
        api, cluster, clock, fleet, store = make_adoption_fleet(
            cfg, session=True)
        api.create(Notebook.new("heal", "u1",
                                tpu=TPUSpec("v5e", "4x4")).obj)
        fleet.settle()
        cluster.set_session_payload("u1", "heal", b"kernel-state-A")
        (snap,) = cluster.snapshot_sessions("u1", "heal")
        owner = fleet.owner_of("u1", "heal")
        adopter_id = next(s for s in fleet.replicas if s != owner)
        victim, adopter = fleet.replicas[owner], fleet.replicas[adopter_id]

        # A's restart sweep dies mid-migration: the restore intent and
        # the attempt charge are already persisted (write-ahead), but no
        # pod delete lands
        cluster.fail_pod("u1", "heal-1")
        api.install_fault_plan(FaultPlan(
            [FaultRule(verbs=("delete",), kinds=("Pod",), error="server",
                       max_matches=100)]))
        victim.manager.enqueue_all()
        victim.manager.run_until_idle(advance_clock=False)
        api.clear_fault_plan()
        entry = session_entry(api)
        assert entry["phase"] == "migrating"
        assert entry["restoreGeneration"] == snap.generation
        charges_before = len(recovery_state(api)["attempts"])
        assert charges_before >= 1

        fleet.kill(owner)
        for _ in range(3):
            clock.advance(8)
            fleet.settle()
        for _ in range(10):
            adopter.manager.advance(10)
            status = api.get("Notebook", "u1", "heal").body["status"]
            if status.get("sliceHealth") == "Healthy" and \
                    (session_entry(api) or {}).get("phase") == "restored":
                break
        entry = session_entry(api)
        assert entry["phase"] == "restored", entry
        # the SAME generation A committed — the intent was resumed, not
        # replaced by a fresh snapshot or a cold restart
        assert entry["restoreGeneration"] == snap.generation
        assert store.latest("u1", "heal", 0).generation == snap.generation
        for pod in api.list("Pod", namespace="u1"):
            got = pod.metadata.annotations.get(
                C.ANNOTATION_RESTORED_GENERATION)
            assert got == str(snap.generation), (pod.name, got)


class TestMainWiring:
    def test_build_sharded_fleet_runs_full_controllers(self):
        from kubeflow_tpu.main import build_sharded_fleet

        clock = FakeClock()
        fleet, api, cluster, metrics = build_sharded_fleet(
            count=3, clock=clock)
        keys = spread(6)
        for ns, name in keys:
            api.create(nb(name, ns))
        fleet.settle()
        snap = fleet.shard_snapshot()
        assert snap["members"] == ["shard-0", "shard-1", "shard-2"]
        owned = {sid: r["keys_owned"] for sid, r in snap["replicas"].items()}
        assert sum(owned.values()) == 6
        assert all(v > 0 for v in owned.values())
        # the real reconcilers ran: every notebook has a StatefulSet
        for ns, name in keys:
            assert api.try_get("StatefulSet", ns, name) is not None
        text = metrics.scrape()
        for family in ("notebook_shard_keys_owned", "notebook_shard_epoch",
                       "notebook_shard_fenced_writes_total",
                       "notebook_shard_handoff_duration_seconds"):
            assert family in text
        assert "shards" in metrics.fleet_snapshot()
