"""MFU sweep harness: measure BENCH_CHIP variants on the real chip.

Two modes:
  --run '<json>'   run ONE config in this process, print one JSON line
  (driver)         run the staged sweep, one subprocess per config (so an
                   OOM or compiler fault can't poison later runs), append
                   results to ci/sweep_results.jsonl and print a ranked
                   summary.

The grid covers the knobs the bench config exposes (configs.py):
loss_chunks (chunked CE — never materializes the [tokens, vocab] fp32
logits), mu_dtype (bf16 first moment), remat_policy, Pallas flash block
sizes, attention impl, and batch — the levers named in BASELINE.md for
closing the 0.23 -> 0.35 MFU gap.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

RESULTS = Path(__file__).parent / "sweep_results.jsonl"


def run_one(spec: dict) -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.configs import BENCH_CHIP
    from kubeflow_tpu.models.train import (
        default_optimizer,
        mfu,
        setup_training,
        timed_steps,
    )
    from kubeflow_tpu.parallel.mesh import MeshConfig, make_mesh
    from kubeflow_tpu.tpu.topology import accelerator_from_device_kind

    batch = spec.pop("batch", 24)
    seq = spec.pop("seq", 2048)
    num_steps = spec.pop("num_steps", 10)
    mu_dtype = spec.pop("mu_dtype", None)
    config = BENCH_CHIP.with_(**spec)

    devices = jax.devices()
    accel = accelerator_from_device_kind(devices[0].device_kind)
    mesh = make_mesh(MeshConfig(data=len(devices)), devices=devices)
    optimizer = default_optimizer(mu_dtype=mu_dtype)

    t0 = time.perf_counter()
    setup = setup_training(config, mesh, optimizer=optimizer,
                           batch_shape=(batch, seq))
    key = jax.random.PRNGKey(0)
    data = {"inputs": jax.random.randint(key, (batch, seq), 0,
                                         config.vocab_size)}
    data["targets"] = jnp.roll(data["inputs"], -1, axis=1)
    result = timed_steps(setup, data, num_steps=num_steps, warmup=2)
    compile_s = time.perf_counter() - t0 - result["step_time_s"] * num_steps

    achieved = mfu(result["tokens_per_s"], config, seq,
                   num_chips=len(devices), accelerator=accel)
    return {
        "mfu": round(achieved, 4),
        "tokens_per_s": round(result["tokens_per_s"], 1),
        "step_time_s": round(result["step_time_s"], 4),
        "loss": round(result["loss"], 4),
        "compile_s": round(compile_s, 1),
    }


BASE = {"batch": 24}  # current committed config, the reproduction anchor

# Staged grid: each stage builds on the best-so-far from the previous one.
STAGES: list[list[dict]] = [
    # stage 0: reproduce the committed number + the two named levers alone
    [
        {},
        {"loss_chunks": 8},
        {"loss_chunks": 16},
        {"loss_chunks": 8, "mu_dtype": "bfloat16"},
    ],
    # stage 1: batch growth with the freed HBM (chunks scale with batch so
    # the per-chunk logits block stays ~constant)
    [
        {"loss_chunks": 8, "mu_dtype": "bfloat16", "batch": 32},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 64},
    ],
    # stage 2: remat + attention impl at the surviving batches
    [
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "remat_policy": "dots"},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "attention_impl": "xla"},
        {"loss_chunks": 8, "mu_dtype": "bfloat16", "batch": 32,
         "remat_policy": "dots"},
    ],
    # stage 3: flash tile sizes on the best flash config
    [
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "flash_block_q": 256, "flash_block_k": 256},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "flash_block_q": 512, "flash_block_k": 1024},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "flash_block_q": 1024, "flash_block_k": 512},
        {"loss_chunks": 16, "mu_dtype": "bfloat16", "batch": 48,
         "flash_block_q": 1024, "flash_block_k": 1024},
    ],
]


def drive(stages=STAGES) -> None:
    for stage_i, stage in enumerate(stages):
        for spec in stage:
            merged = {**BASE, **spec}
            label = json.dumps(merged, sort_keys=True)
            print(f"[stage {stage_i}] {label}", flush=True)
            proc = subprocess.run(
                [sys.executable, __file__, "--run", json.dumps(merged)],
                capture_output=True, text=True, timeout=1200,
            )
            line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            try:
                result = json.loads(line)
            except (json.JSONDecodeError, IndexError):
                result = {"error": (proc.stderr or "no output")[-2000:],
                          "rc": proc.returncode}
            record = {"spec": merged, **result}
            with RESULTS.open("a") as f:
                f.write(json.dumps(record) + "\n")
            print(f"    -> {json.dumps({k: v for k, v in result.items() if k != 'error'}) if 'error' not in result else 'FAILED rc=' + str(proc.returncode)}",
                  flush=True)

    ranked = []
    for line in RESULTS.read_text().splitlines():
        r = json.loads(line)
        if "mfu" in r:
            ranked.append(r)
    ranked.sort(key=lambda r: -r["mfu"])
    print("\n=== ranked ===")
    for r in ranked[:10]:
        print(f"mfu={r['mfu']:.4f} tok/s={r['tokens_per_s']:>8} {json.dumps(r['spec'], sort_keys=True)}")


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--run":
        print(json.dumps(run_one(json.loads(sys.argv[2]))))
    else:
        drive()
