"""In-notebook runtime: distributed bootstrap, checkpoint/cull hooks,
performance metrics.  Ships inside the TPU workbench image; everything the
controller plane arranges (env injection, headless DNS, cull signals) is
consumed here."""

from .checkpoint import CheckpointManager, CullSignalWatcher, checkpoint_on_cull
from .init import WorkerIdentity, parse_worker_env, tpu_init
from .metrics import StepTimer, hbm_usage_bytes

__all__ = [
    "CheckpointManager",
    "CullSignalWatcher",
    "StepTimer",
    "WorkerIdentity",
    "checkpoint_on_cull",
    "hbm_usage_bytes",
    "parse_worker_env",
    "tpu_init",
]
