"""utils/metrics Registry semantics + exposition-format conformance.

The render() output is what Prometheus actually ingests, so these tests
round-trip it through a STRICT text-exposition parser (HELP/TYPE blocks,
sample-to-family suffix rules, histogram bucket monotonicity and
_count/_sum coherence) instead of substring checks — a malformed exposition
fails loudly here rather than silently breaking a scrape.  Also covers the
registry's duplicate-registration guard and the labeled-gauge
set_function rejection.
"""

from __future__ import annotations

import re

import pytest

from kubeflow_tpu.utils.metrics import (
    DEFAULT_BUCKETS,
    OVERFLOW_LABEL,
    Counter,
    Histogram,
    Registry,
    register_cardinality_metrics,
)

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{([^{}]*)\})?"                     # optional labels
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf)|NaN|\+Inf)$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"\\]*)"$')


def parse_exposition(text: str) -> dict:
    """Strict Prometheus text-format parser: returns
    {family: {"help": str, "type": str, "samples": {(name, labels): float}}}
    and raises AssertionError on any structural violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families: dict[str, dict] = {}
    current: str | None = None
    for line in text[:-1].split("\n"):
        if line.startswith("# HELP "):
            _, _, name, help_ = line.split(" ", 3)
            assert name not in families, f"duplicate # HELP block for {name}"
            families[name] = {"help": help_, "type": None, "samples": {}}
            current = None
        elif line.startswith("# TYPE "):
            parts = line.split(" ")
            assert len(parts) == 4, f"malformed TYPE line: {line!r}"
            name, kind = parts[2], parts[3]
            assert kind in ("counter", "gauge", "histogram"), line
            assert name in families, f"TYPE before HELP for {name}"
            assert families[name]["type"] is None, f"duplicate TYPE: {name}"
            families[name]["type"] = kind
            current = name
        else:
            assert current is not None, f"sample before any TYPE: {line!r}"
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            sample_name, label_blob, value = m.groups()
            fam = families[current]
            if fam["type"] == "histogram":
                allowed = {f"{current}_bucket", f"{current}_sum",
                           f"{current}_count"}
            else:
                allowed = {current}
            assert sample_name in allowed, (
                f"sample {sample_name!r} does not belong to family "
                f"{current!r} ({fam['type']})")
            labels = {}
            if label_blob:
                for pair in label_blob.split(","):
                    lm = _LABEL_RE.match(pair)
                    assert lm, f"malformed label pair {pair!r} in {line!r}"
                    assert lm.group(1) not in labels, f"dup label: {line!r}"
                    labels[lm.group(1)] = lm.group(2)
            key = (sample_name, tuple(sorted(labels.items())))
            assert key not in fam["samples"], f"duplicate sample: {line!r}"
            fam["samples"][key] = float(value.replace("Inf", "inf"))
    for name, fam in families.items():
        assert fam["type"] is not None, f"family {name} has HELP but no TYPE"
        if fam["type"] == "histogram":
            _check_histogram_family(name, fam["samples"])
    return families


def _check_histogram_family(name: str, samples: dict) -> None:
    """Bucket cumulativity, +Inf == _count, and _sum presence per series."""
    series: dict[tuple, dict[float, float]] = {}
    counts: dict[tuple, float] = {}
    sums: set[tuple] = set()
    for (sample_name, labels), value in samples.items():
        base = {k: v for k, v in labels if k != "le"}
        key = tuple(sorted(base.items()))
        if sample_name == f"{name}_bucket":
            le = dict(labels)["le"]
            series.setdefault(key, {})[float(le.replace("Inf", "inf"))] = value
        elif sample_name == f"{name}_count":
            counts[key] = value
        elif sample_name == f"{name}_sum":
            sums.add(key)
    for key, buckets in series.items():
        bounds = sorted(buckets)
        assert bounds[-1] == float("inf"), f"{name}{key}: no +Inf bucket"
        cumulative = [buckets[b] for b in bounds]
        assert all(a <= b for a, b in zip(cumulative, cumulative[1:])), (
            f"{name}{key}: buckets not cumulative: {cumulative}")
        assert key in counts and counts[key] == buckets[float("inf")], (
            f"{name}{key}: _count != +Inf bucket")
        assert key in sums, f"{name}{key}: missing _sum"


class TestHistogram:
    def test_observe_buckets_sum_count(self):
        h = Histogram("lat_seconds", "h", (), buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count_value() == 5
        assert h.sum_value() == pytest.approx(56.05)
        assert h.bucket_counts() == {0.1: 1, 1.0: 3, 10.0: 4,
                                     float("inf"): 5}

    def test_labeled_series_are_independent(self):
        h = Histogram("lat_seconds", "h", ("c",), buckets=(1.0,))
        h.labels("a").observe(0.5)
        h.labels("b").observe(2.0)
        assert h.bucket_counts("a") == {1.0: 1, float("inf"): 1}
        assert h.bucket_counts("b") == {1.0: 0, float("inf"): 1}

    def test_boundary_value_lands_in_le_bucket(self):
        # Prometheus buckets are `le` (less-or-EQUAL)
        h = Histogram("lat_seconds", "h", (), buckets=(1.0, 2.0))
        h.observe(1.0)
        assert h.bucket_counts()[1.0] == 1

    def test_inc_and_set_rejected(self):
        h = Histogram("lat_seconds", "h", ("c",))
        with pytest.raises(TypeError):
            h.labels("a").inc()
        with pytest.raises(TypeError):
            h.labels("a").set(1.0)

    def test_observe_on_counter_rejected(self):
        c = Counter("x_total", "c", ("l",))
        with pytest.raises(TypeError):
            c.labels("a").observe(1.0)

    def test_default_buckets_sorted_unique(self):
        assert list(DEFAULT_BUCKETS) == sorted(set(DEFAULT_BUCKETS))


class TestRegistryDuplicates:
    def test_identical_reregistration_returns_existing(self):
        r = Registry()
        a = r.counter("x_total", "help", labels=("l",))
        b = r.counter("x_total", "help", labels=("l",))
        assert a is b
        assert len(r.families()) == 1

    def test_conflicting_kind_raises(self):
        r = Registry()
        r.counter("dup_metric", "help")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("dup_metric", "help")

    def test_conflicting_labels_raise(self):
        r = Registry()
        r.gauge("g", "help", labels=("a",))
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("g", "help", labels=("b",))

    def test_conflicting_histogram_buckets_raise(self):
        r = Registry()
        r.histogram("h_seconds", "help", buckets=(1.0,))
        with pytest.raises(ValueError, match="already registered"):
            r.histogram("h_seconds", "help", buckets=(2.0,))

    def test_labeled_gauge_set_function_rejected(self):
        r = Registry()
        g = r.gauge("g", "help", labels=("l",))
        with pytest.raises(ValueError, match="unlabeled"):
            g.set_function(lambda: 1.0)

    def test_unlabeled_gauge_set_function_renders(self):
        r = Registry()
        g = r.gauge("g", "help")
        g.set_function(lambda: 42.0)
        assert "g 42" in r.render()


class TestExpositionRoundTrip:
    def test_registry_with_all_kinds_parses_strictly(self):
        r = Registry()
        c = r.counter("requests_total", "Total requests", labels=("code",))
        c.labels("200").inc(3)
        c.labels("500").inc()
        g = r.gauge("depth", "Queue depth")
        g.set(7)
        h = r.histogram("lat_seconds", "Latency", labels=("op",),
                        buckets=(0.1, 1.0))
        h.labels("get").observe(0.05)
        h.labels("get").observe(0.5)
        h.labels("put").observe(9.0)

        fams = parse_exposition(r.render())
        assert set(fams) == {"requests_total", "depth", "lat_seconds"}
        assert fams["requests_total"]["type"] == "counter"
        assert fams["requests_total"]["samples"][
            ("requests_total", (("code", "200"),))] == 3
        assert fams["depth"]["samples"][("depth", ())] == 7
        assert fams["lat_seconds"]["type"] == "histogram"
        assert fams["lat_seconds"]["samples"][
            ("lat_seconds_bucket", (("le", "0.1"), ("op", "get")))] == 1
        assert fams["lat_seconds"]["samples"][
            ("lat_seconds_count", (("op", "put"),))] == 1

    def test_parser_rejects_duplicate_family(self):
        bad = ("# HELP x h\n# TYPE x counter\nx 1\n"
               "# HELP x h\n# TYPE x counter\nx 2\n")
        with pytest.raises(AssertionError, match="duplicate # HELP"):
            parse_exposition(bad)

    def test_parser_rejects_noncumulative_histogram(self):
        bad = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\n'
               "h_sum 1\nh_count 3\n")
        with pytest.raises(AssertionError, match="not cumulative"):
            parse_exposition(bad)


class TestFullStackScrape:
    """Acceptance: the combined NotebookMetrics + Manager exposition is a
    valid single scrape with reconcile-time histogram buckets for BOTH
    controllers."""

    def _env(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.core.notebook_controller import setup_core_controllers
        from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
        from kubeflow_tpu.odh.controller import setup_odh_controllers
        from kubeflow_tpu.utils.clock import FakeClock
        from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("cpu-node", allocatable={"cpu": "64",
                                                  "memory": "256Gi"})
        mgr = Manager(api, clock=FakeClock())
        metrics = NotebookMetrics(api, manager=mgr)
        setup_core_controllers(mgr, CoreConfig(), metrics)
        setup_odh_controllers(mgr, OdhConfig(controller_namespace="odh"))
        return api, mgr, metrics

    def test_reconcile_histograms_for_both_controllers(self):
        from kubeflow_tpu.api.types import Notebook

        api, mgr, metrics = self._env()
        api.create(Notebook.new("obs-nb", "user1").obj)
        mgr.run_until_idle()

        text = metrics.scrape()
        fams = parse_exposition(text)
        assert fams["controller_runtime_reconcile_time_seconds"]["type"] \
            == "histogram"
        samples = fams["controller_runtime_reconcile_time_seconds"]["samples"]
        for controller in ("notebook", "odh-notebook"):
            key = ("controller_runtime_reconcile_time_seconds_bucket",
                   (("controller", controller), ("le", "+Inf")))
            assert samples[key] >= 1, f"no reconcile histogram for {controller}"
        # result-labeled totals and workqueue duration histograms ride along
        assert fams["controller_runtime_reconcile_total"]["type"] == "counter"
        assert fams["workqueue_queue_duration_seconds"]["type"] == "histogram"
        assert fams["workqueue_work_duration_seconds"]["type"] == "histogram"
        assert mgr.reconcile_total.value("notebook", "success") >= 1

    def test_notebook_ready_histogram_observed_once(self):
        from kubeflow_tpu.api.types import Notebook

        api, mgr, metrics = self._env()
        api.create(Notebook.new("rdy-nb", "user1").obj)
        mgr.run_until_idle()
        assert metrics.notebook_ready_seconds.count_value("user1") == 1
        # further reconciles must not re-observe an already-ready notebook
        nb = api.get("Notebook", "user1", "rdy-nb")
        nb.metadata.labels["touch"] = "1"
        api.update(nb)
        mgr.run_until_idle()
        assert metrics.notebook_ready_seconds.count_value("user1") == 1

    def test_retry_and_error_totals_are_monotonic_counters(self):
        """The satellite fix: scrape-fed *_total families are counters fed
        by deltas — two scrapes must not double-count."""
        from kubeflow_tpu.core.metrics import NotebookMetrics
        from kubeflow_tpu.kube import ApiServer, KubeObject, Manager, ObjectMeta
        from kubeflow_tpu.utils.clock import FakeClock

        class Failing:
            def reconcile(self, req):
                raise RuntimeError("boom")

        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        mgr.register("nb", Failing(), for_kind="Notebook", max_retries=2)
        api.create(KubeObject(api_version="v1", kind="Notebook",
                              metadata=ObjectMeta(name="x", namespace="d")))
        mgr.run_until_idle()
        metrics = NotebookMetrics(api, manager=mgr)
        first = metrics.scrape()
        second = metrics.scrape()
        fams = parse_exposition(second)
        assert fams["workqueue_retries_total"]["type"] == "counter"
        assert fams["reconcile_errors_total"]["type"] == "counter"
        key = ("workqueue_retries_total", (("controller", "nb"),))
        assert parse_exposition(first)["workqueue_retries_total"][
            "samples"][key] == 2
        assert fams["workqueue_retries_total"]["samples"][key] == 2
        assert fams["reconcile_errors_total"]["samples"][
            ("reconcile_errors_total", (("controller", "nb"),))] == 1


class TestCardinalityGuard:
    """Per-family label-set cap (METRICS_MAX_LABEL_SETS): series past the
    cap fold into the reserved `other` series instead of growing the
    exposition without bound, and every fold is counted."""

    def test_overflow_folds_into_other_series(self):
        r = Registry(max_label_sets=2)
        c = r.counter("x_total", "h", labels=("tenant",))
        c.labels("a").inc(1)
        c.labels("b").inc(2)
        c.labels("c").inc(5)   # third distinct series: folds
        c.labels("d").inc(7)   # folds into the SAME other series
        assert c.value("a") == 1 and c.value("b") == 2
        assert c.value(OVERFLOW_LABEL) == 12
        assert c.labelsets_dropped == 2

    def test_known_series_keep_incrementing_past_cap(self):
        r = Registry(max_label_sets=1)
        c = r.counter("x_total", "h", labels=("l",))
        c.labels("a").inc()
        c.labels("b").inc()    # folds
        c.labels("a").inc()    # known series: never folds
        assert c.value("a") == 2
        assert c.labelsets_dropped == 1

    def test_render_stays_bounded_and_parseable(self):
        r = Registry(max_label_sets=3)
        c = r.counter("x_total", "h", labels=("tenant",))
        for i in range(50):
            c.labels(f"t{i}").inc()
        fams = parse_exposition(r.render())
        series = [k for k in fams["x_total"]["samples"]
                  if k[0] == "x_total"]
        # 3 admitted + 1 overflow series, never 50
        assert len(series) == 4, series
        assert ("x_total", (("tenant", OVERFLOW_LABEL),)) in \
            fams["x_total"]["samples"]

    def test_histogram_observations_fold(self):
        r = Registry(max_label_sets=1)
        h = r.histogram("lat_seconds", "h", labels=("c",), buckets=(1.0,))
        h.labels("a").observe(0.5)
        h.labels("b").observe(0.5)
        h.labels("b").observe(2.0)
        assert h.count_value("a") == 1
        assert h.count_value(OVERFLOW_LABEL) == 2
        assert h.labelsets_dropped == 2
        parse_exposition(r.render())  # fold keeps the exposition valid

    def test_unlabeled_and_exempt_metrics_never_fold(self):
        r = Registry(max_label_sets=1)
        g = r.gauge("depth", "h")
        g.set(7)
        assert g.labelsets_dropped == 0
        exempt = r.counter("y_total", "h", labels=("l",), max_label_sets=0)
        for i in range(10):
            exempt.labels(f"v{i}").inc()
        assert exempt.labelsets_dropped == 0
        assert exempt.value("v9") == 1

    def test_per_metric_override_beats_registry_default(self):
        r = Registry(max_label_sets=100)
        c = r.counter("x_total", "h", labels=("l",), max_label_sets=1)
        c.labels("a").inc()
        c.labels("b").inc()
        assert c.value(OVERFLOW_LABEL) == 1

    def test_env_sets_registry_default(self, monkeypatch):
        monkeypatch.setenv("METRICS_MAX_LABEL_SETS", "1")
        r = Registry()
        assert r.max_label_sets == 1
        c = r.counter("x_total", "h", labels=("l",))
        c.labels("a").inc()
        c.labels("b").inc()
        assert c.value(OVERFLOW_LABEL) == 1
        monkeypatch.setenv("METRICS_MAX_LABEL_SETS", "not-a-number")
        assert Registry().max_label_sets > 0  # falls back to the default

    def test_registry_drop_rollup_and_exported_counter(self):
        r = Registry(max_label_sets=1)
        c = r.counter("x_total", "h", labels=("l",))
        c.labels("a").inc()
        c.labels("b").inc()
        c.labels("c").inc()
        h = r.histogram("lat_seconds", "h", labels=("l",), buckets=(1.0,))
        h.labels("a").observe(0.1)
        assert r.labelsets_dropped() == {"x_total": 2}
        dropped = register_cardinality_metrics(r)
        # the exported family is itself exempt from the cap
        for fam, n in r.labelsets_dropped().items():
            dropped.labels(fam).inc(n)
        assert dropped.value("x_total") == 2
        fams = parse_exposition(r.render())
        assert fams["metrics_labelsets_dropped_total"]["type"] == "counter"


class TestExemplarsAndOpenMetrics:
    """Histogram exemplars + the OpenMetrics exposition variant: exemplars
    render per bucket only in OpenMetrics, counter families drop the
    `_total` suffix from their HELP/TYPE declaration (samples keep it),
    and the classic Prometheus text format stays byte-for-byte free of
    both so existing scrapers never see syntax they cannot parse."""

    def test_exemplar_stored_on_the_falling_bucket(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "h", labels=("c",),
                          buckets=(0.1, 1.0))
        h.labels("nb").observe(0.05, exemplar={"trace_id": "aaa"})
        h.labels("nb").observe(0.5, exemplar={"trace_id": "bbb"})
        h.labels("nb").observe(5.0, exemplar={"trace_id": "ccc"})
        ex = h.exemplar("nb")
        assert ex[0.1] == ({"trace_id": "aaa"}, 0.05)
        assert ex[1.0] == ({"trace_id": "bbb"}, 0.5)
        assert ex[float("inf")] == ({"trace_id": "ccc"}, 5.0)
        # the latest observation per bucket wins
        h.labels("nb").observe(0.07, exemplar={"trace_id": "ddd"})
        assert h.exemplar("nb")[0.1] == ({"trace_id": "ddd"}, 0.07)

    def test_openmetrics_render_carries_exemplars(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "h", buckets=(0.1, 1.0))
        h.observe(0.05, exemplar={"trace_id": "deadbeef"})
        om = reg.render(openmetrics=True)
        assert ('lat_seconds_bucket{le="0.1"} 1 '
                '# {trace_id="deadbeef"} 0.05') in om
        # classic text format: no exemplar syntax anywhere
        prom = reg.render()
        assert "# {" not in prom
        assert 'lat_seconds_bucket{le="0.1"} 1' in prom

    def test_openmetrics_counter_family_drops_total_suffix(self):
        reg = Registry()
        c = reg.counter("reconcile_total", "total reconciles")
        c.inc(3)
        om = reg.render(openmetrics=True)
        assert "# TYPE reconcile counter" in om
        assert "# HELP reconcile total reconciles" in om
        assert "reconcile_total 3" in om
        prom = reg.render()
        assert "# TYPE reconcile_total counter" in prom

    def test_observation_without_exemplar_renders_bare(self):
        reg = Registry()
        h = reg.histogram("lat_seconds", "h", buckets=(0.1,))
        h.observe(0.05)
        om = reg.render(openmetrics=True)
        assert 'lat_seconds_bucket{le="0.1"} 1\n' in om

    def test_prometheus_render_still_parses_strictly(self):
        """Exemplar storage must not leak into the 0.0.4 exposition the
        strict round-trip parser validates."""
        reg = Registry()
        h = reg.histogram("lat_seconds", "h", labels=("c",))
        h.labels("nb").observe(0.003, exemplar={"trace_id": "abc"})
        reg.counter("ops_total", "t", labels=("c",)).labels("nb").inc()
        fams = parse_exposition(reg.render())
        assert fams["lat_seconds"]["type"] == "histogram"
        _check_histogram_family("lat_seconds", fams["lat_seconds"]["samples"])
