"""Schedule-exploring concurrency model checker (CHESS-style).

The PR 9 sanitizer gave every control-plane synchronization action a
deterministic observation point: TrackedLock acquire/release, the store
commit in ApiServer._notify, the workqueue add/pop/done in Manager.  This
module takes those same points over as *preemption* points: N logical
threads run under a cooperative scheduler that keeps exactly one runnable
at a time, and at every yield point decides — systematically, not by OS
luck — which thread runs next.

    explorer = InterleavingExplorer(scenario)
    result = explorer.explore()

`scenario` is a zero-arg factory returning `(threads, check)`: `threads`
is a list of zero-arg callables (or `(name, callable)` pairs) sharing
freshly-built state, `check` is called after every thread finishes and
raises (AssertionError) on an invariant violation.  The factory runs once
per explored schedule — stateless model checking: every schedule replays
the protocol from scratch, so a recorded schedule replays byte-identically
(`replay()` + `render()`).

Enumeration is DFS over the schedule tree with:

  - **iterative preemption bounding** (CHESS): bound 0 first — the
    schedules reachable by only switching when the running thread blocks
    or exits — then bound 1, 2, … up to `max_preemptions`.  Almost every
    real concurrency bug needs very few preemptions, so low bounds find
    them orders of magnitude sooner than unrestricted DFS.
  - **sleep-set pruning** (partial-order reduction): after fully exploring
    thread `a` at a node, sibling subtrees that would start with a step
    *independent* of `a`'s (different lock, different store object) are
    not re-explored — those schedules commute into already-visited ones.
    Independence is deliberately coarse (conservative = less pruning).

A failing schedule is shrunk to its minimal set of *preemption
directives* — the steps where the schedule deviates from the default
run-until-blocked order — by greedy delta-debugging re-execution, and the
shrunk run is rendered as a step-by-step narrative naming the (thread,
yield-point, object) at every step, switches flagged.

The explorer never blocks a granted thread on a modelled lock: a thread
whose pending acquire targets a lock owned by a *suspended* thread is
simply not schedulable until the owner releases.  All-parked with nothing
schedulable is reported as a deadlock schedule, not a hang.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..utils import invariants

# logical thread states
_NEW, _WAITING, _RUNNING, _DONE = "new", "waiting", "running", "done"


@dataclass(frozen=True)
class Op:
    """One pending action at a yield point."""

    kind: str            # lock.acquire | lock.release | store.commit | ...
    detail: str          # stable human-readable object name
    token: object = None  # lock instance (ownership) or wait predicate

    def render(self) -> str:
        return f"{self.kind:13s} {self.detail}" if self.detail \
            else f"{self.kind}"


@dataclass(frozen=True)
class TraceStep:
    step: int
    thread: str
    op: Op
    switched_from: str   # "" when the same thread keeps running
    preemption: bool     # switch while switched_from was still schedulable


@dataclass
class RunResult:
    """One executed schedule."""

    choices: tuple        # thread index chosen at each step
    trace: tuple          # TraceStep per step
    nodes: list           # [(enabled tuple, {tid: Op}, chosen)]
    error: Optional[BaseException]   # thread/check exception, or None
    deadlock: bool = False

    @property
    def failed(self) -> bool:
        return self.error is not None or self.deadlock


@dataclass
class FailingSchedule:
    message: str
    choices: tuple
    directives: dict      # step -> thread index (deviations from default)
    preemptions: int
    trace: tuple
    narrative: str


@dataclass
class ExploreResult:
    schedules: int        # DISTINCT schedules executed
    runs: int             # total executions (bounds re-visit low bounds)
    stopped: str          # exhausted | max_schedules | budget | failure
    bound_reached: int
    failure: Optional[FailingSchedule] = None

    @property
    def ok(self) -> bool:
        return self.failure is None


def checkpoint(label: str) -> None:
    """Test-authored yield point: lets scenario code mark a schedule
    point the production code doesn't have."""
    invariants.yield_point("test.point", label)


def await_cond(label: str, pred: Callable[[], bool]) -> None:
    """Test-authored blocking point: the calling logical thread is not
    schedulable until `pred()` is true (evaluated by the scheduler)."""
    invariants.yield_point("test.wait", label, pred)


def _fmt(detail) -> str:
    if detail is None:
        return ""
    if isinstance(detail, str):
        return detail
    if isinstance(detail, tuple):
        return "/".join("" if d is None else str(d) for d in detail)
    return str(detail)


def _independent(a: Op, b: Op) -> bool:
    """May the two pending ops commute?  Conservative: only provably
    disjoint lock/store actions are independent; everything else is
    treated as conflicting (costs exploration, never soundness)."""
    if a.kind == "thread.start" or b.kind == "thread.start":
        return True
    lock_kinds = ("lock.acquire", "lock.release")
    if a.kind in lock_kinds and b.kind in lock_kinds:
        return a.token is not b.token
    if a.kind == "store.commit" and b.kind == "store.commit":
        # detail = "type/kind/ns/name"; different kinds live on
        # different shards and commute
        return a.detail.split("/")[1:2] != b.detail.split("/")[1:2]
    return False


class _StopRun(BaseException):
    """Raised inside a parked logical thread when a run is abandoned."""


class _LThread:
    __slots__ = ("idx", "name", "fn", "thread", "state", "pending", "error")

    def __init__(self, idx: int, name: str, fn) -> None:
        self.idx = idx
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.state = _NEW
        self.pending: Optional[Op] = None
        self.error: Optional[BaseException] = None


class _DfsPlan:
    """Forced choice prefix; divergence (forced thread not enabled) is a
    determinism bug and raises."""

    def __init__(self, prefix) -> None:
        self.prefix = list(prefix)

    def choose(self, step, enabled, default):
        if step < len(self.prefix):
            want = self.prefix[step]
            if want not in enabled:
                raise ReplayDivergence(
                    f"step {step}: recorded choice T{want} not enabled "
                    f"(enabled: {sorted(enabled)}) — scenario is "
                    "nondeterministic")
            return want
        return default


class _DirectivePlan:
    """Sparse step->thread overrides; inapplicable directives fall back
    to the default (used while shrinking, where dropping one directive
    shifts everything after it)."""

    def __init__(self, directives) -> None:
        self.directives = dict(directives)

    def choose(self, step, enabled, default):
        want = self.directives.get(step)
        return want if want in enabled else default


class ReplayDivergence(AssertionError):
    pass


class _PathEntry:
    __slots__ = ("enabled", "ops", "chosen", "done", "sleep", "preempts")

    def __init__(self, enabled, ops, chosen, sleep, preempts) -> None:
        self.enabled = enabled        # tuple of enabled thread idxs
        self.ops = ops                # {tid: Op}
        self.chosen = chosen
        self.done = {chosen}          # choices already (being) explored
        self.sleep = sleep            # frozenset of pruned thread idxs
        self.preempts = preempts      # preemptions up to AND INCL this step


class InterleavingExplorer:
    """Bounded-exhaustive scheduler for one scenario.  See module doc."""

    #: scheduler-side wedge guard — only trips if a granted thread blocks
    #: outside the modelled world (a real bug in the harness assumptions)
    WEDGE_TIMEOUT_S = 60.0

    def __init__(self, scenario, *, max_preemptions: int = 2,
                 max_schedules: int = 1200,
                 budget_s: float = 60.0) -> None:
        self.scenario = scenario
        self.max_preemptions = max_preemptions
        self.max_schedules = max_schedules
        self.budget_s = budget_s
        # per-run scheduler state
        self._cv = threading.Condition()
        self._lts: list[_LThread] = []
        self._by_ident: dict[int, _LThread] = {}
        self._active: Optional[_LThread] = None
        self._freerun = False

    # -- public ---------------------------------------------------------------

    def explore(self) -> ExploreResult:
        deadline = time.monotonic() + self.budget_s
        seen: set = set()
        runs = 0
        stopped = "exhausted"
        bound_reached = 0
        for bound in range(self.max_preemptions + 1):
            bound_reached = bound
            out = self._dfs(bound, deadline, seen)
            runs += out["runs"]
            if out["failure"] is not None:
                fail = self._shrink(out["failure"])
                return ExploreResult(len(seen), runs, "failure", bound,
                                     failure=fail)
            if out["stopped"] != "exhausted":
                stopped = out["stopped"]
                break
        return ExploreResult(len(seen), runs, stopped, bound_reached)

    def replay(self, choices) -> RunResult:
        """Re-execute a recorded schedule exactly; raises
        ReplayDivergence if the scenario no longer takes it."""
        return self._run(_DfsPlan(choices))

    @staticmethod
    def render(trace) -> str:
        """Stable text rendering of a trace — the byte-exactness unit."""
        lines = []
        for ts in trace:
            mark = ""
            if ts.switched_from:
                mark = (f"   << preempts {ts.switched_from}" if ts.preemption
                        else f"   << takes over from {ts.switched_from}")
            lines.append(f"step {ts.step:4d}  {ts.thread:8s} "
                         f"{ts.op.render()}{mark}")
        return "\n".join(lines)

    # -- one schedule ---------------------------------------------------------

    def _run(self, plan) -> RunResult:
        threads, check = self._build_scenario()
        self._lts = []
        self._by_ident = {}
        self._active = None
        self._freerun = False
        for i, entry in enumerate(threads):
            name, fn = entry if isinstance(entry, tuple) else (f"T{i}", entry)
            self._lts.append(_LThread(i, name, fn))

        prev_hook = invariants.set_yield_hook(self._on_yield)
        owners: dict[int, list] = {}   # id(lock) -> [lthread, depth]
        choices: list[int] = []
        trace: list[TraceStep] = []
        nodes: list = []
        error: Optional[BaseException] = None
        deadlock = False
        try:
            for lt in self._lts:
                lt.thread = threading.Thread(
                    target=self._thread_main, args=(lt,),
                    name=f"interleave-{lt.name}", daemon=True)
                lt.thread.start()
            prev_choice: Optional[int] = None
            step = 0
            while True:
                self._wait_quiescent()
                live = [lt for lt in self._lts if lt.state != _DONE]
                error = next((lt.error for lt in self._lts
                              if lt.error is not None), None)
                if error is not None or not live:
                    break
                enabled = {}
                for lt in live:
                    op = lt.pending
                    if op.kind == "lock.acquire":
                        own = owners.get(id(op.token))
                        if own is not None and own[0] is not lt:
                            continue
                    elif op.kind == "test.wait":
                        if not op.token():
                            continue
                    enabled[lt.idx] = op
                if not enabled:
                    deadlock = True
                    break
                default = prev_choice if prev_choice in enabled \
                    else min(enabled)
                chosen = plan.choose(step, enabled, default)
                op = enabled[chosen]
                if op.kind == "lock.acquire":
                    own = owners.setdefault(id(op.token), [None, 0])
                    own[0] = self._lts[chosen]
                    own[1] += 1
                elif op.kind == "lock.release":
                    own = owners.get(id(op.token))
                    if own is not None:
                        own[1] -= 1
                        if own[1] <= 0:
                            del owners[id(op.token)]
                switched = prev_choice is not None and prev_choice != chosen
                trace.append(TraceStep(
                    step=step, thread=self._lts[chosen].name, op=op,
                    switched_from=(self._lts[prev_choice].name
                                   if switched else ""),
                    preemption=switched and prev_choice in enabled))
                nodes.append((tuple(sorted(enabled)), dict(enabled), chosen))
                choices.append(chosen)
                self._grant(self._lts[chosen])
                prev_choice = chosen
                step += 1
        finally:
            self._abandon()
            invariants.set_yield_hook(prev_hook)
        if error is None and not deadlock:
            try:
                check()
            except BaseException as e:   # noqa: BLE001 — any check failure
                error = e
        return RunResult(tuple(choices), tuple(trace), nodes, error,
                         deadlock=deadlock)

    def _build_scenario(self):
        threads, check = self.scenario()
        if not threads:
            raise ValueError("scenario returned no threads")
        return threads, check

    # -- cooperative scheduling ----------------------------------------------

    def _thread_main(self, lt: _LThread) -> None:
        with self._cv:
            self._by_ident[threading.get_ident()] = lt
        try:
            self._park(lt, Op("thread.start", lt.name))
            lt.fn()
        except _StopRun:
            pass
        except BaseException as e:   # noqa: BLE001 — surfaced as failure
            if not self._freerun:
                lt.error = e
        finally:
            with self._cv:
                lt.state = _DONE
                if self._active is lt:
                    self._active = None
                self._cv.notify_all()

    def _on_yield(self, kind, detail, token) -> None:
        lt = self._by_ident.get(threading.get_ident())
        if lt is None:
            return   # main thread (setup/check) or a non-modelled thread
        self._park(lt, Op(kind, _fmt(detail), token))

    def _park(self, lt: _LThread, op: Op) -> None:
        with self._cv:
            if self._freerun:
                return
            lt.pending = op
            lt.state = _WAITING
            if self._active is lt:
                self._active = None
            self._cv.notify_all()
            while self._active is not lt:
                if self._freerun:
                    raise _StopRun
                self._cv.wait()
            lt.state = _RUNNING

    def _grant(self, lt: _LThread) -> None:
        with self._cv:
            self._active = lt
            self._cv.notify_all()

    def _wait_quiescent(self) -> None:
        deadline = time.monotonic() + self.WEDGE_TIMEOUT_S
        with self._cv:
            while True:
                if self._active is None and all(
                        t.state in (_WAITING, _DONE) for t in self._lts):
                    return
                if not self._cv.wait(timeout=1.0) and \
                        time.monotonic() > deadline:
                    self._freerun = True
                    self._cv.notify_all()
                    raise RuntimeError(
                        "interleave explorer wedged: a granted thread "
                        "blocked outside the modelled yield points")

    def _abandon(self) -> None:
        with self._cv:
            self._freerun = True
            self._cv.notify_all()
        for lt in self._lts:
            if lt.thread is not None:
                lt.thread.join(timeout=5.0)

    # -- DFS with sleep sets + preemption bound -------------------------------

    def _dfs(self, bound: int, deadline: float, seen: set) -> dict:
        path: list[_PathEntry] = []
        runs = 0
        first = True
        while True:
            if not first:
                # backtrack to the deepest entry with a viable sibling
                nxt = None
                while path:
                    e = path[-1]
                    base = path[-2].chosen if len(path) > 1 else None
                    before = path[-2].preempts if len(path) > 1 else 0
                    alts = []
                    for t in e.enabled:
                        if t in e.done or t in e.sleep:
                            continue
                        pre = before + (1 if (base in e.enabled and
                                              t != base) else 0)
                        if pre <= bound:
                            alts.append((t, pre))
                    if alts:
                        nxt = min(alts)
                        break
                    path.pop()
                if nxt is None:
                    return {"runs": runs, "failure": None,
                            "stopped": "exhausted"}
                e = path[-1]
                e.chosen, e.preempts = nxt
                e.done.add(nxt[0])
            first = False
            if time.monotonic() > deadline:
                return {"runs": runs, "failure": None, "stopped": "budget"}
            if len(seen) >= self.max_schedules:
                return {"runs": runs, "failure": None,
                        "stopped": "max_schedules"}
            run = self._run(_DfsPlan([e.chosen for e in path]))
            runs += 1
            seen.add(run.choices)
            if run.failed:
                return {"runs": runs, "failure": run, "stopped": "failure"}
            # extend the path with the default-continuation suffix
            for enabled, ops, chosen in run.nodes[len(path):]:
                if path:
                    parent = path[-1]
                    base = parent.chosen
                    before = parent.preempts
                    # a sibling explored (or slept) at the parent stays
                    # asleep here only while the executed step is
                    # independent of its pending one — a dependent step
                    # wakes it (its orderings are no longer covered)
                    ex_op = parent.ops[base]
                    slept = frozenset(
                        u for u in (parent.sleep | (parent.done - {base}))
                        if u in parent.ops and
                        _independent(parent.ops[u], ex_op))
                else:
                    base, before, slept = None, 0, frozenset()
                pre = before + (1 if (base in enabled and chosen != base)
                                else 0)
                path.append(_PathEntry(enabled, ops, chosen, slept, pre))

    # -- shrinking ------------------------------------------------------------

    def _directives_of(self, run: RunResult) -> dict:
        """Canonical sparse form: the steps where the schedule deviates
        from the default run-until-blocked continuation."""
        directives = {}
        prev = None
        for i, (enabled, _ops, chosen) in enumerate(run.nodes):
            default = prev if prev in enabled else min(enabled)
            if chosen != default:
                directives[i] = chosen
            prev = chosen
        return directives

    def _preemption_count(self, run: RunResult) -> int:
        return sum(1 for ts in run.trace if ts.preemption)

    def _shrink(self, failing: RunResult) -> FailingSchedule:
        current = self._directives_of(failing)
        best = failing
        shrunk = True
        while shrunk and current:
            shrunk = False
            for step in sorted(current):
                cand = dict(current)
                del cand[step]
                run = self._run(_DirectivePlan(cand))
                if run.failed:
                    best = run
                    current = self._directives_of(run)
                    shrunk = True
                    break
        msg = ("deadlock: no schedulable thread"
               if best.deadlock else
               f"{type(best.error).__name__}: {best.error}")
        return FailingSchedule(
            message=msg,
            choices=best.choices,
            directives=current,
            preemptions=self._preemption_count(best),
            trace=best.trace,
            narrative=(f"{msg}\nminimal preemption directives: "
                       f"{sorted(current.items())}\n"
                       + self.render(best.trace)),
        )
