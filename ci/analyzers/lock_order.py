"""Lock discipline: the static acquisition-order graph must be acyclic.

Builds a directed graph over `with <lock>` nesting in the concurrency
core (kube/store.py, kube/cluster.py, kube/cache.py, kube/controller.py):
an edge A -> B means "B was acquired while A was held".  Nesting is
tracked through:

  - literal `with self._x_lock:` statements (a with-item whose last
    attribute matches lock/mutex naming);
  - `ExitStack.enter_context(<lock>)` calls (the store's sorted
    multi-shard acquisition), held for the rest of the enclosing block;
  - one-level-and-transitive call propagation: a call to a same-module
    function/method (`self.f()`, bare `f()`) or to the known cross-module
    receivers (`self.api.*` -> ApiServer, `self.cache.*` ->
    InformerCache) under a held lock contributes every lock the callee
    (transitively) acquires.

Lock identity is (module, class, attr) — `self._lock` in Manager and in
BucketRateLimiter are distinct nodes; non-self receivers (`shard.lock`)
fold to (module, '', attr), which conservatively merges all instances of
a shard-style lock into one node.  A cycle — including the self-edge
from nested same-class acquisition — fails unless allowlisted with a
reason (the runtime LockTracker then enforces the documented rank
order).  Dynamic dispatch (watch callbacks) is out of static reach; the
INVARIANTS_STRICT LockTracker covers it at runtime.
"""

from __future__ import annotations

import ast

from . import Module, Violation, dotted

CHECK = "locks"

#: modules the lock graph is built over (repo-relative posix paths)
LOCK_MODULES = (
    "kubeflow_tpu/kube/store.py",
    "kubeflow_tpu/kube/cluster.py",
    "kubeflow_tpu/kube/cache.py",
    "kubeflow_tpu/kube/controller.py",
)

#: cross-module receiver resolution: attribute name -> class the object
#: is an instance of (kept in sync with the constructor wiring)
_RECEIVER_CLASSES = {
    "api": "ApiServer",
    "cache": "InformerCache",
    "cluster": "FakeCluster",
}

_LOCKISH = ("lock", "mutex")


def _short(rel: str) -> str:
    return rel.rsplit("/", 1)[-1].removesuffix(".py")


def _is_lock_expr(expr) -> bool:
    if isinstance(expr, ast.Attribute):
        last = expr.attr.lower()
    elif isinstance(expr, ast.Name):
        last = expr.id.lower()
    else:
        return False
    return any(p in last for p in _LOCKISH)


class _ModuleGraph:
    """Per-project lock graph builder."""

    def __init__(self, modules: dict[str, Module]):
        self.modules = modules
        # (module, cls, fn) -> list of (held_locks_tuple, lock_node)
        self.acquisitions: dict[tuple, list] = {}
        # (module, cls, fn) -> list of (held_locks_tuple, callee_key)
        self.calls: dict[tuple, list] = {}
        # function key -> set of lock nodes acquired directly
        self.direct: dict[tuple, set] = {}
        self.classes: dict[str, set[tuple]] = {}  # ClassName -> {fn keys}
        self.sites: dict[tuple, tuple] = {}       # edge -> (rel, line)

    def _lock_node(self, expr, module: str, cls: str) -> tuple:
        path = dotted(expr)
        if path.startswith("self."):
            return (module, cls, path.split(".")[-1])
        return (module, "", path.split(".")[-1] if path else "<dynamic>")

    def _callee_key(self, call, module: str, cls: str):
        func = call.func
        if isinstance(func, ast.Name):
            return (module, "", func.id)
        if not isinstance(func, ast.Attribute):
            return None
        recv = dotted(func.value)
        if recv == "self":
            return (module, cls, func.attr)
        last = recv.split(".")[-1] if recv else ""
        target_cls = _RECEIVER_CLASSES.get(last)
        if target_cls is not None:
            owner = self._class_module(target_cls)
            if owner is not None:
                return (owner, target_cls, func.attr)
        return None

    def _class_module(self, cls: str):
        for rel, mod in self.modules.items():
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef) and node.name == cls:
                    return _short(rel)
        return None

    # -- per-function traversal ----------------------------------------------
    def scan_function(self, mod: Module, cls: str, fn: ast.FunctionDef):
        module = _short(mod.rel)
        key = (module, cls, fn.name)
        self.acquisitions.setdefault(key, [])
        self.calls.setdefault(key, [])
        self.direct.setdefault(key, set())
        self.classes.setdefault(cls, set()).add(key)
        self._visit_body(mod, key, fn.body, ())

    def _record_acquire(self, mod, key, held, node_expr, lineno,
                        in_loop=False):
        module, cls, _ = key
        lock = self._lock_node(node_expr, module, cls)
        self.acquisitions[key].append((held, lock, mod.rel, lineno))
        if in_loop:
            # an acquisition inside a loop re-acquires the same lock
            # class on the next pass while instances from earlier passes
            # are still held — a self-edge the order contract must cover
            self.acquisitions[key].append(((lock,), lock, mod.rel, lineno))
        self.direct[key].add(lock)
        return held + (lock,)

    def _visit_body(self, mod, key, stmts, held, in_loop=False):
        for stmt in stmts:
            held = self._visit_stmt(mod, key, stmt, held, in_loop)

    def _visit_stmt(self, mod, key, stmt, held, in_loop=False):
        """Returns the held set for SUBSEQUENT statements in the same
        block (grows on enter_context acquisitions)."""
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                if _is_lock_expr(item.context_expr):
                    inner = self._record_acquire(
                        mod, key, inner, item.context_expr, stmt.lineno)
                else:
                    self._scan_calls(mod, key, item.context_expr, inner)
            self._visit_body(mod, key, stmt.body, inner, in_loop)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return held  # nested scope: scanned separately
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call) \
                and isinstance(stmt.value.func, ast.Attribute) \
                and stmt.value.func.attr == "enter_context" \
                and stmt.value.args \
                and _is_lock_expr(stmt.value.args[0]):
            return self._record_acquire(
                mod, key, held, stmt.value.args[0], stmt.lineno,
                in_loop=in_loop)
        # compound statements: recurse into bodies with the current held
        loops = isinstance(stmt, (ast.For, ast.While))
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, attr, None)
            if sub:
                self._visit_body(mod, key, sub, held, in_loop or loops)
        for h in getattr(stmt, "handlers", ()) or ():
            self._visit_body(mod, key, h.body, held, in_loop)
        # expressions hanging off this statement: record calls under held
        for attr in ("value", "test", "iter", "targets"):
            sub = getattr(stmt, attr, None)
            if sub is None:
                continue
            for node in sub if isinstance(sub, list) else [sub]:
                if isinstance(node, ast.AST):
                    self._scan_calls(mod, key, node, held)
        return held

    def _scan_calls(self, mod, key, expr, held):
        module, cls, _ = key
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                callee = self._callee_key(node, module, cls)
                if callee is not None:
                    self.calls[key].append((held, callee))

    # -- propagation + cycle check -------------------------------------------
    def edges(self) -> tuple[dict, dict]:
        # transitive lock footprint per function
        footprint = {k: set(v) for k, v in self.direct.items()}
        changed = True
        while changed:
            changed = False
            for key, calls in self.calls.items():
                for _, callee in calls:
                    extra = footprint.get(callee)
                    if extra and not extra <= footprint[key]:
                        footprint[key] |= extra
                        changed = True
        graph: dict[tuple, set[tuple]] = {}
        sites: dict[tuple, tuple] = {}

        def add_edge(a, b, rel, line):
            if a == b:
                pass  # self-edges recorded too (multi-instance nesting)
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
            sites.setdefault((a, b), (rel, line))
        for key, acqs in self.acquisitions.items():
            for held, lock, rel, line in acqs:
                for h in held:
                    add_edge(h, lock, rel, line)
        for key, calls in self.calls.items():
            for held, callee in calls:
                if not held:
                    continue
                for lock in footprint.get(callee, ()):
                    for h in held:
                        add_edge(h, lock, "", 0)
        return graph, sites


def _render(node: tuple) -> str:
    module, cls, attr = node
    return f"{module}.{cls or '<instance>'}.{attr}"


def _find_cycles(graph: dict) -> list[list]:
    """Every elementary cycle is overkill; report one cycle per SCC with
    size > 1, plus self-edges."""
    cycles = []
    for a, succs in sorted(graph.items()):
        if a in succs:
            cycles.append([a, a])
    index = {}
    low = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]

    def strongconnect(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    cycles.extend(sccs)
    return cycles


def analyze_project(modules) -> list[Violation]:
    by_rel = {m.rel: m for m in modules if m.rel in LOCK_MODULES}
    if not by_rel:
        return []
    g = _ModuleGraph(by_rel)
    for rel, mod in sorted(by_rel.items()):
        def scan(node, cls):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    g.scan_function(mod, cls, child)
                    scan(child, cls)
                elif isinstance(child, ast.ClassDef):
                    scan(child, child.name)
                else:
                    scan(child, cls)
        scan(mod.tree, "")
    graph, sites = g.edges()
    out = []
    for cycle in _find_cycles(graph):
        if len(cycle) > 2 or cycle[0] != cycle[-1]:
            cycle = cycle + [cycle[0]]  # close the loop for readability
        desc = "->".join(_render(n) for n in cycle)
        rel, line = "", 0
        for a, b in zip(cycle, cycle[1:]):
            if (a, b) in sites and sites[(a, b)][0]:
                rel, line = sites[(a, b)]
                break
        out.append(Violation(
            CHECK, rel, line, desc,
            f"lock acquisition-order cycle: {desc} — a consistent global "
            "order is required (see ARCHITECTURE.md lock ordering)"))
    return out
