"""In-notebook performance metrics: MFU, throughput, HBM.

The north-star metrics from BASELINE.md are measured here (the control-plane
Prometheus metrics live in core/metrics.py; this is the data-plane side,
exported through the same `utils.metrics.Registry` so both planes share one
exposition format, HELP/TYPE metadata, the ci/lint.py naming rule, and the
ci/metrics_drift_check.sh family inventory).

`jax` is imported lazily (hbm_usage_bytes) so the family inventory and the
StepTimer's timing logic are usable from control-plane tooling — the drift
check registers the families without touching an accelerator, and tests
drive the timer off an injected monotonic clock instead of
time.perf_counter.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Callable, Optional

from ..utils.metrics import Histogram, Registry

if TYPE_CHECKING:  # pragma: no cover - typing only
    from ..models.configs import TransformerConfig


def hbm_usage_bytes() -> dict[str, int]:
    """Per-device HBM in use (0s on backends without memory_stats)."""
    import jax

    usage = {}
    for dev in jax.local_devices():
        stats = getattr(dev, "memory_stats", lambda: None)() or {}
        usage[str(dev)] = int(stats.get("bytes_in_use", 0))
    return usage


# train steps span ~ms (tiny models, microbatches) to minutes (large-model
# accumulation); DefaultBuckets tops out at 10s, too short for the tail
STEP_TIME_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def register_step_metrics(registry: Registry) -> dict:
    """Register the data-plane training families on `registry` and return
    them by short name.  Idempotent (the Registry returns the existing
    family on identical re-registration); ci/metrics_drift_check.sh calls
    this to fold the data-plane inventory into the golden list."""
    return {
        "step_duration": registry.histogram(
            "notebook_training_step_duration_seconds",
            "Distribution of synced train-step wall time",
            buckets=STEP_TIME_BUCKETS),
        "tokens_per_second": registry.gauge(
            "notebook_training_tokens_per_second",
            "Rolling training throughput over the step window"),
        "mfu_ratio": registry.gauge(
            "notebook_training_mfu_ratio",
            "Rolling model FLOPs utilization (0-1) over the step window"),
        "hbm_bytes_in_use": registry.gauge(
            "notebook_training_hbm_bytes_in_use",
            "HBM bytes in use across local devices"),
    }


@dataclass
class StepTimer:
    """Rolling train-step telemetry; call `observe()` once per synced step.

    Timing reads `time_fn` — a monotonic-seconds callable, perf_counter by
    default — so tests inject a fake (FakeClock.now works) and assert exact
    step times and histogram buckets.  Every family lives in `registry`
    (own one by default; pass a shared Registry to co-expose with other
    metrics): step time is a real Histogram, and the derived gauges
    (throughput, MFU, HBM) recompute lazily at scrape time."""

    config: "TransformerConfig"
    batch: int
    seq_len: int
    num_chips: int
    accelerator: str = "v5e"
    window: int = 20
    registry: Optional[Registry] = None
    time_fn: Callable[[], float] = time.perf_counter
    _times: list[float] = field(default_factory=list)
    _last: Optional[float] = None

    def __post_init__(self) -> None:
        if self.registry is None:
            self.registry = Registry()
        m = register_step_metrics(self.registry)
        self._step_hist: Histogram = m["step_duration"]
        # derived values recompute at collect()/render() time, so a scrape
        # is always current without observe() having to push gauges
        m["tokens_per_second"].set_function(lambda: self.tokens_per_s)
        m["mfu_ratio"].set_function(lambda: self.mfu)
        m["hbm_bytes_in_use"].set_function(
            lambda: float(sum(hbm_usage_bytes().values())))

    def observe(self) -> None:
        now = self.time_fn()
        if self._last is not None:
            dt = now - self._last
            self._times.append(dt)
            if len(self._times) > self.window:
                self._times.pop(0)
            self._step_hist.observe(dt)
        self._last = now

    @property
    def step_time_s(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    @property
    def tokens_per_s(self) -> float:
        st = self.step_time_s
        return self.batch * self.seq_len / st if st else 0.0

    @property
    def mfu(self) -> float:
        from ..models.train import mfu as mfu_fn

        return mfu_fn(
            self.tokens_per_s,
            self.config,
            self.seq_len,
            self.num_chips,
            self.accelerator,
        )

    def report(self) -> dict:
        return {
            "step_time_s": self.step_time_s,
            "tokens_per_s": self.tokens_per_s,
            "mfu": self.mfu,
            "hbm_bytes_in_use": sum(hbm_usage_bytes().values()),
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition the workbench image can serve on /metrics
        — full HELP/TYPE metadata from the shared Registry."""
        return self.registry.render()
