#!/usr/bin/env bash
# Build the controller image, load it into a kind cluster, deploy the
# FULL webhook-enabled profile (admission + conversion with minted
# self-signed certs — the reference integration lane's shape,
# odh_notebook_controller_integration_test.yaml:62-90,196-218), plus the
# fake TPU device plugin so nodes advertise google.com/tpu, and wait for
# the manager.
set -euo pipefail
cd "$(dirname "$0")/../.."
CLUSTER="${CLUSTER:-kubeflow-tpu}"
IMAGE="${IMAGE:-kubeflow-tpu-controller:kind}"
NAMESPACE="${NAMESPACE:-kubeflow-tpu-system}"
PROFILE="${PROFILE:-kubeflow}"
FAKE_TPU="${FAKE_TPU:-1}"
CHIPS="${CHIPS:-8}"

docker build -t "$IMAGE" .
kind load docker-image "$IMAGE" --name "$CLUSTER"

kubectl create namespace "$NAMESPACE" --dry-run=client -o yaml | kubectl apply -f -
# webhook-enabled profile: CRD with conversion clause, admission webhook
# configs, serving Service — caBundle patched with a freshly minted CA and
# the serving pair delivered as a tls Secret (render_with_certs.py)
python testing/kind/render_with_certs.py \
  --namespace "$NAMESPACE" --image "$IMAGE" --profile "$PROFILE" \
  | sed "s/\$(NAMESPACE)/${NAMESPACE}/g" \
  | kubectl apply -n "$NAMESPACE" -f -

if [[ "$FAKE_TPU" == "1" ]]; then
  # real kubelet device plugin: google.com/tpu allocatable on every node
  sed -e "s|image: kubeflow-tpu-controller:kind|image: ${IMAGE}|" \
      -e "s|--chips=8|--chips=${CHIPS}|" \
    testing/kind/fake_tpu_daemonset.yaml | kubectl apply -f -
  kubectl -n kube-system rollout status daemonset/fake-tpu-device-plugin \
    --timeout=120s
  # GKE topology labels (the device plugin provides capacity; the labels
  # come from the node labeler, as on GKE where the provisioner sets them).
  # topology 2x4 = one v5e host of 8 chips — matches the conformance
  # notebook's spec.tpu and the --chips default
  for node in $(kubectl get nodes -o name); do
    kubectl label --overwrite "$node" \
      cloud.google.com/gke-tpu-accelerator=tpu-v5-lite-podslice \
      cloud.google.com/gke-tpu-topology=2x4
  done
  # wait until EVERY node's kubelet reports the extended resource
  node_count=$(kubectl get nodes --no-headers | wc -l)
  ok=0
  for i in $(seq 1 24); do
    ok=$(kubectl get nodes -o jsonpath='{range .items[*]}{.status.allocatable.google\.com/tpu}{"\n"}{end}' \
      | grep -cvE '^(0)?$' || true)
    [[ "$ok" == "$node_count" ]] && break
    sleep 5
  done
  if [[ "$ok" != "$node_count" ]]; then
    echo "fake-tpu: only $ok/$node_count nodes advertise google.com/tpu" >&2
    kubectl -n kube-system logs daemonset/fake-tpu-device-plugin --tail=50 >&2 || true
    exit 1
  fi
  echo "fake-tpu: $ok/$node_count nodes advertise google.com/tpu=$CHIPS"
fi

kubectl -n "$NAMESPACE" rollout status deployment/notebook-controller-deployment \
  --timeout=180s
echo "deploy: OK"
