"""Golden apiserver-semantics fixtures replayed against the wire server.

The reference grounds store semantics in a real apiserver via envtest
(suite_test.go:50-110); here the same grounding comes from declarative
transcripts of real kube-apiserver behavior (conformance/apiserver_fixtures/)
replayed over real sockets — the store is no longer its own oracle: a
semantics bug surfaces as a fixture diff.  The same transcripts run against
a genuine cluster via `python -m kubeflow_tpu.kube.fixtures --server ...`.
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.kube import ApiServer
from kubeflow_tpu.kube.fixtures import FixtureRunner, dig, load_fixtures, substitute
from kubeflow_tpu.kube.wire import KubeApiWireServer

FIXTURES = load_fixtures()


@pytest.fixture()
def server():
    api = ApiServer()
    srv = KubeApiWireServer(api).start()
    yield srv
    srv.stop()


@pytest.mark.parametrize("fixture", FIXTURES,
                         ids=[f["name"] for f in FIXTURES])
def test_fixture(server, fixture):
    FixtureRunner(server.url).run(fixture)


class TestEngine:
    def test_dig_and_substitute(self):
        obj = {"items": [{"metadata": {"name": "a"}}]}
        assert dig(obj, "items.0.metadata.name") == "a"
        with pytest.raises(KeyError):
            dig(obj, "items.1.metadata.name")
        assert substitute("${x}", {"x": 42}) == 42  # type-preserving
        assert substitute("pre-${x}-post", {"x": 42}) == "pre-42-post"
        assert substitute({"k": ["${x}"]}, {"x": 1}) == {"k": [1]}

    def test_fixture_failure_is_loud(self, server):
        from kubeflow_tpu.kube.fixtures import FixtureFailure

        bad = {"name": "bad", "steps": [
            {"op": "GET", "path": "/api/v1/namespaces/default/configmaps/nope",
             "expect": {"status": 200}}]}
        with pytest.raises(FixtureFailure, match="status 404 != 200"):
            FixtureRunner(server.url).run(bad)
