"""Parallel workqueue workers (kube/controller.py): per-key serialization,
fairness across registrations, the parked-dirty re-queue, rate-limiter
thread-safety under the pool, the status-only event predicate, and the new
workqueue gauges."""

import random
import threading

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    BucketRateLimiter,
    ItemExponentialBackoff,
    KubeObject,
    Manager,
    ObjectMeta,
    Request,
    Result,
    is_status_only_update,
)
from kubeflow_tpu.kube.store import EventType, WatchEvent
from kubeflow_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _strict_invariants(monkeypatch):
    """The threaded suite runs with the runtime sanitizer on: committed
    snapshots deep-frozen (any escaped write raises at the mutation
    site) and every store/manager lock order-tracked
    (utils.invariants, INVARIANTS_STRICT=1)."""
    monkeypatch.setenv("INVARIANTS_STRICT", "1")


def mk(kind, ns, name, labels=None):
    return KubeObject("v1", kind,
                      ObjectMeta(name=name, namespace=ns,
                                 labels=dict(labels or {})),
                      body={"spec": {}})


class TrackingReconciler:
    """Counts per-key concurrency; fails the invariant if two workers ever
    reconcile one key at the same time."""

    def __init__(self, work=None):
        self.lock = threading.Lock()
        self.in_flight = {}
        self.max_concurrency = {}
        self.counts = {}
        self.work = work

    def reconcile(self, req: Request) -> Result:
        key = (req.namespace, req.name)
        with self.lock:
            self.in_flight[key] = self.in_flight.get(key, 0) + 1
            self.max_concurrency[key] = max(
                self.max_concurrency.get(key, 0), self.in_flight[key])
            self.counts[key] = self.counts.get(key, 0) + 1
        try:
            if self.work is not None:
                self.work(req)
        finally:
            with self.lock:
                self.in_flight[key] -= 1
        return Result()


class TestWorkerPool:
    def test_no_duplicate_in_flight_keys_under_pool(self):
        """Seeded stress: many keys, enqueues racing the worker pool, real
        sleeps to force overlap windows — per-key concurrency must never
        exceed 1, and every enqueued key must get reconciled."""
        import time

        api = ApiServer()
        mgr = Manager(api, clock=FakeClock(), workers=8)
        rng = random.Random(99)
        rec = TrackingReconciler(
            work=lambda req: time.sleep(rng.random() * 0.002))
        mgr.register("stress", rec, for_kind="Widget")
        keys = [f"w{i}" for i in range(12)]
        for _ in range(40):
            for name in rng.sample(keys, 5):
                mgr.enqueue("stress", Request("ns", name))
            mgr.run_until_idle()
        assert max(rec.max_concurrency.values()) == 1
        assert set(rec.counts) == {("ns", k) for k in keys}
        assert not mgr.flight_recorder.overlapping_attempts()

    def test_event_during_processing_parks_and_requeues(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock(), workers=1)
        seen = []

        class Reconciler:
            def reconcile(self, req):
                seen.append(len(seen))
                if len(seen) == 1:
                    # an event for the SAME key lands mid-reconcile: it
                    # must park (not double-dispatch) and re-run after
                    mgr.enqueue("park", req)
                return Result()

        mgr.register("park", Reconciler(), for_kind="Widget")
        mgr.enqueue("park", Request("ns", "w"))
        n = mgr.run_until_idle()
        assert n == 2 and len(seen) == 2

    def test_fairness_round_robin_across_controllers(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock(), workers=1)
        order = []

        class Rec:
            def __init__(self, name):
                self.name = name

            def reconcile(self, req):
                order.append(self.name)
                return Result()

        mgr.register("hot", Rec("hot"), for_kind="A")
        mgr.register("cold", Rec("cold"), for_kind="B")
        for i in range(10):
            mgr.enqueue("hot", Request("ns", f"a{i}"))
        mgr.enqueue("cold", Request("ns", "b0"))
        mgr.run_until_idle()
        # the single cold item must not wait behind the whole hot backlog
        assert "cold" in order[:3], order

    def test_one_and_eight_workers_converge_identically(self):
        """Same fleet, same seed: the worker count must not change the
        reconcile outcome (level-triggered idempotence)."""
        def run(workers):
            api = ApiServer()
            mgr = Manager(api, clock=FakeClock(), workers=workers)
            rec = TrackingReconciler()
            mgr.register("c", rec, for_kind="Widget")
            for i in range(20):
                api.create(mk("Widget", "ns", f"w{i:02d}"))
            mgr.run_until_idle()
            return set(rec.counts)

        assert run(1) == run(8)

    def test_workqueue_gauges_exposed(self):
        from kubeflow_tpu.core.metrics import NotebookMetrics

        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        metrics = NotebookMetrics(api, manager=mgr)

        class Rec:
            def reconcile(self, req):
                return Result()

        mgr.register("c", Rec(), for_kind="Widget")
        mgr.enqueue("c", Request("ns", "w"))
        text = metrics.scrape()
        assert 'workqueue_depth{controller="c"} 1' in text
        assert "workqueue_longest_running_processor_seconds" in text
        stats = mgr.queue_stats()
        assert stats["depth"] == {"c": 1}
        assert stats["longest_running_s"] == {}

    def test_longest_running_tracks_inflight_age(self):
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock, workers=1)
        observed = {}

        class Rec:
            def reconcile(self, req):
                clock.advance(2.5)
                observed.update(mgr.queue_stats()["longest_running_s"])
                return Result()

        mgr.register("c", Rec(), for_kind="Widget")
        mgr.enqueue("c", Request("ns", "w"))
        mgr.run_until_idle()
        assert observed == {"c": 2.5}
        assert mgr.queue_stats()["longest_running_s"] == {}


class TestEnqueueAllThroughCache:
    def test_enqueue_all_issues_no_list_and_dedupes(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        rec = TrackingReconciler()
        mgr.register("c", rec, for_kind="Widget")
        for i in range(5):
            api.create(mk("Widget", "ns", f"w{i}"))
        mgr.run_until_idle()
        api.clear_verb_counts()
        mgr.enqueue_all()
        mgr.enqueue_all()  # second resync dedupes against queued items
        assert api.verb_counts() == {}  # keys came from the cache
        before = dict(rec.counts)
        mgr.run_until_idle()
        assert all(rec.counts[k] == before[k] + 1 for k in before)


class TestStatusOnlyPredicate:
    def _pair(self, mutate):
        old = mk("Notebook", "ns", "nb", labels={"a": "1"})
        old.body["status"] = {"readyReplicas": 0}
        old.metadata.resource_version = 5
        new = old.deepcopy()
        new.metadata.resource_version = 6
        mutate(new)
        return WatchEvent(EventType.MODIFIED, new, prev=old)

    def test_status_only_update_detected(self):
        ev = self._pair(lambda o: o.body.__setitem__(
            "status", {"readyReplicas": 1}))
        assert is_status_only_update(ev)

    def test_spec_or_metadata_changes_pass(self):
        ev = self._pair(lambda o: o.spec.__setitem__("x", 1))
        assert not is_status_only_update(ev)
        ev = self._pair(lambda o: o.metadata.annotations.__setitem__(
            "stop", "now"))
        assert not is_status_only_update(ev)

    def test_added_and_prevless_events_pass(self):
        obj = mk("Notebook", "ns", "nb")
        assert not is_status_only_update(WatchEvent(EventType.ADDED, obj))
        assert not is_status_only_update(
            WatchEvent(EventType.MODIFIED, obj, prev=None))

    def test_manager_drops_self_inflicted_status_update(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())
        rec = TrackingReconciler()
        from kubeflow_tpu.kube import suppress_status_only

        mgr.register("c", rec, for_kind="Widget",
                     for_predicate=suppress_status_only)
        w = api.create(mk("Widget", "ns", "w"))
        mgr.run_until_idle()
        n = rec.counts[("ns", "w")]
        w = api.get("Widget", "ns", "w")
        w.body["status"] = {"phase": "Done"}
        api.update_status(w)
        mgr.run_until_idle()
        assert rec.counts[("ns", "w")] == n  # suppressed
        live = api.get("Widget", "ns", "w")
        live.metadata.annotations["touch"] = "1"
        api.update(live)
        mgr.run_until_idle()
        assert rec.counts[("ns", "w")] == n + 1  # real change passes


class TestRateLimiterThreadSafety:
    def test_item_backoff_no_corruption_under_threads(self):
        """Seeded multi-threaded stress: concurrent when()/forget() over a
        shared item set must keep per-item failure counts exact — every
        item hammered by exactly K when() calls and no forget() reads K."""
        rl = ItemExponentialBackoff(base_s=0.001, cap_s=1.0, seed=5)
        items = [f"item-{i}" for i in range(8)]
        per_thread = 200
        threads = []
        errors = []

        def worker(seed):
            rng = random.Random(seed)
            try:
                for _ in range(per_thread):
                    rl.when(items[rng.randrange(len(items))])
            except Exception as err:  # pragma: no cover
                errors.append(err)

        for t in range(8):
            threads.append(threading.Thread(target=worker, args=(t,)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        total = sum(rl.num_failures(i) for i in items)
        assert total == 8 * per_thread  # no lost increments
        for i in items:
            rl.forget(i)
            assert rl.num_failures(i) == 0

    def test_bucket_limiter_never_overfills_under_threads(self):
        clock = FakeClock()
        rl = BucketRateLimiter(qps=100.0, burst=10, clock=clock)
        delays = []
        lock = threading.Lock()

        def worker():
            for _ in range(50):
                d = rl.when("x")
                with lock:
                    delays.append(d)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 400 reservations at burst 10 / 100 qps: the last reservation must
        # be scheduled (400 - 10) / 100 seconds out — token conservation
        # holds exactly even under thread interleaving (the clock is fake,
        # so no tokens refill mid-test)
        assert len(delays) == 400
        assert max(delays) == (400 - 10) / 100.0
        assert sorted(delays)[:10] == [0.0] * 10
