"""Mixture-of-Experts MLP with expert parallelism, TPU-first.

GShard/Switch-style dense dispatch: the router picks top-k experts per
token; tokens are packed into fixed-capacity per-expert buffers with
one-hot dispatch/combine einsums — static shapes, no gather/scatter, so
XLA tiles everything onto the MXU and inserts the dispatch/combine
all-to-alls implied by the shardings (the original GShard recipe).  The
expert-stacked parameters and the [experts, ...] token buffers carry the
logical "expert" axis, mapped to the mesh's "expert" axis by
parallel.sharding.rules_for_mesh — expert parallelism composes with
dp/fsdp/sp/tp/pp in the same jitted step.

Capacity overflow drops tokens (their combine weight is zero and the
residual stream passes them through unchanged), exactly Switch's behavior;
the load-balance auxiliary loss (Switch eq. 4: E * sum_e f_e * P_e) keeps
routing uniform so drops stay rare.

Dispatch paths (cfg.moe_dispatch): "einsum" (default) is the GShard
one-hot recipe above; "sort" routes by argsort + scatter/gather, skipping
the O(E*C*D) dispatch FLOPs entirely.  Measured on v5e (round 4,
BENCH_MOE): sort is SLOWER — 0.17-0.21 vs einsum's 0.21-0.23 MFU
single-window — TPU scatters/gathers of embed-wide rows lose to dense
MXU einsums at this expert count, which is exactly why GShard chose
one-hot dispatch on TPU.  The sort path stays as an option for regimes
where the einsum's E*C factor dominates (many experts, high capacity).

The reference has no compute plane (SURVEY.md §2.5); this extends the
in-notebook model zoo the TPU build adds.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .configs import TransformerConfig


def _dtype(name: str):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[name]


def load_balance_loss(probs: jax.Array, expert_mask: jax.Array) -> jax.Array:
    """Switch Transformers eq. 4: num_experts * sum_e(f_e * P_e), where
    f_e is the fraction of tokens whose TOP-1 choice is expert e and P_e
    the mean router probability for e.  Equals 1.0 under perfectly uniform
    routing; rises as routing collapses."""
    num_experts = probs.shape[-1]
    # fraction of tokens dispatched to each expert (top-1 one-hot)
    f = jnp.mean(expert_mask.astype(jnp.float32), axis=tuple(range(expert_mask.ndim - 1)))
    p = jnp.mean(probs.astype(jnp.float32), axis=tuple(range(probs.ndim - 1)))
    return num_experts * jnp.sum(f * p)


class _ExpertFFN(nn.Module):
    """One expert's gated MLP; vmapped over the expert axis by MoEMLP.
    Uses the transformer's dense factory so `weight_dtype="int8"` serves
    quantized experts (the vmap stacks the int8 kernels on the expert
    axis exactly like the dense kernels)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):  # [tokens..., D]
        from .transformer import _dense

        cfg = self.cfg
        dtype, pdtype = _dtype(cfg.dtype), _dtype(cfg.param_dtype)
        mlp_dim = cfg.moe_mlp_dim or cfg.mlp_dim

        def dense(features, axes, name):
            return _dense(features, axes, name, dtype, pdtype,
                          weight_dtype=cfg.weight_dtype)

        gate = dense(mlp_dim, ("embed", "mlp"), "gate")(x)
        up = dense(mlp_dim, ("embed", "mlp"), "up")(x)
        return dense(cfg.embed_dim, ("mlp", "embed"), "down")(
            nn.silu(gate) * up)


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: [B, S, D] -> ([B, S, D], aux_loss)."""

    cfg: TransformerConfig
    mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, x) -> tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        num_experts, top_k = cfg.moe_experts, cfg.moe_top_k
        batch, seq, dim = x.shape

        # router in fp32 (routing decisions are precision-sensitive)
        router = nn.DenseGeneral(
            num_experts, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)),
            name="router")
        probs = jax.nn.softmax(router(x.astype(jnp.float32)), axis=-1)

        gate_vals, gate_idx = jax.lax.top_k(probs, top_k)  # [B, S, k]
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        if cfg.moe_dispatch == "sort":
            out = self._sort_dispatch(x, gate_vals, gate_idx)
            top1 = jax.nn.one_hot(gate_idx[..., 0], num_experts,
                                  dtype=jnp.float32)
            aux = load_balance_loss(probs.reshape(-1, num_experts),
                                    top1.reshape(-1, num_experts))
            return out, aux
        if cfg.moe_dispatch == "hybrid":
            out = self._hybrid_dispatch(x, gate_vals, gate_idx)
            top1 = jax.nn.one_hot(gate_idx[..., 0], num_experts,
                                  dtype=jnp.float32)
            aux = load_balance_loss(probs.reshape(-1, num_experts),
                                    top1.reshape(-1, num_experts))
            return out, aux
        if cfg.moe_dispatch != "einsum":
            raise ValueError(f"unknown moe_dispatch {cfg.moe_dispatch!r}")

        # fixed per-expert capacity over each row's tokens
        capacity = max(1, int(cfg.moe_capacity_factor * seq * top_k
                              / num_experts))
        # [B, S, k, E] one-hot choice
        choice = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32)
        # position of each (token, choice) in its expert's buffer: running
        # count over the flattened (S, k) dispatch order, per row
        flat = choice.reshape(batch, seq * top_k, num_experts)
        position = jnp.cumsum(flat, axis=1) - flat  # count before me
        within = (position < capacity).astype(jnp.float32) * flat
        position = position.reshape(batch, seq, top_k, num_experts)
        within = within.reshape(batch, seq, top_k, num_experts)

        # combine[B,S,k,E,C]: gate weight at the assigned buffer slot
        slot = jax.nn.one_hot(position.astype(jnp.int32), capacity,
                              dtype=jnp.float32)
        combine = (gate_vals[..., None, None] * within[..., None] * slot)
        combine = jnp.sum(combine, axis=2)          # [B, S, E, C]
        dispatch = (combine > 0.0).astype(x.dtype)  # [B, S, E, C]

        # dispatch: pack tokens into per-expert buffers; the "expert"-
        # sharded output is where XLA inserts the all-to-all
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", "batch", None, "embed"))

        expert_out = nn.vmap(
            _ExpertFFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(cfg, name="experts")(expert_in)          # [E, B, C, D]
        expert_out = nn.with_logical_constraint(
            expert_out, ("expert", "batch", None, "embed"))

        out = jnp.einsum("bsec,ebcd->bsd",
                         combine.astype(expert_out.dtype), expert_out)
        out = nn.with_logical_constraint(out, ("batch", "seq", "embed"))

        top1 = jax.nn.one_hot(gate_idx[..., 0], num_experts,
                              dtype=jnp.float32)
        aux = load_balance_loss(probs.reshape(-1, num_experts),
                                top1.reshape(-1, num_experts))
        return out, aux

    def _hybrid_dispatch(self, x, gate_vals, gate_idx):
        """Einsum dispatch + GATHER combine — the round-5 overhead fix.

        The GShard combine einsum "bsec,ebcd->bsd" is a disguised gather:
        each token reads exactly top_k rows of the expert buffers, yet the
        einsum contracts over all E*C slots — at BENCH_MOE scale that is
        ~26 GFLOP per layer per batch row, and its two backward transposes
        triple the bill (~20% of the whole step, BASELINE.md).  This path
        keeps the MXU-friendly dispatch einsum (scatters are what lose on
        TPU — the sort path measured it) but combines by indexing the
        chosen (expert, slot) row per (token, choice): pure HBM row reads,
        B*S*k*D bytes instead of E*C*D MACs, with the gate weights
        multiplied outside so the router still gets exact gradients.  The
        [B,S,k,E,C] slot one-hot the einsum path materializes (0.5 GiB
        fp32 at bench shape) is also gone: the dispatch one-hot contracts
        the per-choice slot one-hot [B,S,k,C] against the choice mask
        [B,S,k,E] — k is tiny, so the intermediate never exceeds
        [B,S,E,C].  Routing semantics (capacity, drops, gradients)
        are IDENTICAL to the einsum path (tests/test_moe.py pins
        allclose on outputs and router grads).

        SCOPE: single-chip / expert-unsharded meshes.  The combine gather
        indexes data-dependently across the expert-sharded leading axis
        of expert_out — under expert parallelism the SPMD partitioner
        lowers that to an all-gather of the whole [E,B,C,D] buffer, NOT
        the GShard all-to-all the combine einsum gets, so "einsum" stays
        the default and the expert-parallel path."""
        cfg = self.cfg
        num_experts, top_k = cfg.moe_experts, cfg.moe_top_k
        batch, seq, dim = x.shape
        capacity = max(1, int(cfg.moe_capacity_factor * seq * top_k
                              / num_experts))

        choice = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32)
        flat = choice.reshape(batch, seq * top_k, num_experts)
        position = jnp.cumsum(flat, axis=1) - flat
        within = (position < capacity).astype(jnp.float32) * flat
        position = position.reshape(batch, seq, top_k, num_experts)
        within = within.reshape(batch, seq, top_k, num_experts)

        # per-choice scalars: buffer slot + kept flag of the CHOSEN expert
        pos_k = jnp.sum(position * choice, axis=-1).astype(jnp.int32)
        keep_k = jnp.sum(within, axis=-1)                    # [B, S, k]

        # dispatch one-hot via the small per-choice slot one-hot — the
        # [B,S,k,E,C] monster never exists
        slot_k = jax.nn.one_hot(pos_k, capacity, dtype=x.dtype)
        dispatch = jnp.einsum("bske,bskc->bsec",
                              within.astype(x.dtype), slot_k)
        expert_in = jnp.einsum("bsec,bsd->ebcd", dispatch, x)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", "batch", None, "embed"))

        expert_out = nn.vmap(
            _ExpertFFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(cfg, name="experts")(expert_in)          # [E, B, C, D]
        expert_out = nn.with_logical_constraint(
            expert_out, ("expert", "batch", None, "embed"))

        # combine by gather: row (e, b, c) for each (b, s, k)
        b_idx = jnp.arange(batch)[:, None, None]             # [B, 1, 1]
        rows = expert_out[gate_idx, b_idx, pos_k]            # [B, S, k, D]
        weight = (gate_vals * keep_k).astype(rows.dtype)
        out = jnp.sum(rows * weight[..., None], axis=2)
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))

    def _sort_dispatch(self, x, gate_vals, gate_idx):
        """Sort-based dispatch: argsort (token, choice) pairs by expert,
        rank within each expert's segment, scatter the first `capacity`
        into the expert buffers, gather+weight back after the FFN.

        Same routing semantics as the one-hot path but WITHOUT the
        O(E*C*D) dispatch/combine einsum FLOPs — those cost ~94M
        FLOPs/token/layer at BENCH_MOE scale, ~55% of the activated
        expert FLOPs (BASELINE.md).  The data movement is two
        gathers/scatters of [N, D] rows (pure HBM traffic).  Capacity is
        GLOBAL (cf * tokens * k / E) rather than per-batch-row: the
        standard modern convention, and strictly better balanced (drops
        only when an expert is oversubscribed across the whole batch).
        """
        cfg = self.cfg
        num_experts, top_k = cfg.moe_experts, cfg.moe_top_k
        batch, seq, dim = x.shape
        tokens = batch * seq
        n = tokens * top_k
        capacity = max(1, int(cfg.moe_capacity_factor * tokens * top_k
                              / num_experts))

        xf = x.reshape(tokens, dim)
        e_flat = gate_idx.reshape(-1)            # [N], token-major
        g_flat = gate_vals.reshape(-1).astype(jnp.float32)
        tok = jnp.repeat(jnp.arange(tokens), top_k)

        order = jnp.argsort(e_flat, stable=True)  # token order kept per expert
        e_s = e_flat[order]
        tok_s = tok[order]
        g_s = g_flat[order]
        counts = jnp.bincount(e_flat, length=num_experts)
        starts = jnp.cumsum(counts) - counts
        rank = jnp.arange(n) - starts[e_s]
        keep = rank < capacity
        # kept entries get unique slots; dropped entries collide on their
        # expert's last slot but contribute an added zero, so .add is safe
        slot = e_s * capacity + jnp.minimum(rank, capacity - 1)

        buf = jnp.zeros((num_experts * capacity, dim), x.dtype)
        gathered = jnp.where(keep[:, None], xf[tok_s], 0)
        expert_in = buf.at[slot].add(gathered).reshape(
            num_experts, capacity, dim)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", None, "embed"))

        expert_out = nn.vmap(
            _ExpertFFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(cfg, name="experts")(expert_in)        # [E, C, D]
        expert_out = nn.with_logical_constraint(
            expert_out, ("expert", None, "embed"))

        rows = expert_out.reshape(num_experts * capacity, dim)[slot]
        weighted = rows.astype(jnp.float32) * (g_s * keep)[:, None]
        out = jnp.zeros((tokens, dim), jnp.float32).at[tok_s].add(weighted)
        out = out.astype(x.dtype).reshape(batch, seq, dim)
        return nn.with_logical_constraint(out, ("batch", "seq", "embed"))


__all__ = ["MoEMLP", "load_balance_loss"]
