#!/usr/bin/env bash
# Seeded chaos soaks (tests/test_chaos.py::TestChaosSoak +
# ::TestSliceRecoverySoak + ::TestMigrationRecoverySoak): N rounds of
# random fault plans (kube/faults.py) against a TPU+auth notebook, plus
# the self-healing recovery soak (seeded worker kills/crashloops under
# API faults; the engine — not an annotation — must restore
# sliceHealth=Healthy with slice-atomic restarts only, survive a
# mid-soak leader failover, and exhaust exactly at the attempt cap on a
# permanently broken slice), plus the checkpoint/migrate drill
# (self-healing on, session checkpoints enabled: fresh checkpoints must
# recover via the migrate verb with restored-state equivalence asserted
# byte-for-byte, stale ones must fall back to the bare restart, and a
# manager failover mid-migration must resume from status.sessionState
# without double-restoring), plus the fleet SLO soak (TestFleetSLOSoak:
# every injected degradation window fires exactly one burn alert that
# resolves on recovery with a flight-recorder-resolvable trace id, ZERO
# alerts firing at soak end, /debug/fleet counts matching apiserver
# ground truth, profiler overhead < 5%, and an ops.diagnose bundle that
# reconstructs the slowest attempt offline), plus the active-active
# kill/rejoin soak (TestShardKillRejoinSoak: a 3-replica sharded fleet
# under seeded kill / zombie-write / rejoin / churn rounds — zero
# cross-process double-reconciles over the MERGED flight-recorder
# histories, every zombie write fenced and counted, epoch strictly
# monotonic, and per-replica diagnose bundles merged offline agreeing
# with the in-process sweep), plus the failover lane
# (TestFailoverSoak: seeded primary-gang kills under control-plane
# partitions against a spec.replication notebook — every round must
# promote the warm follower with zero kernel-state loss and exactly one
# epoch bump, fence the demoted zombie's writes, and keep the promotion
# p99 at least 5x below the snapshot->restore baseline and under the
# ci/fleet_budget.json "failover" ceiling), plus the preemption
# lane (TestPreemptionSoak: seeded manager kills at every point of
# the checkpoint-then-preempt write-ahead protocol — the successor
# must resume, never repeat, the eviction: exactly one whole-slice
# StatefulSet delete per victim across both managers, zero pod-level
# client deletes, every record folding terminal exactly once, and
# the victims' secured checkpoints intact).  All driven on the
# FakeClock so wall time stays in seconds regardless of how much backoff
# the injected faults provoke.
#
# The seed is printed up front and on failure — reproduce any run with
#   CHAOS_SOAK_SEED=<seed> CHAOS_SOAK_ROUNDS=<n> \
#     SELFHEAL_SOAK_ROUNDS=<m> ci/chaos_soak.sh
# The default seed is date-stable (not time-derived) so CI is
# deterministic; pass CHAOS_SOAK_SEED=random for an exploratory roll.
set -euo pipefail
cd "$(dirname "$0")/.."

ROUNDS="${CHAOS_SOAK_ROUNDS:-25}"
SHARD_ROUNDS="${SHARD_SOAK_ROUNDS:-10}"
HEAL_ROUNDS="${SELFHEAL_SOAK_ROUNDS:-16}"
MIGRATE_ROUNDS="${MIGRATE_SOAK_ROUNDS:-12}"
PREEMPT_ROUNDS="${PREEMPT_SOAK_ROUNDS:-6}"
FAILOVER_ROUNDS="${FAILOVER_SOAK_ROUNDS:-50}"
SEED="${CHAOS_SOAK_SEED:-20260804}"
# the CI soak runs the manager with a parallel worker pool: the invariants
# (steady state restored, slice-atomic restarts, fault<->span pairing) must
# hold identically in threaded mode, and the soaks additionally assert no
# per-key concurrent reconcile via the flight recorder's overlap check
WORKERS="${WORKQUEUE_WORKERS:-8}"
# the soaks run with the runtime concurrency sanitizer ON: committed
# store snapshots are deep-frozen (a mutate-after-list raises at the
# mutation site with the active trace id) and every store/cluster/cache
# lock is order-tracked (an inversion raises instead of deadlocking) —
# utils/invariants.py, docs/STATIC_ANALYSIS.md
STRICT="${INVARIANTS_STRICT:-1}"
if [[ "$SEED" == "random" ]]; then
  SEED=$((RANDOM * 32768 + RANDOM))
fi

echo "== chaos soak: seed=${SEED} rounds=${ROUNDS} selfheal_rounds=${HEAL_ROUNDS} migrate_rounds=${MIGRATE_ROUNDS} preempt_rounds=${PREEMPT_ROUNDS} shard_rounds=${SHARD_ROUNDS} failover_rounds=${FAILOVER_ROUNDS} workers=${WORKERS} strict=${STRICT} =="
if ! CHAOS_SOAK_SEED="$SEED" CHAOS_SOAK_ROUNDS="$ROUNDS" \
    SELFHEAL_SOAK_ROUNDS="$HEAL_ROUNDS" MIGRATE_SOAK_ROUNDS="$MIGRATE_ROUNDS" \
    SHARD_SOAK_ROUNDS="$SHARD_ROUNDS" FAILOVER_SOAK_ROUNDS="$FAILOVER_ROUNDS" \
    PREEMPT_SOAK_ROUNDS="$PREEMPT_ROUNDS" \
    WORKQUEUE_WORKERS="$WORKERS" INVARIANTS_STRICT="$STRICT" \
    python -m pytest tests/test_chaos.py::TestChaosSoak \
      tests/test_chaos.py::TestSliceRecoverySoak \
      tests/test_chaos.py::TestMigrationRecoverySoak \
      tests/test_chaos.py::TestPreemptionSoak \
      tests/test_chaos.py::TestFleetSLOSoak \
      tests/test_chaos.py::TestShardKillRejoinSoak \
      tests/test_chaos.py::TestFailoverSoak -q "$@"; then
  echo "chaos soak FAILED — reproduce with:" >&2
  echo "  CHAOS_SOAK_SEED=${SEED} CHAOS_SOAK_ROUNDS=${ROUNDS} \\" >&2
  echo "    SELFHEAL_SOAK_ROUNDS=${HEAL_ROUNDS} MIGRATE_SOAK_ROUNDS=${MIGRATE_ROUNDS} \\" >&2
  echo "    PREEMPT_SOAK_ROUNDS=${PREEMPT_ROUNDS} \\" >&2
  echo "    SHARD_SOAK_ROUNDS=${SHARD_ROUNDS} FAILOVER_SOAK_ROUNDS=${FAILOVER_ROUNDS} \\" >&2
  echo "    WORKQUEUE_WORKERS=${WORKERS} ci/chaos_soak.sh" >&2
  exit 1
fi
echo "chaos soak OK (seed=${SEED}, rounds=${ROUNDS}, selfheal_rounds=${HEAL_ROUNDS}, migrate_rounds=${MIGRATE_ROUNDS}, preempt_rounds=${PREEMPT_ROUNDS}, shard_rounds=${SHARD_ROUNDS}, failover_rounds=${FAILOVER_ROUNDS}, workers=${WORKERS})"

# INTERLEAVE_DEEP=1: re-run the schedule-exploring protocol tests
# (tests/test_interleave.py) with a much larger enumeration budget than
# the in-suite smoke — more distinct schedules and a longer wall budget
# buy coverage of deeper preemption patterns.  Off by default: the smoke
# already proves >=1000 schedules per protocol inside tier-1.
if [[ "${INTERLEAVE_DEEP:-0}" == "1" ]]; then
  DEEP_SCHEDULES="${INTERLEAVE_DEEP_SCHEDULES:-20000}"
  DEEP_BUDGET="${INTERLEAVE_DEEP_BUDGET_S:-600}"
  echo "== interleave deep exploration: max_schedules=${DEEP_SCHEDULES} budget_s=${DEEP_BUDGET} =="
  if ! INTERLEAVE_MAX_SCHEDULES="$DEEP_SCHEDULES" \
      INTERLEAVE_BUDGET_S="$DEEP_BUDGET" INVARIANTS_STRICT="$STRICT" \
      python -m pytest tests/test_interleave.py -q; then
    echo "interleave deep exploration FAILED — reproduce with:" >&2
    echo "  INTERLEAVE_DEEP=1 INTERLEAVE_DEEP_SCHEDULES=${DEEP_SCHEDULES} \\" >&2
    echo "    INTERLEAVE_DEEP_BUDGET_S=${DEEP_BUDGET} ci/chaos_soak.sh" >&2
    exit 1
  fi
  echo "interleave deep exploration OK"
fi

# FLEET_SCALE_DEEP=1: the tail of the sharded scale curve — 50k then
# 100k notebooks over the 5-shard active-active fleet, each point gated
# against its committed ci/fleet_budget.json "sharded_100k" sub-budget
# (wall clock, p99 event->reconcile-start, ring balance,
# reconciles/notebook) with the same safety contract as the default
# lane's 2k/10k head (zero cross-process overlaps, zero steady-state
# data-plane writes, zero conservation violations).  Off by default:
# the 100k point alone runs ~20 minutes of real wall time (the fleet is
# FakeClock-driven but the reconcile work is real CPU).
if [[ "${FLEET_SCALE_DEEP:-0}" == "1" ]]; then
  echo "== fleet scale deep sweep (5 shards, 50k/100k) =="
  python loadtest/convergence.py --sweep 50000,100000 --shards 5 \
    --check-budget ci/fleet_budget.json --budget-section sharded_100k \
    --out "${FLEET_SCALE_OUT:-/tmp/fleet_scale_deep.json}"
  python - "${FLEET_SCALE_OUT:-/tmp/fleet_scale_deep.json}" <<'PYEOF'
import json, sys
out = json.load(open(sys.argv[1]))
for rec in out["sweep"]:
    n = rec["count"]
    assert rec.get("budget_ok"), f"point {n} over sharded_100k sub-budget"
    assert rec["cross_process_overlaps"] == 0, f"point {n}: overlap"
    assert rec["steady_data_plane_writes"] == 0, \
        f"point {n}: steady-state data-plane writes"
    assert rec["criticalpath"]["conservation"]["violations"] == 0, \
        f"point {n}: conservation violations"
    print(f"  {n}: wall={rec['wall_s']}s p99={rec['p99_event_to_reconcile_s']}s "
          f"rss={rec['peak_rss_mb']}MB rmw_conflicts={rec['shard_map_rmw_conflicts']} "
          f"binding={rec['binding_stage']}")
print("fleet scale deep sweep OK")
PYEOF
fi
