"""Notebook controller metrics, mirroring pkg/metrics/metrics.go:13-99:
counters for creations/failures/cullings plus a scraper-style gauge that
counts running notebooks by listing workload StatefulSets with the
notebook-name label, extended with TPU slice/chip gauges."""

from __future__ import annotations

from typing import Optional

from ..kube import ApiServer, parse_quantity
from ..utils.metrics import Registry
from . import constants as C


class NotebookMetrics:
    def __init__(self, api: ApiServer, registry: Optional[Registry] = None,
                 manager=None):
        self.api = api
        self.registry = registry or Registry()
        self.manager = manager  # kube.Manager: workqueue gauges source
        self.running = self.registry.gauge(
            "notebook_running",
            "Current running notebooks in the cluster",
            labels=("namespace",),
        )
        self.creation = self.registry.counter(
            "notebook_create_total",
            "Total times of creating notebooks",
            labels=("namespace",),
        )
        self.fail_creation = self.registry.counter(
            "notebook_create_failed_total",
            "Total failure times of creating notebooks",
            labels=("namespace",),
        )
        self.culling = self.registry.counter(
            "notebook_culling_total",
            "Total times of culling notebooks",
            labels=("namespace", "name"),
        )
        self.last_culling_timestamp = self.registry.gauge(
            "last_notebook_culling_timestamp_seconds",
            "Timestamp of the last notebook culling in seconds",
            labels=("namespace", "name"),
        )
        # TPU extensions
        self.tpu_chips_requested = self.registry.gauge(
            "notebook_tpu_chips_requested",
            "TPU chips requested by running notebook slices",
            labels=("namespace",),
        )
        # first-readiness latency distribution, observed once per notebook
        # by the NotebookReconciler off the injected clock (the reference
        # has no such metric; NotebookOS-style schedulers want it)
        self.notebook_ready_seconds = self.registry.histogram(
            "notebook_to_ready_seconds",
            "Latency from Notebook creation to all workers Ready",
            labels=("namespace",),
            buckets=(1.0, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1800.0, 3600.0),
        )
        # self-healing (core/selfheal.py): slice-atomic restarts performed
        # by the recovery engine, labeled by the disruption classification
        # (a bounded set — see selfheal.REASON_*), and the
        # disruption-detected -> slice-Healthy-again latency distribution
        self.slice_restarts = self.registry.counter(
            "notebook_slice_restarts_total",
            "Slice-atomic worker restarts performed by the self-healing "
            "engine",
            labels=("namespace", "reason"),
        )
        self.disruption_recovery_seconds = self.registry.histogram(
            "notebook_disruption_recovery_seconds",
            "Latency from disruption detection to the slice reading "
            "Healthy again",
            labels=("namespace",),
            buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0,
                     1800.0),
        )
        # session-state tier (core/sessionstate.py + selfheal migrate verb):
        # snapshots the control plane recorded/confirmed (trigger: final |
        # cull), the checkpoint age observed at each migrate decision, and
        # the migrate-verb outcomes.  trigger/result are bounded sets —
        # selfheal.MIGRATE_* constants.
        self.checkpoint_snapshots = self.registry.counter(
            "notebook_checkpoint_snapshots_total",
            "Session checkpoints recorded or confirmed by the controllers",
            labels=("namespace", "trigger"),
        )
        self.checkpoint_age_seconds = self.registry.histogram(
            "notebook_checkpoint_age_seconds",
            "Age of the freshest session checkpoint at migrate-decision "
            "time",
            labels=("namespace",),
            buckets=(1.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
                     3600.0),
        )
        self.migrations = self.registry.counter(
            "notebook_migrations_total",
            "Checkpoint/migrate recoveries by trigger and outcome",
            labels=("trigger", "result"),
        )
        # slice scheduler + warm pool (core/scheduler.py): per-reconcile
        # scheduling outcomes (result is the bounded scheduler.SCHEDULE_*
        # set), per-claim warm-pool outcomes (hit | miss | bypass), and the
        # per-shape pool census recomputed at scrape time from the
        # TPUWarmPool objects (state: Provisioning | Ready | Claimed)
        self.schedule_attempts = self.registry.counter(
            "notebook_schedule_attempts_total",
            "Slice-scheduler placement attempts by outcome",
            labels=("result",),
        )
        self.warmpool_hits = self.registry.counter(
            "notebook_warmpool_hits_total",
            "Warm-pool claim outcomes (hit=pre-provisioned slice claimed, "
            "miss=cold provision, bypass=pre-existing capacity)",
            labels=("result",),
        )
        self.warmpool_size = self.registry.gauge(
            "notebook_warmpool_size",
            "Warm-pool slices per accelerator-topology shape and state",
            labels=("shape", "state"),
        )
        # workqueue / retry observability (controller-runtime exports the
        # same family: workqueue_depth, workqueue_retries_total) — scraped
        # from Manager.queue_stats() when a manager is attached.  The
        # *_total families are monotonic counters fed by deltas from the
        # scrape-state snapshot (a gauge set() from scrape state would
        # break Prometheus rate()/increase() on counter-suffixed names)
        self.workqueue_depth = self.registry.gauge(
            "workqueue_depth",
            "Current reconcile requests queued per controller",
            labels=("controller",),
        )
        self.workqueue_backoff_pending = self.registry.gauge(
            "workqueue_backoff_pending",
            "Reconcile requests waiting out a retry backoff",
            labels=("controller",),
        )
        self.workqueue_retries_total = self.registry.counter(
            "workqueue_retries_total",
            "Total rate-limited requeues scheduled per controller",
            labels=("controller",),
        )
        self.workqueue_last_backoff_seconds = self.registry.gauge(
            "workqueue_last_backoff_seconds",
            "Most recent backoff delay handed out per controller",
            labels=("controller",),
        )
        self.workqueue_longest_running = self.registry.gauge(
            "workqueue_longest_running_processor_seconds",
            "Age of the oldest reconcile currently being processed per "
            "controller (0 when idle)",
            labels=("controller",),
        )
        self.reconcile_errors_total = self.registry.counter(
            "reconcile_errors_total",
            "Reconcile requests dropped after exhausting their retry budget",
            labels=("controller",),
        )
        # last snapshot of the manager's cumulative totals, so each scrape
        # feeds the counters exactly the delta since the previous scrape
        self._counter_snapshots: dict[tuple[str, str], float] = {}
        # shape labels emitted by the last warm-pool census — a deleted
        # pool's series must be driven to 0, not left at its last value
        self._warmpool_shapes: set[str] = set()

    def attach_manager(self, manager) -> None:
        self.manager = manager

    def _feed_counter(self, counter, label: str, total: float) -> None:
        """Advance a monotonic counter to `total` using deltas against the
        previous scrape; a source reset (new manager) re-counts from zero."""
        key = (counter.name, label)
        prev = self._counter_snapshots.get(key, 0.0)
        if total > prev:
            counter.labels(label).inc(total - prev)
        elif total < prev:
            counter.labels(label).inc(total)
        self._counter_snapshots[key] = float(total)

    def scrape(self, openmetrics: bool = False) -> str:
        """List-based scrape (metrics.go:82-99): recompute gauges from the
        live StatefulSet set, then render."""
        running_notebooks: dict[str, set[str]] = {}  # ns -> notebook names
        per_ns_chips: dict[str, float] = {}
        cache = getattr(self.manager, "cache", None)
        statefulsets = cache.list("StatefulSet") if cache is not None \
            else self.api.list("StatefulSet")
        for sts in statefulsets:
            nb_name = (
                sts.spec.get("template", {})
                .get("metadata", {})
                .get("labels", {})
                .get(C.NOTEBOOK_NAME_LABEL)
            )
            if nb_name is None:
                continue
            ns = sts.namespace
            replicas = int(sts.spec.get("replicas", 0))
            if replicas > 0:
                # dedupe by notebook: a multi-slice notebook renders one STS
                # per slice but is still one running notebook
                running_notebooks.setdefault(ns, set()).add(nb_name)
            for c in sts.spec.get("template", {}).get("spec", {}).get("containers", []):
                chips = (c.get("resources", {}).get("requests") or {}).get(
                    C.TPU_RESOURCE
                )
                if chips:
                    per_ns_chips[ns] = per_ns_chips.get(ns, 0.0) + parse_quantity(
                        chips
                    ) * replicas
        for ns, names in running_notebooks.items():
            self.running.labels(ns).set(len(names))
        for ns, n in per_ns_chips.items():
            self.tpu_chips_requested.labels(ns).set(n)
        # warm-pool census: every shape x state combination is set each
        # scrape (zeros included) so a drained state reads 0, not stale
        try:
            pools = self.api.list(C.WARMPOOL_KIND)
        except Exception:  # noqa: BLE001 — a real-cluster backend without
            pools = []     # the CRD must not break the scrape
        seen_shapes: set[str] = set()
        for pool in pools:
            shape = "%s-%s" % (pool.spec.get("accelerator", ""),
                               pool.spec.get("topology", ""))
            seen_shapes.add(shape)
            counts = {state: 0 for state in C.WARMSLICE_STATES}
            for e in (pool.body.get("status", {}).get("slices")
                      or {}).values():
                if e.get("external"):
                    continue  # bypass claims are not pool capacity
                state = e.get("state", "")
                if state in counts:
                    counts[state] += 1
            for state, n in counts.items():
                self.warmpool_size.labels(shape, state).set(n)
        # a TPUWarmPool deleted between scrapes would otherwise leave its
        # shape's series frozen at the last census — drive them to 0
        for shape in self._warmpool_shapes - seen_shapes:
            for state in C.WARMSLICE_STATES:
                self.warmpool_size.labels(shape, state).set(0)
        self._warmpool_shapes = seen_shapes
        if self.manager is not None:
            stats = self.manager.queue_stats()
            for name in stats["controllers"]:
                self.workqueue_depth.labels(name).set(
                    stats["depth"].get(name, 0))
                self.workqueue_backoff_pending.labels(name).set(
                    stats["backoff_pending"].get(name, 0))
                self._feed_counter(self.workqueue_retries_total, name,
                                   stats["retries_total"].get(name, 0))
                self.workqueue_last_backoff_seconds.labels(name).set(
                    stats["last_backoff_s"].get(name, 0.0))
                self.workqueue_longest_running.labels(name).set(
                    stats.get("longest_running_s", {}).get(name, 0.0))
                self._feed_counter(self.reconcile_errors_total, name,
                                   stats["errors_total"].get(name, 0))
        return self.render(openmetrics=openmetrics)

    def render(self, openmetrics: bool = False) -> str:
        """Full exposition: this registry plus the attached manager's
        reconcile/workqueue registry (controller_runtime_reconcile_*,
        workqueue_*_duration_seconds) as one scrape body.  Families are
        disjoint between the two registries, so the combined text stays a
        valid single exposition.  The OpenMetrics variant carries bucket
        exemplars and ends with the spec-required `# EOF` terminator."""
        text = self.registry.render(openmetrics=openmetrics)
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            text += mgr_registry.render(openmetrics=openmetrics)
        if openmetrics:
            text += "# EOF\n"
        return text

    def families(self) -> list[tuple[str, str]]:
        """(name, kind) inventory across both registries — what
        ci/metrics_drift_check.sh freezes in its golden list."""
        fams = self.registry.families()
        mgr_registry = getattr(self.manager, "metrics_registry", None)
        if mgr_registry is not None:
            fams += mgr_registry.families()
        return fams
