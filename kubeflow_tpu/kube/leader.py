"""Lease-based leader election for manager HA.

The reference enables controller-runtime leader election so two manager
replicas never double-reconcile (notebook-controller/main.go:91-93, odh
main.go:221-222).  Same protocol here: a coordination.k8s.io/v1 Lease named
per manager, acquired/renewed with optimistic concurrency; a candidate takes
over only when the holder's renewTime is older than the lease duration.
Works identically against the in-memory ApiServer and a real cluster via
KubeClient (Lease is just another object to both).

Leadership alone is not enough to keep a paused-then-resumed replica from
racing its successor: the old holder's threads may wake AFTER a rival
legally took over and issue writes under authority they no longer have.
The elector therefore carries a **fencing token** — the lease's
`leaseTransitions` count doubles as the fencing epoch, stamped onto every
lease write (`spec.fencingEpoch`) and latched into `self.token` on each
successful acquire/renew.  Any failure path (a lost round, `release()`)
invalidates the token BEFORE any subsequent write could race the new
leader, and `verify()` re-checks the lease so a deposed holder's late
write raises `StaleEpochError` instead of landing (see `kube/shard.py`
`FencedApi`, which proxies write verbs through `verify()`).
"""

from __future__ import annotations

import logging
import threading
from datetime import datetime, timezone
from typing import Callable, Optional

from ..utils.clock import Clock, parse_iso
from .errors import ApiError, ConflictError, ForbiddenError, NotFoundError
from .meta import KubeObject, ObjectMeta

logger = logging.getLogger("kubeflow_tpu.kube.leader")

LEASE_KIND = "Lease"
LEASE_API_VERSION = "coordination.k8s.io/v1"


def _iso(t: float) -> str:
    return datetime.fromtimestamp(t, tz=timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ")


class StaleEpochError(ForbiddenError):
    """A write carried a fencing epoch that is no longer the authority's
    current one (deposed leader, evicted shard member, zombie process).
    Forbidden-family, not Conflict: retrying cannot help — the caller
    lost its authority and must stop writing."""


class FencingToken:
    """The local half of a fencing-token lease: the epoch the holder last
    proved authority at, plus a validity latch.  The latch is flipped off
    BEFORE any code path that could let a rival take over observes the
    loss — so a holder that merely *suspects* it lost (failed renew,
    release) stops writing immediately, and a holder that provably lost
    gets `StaleEpochError` from `verify()`."""

    __slots__ = ("epoch", "valid")

    def __init__(self) -> None:
        self.epoch = -1
        self.valid = False

    def renew(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self.valid = True

    def invalidate(self) -> None:
        self.valid = False


class LeaderElector:
    """client-go leaderelection.LeaderElector over a Lease object."""

    def __init__(
        self,
        api,
        lease_name: str,
        namespace: str,
        identity: str,
        lease_duration_s: float = 15.0,
        renew_period_s: float = 10.0,
        retry_period_s: float = 2.0,
        renew_deadline_s: Optional[float] = None,
        clock: Optional[Clock] = None,
    ) -> None:
        self.api = api
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.retry_period_s = retry_period_s
        # client-go requires RenewDeadline < LeaseDuration: the deposed
        # leader must stop reconciling BEFORE a rival can legally take over
        # (renew + duration elapsed), or the single-writer guarantee breaks
        # for the gap.  The derived default leaves two retry rounds of
        # margin, clamped so it stays < lease_duration for short leases.
        if renew_deadline_s is None:
            renew_deadline_s = max(lease_duration_s - 2 * retry_period_s,
                                   lease_duration_s * 0.6)
        elif renew_deadline_s >= lease_duration_s:
            raise ValueError(
                f"renew_deadline_s ({renew_deadline_s}) must be < "
                f"lease_duration_s ({lease_duration_s})")
        self.renew_deadline_s = renew_deadline_s
        self.clock = clock or Clock()
        self.is_leader = False
        #: fencing token: epoch = the lease's leaseTransitions at the last
        #: successful acquire/renew; invalidated before any write can race
        #: a successor (see verify())
        self.token = FencingToken()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- single protocol step -------------------------------------------------
    def try_acquire_or_renew(self) -> bool:
        """One election round; returns True while holding leadership."""
        now = self.clock.now()
        try:
            lease = self.api.try_get(LEASE_KIND, self.namespace, self.lease_name)
            if lease is None:
                lease = KubeObject(
                    api_version=LEASE_API_VERSION,
                    kind=LEASE_KIND,
                    metadata=ObjectMeta(name=self.lease_name,
                                        namespace=self.namespace),
                    body={"spec": {
                        "holderIdentity": self.identity,
                        "leaseDurationSeconds": int(self.lease_duration_s),
                        "acquireTime": _iso(now),
                        "renewTime": _iso(now),
                        "leaseTransitions": 0,
                        "fencingEpoch": 0,
                    }},
                )
                self.api.create(lease)
                return self._became(True, epoch=0)
            spec = lease.body.get("spec", {})
            holder = spec.get("holderIdentity", "")
            renew = parse_iso(spec["renewTime"]) if spec.get("renewTime") else 0.0
            duration = float(spec.get("leaseDurationSeconds",
                                      self.lease_duration_s))
            if holder == self.identity:
                spec["renewTime"] = _iso(now)
            elif renew + duration < now:
                # stale holder: take over (the transition count doubles as
                # the fencing epoch — client-go bumps it the same way, the
                # bump is what deposes the old holder's token)
                spec["holderIdentity"] = self.identity
                spec["acquireTime"] = _iso(now)
                spec["renewTime"] = _iso(now)
                spec["leaseTransitions"] = int(spec.get("leaseTransitions", 0)) + 1
            else:
                return self._became(False)
            epoch = int(spec.get("leaseTransitions", 0))
            spec["fencingEpoch"] = epoch
            lease.body["spec"] = spec
            self.api.update(lease)
            return self._became(True, epoch=epoch)
        except (ConflictError, NotFoundError):
            return self._became(False)  # raced another candidate; retry later
        except ApiError as err:
            logger.warning("leader election round failed: %s", err)
            return self._became(False)

    def _became(self, leader: bool, epoch: Optional[int] = None) -> bool:
        if not leader:
            # invalidate FIRST: from this instant no write under this
            # elector's authority may land, even if a worker thread is
            # already past its own is_leader check
            self.token.invalidate()
        if leader != self.is_leader:
            logger.info("leader election: %s is now %s", self.identity,
                        "leader" if leader else "follower")
        self.is_leader = leader
        if leader and epoch is not None:
            self.token.renew(epoch)
        return leader

    def release(self) -> None:
        """Graceful handoff on shutdown (client-go ReleaseOnCancel).
        Leadership and the fencing token drop BEFORE the lease write: a
        successor may legally acquire the instant our update lands, so
        any of our writes racing past this point must already be fenced."""
        if not self.is_leader:
            return
        self.is_leader = False
        self.token.invalidate()
        try:
            lease = self.api.try_get(LEASE_KIND, self.namespace, self.lease_name)
            if lease and lease.body.get("spec", {}).get(
                    "holderIdentity") == self.identity:
                lease.body["spec"]["holderIdentity"] = ""
                lease.body["spec"]["renewTime"] = _iso(0.0)
                self.api.update(lease)
        except ApiError:
            pass

    def verify(self) -> int:
        """Fencing check for writes issued under this elector's authority
        (kube/shard.py FencedApi calls this before every proxied write):
        returns the fencing epoch, or raises StaleEpochError unless the
        token is valid AND the lease still names this identity at the
        token's epoch.  A verify failure invalidates the token, so every
        later write fails fast without re-reading the lease."""
        tok = self.token
        if not tok.valid:
            raise StaleEpochError(
                f"{self.identity}: fencing token invalidated (leadership "
                "lost or released)")
        try:
            lease = self.api.try_get(LEASE_KIND, self.namespace,
                                     self.lease_name)
        except ApiError:
            lease = None
        spec = (lease.body.get("spec") or {}) if lease is not None else {}
        if spec.get("holderIdentity") != self.identity or \
                int(spec.get("leaseTransitions", 0) or 0) != tok.epoch:
            tok.invalidate()
            raise StaleEpochError(
                f"{self.identity}: lease epoch moved on (held epoch "
                f"{tok.epoch}, holder now "
                f"{spec.get('holderIdentity', '<gone>')!r} at epoch "
                f"{int(spec.get('leaseTransitions', 0) or 0)})")
        return tok.epoch

    # -- blocking run loop ----------------------------------------------------
    def run(
        self,
        on_started_leading: Callable[[], None],
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ) -> None:
        """Block until leadership is acquired, invoke on_started_leading,
        then keep renewing; if leadership is lost, invoke on_stopped_leading
        and return (the process should exit and restart, as controller-runtime
        does)."""
        started = False
        last_ok = self.clock.now()
        while not self._stop.is_set():
            leader = self.try_acquire_or_renew()
            if leader:
                last_ok = self.clock.now()
                if not started:
                    started = True
                    on_started_leading()
            elif started:
                # a transient renew failure must not abdicate immediately —
                # client-go retries until the renew DEADLINE, which is
                # strictly shorter than the lease duration so we stop
                # reconciling before any rival may legally take over
                if self.clock.now() - last_ok > self.renew_deadline_s:
                    logger.error("renew deadline passed; leadership lost "
                                 "for %s", self.identity)
                    if on_stopped_leading:
                        on_stopped_leading()
                    return
                logger.warning(
                    "lease renew failed for %s; retrying within the "
                    "%.0fs renew deadline", self.identity,
                    self.renew_deadline_s)
            self._stop.wait(self.renew_period_s if leader
                            else self.retry_period_s)
        if started:
            self.release()

    def start_background(self, on_started: Callable[[], None],
                         on_stopped: Optional[Callable[[], None]] = None) -> None:
        self._thread = threading.Thread(
            target=self.run, args=(on_started, on_stopped),
            daemon=True, name="leader-elector")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None


__all__ = ["FencingToken", "LeaderElector", "StaleEpochError",
           "LEASE_KIND", "LEASE_API_VERSION"]
