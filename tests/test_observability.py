"""Traced reconciles + trace-correlated logs (the PR-2 tentpole surface).

Covers the manager's per-attempt reconcile root spans (one trace per retry
chain, attempt numbers as attributes), controller phase child spans
parenting onto the live reconcile span through the shared context stack,
fault injections landing as span events on the attempt they hit, the
structured-JSON log layer's trace_id/span_id injection, and the lint
gate's metric naming-convention rule.
"""

from __future__ import annotations

import ast
import io
import json
import logging as pylog

import pytest

from kubeflow_tpu.kube import (
    ApiServer,
    KubeObject,
    Manager,
    ObjectMeta,
    Result,
)
from kubeflow_tpu.kube.faults import FaultPlan, FaultRule
from kubeflow_tpu.utils import tracing
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.logging import JsonFormatter, setup_structured_logging
from kubeflow_tpu.utils.tracing import InMemorySpanExporter, get_tracer


@pytest.fixture()
def exporter():
    exp = InMemorySpanExporter()
    tracing.set_exporter(exp)
    yield exp
    tracing.set_exporter(None)


def mk(kind: str, name: str, namespace: str = "default") -> KubeObject:
    return KubeObject(api_version="v1", kind=kind,
                      metadata=ObjectMeta(name=name, namespace=namespace))


class TestReconcileSpans:
    def test_every_attempt_gets_a_root_span_sharing_one_trace(self, exporter):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())

        class Flaky:
            calls = 0

            def reconcile(self, req):
                Flaky.calls += 1
                if Flaky.calls <= 2:
                    raise RuntimeError("boom")
                return Result()

        mgr.register("nb", Flaky(), for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()

        spans = exporter.find("reconcile")
        assert len(spans) == 3
        # one retry chain == one trace; attempts number 1..3
        assert len({s.trace_id for s in spans}) == 1
        assert [s.attributes["attempt"] for s in spans] == [1, 2, 3]
        assert [s.attributes["reconcile.result"] for s in spans] == \
            ["error", "error", "success"]
        assert all(s.attributes["controller"] == "nb" for s in spans)
        assert all(s.attributes["name"] == "nb1" for s in spans)
        # failed attempts carry the exception as a span event
        err_events = [e for s in spans[:2] for e in s.events
                      if e.name == "reconcile.error"]
        assert len(err_events) == 2
        assert err_events[0].attributes["exception.type"] == "RuntimeError"

    def test_fresh_event_starts_a_fresh_trace(self, exporter):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())

        class Ok:
            def reconcile(self, req):
                return Result()

        mgr.register("nb", Ok(), for_kind="Notebook")
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        obj = api.get("Notebook", "default", "nb1")
        obj.metadata.labels["touch"] = "1"
        api.update(obj)
        mgr.run_until_idle()

        spans = exporter.find("reconcile")
        assert len(spans) == 2
        assert spans[0].trace_id != spans[1].trace_id
        assert [s.attributes["attempt"] for s in spans] == [1, 1]

    def test_requeue_true_extends_the_trace(self, exporter):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())

        class Requeuer:
            calls = 0

            def reconcile(self, req):
                Requeuer.calls += 1
                return Result(requeue=Requeuer.calls < 2)

        mgr.register("nb", Requeuer(), for_kind="Notebook")
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        spans = exporter.find("reconcile")
        assert len(spans) == 2
        assert spans[0].trace_id == spans[1].trace_id
        assert spans[0].attributes["reconcile.result"] == "requeue"

    def test_reconcile_total_classifies_outcomes(self):
        api = ApiServer()
        mgr = Manager(api, clock=FakeClock())

        class Script:
            calls = 0

            def reconcile(self, req):
                Script.calls += 1
                if Script.calls == 1:
                    raise RuntimeError("boom")
                if Script.calls == 2:
                    return Result(requeue=True)
                if Script.calls == 3:
                    return Result(requeue_after=30.0)
                return Result()

        mgr.register("nb", Script(), for_kind="Notebook", max_retries=5)
        api.create(mk("Notebook", "nb1"))
        mgr.run_until_idle()
        mgr.advance(31)
        assert mgr.reconcile_total.value("nb", "error") == 1
        assert mgr.reconcile_total.value("nb", "requeue") == 1
        assert mgr.reconcile_total.value("nb", "requeue_after") == 1
        assert mgr.reconcile_total.value("nb", "success") == 1
        assert mgr.reconcile_time.count_value("nb") == 4
        assert mgr.work_duration.count_value("nb") == 4

    def test_controller_phase_spans_parent_onto_reconcile_root(self, exporter):
        from kubeflow_tpu.api.types import Notebook
        from kubeflow_tpu.core.notebook_controller import setup_core_controllers
        from kubeflow_tpu.kube import FakeCluster
        from kubeflow_tpu.utils.config import CoreConfig

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1", allocatable={"cpu": "64", "memory": "256Gi"})
        mgr = Manager(api, clock=FakeClock())
        setup_core_controllers(mgr, CoreConfig())
        api.create(Notebook.new("traced", "user1").obj)
        mgr.run_until_idle()

        roots = {s.span_id: s for s in exporter.find("reconcile")}
        for phase in ("render", "apply", "status"):
            phase_spans = exporter.find(phase)
            assert phase_spans, f"no {phase!r} spans exported"
            for s in phase_spans:
                assert s.parent is not None and \
                    s.parent.span_id in roots, f"{phase} span not parented"
                assert s.trace_id == s.parent.trace_id

    def test_condition_and_ready_events_on_status_span(self, exporter):
        from kubeflow_tpu.api.types import Notebook
        from kubeflow_tpu.core.notebook_controller import setup_core_controllers
        from kubeflow_tpu.kube import FakeCluster
        from kubeflow_tpu.utils.config import CoreConfig

        api = ApiServer()
        cluster = FakeCluster(api)
        cluster.add_node("n1", allocatable={"cpu": "64", "memory": "256Gi"})
        mgr = Manager(api, clock=FakeClock())
        setup_core_controllers(mgr, CoreConfig())
        api.create(Notebook.new("evt", "user1").obj)
        mgr.run_until_idle()

        events = [e for s in exporter.find("status") for e in s.events]
        names = {e.name for e in events}
        assert "condition.transition" in names
        assert "notebook.ready" in names


class TestFaultSpanEvents:
    def test_injected_fault_stamps_the_live_reconcile_span(self, exporter):
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock)

        class Getter:
            def reconcile(self, req):
                api.get("Notebook", req.namespace, req.name)
                return Result()

        mgr.register("nb", Getter(), for_kind="Notebook", max_retries=5)
        plan = FaultPlan([FaultRule(verbs=("get",), kinds=("Notebook",),
                                    error="unavailable", max_matches=1,
                                    name="drill")], clock=clock)
        api.create(mk("Notebook", "nb1"))
        api.install_fault_plan(plan)
        mgr.run_until_idle()
        api.clear_fault_plan()

        assert len(plan.log) == 1
        rec = plan.log[0]
        assert rec.span_id and rec.trace_id
        span = next(s for s in exporter.find("reconcile")
                    if s.span_id == rec.span_id)
        assert span.attributes["controller"] == "nb"
        fault_events = [e for e in span.events if e.name == "fault.injected"]
        assert len(fault_events) == 1
        assert fault_events[0].attributes["fault.action"] == \
            "error:unavailable"
        assert fault_events[0].attributes["fault.verb"] == "get"
        assert fault_events[0].attributes["fault.seq"] == rec.seq
        # the faulted attempt errored; the retry succeeded on the SAME trace
        spans = [s for s in exporter.find("reconcile")
                 if s.trace_id == rec.trace_id]
        assert len(spans) == 2
        assert spans[0].attributes["reconcile.result"] == "error"
        assert spans[1].attributes["reconcile.result"] == "success"

    def test_fault_inside_phase_child_lands_on_root_span(self, exporter):
        """A fault hitting an ApiServer call made inside a controller phase
        child span must stamp the reconcile ROOT, not the child."""
        api = ApiServer()
        clock = FakeClock()
        mgr = Manager(api, clock=clock)
        tracer = get_tracer("test.phase")

        class Phased:
            def reconcile(self, req):
                with tracer.start_span("inner-phase"):
                    api.list("Pod", namespace=req.namespace)
                return Result()

        mgr.register("nb", Phased(), for_kind="Notebook", max_retries=5)
        plan = FaultPlan([FaultRule(verbs=("list",), kinds=("Pod",),
                                    latency_s=0.25, max_matches=1)],
                         clock=clock)
        api.create(mk("Notebook", "nb1"))
        api.install_fault_plan(plan)
        mgr.run_until_idle()
        api.clear_fault_plan()

        assert len(plan.log) == 1
        rec = plan.log[0]
        root = next(s for s in exporter.find("reconcile")
                    if s.span_id == rec.span_id)
        assert [e.name for e in root.events] == ["fault.injected"]
        inner = exporter.find("inner-phase")[0]
        assert not inner.events
        assert inner.parent.span_id == root.span_id
        # injected latency advanced the manager clock inside the attempt,
        # so the reconcile-time histogram saw it deterministically
        assert mgr.reconcile_time.sum_value("nb") == pytest.approx(0.25)


class TestStructuredLogging:
    def test_log_lines_inside_a_span_carry_trace_ids(self, exporter):
        formatter = JsonFormatter()
        record = pylog.LogRecord("kubeflow_tpu.core", pylog.INFO, __file__,
                                 1, "culling notebook %s/%s", ("ns", "nb"),
                                 None)
        with get_tracer("t").start_span("reconcile") as span:
            line = formatter.format(record)
        data = json.loads(line)
        assert data["msg"] == "culling notebook ns/nb"
        assert data["level"] == "info"
        assert data["logger"] == "kubeflow_tpu.core"
        assert data["trace_id"] == span.trace_id
        assert data["span_id"] == span.span_id

    def test_log_lines_outside_spans_omit_trace_ids(self):
        formatter = JsonFormatter()
        record = pylog.LogRecord("x", pylog.WARNING, __file__, 1, "m", (),
                                 None)
        data = json.loads(formatter.format(record))
        assert "trace_id" not in data and "span_id" not in data
        assert data["level"] == "warning"

    def test_extra_fields_and_exceptions_serialize(self):
        formatter = JsonFormatter()
        try:
            raise ValueError("nope")
        except ValueError:
            import sys

            record = pylog.LogRecord("x", pylog.ERROR, __file__, 1,
                                     "failed", (), sys.exc_info())
        record.namespace = "user1"
        data = json.loads(formatter.format(record))
        assert data["namespace"] == "user1"
        assert "ValueError: nope" in data["exc"]

    def test_setup_structured_logging_emits_parseable_lines(self):
        stream = io.StringIO()
        root = pylog.getLogger()
        saved_handlers = list(root.handlers)
        saved_level = root.level
        try:
            setup_structured_logging(pylog.INFO, stream=stream)
            pylog.getLogger("kubeflow_tpu.test").info(
                "hello %d", 7, extra={"controller": "nb"})
        finally:
            for h in list(root.handlers):
                root.removeHandler(h)
            for h in saved_handlers:
                root.addHandler(h)
            root.setLevel(saved_level)
        data = json.loads(stream.getvalue().strip())
        assert data["msg"] == "hello 7"
        assert data["controller"] == "nb"
        assert data["logger"] == "kubeflow_tpu.test"


class TestMetricNamingLint:
    def _problems(self, src: str):
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "ci_lint", Path(__file__).parent.parent / "ci" / "lint.py")
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        return lint.check_metric_names(ast.parse(src))

    def test_total_suffix_requires_counter(self):
        problems = self._problems(
            "reg.gauge('workqueue_retries_total', 'h')\n")
        assert len(problems) == 1
        assert "_total" in problems[0][1]

    def test_seconds_suffix_rejects_counter(self):
        problems = self._problems("reg.counter('reconcile_seconds', 'h')\n")
        assert len(problems) == 1

    def test_conforming_registrations_pass(self):
        src = (
            "reg.counter('x_total', 'h')\n"
            "reg.counter('cpu_seconds_total', 'h')\n"
            "reg.gauge('depth', 'h')\n"
            "reg.histogram('lat_seconds', 'h')\n"
            "reg.gauge('last_backoff_seconds', 'h')\n"
        )
        assert self._problems(src) == []
