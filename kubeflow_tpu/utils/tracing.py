"""Minimal OpenTelemetry-style tracing.

The reference traces its mutating webhook with OTel — a lazily-created tracer
(sync.OnceValue, notebook_mutating_webhook.go:74-76), a root span per
admission with notebook attributes (:366-373), child spans, and span events
that the test suite asserts on via an in-memory exporter
(opentelemetry_test.go:26-78).  We keep the same shape: a process-global
provider that defaults to noop, swappable for an InMemorySpanExporter in
tests — tracing as a test observability channel.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional


@dataclass
class SpanEvent:
    name: str
    attributes: dict = field(default_factory=dict)
    timestamp: float = 0.0


@dataclass
class Span:
    name: str
    attributes: dict = field(default_factory=dict)
    events: list[SpanEvent] = field(default_factory=list)
    parent: Optional["Span"] = None
    start_time: float = 0.0
    end_time: float = 0.0
    recording: bool = True

    def add_event(self, name: str, attributes: Optional[dict] = None) -> None:
        if self.recording:
            self.events.append(SpanEvent(name, dict(attributes or {}), time.time()))

    def set_attribute(self, key: str, value) -> None:
        if self.recording:
            self.attributes[key] = value


_NOOP_SPAN = Span(name="", recording=False)


class InMemorySpanExporter:
    """Collects finished spans for test assertions
    (opentelemetry_test.go InMemoryExporter analog)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: list[Span] = []

    def export(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)

    @property
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def find(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]

    def events(self) -> list[str]:
        return [e.name for s in self.spans for e in s.events]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


class Tracer:
    def __init__(self, name: str) -> None:
        self.name = name
        self._local = threading.local()

    def current_span(self) -> Span:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else _NOOP_SPAN

    @contextlib.contextmanager
    def start_span(
        self, name: str, attributes: Optional[dict] = None
    ) -> Iterator[Span]:
        # the exporter is resolved per-span, matching the reference's lazily
        # created tracer whose provider is swapped in by tests
        exporter = _exporter
        if exporter is None:
            yield _NOOP_SPAN
            return
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        span = Span(
            name=name,
            attributes=dict(attributes or {}),
            parent=stack[-1] if stack else None,
            start_time=time.time(),
        )
        stack.append(span)
        try:
            yield span
        finally:
            stack.pop()
            span.end_time = time.time()
            exporter.export(span)


_provider_lock = threading.Lock()
_exporter: Optional[InMemorySpanExporter] = None


def set_exporter(exporter: Optional[InMemorySpanExporter]) -> None:
    """Install the process-wide exporter (tests); None restores noop."""
    global _exporter
    with _provider_lock:
        _exporter = exporter


def get_tracer(name: str) -> Tracer:
    """Tracer whose exporter is resolved at each span start, matching the
    reference's OnceValue'd tracer that resolves the provider lazily."""
    return Tracer(name)
