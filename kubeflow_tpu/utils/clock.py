"""Clock abstraction so culling/idleness logic is testable.

The reference manipulates time in tests by rewriting annotation timestamps
(culling_controller_test.go:95-142); we inject a clock instead.
"""

from __future__ import annotations

import time


class Clock:
    """The ONE sanctioned home of direct time calls (ci/analyzers clock
    discipline): everything else takes an injected Clock so FakeClock
    tests stay deterministic."""

    def now(self) -> float:
        return time.time()

    def now_iso(self) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.now()))

    def monotonic(self) -> float:
        """Monotonic reading for interval arithmetic (rate limiters,
        retry deadlines) — never compared against now()."""
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)


class FakeClock(Clock):
    def __init__(self, start: float = 1_700_000_000.0) -> None:
        self._now = start

    def now(self) -> float:
        return self._now

    def monotonic(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        # a FakeClock sleep advances logical time instead of blocking, so
        # code routed through Clock.sleep is instant and deterministic
        if seconds > 0:
            self.advance(seconds)

    def advance(self, seconds: float) -> None:
        self._now += seconds

    def set(self, t: float) -> None:
        self._now = t


def parse_iso(ts: str) -> float:
    """RFC3339 parse accepting fractional seconds and offsets — real Jupyter
    reports e.g. 2026-07-29T10:00:00.533016Z (the Go reference parses with
    time.RFC3339, which accepts the same)."""
    from datetime import datetime, timezone

    s = ts.strip()
    if s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    dt = datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()
