"""Invariant analyzer suite (ci/analyzers) + runtime sanitizer
(utils/invariants): every static check catches its seeded violation and
passes the clean twin; strict mode deep-freezes committed snapshots and
the LockTracker raises on a seeded inversion; the gate itself runs clean
on the repo."""

import ast
import threading
from pathlib import Path

import pytest

from ci.analyzers import (
    Module,
    clock_discipline,
    cow_contract,
    hot_path,
    lock_order,
    lockset,
    run_all,
    write_ahead,
)
from ci.analyzers.allowlist import Allow
from ci.analyzers import allowlist as allowlist_mod
from kubeflow_tpu.kube.meta import KubeObject, ObjectMeta
from kubeflow_tpu.kube.store import ApiServer
from kubeflow_tpu.utils import invariants, tracing
from kubeflow_tpu.utils.invariants import (
    FrozenMutationError,
    LockInversionError,
    LockTracker,
    TrackedLock,
)


def mod(src: str, rel: str = "kubeflow_tpu/fixture.py") -> Module:
    return Module(Path(rel), rel, src, ast.parse(src))


def nb(name="n", ns="d", spec=None):
    return KubeObject("kubeflow.org/v1", "Notebook",
                      ObjectMeta(name=name, namespace=ns),
                      body={"spec": dict(spec or {"image": "x"})})


# ---------------------------------------------------------------------------
# clock discipline
# ---------------------------------------------------------------------------

class TestClockAnalyzer:
    def test_direct_calls_flagged(self):
        src = (
            "import time\n"
            "import datetime\n"
            "def f():\n"
            "    a = time.time()\n"
            "    time.sleep(1)\n"
            "    b = time.monotonic()\n"
            "    c = datetime.datetime.now()\n"
            "    return a, b, c\n")
        v = clock_discipline.analyze(mod(src))
        assert len(v) == 4
        assert all(x.check == "clock" for x in v)
        assert v[0].context == "f"

    def test_alias_imports_resolved(self):
        src = (
            "import time as _t\n"
            "from datetime import datetime as dt\n"
            "def f():\n"
            "    return _t.time(), dt.utcnow()\n")
        assert len(clock_discipline.analyze(mod(src))) == 2

    def test_argless_gmtime_is_an_implicit_now(self):
        src = "import time\ndef f():\n    return time.gmtime()\n"
        assert len(clock_discipline.analyze(mod(src))) == 1
        # with an argument it converts a timestamp: no time read
        src = "import time\ndef f(t):\n    return time.gmtime(t)\n"
        assert clock_discipline.analyze(mod(src)) == []

    def test_clean_twin_injected_clock(self):
        src = (
            "def f(clock):\n"
            "    clock.sleep(1)\n"
            "    return clock.now()\n")
        assert clock_discipline.analyze(mod(src)) == []

    def test_injectable_default_reference_not_flagged(self):
        # time_fn=time.time is the injection idiom, not a hardwired read
        src = (
            "import time\n"
            "def f(time_fn=time.time):\n"
            "    return time_fn()\n")
        assert clock_discipline.analyze(mod(src)) == []


# ---------------------------------------------------------------------------
# COW / frozen contract
# ---------------------------------------------------------------------------

class TestCowAnalyzer:
    @pytest.mark.parametrize("body", [
        # the PR 8 bug class, in its observed shapes
        "for o in api.list('Pod'):\n        o.metadata.labels['a'] = 'b'",
        "objs = api.list('Pod')\n    objs[0].spec['x'] = 1",
        "objs, rv = api.list_with_rv('Pod')\n"
        "    del objs[0].body['spec']",
        "for o in cache.select('Pod', None, {}):\n"
        "        o.status.setdefault('conditions', [])",
        "for o in cache.by_index('Pod', 'ns', 'd'):\n"
        "        o.body['status'].update({'k': 1})",
        "for o in api.list('Pod'):\n"
        "        ann = o.metadata.annotations\n"
        "        ann['k'] = 'v'",
        "for o in sorted(api.list('Pod')):\n        o.spec['x'] += 1",
    ])
    def test_seeded_violation_caught(self, body):
        src = f"def f(api, cache):\n    {body}\n"
        v = cow_contract.analyze(mod(src))
        assert len(v) >= 1 and all(x.check == "cow" for x in v)

    @pytest.mark.parametrize("body", [
        # deepcopy/get are the sanctioned escape hatches
        "for o in api.list('Pod'):\n"
        "        o = o.deepcopy()\n"
        "        o.metadata.labels['a'] = 'b'",
        "for o in api.list('Pod'):\n"
        "        fresh = api.get('Pod', o.namespace, o.name)\n"
        "        fresh.spec['x'] = 1",
        # mutating your own list container is fine — the OBJECTS are shared
        "objs = api.list('Pod')\n    objs.sort()\n    objs.append(None)",
        "objs = api.list('Pod')\n    objs[0] = None",
        # reads don't taint
        "names = [o.name for o in api.list('Pod')]\n    names.append('x')",
    ])
    def test_clean_twin_passes(self, body):
        src = f"def f(api, cache):\n    {body}\n"
        assert cow_contract.analyze(mod(src)) == []

    def test_rebind_clears_taint(self):
        src = (
            "def f(api):\n"
            "    o = api.list('Pod')\n"
            "    o = {}\n"
            "    o['x'] = 1\n")
        assert cow_contract.analyze(mod(src)) == []


# ---------------------------------------------------------------------------
# lock order
# ---------------------------------------------------------------------------

_STORE_REL = "kubeflow_tpu/kube/store.py"


class TestLockAnalyzer:
    def test_seeded_inversion_cycle(self):
        src = (
            "class A:\n"
            "    def f(self):\n"
            "        with self._a_lock:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def g(self):\n"
            "        with self._b_lock:\n"
            "            with self._a_lock:\n"
            "                pass\n")
        v = lock_order.analyze_project([mod(src, _STORE_REL)])
        assert len(v) == 1 and v[0].check == "locks"
        assert "_a_lock" in v[0].context and "_b_lock" in v[0].context

    def test_clean_consistent_order(self):
        src = (
            "class A:\n"
            "    def f(self):\n"
            "        with self._a_lock:\n"
            "            with self._b_lock:\n"
            "                pass\n"
            "    def g(self):\n"
            "        with self._a_lock:\n"
            "            pass\n")
        assert lock_order.analyze_project([mod(src, _STORE_REL)]) == []

    def test_cycle_through_call_propagation(self):
        src = (
            "class A:\n"
            "    def f(self):\n"
            "        with self._a_lock:\n"
            "            self.h()\n"
            "    def g(self):\n"
            "        with self._b_lock:\n"
            "            self.i()\n"
            "    def h(self):\n"
            "        with self._b_lock:\n"
            "            pass\n"
            "    def i(self):\n"
            "        with self._a_lock:\n"
            "            pass\n")
        v = lock_order.analyze_project([mod(src, _STORE_REL)])
        assert len(v) == 1

    def test_loop_enter_context_self_edge(self):
        src = (
            "from contextlib import ExitStack\n"
            "class A:\n"
            "    def f(self, shards):\n"
            "        with ExitStack() as stack:\n"
            "            for s in shards:\n"
            "                stack.enter_context(s.lock)\n")
        v = lock_order.analyze_project([mod(src, _STORE_REL)])
        assert len(v) == 1 and "lock->" in v[0].context

    def test_real_repo_graph_is_acyclic_modulo_allowlist(self):
        violations, _ = run_all()
        assert [v for v in violations if v.check == "locks"] == []


# ---------------------------------------------------------------------------
# hot-path scan ban
# ---------------------------------------------------------------------------

class TestHotPathAnalyzer:
    def test_unguarded_api_list_in_reconciler_flagged(self):
        src = (
            "class FooReconciler:\n"
            "    def reconcile(self, req):\n"
            "        return self.api.list('Pod', namespace=req.namespace)\n")
        v = hot_path.analyze(mod(src))
        assert len(v) == 1 and v[0].check == "hotpath"

    @pytest.mark.parametrize("body", [
        # both sanctioned fallback shapes: else-branch and early-return
        ("        if self.cache is not None:\n"
         "            return self.cache.list('Pod')\n"
         "        else:\n"
         "            return self.api.list('Pod')\n"),
        ("        if self.cache is not None:\n"
         "            return self.cache.list('Pod')\n"
         "        return self.api.list('Pod')\n"),
    ])
    def test_cache_guarded_fallback_allowed(self, body):
        src = ("class FooController:\n"
               "    def reconcile(self, req):\n" + body)
        assert hot_path.analyze(mod(src)) == []

    def test_non_reconciler_class_not_in_scope(self):
        src = (
            "class EventRecorder:\n"
            "    def emit(self):\n"
            "        return self.api.list('Event')\n")
        assert hot_path.analyze(mod(src)) == []


# ---------------------------------------------------------------------------
# write-ahead dominance
# ---------------------------------------------------------------------------

SELFHEAL_REL = "kubeflow_tpu/core/selfheal.py"
SCHEDULER_REL = "kubeflow_tpu/core/scheduler.py"


class TestWriteAheadAnalyzer:
    def test_conditional_persist_does_not_dominate(self):
        src = (
            "class RecoveryEngine:\n"
            "    def maybe_recover(self, nb, restart_slice):\n"
            "        if nb:\n"
            "            self._write_bookkeeping(nb, {})\n"
            "        restart_slice(['s'])\n")
        v = write_ahead.analyze(mod(src, SELFHEAL_REL))
        assert len(v) == 1
        assert v[0].check == "writeahead"
        assert "not dominated" in v[0].message

    def test_clean_twin_unconditional_persist(self):
        src = (
            "class RecoveryEngine:\n"
            "    def maybe_recover(self, nb, restart_slice):\n"
            "        self._write_bookkeeping(nb, {})\n"
            "        if nb:\n"
            "            restart_slice(['s'])\n")
        assert write_ahead.analyze(mod(src, SELFHEAL_REL)) == []

    def test_one_statement_cannot_satisfy_itself(self):
        # persist+destroy inside a single helper: ordering is invisible
        # statically, so the strict check still fires
        src = (
            "class RecoveryEngine:\n"
            "    def maybe_recover(self, nb, restart_slice):\n"
            "        self._both(nb, restart_slice)\n"
            "    def _both(self, nb, restart_slice):\n"
            "        self._write_bookkeeping(nb, {})\n"
            "        restart_slice(['s'])\n")
        v = write_ahead.analyze(mod(src, SELFHEAL_REL))
        assert len(v) == 1

    def test_callback_passed_by_name_is_destructive(self):
        src = (
            "class RecoveryEngine:\n"
            "    def maybe_recover(self, nb, restart_slice):\n"
            "        self._run(restart_slice)\n"
            "    def _run(self, fn):\n"
            "        fn()\n")
        assert len(write_ahead.analyze(mod(src, SELFHEAL_REL))) == 1

    def test_missing_configured_flow_is_flagged(self):
        src = "class RecoveryEngine:\n    pass\n"
        v = write_ahead.analyze(mod(src, SELFHEAL_REL))
        assert any("not found" in x.message for x in v)

    def test_repo_protocols_clean(self):
        for rel in (SELFHEAL_REL, SCHEDULER_REL):
            src = (Path(rel)).read_text()
            assert write_ahead.analyze(mod(src, rel)) == [], rel

    @pytest.mark.parametrize("which", ["A", "B"])
    def test_interleave_mutants_also_fail_statically(self, which):
        # the same textual mutants the explorer kills dynamically
        # (tests/test_interleave.py) must fail the static gate too
        import test_interleave as ti
        rel, muts = {
            "A": (SELFHEAL_REL, ti.MUTANT_A),
            "B": (SCHEDULER_REL, ti.MUTANT_B),
        }[which]
        src = Path(rel).read_text()
        for old, new in muts:
            assert src.count(old) == 1
            src = src.replace(old, new)
        v = write_ahead.analyze(mod(src, rel))
        assert v, f"mutant {which} not caught"
        assert all("not dominated" in x.message for x in v)


# ---------------------------------------------------------------------------
# lockset (lock-inconsistent field access)
# ---------------------------------------------------------------------------

CLUSTER_REL = "kubeflow_tpu/kube/cluster.py"


class TestLocksetAnalyzer:
    def test_mixed_access_flagged_per_field(self):
        src = (
            "class C:\n"
            "    def guarded(self):\n"
            "        with self._lock:\n"
            "            self._items['k'] = 1\n"
            "    def naked(self):\n"
            "        self._items.pop('k', None)\n")
        v = lockset.analyze(mod(src, CLUSTER_REL))
        assert len(v) == 1
        assert v[0].check == "lockset"
        assert v[0].context == "C._items"
        assert "naked:6" in v[0].message

    def test_clean_twin_consistent_locking(self):
        src = (
            "class C:\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._items['k'] = 1\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._items.pop('k', None)\n")
        assert lockset.analyze(mod(src, CLUSTER_REL)) == []

    def test_private_helper_inherits_callers_lock(self):
        src = (
            "class C:\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._flush()\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._items['y'] = 2\n"
            "    def _flush(self):\n"
            "        self._items['x'] = 1\n")
        assert lockset.analyze(mod(src, CLUSTER_REL)) == []

    def test_public_method_never_inherits(self):
        # a public method is callable from outside with nothing held
        src = (
            "class C:\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self.flush()\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._items['y'] = 2\n"
            "    def flush(self):\n"
            "        self._items['x'] = 1\n")
        v = lockset.analyze(mod(src, CLUSTER_REL))
        assert len(v) == 1 and v[0].context == "C._items"

    def test_read_only_after_init_exempt(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cfg = {}\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._x = self._cfg.get('k')\n"
            "    def b(self):\n"
            "        return self._cfg.get('k')\n")
        assert not any(v.context == "C._cfg"
                       for v in lockset.analyze(mod(src, CLUSTER_REL)))

    def test_init_callsites_do_not_dilute_inheritance(self):
        src = (
            "class C:\n"
            "    def __init__(self):\n"
            "        self._index()\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._index()\n"
            "    def b(self):\n"
            "        with self._lock:\n"
            "            self._items['y'] = 2\n"
            "    def _index(self):\n"
            "        self._items['x'] = 1\n")
        assert lockset.analyze(mod(src, CLUSTER_REL)) == []

    def test_out_of_scope_module_skipped(self):
        src = (
            "class C:\n"
            "    def a(self):\n"
            "        with self._lock:\n"
            "            self._items['k'] = 1\n"
            "    def naked(self):\n"
            "        self._items.pop('k', None)\n")
        assert lockset.analyze(mod(src)) == []


# ---------------------------------------------------------------------------
# allowlist semantics + the repo gate itself
# ---------------------------------------------------------------------------

class TestAllowlistAndGate:
    def test_stale_entries_fail(self, monkeypatch):
        monkeypatch.setattr(
            allowlist_mod, "ALLOWLIST",
            (Allow("clock", "kubeflow_tpu/nonexistent.py", "*",
                   "covers nothing"),))
        kept, allowed, stale = allowlist_mod.apply([])
        assert kept == [] and allowed == []
        assert len(stale) == 1 and "stale" in stale[0].message

    def test_every_entry_has_a_reason(self):
        for entry in allowlist_mod.ALLOWLIST:
            assert len(entry.reason.strip()) > 10, entry

    def test_repo_gate_clean(self):
        # the acceptance criterion: python -m ci.analyzers exits 0
        violations, stats = run_all()
        assert violations == [], "\n".join(v.render() for v in violations)
        assert stats["files"] > 100


# ---------------------------------------------------------------------------
# runtime sanitizer: deep-freeze
# ---------------------------------------------------------------------------

class TestStrictDeepFreeze:
    @pytest.fixture(autouse=True)
    def _strict(self, monkeypatch):
        monkeypatch.setenv("INVARIANTS_STRICT", "1")

    def test_mutate_after_list_raises(self):
        api = ApiServer()
        api.create(nb())
        o = api.list("Notebook")[0]
        with pytest.raises(FrozenMutationError):
            o.spec["image"] = "evil"
        with pytest.raises(FrozenMutationError):
            o.metadata.labels["a"] = "b"
        with pytest.raises(FrozenMutationError):
            o.body["spec"].setdefault("x", 1)

    def test_mutate_watch_event_object_raises(self):
        api = ApiServer()
        seen = []
        api.watch(seen.append, kinds=["Notebook"])
        api.create(nb())
        assert seen
        with pytest.raises(FrozenMutationError):
            seen[0].obj.status["phase"] = "Hacked"

    def test_empty_status_view_traps(self):
        api = ApiServer()
        api.create(nb())
        o = api.list("Notebook")[0]
        assert o.status == {}
        with pytest.raises(FrozenMutationError):
            o.status["c"] = 1

    def test_get_returns_private_mutable_copy(self):
        api = ApiServer()
        api.create(nb())
        fresh = api.get("Notebook", "d", "n")
        fresh.spec["image"] = "new"       # no raise
        api.update(fresh)
        assert api.get("Notebook", "d", "n").spec["image"] == "new"

    def test_deepcopy_of_frozen_is_mutable(self):
        api = ApiServer()
        api.create(nb())
        o = api.list("Notebook")[0].deepcopy()
        o.spec["image"] = "new"           # no raise
        o.metadata.labels["l"] = "v"      # no raise

    def test_error_carries_active_trace_id(self):
        api = ApiServer()
        api.create(nb())
        o = api.list("Notebook")[0]
        tracer = tracing.Tracer("test")
        with tracer.start_span("reconcile", trace_id="cafe" * 8):
            with pytest.raises(FrozenMutationError) as err:
                o.spec["image"] = "evil"
        assert "cafe" * 8 in str(err.value)

    def test_strict_off_keeps_zero_cost_path(self, monkeypatch):
        monkeypatch.delenv("INVARIANTS_STRICT", raising=False)
        api = ApiServer()
        api.create(nb())
        o = api.list("Notebook")[0]
        assert type(o.body) is dict  # no wrappers rebuilt
        lock = threading.Lock()
        assert invariants.tracked(lock, "x") is lock


# ---------------------------------------------------------------------------
# runtime sanitizer: lock tracking
# ---------------------------------------------------------------------------

class TestLockTracker:
    def test_seeded_inversion_raises(self):
        tr = LockTracker()
        a = TrackedLock(threading.Lock(), "A", tracker=tr)
        b = TrackedLock(threading.Lock(), "B", tracker=tr)
        with a:
            with b:
                pass
        with b:
            with pytest.raises(LockInversionError) as err:
                a.acquire()
        assert "'A'" in str(err.value) and "'B'" in str(err.value)

    def test_inversion_detected_across_threads(self):
        tr = LockTracker()
        a = TrackedLock(threading.Lock(), "A", tracker=tr)
        b = TrackedLock(threading.Lock(), "B", tracker=tr)

        def t1():
            with a:
                with b:
                    pass

        th = threading.Thread(target=t1)
        th.start()
        th.join()
        with b:
            with pytest.raises(LockInversionError):
                a.acquire()

    def test_consistent_order_is_fine(self):
        tr = LockTracker()
        a = TrackedLock(threading.Lock(), "A", tracker=tr)
        b = TrackedLock(threading.Lock(), "B", tracker=tr)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert tr.edges() == {"A": {"B"}}

    def test_reentrant_same_instance_transparent(self):
        tr = LockTracker()
        a = TrackedLock(threading.RLock(), "A", tracker=tr)
        with a:
            with a:       # RLock re-entry: no self-edge, no raise
                pass
        assert tr.edges() == {}

    def test_sibling_rank_order_enforced(self):
        # the per-kind shard locks: sorted-by-kind acquisition is legal,
        # unsorted raises (the PR 8 multi-shard subscribe contract)
        tr = LockTracker()
        pod = TrackedLock(threading.RLock(), "shard", rank="Pod",
                          tracker=tr)
        sts = TrackedLock(threading.RLock(), "shard", rank="StatefulSet",
                          tracker=tr)
        with pod:
            with sts:     # "Pod" < "StatefulSet": sorted, allowed
                pass
        with sts:
            with pytest.raises(LockInversionError):
                pod.acquire()

    def test_strict_mode_store_is_tracked(self, monkeypatch):
        monkeypatch.setenv("INVARIANTS_STRICT", "1")
        api = ApiServer()
        api.create(nb())
        api.list("Notebook")
        edges = invariants.GLOBAL_TRACKER.edges()
        assert any("shard.lock" in k for k in edges), edges
