"""Feast feature-store integration.

Port of notebook_feast_config.go: the `opendatahub.io/feast-integration`
label mounts the `{name}-feast-config` ConfigMap at
/opt/app-root/src/feast-config; removing the label unmounts it
(notebook_feast_config.go:34-146).
"""

from __future__ import annotations

from ..api.types import Notebook
from ..tpu.env import upsert_by_name
from . import constants as C


def is_feast_enabled(nb: Notebook) -> bool:
    return nb.metadata.labels.get(C.LABEL_FEAST_INTEGRATION) == "true"


def feast_configmap_name(nb: Notebook) -> str:
    return nb.name + C.FEAST_CONFIGMAP_SUFFIX


def mount_feast_config(nb: Notebook) -> None:
    """Idempotent volume + first-container mount
    (mountFeastConfig, notebook_feast_config.go:53-117)."""
    spec = nb.pod_spec
    upsert_by_name(
        spec.setdefault("volumes", []),
        {
            "name": C.FEAST_VOLUME_NAME,
            "configMap": {"name": feast_configmap_name(nb), "optional": True},
        },
    )
    containers = spec.get("containers") or []
    if not containers:
        return
    upsert_by_name(
        containers[0].setdefault("volumeMounts", []),
        {"name": C.FEAST_VOLUME_NAME, "mountPath": C.FEAST_MOUNT_PATH},
    )


def unmount_feast_config(nb: Notebook) -> None:
    """Remove the volume and every container's mount
    (unmountFeastConfig, notebook_feast_config.go:120-146)."""
    spec = nb.pod_spec
    volumes = [
        v for v in spec.get("volumes") or [] if v.get("name") != C.FEAST_VOLUME_NAME
    ]
    if volumes:
        spec["volumes"] = volumes
    else:
        spec.pop("volumes", None)
    for container in spec.get("containers") or []:
        mounts = [
            m
            for m in container.get("volumeMounts") or []
            if m.get("name") != C.FEAST_VOLUME_NAME
        ]
        if mounts:
            container["volumeMounts"] = mounts
        else:
            container.pop("volumeMounts", None)


def apply_feast_config(nb: Notebook) -> None:
    """Webhook entry point: mount when labeled, unmount when not
    (notebook_mutating_webhook.go:439-452)."""
    if is_feast_enabled(nb):
        mount_feast_config(nb)
    else:
        unmount_feast_config(nb)
