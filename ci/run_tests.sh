#!/usr/bin/env bash
# Unit + integration suite on the 8-device virtual CPU mesh
# (reference .github/workflows unit job analog).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/ -q "$@"
