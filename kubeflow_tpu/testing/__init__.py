"""Deterministic concurrency-testing harnesses (model checking).

`interleave` is the schedule-exploring model checker built on the
INVARIANTS_STRICT yield points (utils/invariants.py); see
docs/STATIC_ANALYSIS.md "Model checking protocols".
"""
