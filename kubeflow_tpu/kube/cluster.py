"""Fake cluster data plane: kubelet + scheduler + node inventory.

The reference never needed this — envtest has no kubelet and its single-pod
workloads never run in tests (SURVEY.md §4.5).  A TPU framework does need it:
multi-host slice scheduling must be testable without TPUs.  FakeCluster
realizes StatefulSets into Pods (honoring ordinals), schedules them onto fake
nodes with `google.com/tpu` allocatable capacity and
`cloud.google.com/gke-tpu-*` labels (the fake device plugin), marks them
Running/Ready, and emulates the OpenShift controller that mints a dockercfg
pull secret per ServiceAccount (which the ODH lock-removal flow waits on,
odh notebook_controller.go:155-186).
"""

from __future__ import annotations

import copy
from typing import Optional

from .errors import NotFoundError
from .meta import KubeObject, ObjectMeta, set_controller_reference
from .store import ApiServer, EventType, WatchEvent


def parse_quantity(q) -> float:
    """Minimal k8s resource.Quantity parser (enough for cpu/memory/tpu)."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q)
    suffixes = {
        "m": 1e-3, "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
        "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
    }
    for suf in sorted(suffixes, key=len, reverse=True):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * suffixes[suf]
    return float(s)


class FakeCluster:
    """Subscribes to the ApiServer and plays kubelet/scheduler/cloud.

    Fault-exempt by construction: an installed kube.faults.FaultPlan models
    client<->apiserver failures, and the data plane (kubelet, scheduler,
    the SA secret controller) lives on the cluster side of that boundary —
    its API calls run inside `api.fault_exempt()` so injected chaos breaks
    the controllers under test, never the cluster's own machinery."""

    def __init__(self, api: ApiServer, auto_ready: bool = True) -> None:
        self.api = api
        self.auto_ready = auto_ready
        self._pod_ip_counter = 0
        self._failed_pods: set[tuple[str, str]] = set()
        # (namespace, sts_name) -> failure reason: pods (re)created for a
        # poisoned StatefulSet come up Failed (see poison_statefulset)
        self._poisoned: dict[tuple[str, str], str] = {}
        api.watch(self._on_event)

    # -- node inventory --------------------------------------------------------
    def add_node(
        self,
        name: str,
        labels: Optional[dict[str, str]] = None,
        allocatable: Optional[dict[str, str]] = None,
    ) -> KubeObject:
        node = KubeObject(
            api_version="v1",
            kind="Node",
            metadata=ObjectMeta(name=name, labels=dict(labels or {})),
            body={
                "status": {
                    "allocatable": dict(allocatable or {"cpu": "8", "memory": "32Gi"}),
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
            },
        )
        with self.api.fault_exempt():
            return self.api.create(node)

    def add_tpu_slice_nodes(
        self,
        accelerator: str,
        topology: str,
        num_hosts: int,
        chips_per_host: int,
        name_prefix: str = "tpu-node",
    ) -> list[KubeObject]:
        """Fake GKE TPU node pool: one node per slice host, labeled the way
        GKE labels TPU nodes so nodeSelector scheduling is exercised."""
        nodes = []
        for i in range(num_hosts):
            nodes.append(
                self.add_node(
                    f"{name_prefix}-{accelerator}-{i}",
                    labels={
                        "cloud.google.com/gke-tpu-accelerator": accelerator,
                        "cloud.google.com/gke-tpu-topology": topology,
                    },
                    allocatable={
                        "cpu": "96",
                        "memory": "192Gi",
                        "google.com/tpu": str(chips_per_host),
                    },
                )
            )
        return nodes

    # -- failure injection -----------------------------------------------------
    def fail_pod(self, namespace: str, name: str, reason: str = "TPUUnhealthy") -> None:
        """Chaos hook: mark a pod failed (analog of the operator-chaos harness,
        chaos/knowledge/workbenches.yaml)."""
        with self.api.fault_exempt():
            self._fail_pod(namespace, name, reason)

    def _fail_pod(self, namespace: str, name: str, reason: str) -> None:
        pod = self.api.get("Pod", namespace, name)
        pod.status = {
            "phase": "Failed",
            "reason": reason,
            "conditions": [{"type": "Ready", "status": "False", "reason": reason}],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": False,
                    "state": {"terminated": {"exitCode": 137, "reason": reason}},
                }
                for c in pod.spec.get("containers", [])
            ],
        }
        self._failed_pods.add((namespace, name))
        self.api.update_status(pod)
        self._sync_sts_status_for_pod(pod)

    def crashloop_pod(self, namespace: str, name: str) -> None:
        """Chaos hook: the pod's container is stuck in the kubelet's
        CrashLoopBackOff — pod phase stays Running but the container
        waits out restart backoffs forever and the pod never turns
        Ready (the state core.selfheal classifies as crash-loop)."""
        with self.api.fault_exempt():
            pod = self.api.get("Pod", namespace, name)
            pod.status = {
                "phase": "Running",
                "conditions": [
                    {"type": "PodScheduled", "status": "True"},
                    {"type": "Ready", "status": "False",
                     "reason": "ContainersNotReady"},
                ],
                "containerStatuses": [
                    {
                        "name": c.get("name", "main"),
                        "ready": False,
                        "restartCount": 7,
                        "state": {"waiting": {
                            "reason": "CrashLoopBackOff",
                            "message": "back-off 5m0s restarting failed "
                                       "container",
                        }},
                    }
                    for c in pod.spec.get("containers", [])
                ],
            }
            self.api.update_status(pod)
            self._sync_sts_status_for_pod(pod)

    def delete_node(self, name: str) -> None:
        """Chaos hook: node-driven disruption (preemption / pool
        scale-down): the Node object vanishes while its pods linger with
        a dangling nodeName — exactly what a TPU host preemption looks
        like to a controller between node-controller sweeps."""
        with self.api.fault_exempt():
            try:
                self.api.delete("Node", "", name)
            except NotFoundError:
                pass

    def poison_statefulset(self, namespace: str, name: str,
                           reason: str = "TPUUnhealthy") -> None:
        """Chaos hook: every pod (re)created for this StatefulSet comes up
        Failed — a permanently broken slice (bad host, torn interconnect).
        Self-healing must exhaust its restart budget on it, not churn
        forever.  Existing pods fail immediately."""
        self._poisoned[(namespace, name)] = reason
        with self.api.fault_exempt():
            for pod in self.api.list("Pod", namespace=namespace):
                ref = pod.metadata.controller_owner()
                if ref is not None and ref.kind == "StatefulSet" \
                        and ref.name == name:
                    self._fail_pod(namespace, pod.name, reason)

    def heal_statefulset(self, namespace: str, name: str) -> None:
        """Undo poison_statefulset: the next slice restart comes up
        clean (the operator replaced the broken hardware)."""
        self._poisoned.pop((namespace, name), None)

    # -- event loop ------------------------------------------------------------
    def _on_event(self, ev: WatchEvent) -> None:
        with self.api.fault_exempt():
            self._handle_event(ev)

    def _handle_event(self, ev: WatchEvent) -> None:
        kind = ev.obj.kind
        if kind == "StatefulSet":
            if ev.type in (EventType.ADDED, EventType.MODIFIED):
                self._reconcile_sts(ev.obj.namespace, ev.obj.name)
            elif ev.type == EventType.DELETED:
                pass  # pods cascade via owner-ref GC
        elif kind == "Pod" and ev.type == EventType.DELETED:
            self._failed_pods.discard((ev.obj.namespace, ev.obj.name))
            owner = ev.obj.metadata.controller_owner()
            if owner is not None and owner.kind == "StatefulSet":
                self._reconcile_sts(ev.obj.namespace, owner.name)
            self._retry_pending_pods()  # freed capacity may unblock others
        elif kind == "Node" and ev.type in (EventType.ADDED, EventType.MODIFIED):
            self._retry_pending_pods()
        elif kind == "ServiceAccount" and ev.type == EventType.ADDED:
            self._mint_pull_secret(ev.obj)

    # -- kubelet/scheduler -----------------------------------------------------
    def _reconcile_sts(self, namespace: str, name: str) -> None:
        sts = self.api.try_get("StatefulSet", namespace, name)
        if sts is None:
            return
        want = int(sts.spec.get("replicas", 1))
        for ordinal in range(want):
            pod_name = f"{name}-{ordinal}"
            if self.api.try_get("Pod", namespace, pod_name) is None:
                self._create_pod(sts, ordinal)
        # scale-down: delete pods beyond want (highest ordinal first)
        extra = [
            p
            for p in self.api.list("Pod", namespace=namespace)
            if (ref := p.metadata.controller_owner()) is not None
            and ref.kind == "StatefulSet"
            and ref.name == name
            and _ordinal_of(p.name, name) is not None
            and _ordinal_of(p.name, name) >= want
        ]
        for p in sorted(extra, key=lambda p: -(_ordinal_of(p.name, name) or 0)):
            try:
                self.api.delete("Pod", namespace, p.name)
            except NotFoundError:
                pass
        self._sync_sts_status(namespace, name)

    def _create_pod(self, sts: KubeObject, ordinal: int) -> None:
        namespace, name = sts.namespace, f"{sts.name}-{ordinal}"
        template = sts.spec.get("template", {})
        tmeta = template.get("metadata", {})
        pod = KubeObject(
            api_version="v1",
            kind="Pod",
            metadata=ObjectMeta(
                name=name,
                namespace=namespace,
                labels=dict(tmeta.get("labels") or {}),
                annotations=dict(tmeta.get("annotations") or {}),
            ),
            body={"spec": copy.deepcopy(template.get("spec", {}))},
        )
        # indexed-statefulset identity: hostname + subdomain give each worker
        # a stable DNS name through the headless service — the property
        # TPU_WORKER_HOSTNAMES depends on
        pod.spec["hostname"] = name
        if sts.spec.get("serviceName"):
            pod.spec["subdomain"] = sts.spec["serviceName"]
        pod.metadata.labels["apps.kubernetes.io/pod-index"] = str(ordinal)
        pod.metadata.labels.setdefault(
            "statefulset.kubernetes.io/pod-name", name
        )
        sts_live = self.api.get("StatefulSet", namespace, sts.name)
        set_controller_reference(sts_live, pod)

        node = self._schedule(pod)
        pod = self.api.create(pod)
        if node is None:
            pod.status = {
                "phase": "Pending",
                "conditions": [
                    {
                        "type": "PodScheduled",
                        "status": "False",
                        "reason": "Unschedulable",
                        "message": "no node satisfies nodeSelector/resources",
                    }
                ],
            }
            self.api.update_status(pod)
            return
        pod.spec["nodeName"] = node.name
        pod = self.api.update(pod)
        poison = self._poisoned.get((namespace, sts.name))
        if poison is not None:
            self._fail_pod(namespace, name, poison)
        elif self.auto_ready:
            self._mark_running(pod)

    def _mark_running(self, pod: KubeObject) -> None:
        self._pod_ip_counter += 1
        pod.status = {
            "phase": "Running",
            "podIP": f"10.0.{self._pod_ip_counter // 256}.{self._pod_ip_counter % 256}",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Initialized", "status": "True"},
                {"type": "ContainersReady", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": True,
                    "restartCount": 0,
                    "image": c.get("image", ""),
                    "state": {"running": {"startedAt": pod.metadata.creation_timestamp}},
                }
                for c in pod.spec.get("containers", [])
            ],
        }
        self.api.update_status(pod)

    def _schedule(self, pod: KubeObject) -> Optional[KubeObject]:
        selector = pod.spec.get("nodeSelector") or {}
        requests: dict[str, float] = {}
        for c in pod.spec.get("containers", []):
            for res, q in (c.get("resources", {}).get("requests") or {}).items():
                requests[res] = requests.get(res, 0.0) + parse_quantity(q)
        for node in self.api.list("Node"):
            node_labels = node.metadata.labels
            if not all(node_labels.get(k) == v for k, v in selector.items()):
                continue
            alloc = node.body.get("status", {}).get("allocatable", {})
            # subtract pods already bound to this node
            used: dict[str, float] = {}
            for p in self.api.list("Pod"):
                if p.spec.get("nodeName") != node.name:
                    continue
                for c in p.spec.get("containers", []):
                    for res, q in (c.get("resources", {}).get("requests") or {}).items():
                        used[res] = used.get(res, 0.0) + parse_quantity(q)
            if all(
                parse_quantity(alloc.get(res, 0)) - used.get(res, 0.0) >= need
                for res, need in requests.items()
            ):
                return node
        return None

    def _retry_pending_pods(self) -> None:
        """Re-run scheduling for pods that previously found no fitting node
        (real kube-scheduler retries on Node add / capacity change)."""
        for pod in self.api.list("Pod"):
            status = pod.body.get("status", {})
            if status.get("phase") != "Pending" or pod.spec.get("nodeName"):
                continue
            node = self._schedule(pod)
            if node is None:
                continue
            pod.spec["nodeName"] = node.name
            pod = self.api.update(pod)
            ref = pod.metadata.controller_owner()
            poison = self._poisoned.get((pod.namespace, ref.name)) \
                if ref is not None and ref.kind == "StatefulSet" else None
            if poison is not None:
                self._fail_pod(pod.namespace, pod.name, poison)
            elif self.auto_ready:
                self._mark_running(pod)
            self._sync_sts_status_for_pod(pod)

    def _sync_sts_status_for_pod(self, pod: KubeObject) -> None:
        ref = pod.metadata.controller_owner()
        if ref is not None and ref.kind == "StatefulSet":
            self._sync_sts_status(pod.namespace, ref.name)

    def _sync_sts_status(self, namespace: str, name: str) -> None:
        sts = self.api.try_get("StatefulSet", namespace, name)
        if sts is None:
            return
        pods = [
            p
            for p in self.api.list("Pod", namespace=namespace)
            if (ref := p.metadata.controller_owner()) is not None
            and ref.kind == "StatefulSet"
            and ref.name == name
        ]
        ready = sum(
            1
            for p in pods
            if any(
                c.get("type") == "Ready" and c.get("status") == "True"
                for c in p.body.get("status", {}).get("conditions", [])
            )
        )
        sts.status = {
            "replicas": len(pods),
            "readyReplicas": ready,
            "currentReplicas": len(pods),
            "observedGeneration": sts.metadata.generation,
        }
        self.api.update_status(sts)

    # -- openshift service-account controller ---------------------------------
    def _mint_pull_secret(self, sa: KubeObject) -> None:
        secret = KubeObject(
            api_version="v1",
            kind="Secret",
            metadata=ObjectMeta(
                name=f"{sa.name}-dockercfg",
                namespace=sa.namespace,
                annotations={"kubernetes.io/service-account.name": sa.name},
            ),
            body={"type": "kubernetes.io/dockercfg", "data": {".dockercfg": "e30="}},
        )
        try:
            self.api.create(secret)
        except Exception:
            pass
        live = self.api.get("ServiceAccount", sa.namespace, sa.name)
        secrets = live.body.setdefault("imagePullSecrets", [])
        if {"name": secret.name} not in secrets:
            secrets.append({"name": secret.name})
            self.api.update(live)


def _ordinal_of(pod_name: str, sts_name: str) -> Optional[int]:
    prefix = sts_name + "-"
    if not pod_name.startswith(prefix):
        return None
    suffix = pod_name[len(prefix):]
    return int(suffix) if suffix.isdigit() else None
