"""Round-5 seq-2048 MFU sweep: the flash-tile-at-2k hypothesis.

The round-4 data says the hardware runs at ~89% of the chip's chained-
matmul ceiling at seq 4096 (flash 512x512, batch 20 = 82k tokens/step)
but only ~76% at seq 2048 (flash 256x256, batch 48 = 98k tokens/step).
The configs differ in batch and tile size — 512x512 OOMed at batch 48.
This sweep separates the two: batch 40 at 2k carries the SAME tokens/step
as the 4k winner and fits the bigger tiles.

Reuses ci/mfu_sweep.py --run for each config (one subprocess per config
so OOMs can't poison later runs); appends to ci/sweep_r5_results.jsonl;
re-measures the top 2 to reject relay half-speed windows.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).resolve().parent
RESULTS = HERE / "sweep_r5_results.jsonl"

# committed bench config knobs (bench.py): loss_chunks=32 in BENCH_CHIP,
# mu bf16 via default_optimizer(mu_dtype=...)
COMMON = {"mu_dtype": "bfloat16", "num_steps": 12}

GRID: list[dict] = [
    {"batch": 48},  # control: reproduce the committed 0.391
    {"batch": 40, "flash_block_q": 512, "flash_block_k": 512},
    {"batch": 40},  # batch control at the committed tiles
    {"batch": 48, "flash_block_q": 512, "flash_block_k": 256},
    {"batch": 48, "flash_block_q": 256, "flash_block_k": 512},
    {"batch": 44, "flash_block_q": 512, "flash_block_k": 512},
    {"batch": 48, "flash_block_q": 512, "flash_block_k": 512},  # OOM check
    {"batch": 40, "flash_block_q": 512, "flash_block_k": 1024},
    {"batch": 40, "flash_block_q": 1024, "flash_block_k": 512},
]


def run_spec(spec: dict) -> dict:
    proc = subprocess.run(
        [sys.executable, str(HERE / "mfu_sweep.py"), "--run",
         json.dumps(spec)],
        capture_output=True, text=True, timeout=1200,
    )
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    try:
        return json.loads(line)
    except (json.JSONDecodeError, IndexError):
        return {"error": (proc.stderr or "no output")[-1500:],
                "rc": proc.returncode}


def main() -> None:
    results = []
    for spec in GRID:
        merged = {**COMMON, **spec}
        print(f"run {json.dumps(spec, sort_keys=True)}", flush=True)
        result = run_spec(merged)
        record = {"spec": merged, **result}
        results.append(record)
        with RESULTS.open("a") as f:
            f.write(json.dumps(record) + "\n")
        short = {k: v for k, v in result.items() if k != "error"}
        print(f"    -> {json.dumps(short) if short else 'FAILED'}", flush=True)

    ok = [r for r in results if "mfu" in r]
    ok.sort(key=lambda r: -r["mfu"])
    # confirmation pass: the relay intermittently halves a whole window, so
    # the top 2 get a second independent measurement
    print("\n=== confirm top 2 ===", flush=True)
    for r in ok[:2]:
        result = run_spec(r["spec"])
        record = {"spec": r["spec"], "confirm": True, **result}
        with RESULTS.open("a") as f:
            f.write(json.dumps(record) + "\n")
        print(f"{json.dumps(r['spec'], sort_keys=True)} -> "
              f"{json.dumps({k: v for k, v in result.items() if k != 'error'})}",
              flush=True)


if __name__ == "__main__":
    main()
