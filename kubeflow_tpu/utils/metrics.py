"""Minimal Prometheus-style metrics registry (counters + gauges with labels)
with text exposition, standing in for the controller-runtime metrics registry
the reference uses (pkg/metrics/metrics.go:13-64)."""

from __future__ import annotations

import threading
from typing import Callable


class _Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._values: dict[tuple[str, ...], float] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str) -> "_Child":
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        return _Child(self, tuple(values))

    def _set(self, key: tuple[str, ...], v: float) -> None:
        with self._lock:
            self._values[key] = v

    def _add(self, key: tuple[str, ...], v: float) -> None:
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + v

    def value(self, *values: str) -> float:
        return self._values.get(tuple(values), 0.0)

    def kind(self) -> str:
        raise NotImplementedError

    def collect(self) -> dict[tuple[str, ...], float]:
        return dict(self._values)


class _Child:
    def __init__(self, metric: _Metric, key: tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric._add(self._key, amount)

    def set(self, v: float) -> None:
        self._metric._set(self._key, v)


class Counter(_Metric):
    def kind(self) -> str:
        return "counter"

    def inc(self, amount: float = 1.0) -> None:
        self._add((), amount)


class Gauge(_Metric):
    def kind(self) -> str:
        return "gauge"

    def set(self, v: float) -> None:
        self._set((), v)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    def collect(self) -> dict[tuple[str, ...], float]:
        fn = getattr(self, "_fn", None)
        if fn is not None:
            self._set((), float(fn()))
        return super().collect()


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []

    def counter(
        self, name: str, help_: str = "", labels: tuple[str, ...] = ()
    ) -> Counter:
        m = Counter(name, help_, labels)
        self._metrics.append(m)
        return m

    def gauge(
        self, name: str, help_: str = "", labels: tuple[str, ...] = ()
    ) -> Gauge:
        m = Gauge(name, help_, labels)
        self._metrics.append(m)
        return m

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: list[str] = []
        for m in self._metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind()}")
            for key, v in sorted(m.collect().items()):
                if key:
                    labels = ",".join(
                        f'{n}="{val}"' for n, val in zip(m.label_names, key)
                    )
                    lines.append(f"{m.name}{{{labels}}} {v:g}")
                else:
                    lines.append(f"{m.name} {v:g}")
        return "\n".join(lines) + "\n"
