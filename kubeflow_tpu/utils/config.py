"""Env-var configuration surface.

The reference's de-facto config system is environment variables fed by
kustomize params ConfigMaps (SURVEY.md §5 "Config/flag system";
culling_controller.go:32-42,534-567, notebook_controller.go:238,514,587,596).
We keep the same variable names for drop-in compatibility but bind them into
an injectable Config object so tests don't mutate process env.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Mapping, Optional


SA_NAMESPACE_FILE = "/var/run/secrets/kubernetes.io/serviceaccount/namespace"


def detect_namespace(default: str = "default",
                     env: Optional[Mapping[str, str]] = None) -> str:
    """Controller namespace: K8S_NAMESPACE env var, else the in-cluster
    ServiceAccount token mount, else `default` (odh main.go:127-139).
    The single source of truth — kube.client re-exports this.  Passing an
    explicit `env` mapping keeps the lookup hermetic (an empty mapping never
    falls through to os.environ or the SA mount — tests with from_env({})
    must not pick up ambient cluster state)."""
    hermetic = env is not None
    env = env if env is not None else os.environ
    ns = env.get("K8S_NAMESPACE", "")
    if ns:
        return ns
    if hermetic:
        return default
    try:
        with open(SA_NAMESPACE_FILE) as f:
            return f.read().strip() or default
    except OSError:
        return default


def _bool(env: Mapping[str, str], key: str, default: bool) -> bool:
    v = env.get(key)
    if v is None:
        return default
    return v.strip().lower() == "true"


def _int(env: Mapping[str, str], key: str, default: int) -> int:
    v = env.get(key)
    if v is None or not v.strip():
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _float(env: Mapping[str, str], key: str, default: float) -> float:
    """Float knob parse.  Duration knobs MUST come through here, not
    `_int`: sub-second values like RECOVERY_BACKOFF_BASE_S=0.5 (fast soak
    configs) silently truncated to the default under int()."""
    v = env.get(key)
    if v is None or not v.strip():
        return default
    try:
        return float(v)
    except ValueError:
        return default


@dataclass
class CoreConfig:
    """Core notebook-controller config (reference main.go:58-148 flags +
    controller env vars)."""

    # culling (culling_controller.go:32-42)
    enable_culling: bool = False
    cull_idle_time_min: int = 1440       # CULL_IDLE_TIME
    idleness_check_period_min: int = 1   # IDLENESS_CHECK_PERIOD
    cluster_domain: str = "cluster.local"
    dev: bool = False
    # workload rendering (notebook_controller.go:238,514)
    use_istio: bool = False
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    add_fsgroup: bool = True
    # TPU extensions
    checkpoint_before_cull: bool = False  # signal workers before slice stop
    # workqueue rate limiting (kube.controller.default_rate_limiter):
    # per-item exponential backoff base/cap + overall token bucket,
    # mirroring controller-runtime's DefaultControllerRateLimiter
    workqueue_base_delay_s: float = 0.005   # WORKQUEUE_BASE_DELAY_MS / 1000
    workqueue_max_delay_s: float = 1000.0   # WORKQUEUE_MAX_DELAY_S
    workqueue_qps: float = 10.0             # WORKQUEUE_QPS
    workqueue_burst: int = 100              # WORKQUEUE_BURST
    # parallel reconcile workers (controller-runtime MaxConcurrentReconciles
    # analog, shared across controllers): per-key serialization always holds
    workqueue_workers: int = 1              # WORKQUEUE_WORKERS
    # per-kind watch-history ring size on the in-memory ApiServer
    # (kube/store.py): each kind retains this many events for
    # subscribe(since_rv) resume; a resume older than a kind's retained
    # window gets 410 Gone and relists.  Sized per kind, so one chatty
    # kind cannot evict another's resume window.
    watch_history_size: int = 2048          # WATCH_HISTORY_SIZE
    # slice-atomic self-healing (core.selfheal): budgeted recovery of
    # disrupted TPU slices.  Backoff between slice restarts is exponential
    # (base * 2^n, capped); at most recovery_max_attempts restarts within a
    # sliding recovery_window_s before the slice is declared
    # RecoveryExhausted; a worker Pending longer than
    # recovery_pending_deadline_s counts as disrupted.
    enable_self_healing: bool = True          # ENABLE_SELF_HEALING
    recovery_backoff_base_s: float = 10.0     # RECOVERY_BACKOFF_BASE_S
    recovery_backoff_max_s: float = 300.0     # RECOVERY_BACKOFF_MAX_S
    recovery_max_attempts: int = 5            # RECOVERY_MAX_ATTEMPTS
    recovery_window_s: float = 3600.0         # RECOVERY_WINDOW_S
    recovery_pending_deadline_s: float = 300.0  # RECOVERY_PENDING_DEADLINE_S
    # session-state tier (core/sessionstate.py + runtime/checkpoint.py):
    # a non-empty store URI turns on the checkpoint-sidecar contract in the
    # rendered pod template and teaches the RecoveryEngine the `migrate`
    # verb.  A checkpoint older than checkpoint_max_age_s is stale — the
    # engine falls back to a bare restart rather than restoring an ancient
    # session.  checkpoint_signal_root hosts the per-notebook cull-signal
    # dirs the CullSignalWatcher polls (empty = annotation handshake only).
    checkpoint_store_uri: str = ""            # CHECKPOINT_STORE_URI
    checkpoint_interval_s: float = 300.0      # CHECKPOINT_INTERVAL_S
    checkpoint_max_age_s: float = 600.0       # CHECKPOINT_MAX_AGE_S
    checkpoint_signal_root: str = ""          # CHECKPOINT_SIGNAL_ROOT
    # replicated-kernel tier (spec.replication + core/selfheal.py promote
    # verb): a follower counts as caught up — and is eligible for
    # promotion — when it has applied the latest base snapshot and trails
    # the delta chain head by at most replication_max_lag deltas.
    # slo_promotion_p99_s bounds the promote verb's latency objective
    # (<= 0 disables it); promotions also land in the shared
    # notebook_disruption_recovery_seconds stream.
    replication_max_lag: int = 2              # REPLICATION_MAX_LAG
    slo_promotion_p99_s: float = 1.0          # SLO_PROMOTION_P99_S
    # topology-aware slice scheduler + warm-pool autoscaler
    # (core/scheduler.py).  When enabled, TPU workload StatefulSets are
    # gang-gated on an all-or-nothing placement intent, and a warm pool of
    # pre-provisioned slices per shape (WARMPOOL_SHAPES, e.g.
    # "v5e:4x4,v5p:2x2x2") turns notebook start into a claim instead of a
    # cold slice provision (warmpool_provision_s of fake/real time).  The
    # autoscaler grows the per-shape target on misses (bounded by
    # warmpool_max_size) and decays it back toward warmpool_size while the
    # observed hit rate holds above warmpool_target_hit_rate.
    enable_slice_scheduler: bool = False      # ENABLE_SLICE_SCHEDULER
    warmpool_size: int = 0                    # WARMPOOL_SIZE
    warmpool_shapes: str = ""                 # WARMPOOL_SHAPES
    warmpool_provision_s: float = 120.0       # WARMPOOL_PROVISION_S
    warmpool_max_size: int = 64               # WARMPOOL_MAX_SIZE
    warmpool_target_hit_rate: float = 0.9     # WARMPOOL_TARGET_HIT_RATE
    warmpool_decay_s: float = 600.0           # WARMPOOL_DECAY_S
    # tenancy layer (core/scheduler.py admission gate + core/preemption.py
    # checkpoint-then-preempt).  A gang over its tenant's chip quota or
    # weighted fair share queues (sliceHealth="Queued") and is re-examined
    # every queue_requeue_s; dequeue order is the aged weighted fair-share
    # score priority_rank + weight * age / queue_aging_s, so every queued
    # gang's score grows without bound and starvation is impossible (a
    # "low" gang overtakes an idle "high" slot after
    # (200 - 0) / weight * queue_aging_s seconds).  enable_preemption
    # gates checkpoint-then-preempt; slo_placement_p99_s bounds the
    # queue-wait (time-to-placement) latency objective (<= 0 disables it).
    enable_preemption: bool = True            # ENABLE_PREEMPTION
    queue_requeue_s: float = 15.0             # QUEUE_REQUEUE_S
    queue_aging_s: float = 60.0               # QUEUE_AGING_S
    slo_placement_p99_s: float = 0.0          # SLO_PLACEMENT_P99_S
    # fleet SLO engine (utils/slo.py): declared objectives over the
    # existing metric streams, evaluated into multi-window burn rates at
    # every scrape.  Latency knobs are p99 ceilings (at most 1% of events
    # may exceed them per window); a knob <= 0 disables its objective.
    # Alerts fire when EVERY window (slo_short_window_s AND
    # slo_long_window_s) burns the error budget faster than
    # slo_burn_alert_threshold, and resolve when the short window
    # recovers — served at /debug/alerts.
    slo_time_to_ready_p99_s: float = 600.0      # SLO_TIME_TO_READY_P99_S
    slo_event_to_reconcile_p99_s: float = 30.0  # SLO_EVENT_TO_RECONCILE_P99_S
    slo_reconcile_error_rate: float = 0.01      # SLO_RECONCILE_ERROR_RATE
    slo_recovery_p99_s: float = 300.0           # SLO_RECOVERY_DURATION_P99_S
    slo_warmpool_hit_rate: float = 0.6          # SLO_WARMPOOL_HIT_RATE
    slo_short_window_s: float = 300.0           # SLO_SHORT_WINDOW_S
    slo_long_window_s: float = 3600.0           # SLO_LONG_WINDOW_S
    slo_burn_alert_threshold: float = 2.0       # SLO_BURN_ALERT_THRESHOLD
    # continuous sampling profiler (utils/profiler.py): always-on
    # (controller, phase) CPU attribution served at /debug/profile.  Off
    # by default — tier-1 tests and FakeClock harnesses must not run a
    # real-time sampler thread; its self-overhead is exported as
    # notebook_profiler_overhead_ratio when on.
    enable_continuous_profiler: bool = False    # ENABLE_CONTINUOUS_PROFILER
    profiler_interval_ms: float = 10.0          # PROFILER_INTERVAL_MS
    # data-plane telemetry (runtime/telemetry.py TelemetryAgent publishes
    # rolling summaries into pod annotations; core/telemetry.py
    # WorkerTelemetryAggregator rolls them up at every scrape).  A worker
    # whose rolling step time exceeds dataplane_straggler_ratio x the
    # slice median (with at least dataplane_straggler_min_workers
    # reporting) fires the straggler gauge + Warning event —
    # observability only, never a healing action.  dataplane_mfu_target
    # feeds the (knob-disabled) fleet-MFU SLO objective's low/ok verdict
    # counter; slo_fleet_mfu / slo_straggler_rate <= 0 keep those
    # objectives off.
    dataplane_straggler_ratio: float = 1.5      # DATAPLANE_STRAGGLER_RATIO
    dataplane_straggler_min_workers: int = 2    # DATAPLANE_STRAGGLER_MIN_WORKERS
    dataplane_mfu_target: float = 0.0           # DATAPLANE_MFU_TARGET
    telemetry_ring_size: int = 512              # TELEMETRY_RING_SIZE
    telemetry_publish_interval_s: float = 30.0  # TELEMETRY_PUBLISH_INTERVAL_S
    slo_fleet_mfu: float = 0.0                  # SLO_FLEET_MFU
    slo_straggler_rate: float = 0.0             # SLO_STRAGGLER_RATE
    # active-active sharded control plane (kube/shard.py): SHARD_COUNT > 1
    # runs that many in-process manager replicas over a fenced
    # ControlPlaneShardMap; shard_lease_duration_s is each member's lease
    # (a dead replica is evicted once its lease ages past it).
    # slo_shard_handoff_p99_s bounds the handoff duration (commit ->
    # last ack) — a stalled handoff burns that objective's budget and
    # fires the multi-window burn alert; <= 0 disables it.
    shard_count: int = 1                        # SHARD_COUNT
    shard_lease_duration_s: float = 15.0        # SHARD_LEASE_DURATION_S
    slo_shard_handoff_p99_s: float = 0.0        # SLO_SHARD_HANDOFF_P99_S
    # schedule-exploring model checker (testing/interleave.py): per-test
    # exploration budget — distinct-schedule cap and wall cap, whichever
    # bites first.  The CI smoke lane runs the defaults; the chaos-soak
    # lane raises them via INTERLEAVE_DEEP (ci/chaos_soak.sh).
    interleave_max_schedules: int = 1200        # INTERLEAVE_MAX_SCHEDULES
    interleave_budget_s: float = 60.0           # INTERLEAVE_BUDGET_S
    # lifecycle stage ledger (utils/lifecycle.py): per-notebook
    # event->ready critical-path attribution behind /debug/criticalpath.
    # lifecycle_max_notebooks bounds the LRU of open/finalized ledgers,
    # lifecycle_samples_per_stage the per-stage p99 sample ring, and
    # lifecycle_tolerance the conservation check's relative-error gate.
    lifecycle_max_notebooks: int = 4096         # LIFECYCLE_MAX_NOTEBOOKS
    lifecycle_samples_per_stage: int = 2048     # LIFECYCLE_SAMPLES_PER_STAGE
    lifecycle_tolerance: float = 0.05           # LIFECYCLE_TOLERANCE
    # in-process time-series store (utils/tsdb.py): per-series raw ring
    # plus 10s/60s downsampled tiers, fed once per metrics scrape and
    # served at /debug/timeline; tsdb_max_series caps the name space.
    tsdb_raw_capacity: int = 512                # TSDB_RAW_CAPACITY
    tsdb_tier10_capacity: int = 1024            # TSDB_TIER10_CAPACITY
    tsdb_tier60_capacity: int = 1024            # TSDB_TIER60_CAPACITY
    tsdb_max_series: int = 256                  # TSDB_MAX_SERIES
    # tenant metering ledger (utils/metering.py): per-namespace
    # chip-second accounting + control-plane attribution behind
    # /debug/tenants.  metering_max_tenants bounds the tenant table
    # (overflow folds into the reserved "other" tenant),
    # metering_max_notebooks the live placement-meter LRU, and
    # metering_tolerance the conservation gate.  A tenant whose rolling
    # control-plane share exceeds tenant_fairshare_factor x fair share
    # while another tenant's event->reconcile p99 is degraded is flagged
    # noisy; tenant_top_k sizes the /debug/tenants + TSDB top-consumer
    # views.  slo_tenant_fairness > 0 enables the tenant_fairness SLO
    # objective at that allowed noisy-verdict ratio.
    metering_max_tenants: int = 64              # METERING_MAX_TENANTS
    metering_max_notebooks: int = 4096          # METERING_MAX_NOTEBOOKS
    metering_tolerance: float = 0.05            # METERING_TOLERANCE
    tenant_fairshare_factor: float = 3.0        # TENANT_FAIRSHARE_FACTOR
    tenant_top_k: int = 8                       # TENANT_TOP_K
    slo_tenant_fairness: float = 0.01           # SLO_TENANT_FAIRNESS

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "CoreConfig":
        env = env if env is not None else os.environ
        return cls(
            enable_culling=_bool(env, "ENABLE_CULLING", False),
            cull_idle_time_min=_int(env, "CULL_IDLE_TIME", 1440),
            idleness_check_period_min=_int(env, "IDLENESS_CHECK_PERIOD", 1),
            cluster_domain=env.get("CLUSTER_DOMAIN", "cluster.local"),
            dev=_bool(env, "DEV", False),
            use_istio=_bool(env, "USE_ISTIO", False),
            istio_gateway=env.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"),
            istio_host=env.get("ISTIO_HOST", "*"),
            add_fsgroup=_bool(env, "ADD_FSGROUP", True),
            checkpoint_before_cull=_bool(env, "CHECKPOINT_BEFORE_CULL", False),
            workqueue_base_delay_s=_int(
                env, "WORKQUEUE_BASE_DELAY_MS", 5) / 1000.0,
            workqueue_max_delay_s=float(
                _int(env, "WORKQUEUE_MAX_DELAY_S", 1000)),
            workqueue_qps=float(_int(env, "WORKQUEUE_QPS", 10)),
            workqueue_burst=_int(env, "WORKQUEUE_BURST", 100),
            workqueue_workers=max(1, _int(env, "WORKQUEUE_WORKERS", 1)),
            watch_history_size=max(1, _int(env, "WATCH_HISTORY_SIZE", 2048)),
            enable_self_healing=_bool(env, "ENABLE_SELF_HEALING", True),
            recovery_backoff_base_s=_float(
                env, "RECOVERY_BACKOFF_BASE_S", 10.0),
            recovery_backoff_max_s=_float(
                env, "RECOVERY_BACKOFF_MAX_S", 300.0),
            recovery_max_attempts=_int(env, "RECOVERY_MAX_ATTEMPTS", 5),
            recovery_window_s=_float(env, "RECOVERY_WINDOW_S", 3600.0),
            recovery_pending_deadline_s=_float(
                env, "RECOVERY_PENDING_DEADLINE_S", 300.0),
            checkpoint_store_uri=env.get("CHECKPOINT_STORE_URI", ""),
            checkpoint_interval_s=_float(
                env, "CHECKPOINT_INTERVAL_S", 300.0),
            checkpoint_max_age_s=_float(
                env, "CHECKPOINT_MAX_AGE_S", 600.0),
            checkpoint_signal_root=env.get("CHECKPOINT_SIGNAL_ROOT", ""),
            replication_max_lag=max(0, _int(
                env, "REPLICATION_MAX_LAG", 2)),
            slo_promotion_p99_s=_float(env, "SLO_PROMOTION_P99_S", 1.0),
            enable_slice_scheduler=_bool(
                env, "ENABLE_SLICE_SCHEDULER", False),
            warmpool_size=max(0, _int(env, "WARMPOOL_SIZE", 0)),
            warmpool_shapes=env.get("WARMPOOL_SHAPES", ""),
            warmpool_provision_s=_float(env, "WARMPOOL_PROVISION_S", 120.0),
            warmpool_max_size=max(1, _int(env, "WARMPOOL_MAX_SIZE", 64)),
            warmpool_target_hit_rate=_float(
                env, "WARMPOOL_TARGET_HIT_RATE", 0.9),
            warmpool_decay_s=_float(env, "WARMPOOL_DECAY_S", 600.0),
            enable_preemption=_bool(env, "ENABLE_PREEMPTION", True),
            queue_requeue_s=_float(env, "QUEUE_REQUEUE_S", 15.0),
            queue_aging_s=_float(env, "QUEUE_AGING_S", 60.0),
            slo_placement_p99_s=_float(env, "SLO_PLACEMENT_P99_S", 0.0),
            slo_time_to_ready_p99_s=_float(
                env, "SLO_TIME_TO_READY_P99_S", 600.0),
            slo_event_to_reconcile_p99_s=_float(
                env, "SLO_EVENT_TO_RECONCILE_P99_S", 30.0),
            slo_reconcile_error_rate=_float(
                env, "SLO_RECONCILE_ERROR_RATE", 0.01),
            slo_recovery_p99_s=_float(
                env, "SLO_RECOVERY_DURATION_P99_S", 300.0),
            slo_warmpool_hit_rate=_float(
                env, "SLO_WARMPOOL_HIT_RATE", 0.6),
            slo_short_window_s=_float(env, "SLO_SHORT_WINDOW_S", 300.0),
            slo_long_window_s=_float(env, "SLO_LONG_WINDOW_S", 3600.0),
            slo_burn_alert_threshold=_float(
                env, "SLO_BURN_ALERT_THRESHOLD", 2.0),
            enable_continuous_profiler=_bool(
                env, "ENABLE_CONTINUOUS_PROFILER", False),
            profiler_interval_ms=_float(env, "PROFILER_INTERVAL_MS", 10.0),
            dataplane_straggler_ratio=_float(
                env, "DATAPLANE_STRAGGLER_RATIO", 1.5),
            dataplane_straggler_min_workers=max(2, _int(
                env, "DATAPLANE_STRAGGLER_MIN_WORKERS", 2)),
            dataplane_mfu_target=_float(env, "DATAPLANE_MFU_TARGET", 0.0),
            telemetry_ring_size=max(1, _int(
                env, "TELEMETRY_RING_SIZE", 512)),
            telemetry_publish_interval_s=_float(
                env, "TELEMETRY_PUBLISH_INTERVAL_S", 30.0),
            slo_fleet_mfu=_float(env, "SLO_FLEET_MFU", 0.0),
            slo_straggler_rate=_float(env, "SLO_STRAGGLER_RATE", 0.0),
            shard_count=max(1, _int(env, "SHARD_COUNT", 1)),
            shard_lease_duration_s=_float(
                env, "SHARD_LEASE_DURATION_S", 15.0),
            slo_shard_handoff_p99_s=_float(
                env, "SLO_SHARD_HANDOFF_P99_S", 0.0),
            interleave_max_schedules=max(1, _int(
                env, "INTERLEAVE_MAX_SCHEDULES", 1200)),
            interleave_budget_s=_float(env, "INTERLEAVE_BUDGET_S", 60.0),
            lifecycle_max_notebooks=max(1, _int(
                env, "LIFECYCLE_MAX_NOTEBOOKS", 4096)),
            lifecycle_samples_per_stage=max(1, _int(
                env, "LIFECYCLE_SAMPLES_PER_STAGE", 2048)),
            lifecycle_tolerance=_float(env, "LIFECYCLE_TOLERANCE", 0.05),
            tsdb_raw_capacity=max(1, _int(env, "TSDB_RAW_CAPACITY", 512)),
            tsdb_tier10_capacity=max(1, _int(
                env, "TSDB_TIER10_CAPACITY", 1024)),
            tsdb_tier60_capacity=max(1, _int(
                env, "TSDB_TIER60_CAPACITY", 1024)),
            tsdb_max_series=max(1, _int(env, "TSDB_MAX_SERIES", 256)),
            metering_max_tenants=max(1, _int(
                env, "METERING_MAX_TENANTS", 64)),
            metering_max_notebooks=max(1, _int(
                env, "METERING_MAX_NOTEBOOKS", 4096)),
            metering_tolerance=_float(env, "METERING_TOLERANCE", 0.05),
            tenant_fairshare_factor=_float(
                env, "TENANT_FAIRSHARE_FACTOR", 3.0),
            tenant_top_k=max(1, _int(env, "TENANT_TOP_K", 8)),
            slo_tenant_fairness=_float(env, "SLO_TENANT_FAIRNESS", 0.01),
        )


@dataclass
class OdhConfig:
    """ODH controller config (odh main.go:141-347 + per-file env reads)."""

    set_pipeline_rbac: bool = False          # SET_PIPELINE_RBAC
    set_pipeline_secret: bool = False        # SET_PIPELINE_SECRET
    inject_cluster_proxy_env: bool = False   # INJECT_CLUSTER_PROXY_ENV
    mlflow_enabled: bool = False             # MLFLOW_ENABLED
    gateway_url: str = ""                    # GATEWAY_URL
    gateway_name: str = "data-science-gateway"       # NOTEBOOK_GATEWAY_NAME
    gateway_namespace: str = "openshift-ingress"     # NOTEBOOK_GATEWAY_NAMESPACE
    controller_namespace: str = "opendatahub"        # K8S_NAMESPACE
    kube_rbac_proxy_image: str = "kube-rbac-proxy:latest"
    # TPU extension: image swap table, CUDA image -> JAX/libtpu image
    tpu_image_map: dict[str, str] = field(default_factory=dict)
    tpu_default_image: str = "jupyter-tpu-jax:latest"

    @classmethod
    def from_env(cls, env: Optional[Mapping[str, str]] = None) -> "OdhConfig":
        explicit = env is not None
        env = env if env is not None else os.environ
        return cls(
            set_pipeline_rbac=_bool(env, "SET_PIPELINE_RBAC", False),
            set_pipeline_secret=_bool(env, "SET_PIPELINE_SECRET", False),
            inject_cluster_proxy_env=_bool(env, "INJECT_CLUSTER_PROXY_ENV", False),
            mlflow_enabled=_bool(env, "MLFLOW_ENABLED", False),
            gateway_url=env.get("GATEWAY_URL", ""),
            gateway_name=env.get("NOTEBOOK_GATEWAY_NAME", "data-science-gateway"),
            gateway_namespace=env.get("NOTEBOOK_GATEWAY_NAMESPACE", "openshift-ingress"),
            # namespace detection: K8S_NAMESPACE, else the in-cluster SA
            # mount, else the dev default (odh main.go:127-139); an explicit
            # mapping stays hermetic (no ambient os.environ / SA-mount reads)
            controller_namespace=detect_namespace(
                "opendatahub", env=env if explicit else None),
            kube_rbac_proxy_image=env.get("KUBE_RBAC_PROXY_IMAGE", "kube-rbac-proxy:latest"),
            tpu_default_image=env.get("TPU_DEFAULT_IMAGE", "jupyter-tpu-jax:latest"),
        )
