"""Data-plane telemetry: roofline closed forms, the TelemetryAgent, the
control-plane WorkerTelemetryAggregator, straggler detection, and the
bench-trajectory CI gate.

Everything here is jax-free (controlplane lane): runtime.roofline and
runtime.telemetry are pure stdlib math, models.configs is dataclasses,
and the aggregator runs against the in-memory apiserver + InformerCache.
"""

import json

import pytest

from ci.bench_trajectory_check import check as trajectory_check
from ci.bench_trajectory_check import load_records
from kubeflow_tpu.core.telemetry import (
    EVENT_STRAGGLER,
    EVENT_STRAGGLER_CLEARED,
    WorkerTelemetryAggregator,
    parse_pod_telemetry,
)
from kubeflow_tpu.core import telemetry as core_telemetry
from kubeflow_tpu.kube import ApiServer, EventRecorder, FakeCluster, InformerCache
from kubeflow_tpu.kube.meta import KubeObject, ObjectMeta
from kubeflow_tpu.models.configs import BENCH_CHIP, BENCH_MOE, TINY
import kubeflow_tpu.runtime.roofline as roofline
import kubeflow_tpu.runtime.telemetry as telemetry
from kubeflow_tpu.runtime.metrics import StepTimer
from kubeflow_tpu.runtime.telemetry import (
    JsonlRing,
    TelemetryAgent,
    annotation_payload,
    parse_annotation,
)
from kubeflow_tpu.tpu.topology import ACCELERATORS
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig
from kubeflow_tpu.utils.metrics import Registry
from kubeflow_tpu.utils.slo import default_objectives


# -- roofline closed forms ----------------------------------------------------


class TestRooflineClosedForm:
    def test_dense_train_flops_match_hand_formula(self):
        cfg, batch, seq = TINY, 4, 128
        # PaLM appendix-B accounting: 6x matmul params + causal attention
        matmul = cfg.num_params - cfg.vocab_size * cfg.embed_dim
        attn = 12 * cfg.num_layers * seq * cfg.num_heads * cfg.head_dim / 2
        expected = (6.0 * matmul + attn) * batch * seq
        assert roofline.train_step_flops(cfg, batch, seq) == \
            pytest.approx(expected)

    def test_moe_counts_activated_experts_only(self):
        cfg = TINY.with_(moe_experts=4, moe_top_k=2, moe_mlp_dim=64)
        dense_twin = TINY.with_(moe_experts=0)
        expert_mlp = 3 * cfg.embed_dim * 64
        inactive = (4 - 2) * expert_mlp * cfg.num_layers
        # activated-FLOPs convention: the 2 inactive experts' matmul
        # params are excluded from the numerator
        full = roofline.train_step_flops(
            cfg.with_(moe_top_k=4), 2, 64)
        active = roofline.train_step_flops(cfg, 2, 64)
        assert full - active == pytest.approx(6.0 * inactive * 2 * 64)
        del dense_twin

    def test_train_hbm_bytes_closed_form(self):
        cfg, batch, seq = TINY, 2, 64
        ab = 4.0  # TINY runs fp32 activations
        pb = 4.0
        weights = cfg.num_params * (2 * ab + 2 * pb + 16.0)
        stash = 2.0 * batch * seq * cfg.embed_dim * cfg.num_layers * ab
        assert roofline.train_step_hbm_bytes(cfg, batch, seq) == \
            pytest.approx(weights + stash)

    def test_compute_vs_memory_crossover(self):
        # tiny batches cannot amortize the weight traffic: memory-bound;
        # the bench batch keeps the MXU fed: compute-bound
        small = roofline.train_estimate(BENCH_CHIP, 1, 128)
        big = roofline.train_estimate(BENCH_CHIP, 40, 2048)
        assert small.bound == "memory"
        assert big.bound == "compute"
        # floors are exactly the work / peak ratios of the chip table
        spec = ACCELERATORS["v5e"]
        assert big.compute_floor_s == pytest.approx(
            big.flops / (spec.bf16_peak_tflops * 1e12))
        assert big.memory_floor_s == pytest.approx(
            big.hbm_bytes / (spec.hbm_gbps * 1e9))
        assert big.step_floor_s == max(big.compute_floor_s,
                                       big.memory_floor_s)

    def test_decode_estimate_matches_bench_formula(self):
        cfg = BENCH_CHIP.with_(max_seq_len=384, decode=True)
        batch = 16
        est = roofline.decode_estimate(cfg, batch)
        kv = (2 * batch * 384 * cfg.num_kv_heads * cfg.head_dim
              * 2 * cfg.num_layers)
        stream = roofline.matmul_params(cfg) * 2.0  # bf16
        assert est.hbm_bytes == pytest.approx(stream + kv)
        assert est.bound == "memory"
        # int8 weight streaming halves the stream share, exactly
        est8 = roofline.decode_estimate(cfg.with_(weight_dtype="int8"),
                                        batch)
        assert est.hbm_bytes - est8.hbm_bytes == pytest.approx(stream / 2)
        # a measured byte count (bench passes quantized_bytes) overrides
        est_m = roofline.decode_estimate(cfg, batch, param_bytes=1e9)
        assert est_m.hbm_bytes == pytest.approx(1e9 + kv)

    def test_tied_embeddings_stream_and_count(self):
        tied = TINY.with_(tie_embeddings=True)
        assert roofline.matmul_params(tied) == tied.num_params
        assert roofline.matmul_params(TINY) == \
            TINY.num_params - TINY.vocab_size * TINY.embed_dim

    def test_mfu_single_definition(self):
        # the acceptance identity: bench.py (models.train.mfu ->
        # roofline.mfu) and the TelemetryAgent report the same MFU for
        # the same (config, step time)
        step_time = 3.5071
        tokens = 40 * 2048 / step_time
        by_fn = roofline.mfu(tokens, BENCH_CHIP, 2048, 1, "v5e")
        spec = ACCELERATORS["v5e"]
        assert by_fn == pytest.approx(
            tokens * BENCH_CHIP.flops_per_token(2048)
            / (spec.bf16_peak_tflops * 1e12))
        est = roofline.train_estimate(BENCH_CHIP, 40, 2048)
        assert est.mfu_at(step_time) == pytest.approx(by_fn)
        agent = TelemetryAgent(config=BENCH_CHIP, batch=40, seq_len=2048,
                               time_fn=FakeClock(0.0).now, hbm_fn=dict)
        agent.record_step(step_time)
        assert agent.mfu == pytest.approx(by_fn)

    def test_roofline_fraction_equals_mfu_when_compute_bound(self):
        est = roofline.train_estimate(BENCH_CHIP, 40, 2048)
        assert est.bound == "compute"
        assert est.roofline_fraction(2.0) == pytest.approx(est.mfu_at(2.0))

    def test_moe_train_estimate(self):
        est = roofline.train_estimate(BENCH_MOE, 16, 2048)
        assert est.flops == pytest.approx(
            BENCH_MOE.flops_per_token(2048) * 16 * 2048)
        assert est.bound in ("compute", "memory")

    def test_zero_step_time_is_safe(self):
        est = roofline.train_estimate(TINY, 1, 8)
        assert est.mfu_at(0.0) == 0.0
        assert est.roofline_fraction(0.0) == 0.0
        assert roofline.mfu_from_flops(0.0, 1e9, 1) == 0.0


# -- TelemetryAgent -----------------------------------------------------------


class TestTelemetryAgent:
    def make(self, clock, **kw):
        kw.setdefault("config", TINY)
        kw.setdefault("batch", 4)
        kw.setdefault("seq_len", 128)
        kw.setdefault("hbm_fn", lambda: {"d0": 123})
        return TelemetryAgent(time_fn=clock.now, **kw)

    def test_step_boundary_off_fake_clock(self):
        clock = FakeClock(0.0)
        agent = self.make(clock)
        assert agent.step_boundary() is None  # arms only
        clock.advance(0.1)
        sample = agent.step_boundary()
        assert sample["step_time_s"] == pytest.approx(0.1)
        assert sample["tokens_per_s"] == pytest.approx(4 * 128 / 0.1)
        assert sample["mfu"] == pytest.approx(
            roofline.mfu(4 * 128 / 0.1, TINY, 128, 1, "v5e"))
        est = roofline.train_estimate(TINY, 4, 128)
        assert sample["roofline_fraction"] == \
            pytest.approx(est.roofline_fraction(0.1))
        assert sample["bound"] == est.bound
        assert sample["hbm_bytes"] == 123

    def test_phase_scopes_attach_to_next_sample(self):
        clock = FakeClock(0.0)
        agent = self.make(clock)
        agent.step_boundary()
        with agent.scope("fwd"):
            clock.advance(0.06)
        with agent.scope("bwd"):
            clock.advance(0.03)
        with agent.scope("opt"):
            clock.advance(0.01)
        sample = agent.step_boundary()
        assert sample["step_time_s"] == pytest.approx(0.1)
        assert sample["phases"] == pytest.approx(
            {"fwd": 0.06, "bwd": 0.03, "opt": 0.01})
        # consumed: the next sample carries no stale phases
        clock.advance(0.1)
        assert "phases" not in agent.step_boundary()

    def test_ring_is_bounded(self):
        clock = FakeClock(0.0)
        agent = self.make(clock, ring_size=8)
        for _ in range(20):
            agent.record_step(0.05)
        assert agent.steps_recorded == 20
        samples = agent.samples()
        assert len(samples) == 8
        assert [s["step"] for s in samples] == list(range(13, 21))

    def test_rolling_window_bounded(self):
        clock = FakeClock(0.0)
        agent = self.make(clock, window=3)
        for dt in (1.0, 1.0, 0.2, 0.2, 0.2):
            agent.record_step(dt)
        assert agent.step_time_s == pytest.approx(0.2)

    def test_jsonl_spool_bounded_and_parseable(self, tmp_path):
        clock = FakeClock(0.0)
        agent = self.make(clock, ring_size=8)
        path = str(tmp_path / "telemetry.jsonl")
        agent.spool_to(path)
        for _ in range(20):
            agent.record_step(0.05)
        ring = JsonlRing(path, max_records=8)
        records = ring.read()
        assert [r["step"] for r in records] == list(range(13, 21))
        # the on-disk file stays bounded (compaction), not append-forever
        with open(path) as f:
            assert len(f.readlines()) <= 16

    def test_publish_rate_limited(self):
        clock = FakeClock(0.0)
        published = []
        agent = self.make(clock, publish_fn=published.append,
                          publish_interval_s=10.0)
        agent.record_step(0.1)   # first step publishes immediately
        assert len(published) == 1
        clock.advance(3)
        agent.record_step(0.1)
        assert len(published) == 1  # inside the interval
        clock.advance(10)
        agent.record_step(0.1)
        assert len(published) == 2
        assert published[-1]["steps"] == 3
        assert agent.publish_now()
        assert len(published) == 3

    def test_summary_annotation_round_trip(self):
        clock = FakeClock(5.0)
        agent = self.make(clock, worker="nb-0-0")
        agent.record_step(0.25)
        summary = agent.summary()
        assert summary["worker"] == "nb-0-0"
        assert summary["mfu"] == pytest.approx(agent.mfu)
        assert summary["bound"] in ("compute", "memory")
        assert parse_annotation(annotation_payload(summary)) == \
            pytest.approx(summary)
        assert parse_annotation("not json") is None
        assert parse_annotation(json.dumps({"v": 999})) is None
        assert parse_annotation(json.dumps(["list"])) is None

    def test_flops_override_skips_config(self):
        clock = FakeClock(0.0)
        agent = TelemetryAgent(flops_per_token=1e9, batch=8, seq_len=16,
                               time_fn=clock.now, hbm_fn=dict)
        agent.record_step(0.5)
        assert agent.mfu == pytest.approx(
            roofline.mfu_from_flops(8 * 16 / 0.5, 1e9, 1, "v5e"))
        # no config = no traffic model = no roofline attribution
        assert agent.estimate() is None
        assert "roofline_fraction" not in agent.samples()[-1]


class TestStepTimerShim:
    """The deprecated direct path routes through the agent — the
    histogram and the agent's samples cannot disagree."""

    def test_observe_feeds_agent_and_histogram_once(self):
        clock = FakeClock(0.0)
        timer = StepTimer(TINY, batch=4, seq_len=128, num_chips=1,
                          time_fn=clock.now)
        timer.observe()
        clock.advance(0.1)
        timer.observe()
        clock.advance(0.3)
        timer.observe()
        hist = timer.registry.get("notebook_training_step_duration_seconds")
        assert hist.count_value() == 2
        assert timer.agent.steps_recorded == 2
        assert [s["step_time_s"] for s in timer.agent.samples()] == \
            pytest.approx([0.1, 0.3])
        # every derived stat is the agent's stat
        assert timer.step_time_s == timer.agent.step_time_s
        assert timer.tokens_per_s == timer.agent.tokens_per_s
        assert timer.mfu == timer.agent.mfu
        assert timer.mfu == pytest.approx(
            roofline.mfu(timer.tokens_per_s, TINY, 128, 1, "v5e"))

    def test_legacy_times_poke_still_works(self):
        timer = StepTimer(TINY, batch=4, seq_len=128, num_chips=1)
        timer._times = [0.1, 0.1]
        assert timer.tokens_per_s == pytest.approx(4 * 128 / 0.1)
        assert timer._times == [0.1, 0.1]

    def test_report_and_exposition(self):
        timer = StepTimer(TINY, batch=4, seq_len=128, num_chips=1)
        timer.agent.hbm_fn = dict
        timer._times = [0.2]
        rep = timer.report()
        assert rep["step_time_s"] == pytest.approx(0.2)
        text = timer.prometheus_text()
        assert "# TYPE notebook_training_mfu_ratio gauge" in text


class TestAnnotationContractSync:
    def test_core_and_runtime_constants_match(self):
        # core must not import the runtime package; the literals are
        # duplicated and THIS is the tripwire that keeps them in sync
        assert core_telemetry.TELEMETRY_ANNOTATION == \
            telemetry.TELEMETRY_ANNOTATION
        assert core_telemetry.SUMMARY_VERSION == telemetry.SUMMARY_VERSION


# -- control-plane aggregation ------------------------------------------------


def make_pod(api, ns, notebook, name, summary=None, raw=None):
    annotations = {}
    if raw is not None:
        annotations[core_telemetry.TELEMETRY_ANNOTATION] = raw
    elif summary is not None:
        annotations[core_telemetry.TELEMETRY_ANNOTATION] = \
            annotation_payload(summary)
    return api.create(KubeObject(
        api_version="v1", kind="Pod",
        metadata=ObjectMeta(name=name, namespace=ns,
                            labels={"notebook-name": notebook},
                            annotations=annotations),
        body={"status": {"phase": "Running"}}))


def make_notebook(api, ns, name):
    return api.create(KubeObject(
        api_version="kubeflow.org/v1", kind="Notebook",
        metadata=ObjectMeta(name=name, namespace=ns), body={"spec": {}}))


def worker_summary(worker, step_time_s, tokens_per_s=None, mfu=0.3):
    if tokens_per_s is None:
        tokens_per_s = 1000.0 / step_time_s
    return {"v": 1, "worker": worker, "mode": "train", "steps": 5,
            "step_time_s": step_time_s, "tokens_per_s": tokens_per_s,
            "mfu": mfu, "hbm_bytes": 1 << 30, "t": 0.0}


class TestWorkerTelemetryAggregator:
    def build(self, api, with_cache=True, recorder=None, **kw):
        registry = Registry()
        cache = InformerCache(api) if with_cache else None
        agg = WorkerTelemetryAggregator(
            api, registry, FakeClock(), cache=cache, recorder=recorder,
            **kw)
        return agg, registry

    def test_rollup_matches_brute_force_over_pods(self):
        api = ApiServer()
        import random

        rng = random.Random(11)
        for i in range(5):
            for w in range(rng.randint(1, 6)):
                st = rng.uniform(0.1, 2.0)
                make_pod(api, f"ns{i % 2}", f"nb-{i}", f"nb-{i}-{w}",
                         worker_summary(f"nb-{i}-{w}", st,
                                        mfu=rng.uniform(0.1, 0.5)))
        # noise: annotation-less and malformed pods never contribute
        make_pod(api, "ns0", "nb-0", "nb-0-noann")
        make_pod(api, "ns0", "nb-1", "nb-1-bad", raw="{not json")
        make_pod(api, "ns0", "nb-1", "nb-1-oldv",
                 raw=json.dumps({"v": 0, "step_time_s": 1.0}))
        cached, _ = self.build(api, with_cache=True)
        brute, _ = self.build(api, with_cache=False)
        a, b = cached.evaluate(), brute.evaluate()
        # identical float inputs through identical rollup code: the
        # cache-fed and brute-force paths must agree EXACTLY
        assert a["notebooks"] == b["notebooks"]
        assert a["fleet"] == b["fleet"]
        # and equals a by-hand rollup straight off the pod list
        for key, entry in a["notebooks"].items():
            ns, nb = key.split("/")
            pods = [p for p in api.list("Pod", namespace=ns)
                    if parse_pod_telemetry(p)
                    and parse_pod_telemetry(p)["notebook"] == nb]
            assert len(entry["workers"]) == len(pods)
            assert entry["tokens_per_s"] == pytest.approx(sum(
                parse_pod_telemetry(p)["summary"]["tokens_per_s"]
                for p in pods))

    def test_watch_fed_updates_replace_worker_contribution(self):
        api = ApiServer()
        pod = make_pod(api, "u1", "nb", "nb-0",
                       worker_summary("nb-0", 1.0))
        agg, _ = self.build(api)
        assert agg.evaluate()["notebooks"]["u1/nb"]["step_time_s"] == \
            pytest.approx(1.0)
        live = api.get("Pod", "u1", pod.name)
        live.metadata.annotations[core_telemetry.TELEMETRY_ANNOTATION] = \
            annotation_payload(worker_summary("nb-0", 0.25))
        api.update(live)
        assert agg.evaluate()["notebooks"]["u1/nb"]["step_time_s"] == \
            pytest.approx(0.25)

    def test_straggler_fire_and_clear_with_events(self):
        api = ApiServer()
        make_notebook(api, "u1", "nb")
        for w in range(4):
            make_pod(api, "u1", "nb", f"nb-0-{w}",
                     worker_summary(f"nb-0-{w}", 0.5))
        recorder = EventRecorder(api, "test-telemetry")
        agg, registry = self.build(api, recorder=recorder,
                                   straggler_ratio=1.5)
        out = agg.evaluate()
        assert out["stragglers"] == []
        gauge = registry.get("notebook_dataplane_straggler")
        assert gauge.collect()[("u1", "nb")] == 0.0

        # one worker falls 4x behind the slice median
        live = api.get("Pod", "u1", "nb-0-3")
        live.metadata.annotations[core_telemetry.TELEMETRY_ANNOTATION] = \
            annotation_payload(worker_summary("nb-0-3", 2.0))
        api.update(live)
        out = agg.evaluate()
        assert [s["worker"] for s in out["stragglers"]] == ["nb-0-3"]
        assert out["stragglers"][0]["ratio"] == pytest.approx(4.0)
        assert out["notebooks"]["u1/nb"]["straggler"] == "nb-0-3"
        assert out["notebooks"]["u1/nb"]["step_time_s"] == \
            pytest.approx(2.0)
        assert gauge.collect()[("u1", "nb")] == 1.0
        events = [e for e in api.list("Event", namespace="u1")
                  if e.body.get("reason") == EVENT_STRAGGLER]
        assert len(events) == 1
        assert "nb-0-3" in events[0].body["message"]
        # continued breach dedups into the same event (count bump)
        agg.evaluate()
        events = [e for e in api.list("Event", namespace="u1")
                  if e.body.get("reason") == EVENT_STRAGGLER]
        assert len(events) == 1

        # heal: the worker rejoins the pace; gauge and state clear
        live = api.get("Pod", "u1", "nb-0-3")
        live.metadata.annotations[core_telemetry.TELEMETRY_ANNOTATION] = \
            annotation_payload(worker_summary("nb-0-3", 0.5))
        api.update(live)
        out = agg.evaluate()
        assert out["stragglers"] == []
        assert gauge.collect()[("u1", "nb")] == 0.0
        cleared = [e for e in api.list("Event", namespace="u1")
                   if e.body.get("reason") == EVENT_STRAGGLER_CLEARED]
        assert len(cleared) == 1

    def test_single_worker_never_straggles(self):
        api = ApiServer()
        make_pod(api, "u1", "solo", "solo-0",
                 worker_summary("solo-0", 10.0))
        agg, registry = self.build(api)
        assert agg.evaluate()["stragglers"] == []
        assert registry.get("notebook_dataplane_straggler") \
            .collect()[("u1", "solo")] == 0.0

    def test_vanished_workers_zero_the_series(self):
        api = ApiServer()
        for w in range(2):
            make_pod(api, "u1", "nb", f"nb-0-{w}",
                     worker_summary(f"nb-0-{w}", 0.5))
        agg, registry = self.build(api)
        agg.evaluate()
        tokens = registry.get("notebook_dataplane_tokens_per_second")
        assert tokens.collect()[("u1", "nb")] > 0
        for w in range(2):
            api.delete("Pod", "u1", f"nb-0-{w}")
        out = agg.evaluate()
        assert out["notebooks"] == {}
        assert tokens.collect()[("u1", "nb")] == 0.0

    def test_check_counters_feed_slo_objectives(self):
        api = ApiServer()
        for w in range(3):
            make_pod(api, "u1", "nb", f"nb-0-{w}",
                     worker_summary(f"nb-0-{w}", 0.5, mfu=0.2))
        agg, registry = self.build(api, mfu_target=0.35)
        agg.evaluate()
        checks = registry.get("notebook_dataplane_straggler_checks_total")
        assert checks.collect()[("ok",)] == 1.0
        mfu_checks = registry.get("notebook_dataplane_mfu_checks_total")
        assert mfu_checks.collect()[("low",)] == 1.0  # 0.2 < 0.35
        # and the (knob-enabled) objectives read exactly these families
        cfg = CoreConfig(slo_fleet_mfu=0.99, slo_straggler_rate=0.05)
        names = {o.name: o for o in default_objectives(cfg)}
        assert names["fleet_mfu"].metric == \
            "notebook_dataplane_mfu_checks_total"
        assert names["straggler_rate"].metric == \
            "notebook_dataplane_straggler_checks_total"
        assert names["straggler_rate"].target_ratio == pytest.approx(0.95)
        # knob-disabled by default
        defaults = {o.name for o in default_objectives(CoreConfig())}
        assert "fleet_mfu" not in defaults
        assert "straggler_rate" not in defaults

    def test_snapshot_refreshes(self):
        api = ApiServer()
        agg, _ = self.build(api)
        assert agg.snapshot()["fleet"]["notebooks"] == 0
        make_pod(api, "u1", "nb", "nb-0-0", worker_summary("nb-0-0", 0.5))
        make_pod(api, "u1", "nb", "nb-0-1", worker_summary("nb-0-1", 0.5))
        snap = agg.snapshot()  # no explicit evaluate() needed
        assert snap["fleet"]["notebooks"] == 1
        assert snap["notebooks"]["u1/nb"]["mfu"] == pytest.approx(0.3)


class TestFakeClusterStamping:
    def test_stamp_runs_real_agents_and_flags_slow_worker(self):
        api = ApiServer()
        cluster = FakeCluster(api)
        for w in range(3):
            make_pod(api, "u1", "nb", f"nb-0-{w}")
        out = cluster.stamp_worker_telemetry(
            "u1", "nb", step_time_s=0.5, config=TINY, batch=4,
            seq_len=128, num_chips=1, slow_worker=1, slow_factor=4.0,
            now=42.0)
        assert set(out) == {"nb-0-0", "nb-0-1", "nb-0-2"}
        assert out["nb-0-1"]["step_time_s"] == pytest.approx(2.0)
        assert out["nb-0-0"]["step_time_s"] == pytest.approx(0.5)
        # the stamped annotation IS a real agent summary (same MFU
        # definition as bench.py, via roofline)
        pod = api.get("Pod", "u1", "nb-0-0")
        parsed = parse_pod_telemetry(pod)
        assert parsed["summary"] == pytest.approx(out["nb-0-0"])
        assert out["nb-0-0"]["mfu"] == pytest.approx(
            roofline.mfu(4 * 128 / 0.5, TINY, 128, 1, "v5e"))
        # the aggregator attributes the slow worker
        agg = WorkerTelemetryAggregator(api, Registry(), FakeClock())
        snap = agg.snapshot()
        assert snap["notebooks"]["u1/nb"]["straggler"] == "nb-0-1"
        cluster.clear_worker_telemetry("u1", "nb")
        assert agg.snapshot()["notebooks"] == {}


# -- bench trajectory gate ----------------------------------------------------


def bench_record(tmp_path, n, parsed, rc=0):
    path = tmp_path / f"BENCH_r{n:02d}.json"
    path.write_text(json.dumps({"n": n, "rc": rc, "parsed": parsed}))
    return str(path)


class TestBenchTrajectoryGate:
    def test_repo_history_gates_green(self):
        import glob
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        records = load_records(glob.glob(os.path.join(root,
                                                      "BENCH_r*.json")))
        assert len(records) >= 5
        ok, msgs = trajectory_check(records)
        assert ok, msgs

    def test_regression_beyond_10pct_fails(self, tmp_path):
        paths = [
            bench_record(tmp_path, 1,
                         {"metric": "train_mfu_v5e", "value": 0.40}),
            bench_record(tmp_path, 2,
                         {"metric": "train_mfu_v5e", "value": 0.35}),
        ]
        ok, msgs = trajectory_check(load_records(paths))
        assert not ok
        assert any("FAIL" in m for m in msgs)
        # within tolerance passes
        paths[1] = bench_record(tmp_path, 2,
                                {"metric": "train_mfu_v5e", "value": 0.37})
        ok, _ = trajectory_check(load_records(paths))
        assert ok

    def test_silent_skip_fails_reasoned_skip_passes(self, tmp_path):
        base = bench_record(tmp_path, 1,
                            {"metric": "train_mfu_v5e", "value": 0.40})
        silent = bench_record(tmp_path, 2,
                              {"metric": "train_mfu_v5e", "skipped": True})
        ok, msgs = trajectory_check(load_records([base, silent]))
        assert not ok and any("silent" in m for m in msgs)
        reasoned = bench_record(
            tmp_path, 3, {"metric": "train_mfu_v5e", "skipped": True,
                          "reason": "no usable JAX backend"})
        ok, _ = trajectory_check(load_records([base, reasoned]))
        assert ok

    def test_newest_crash_warns_but_gates_on_measured(self, tmp_path):
        paths = [
            bench_record(tmp_path, 1,
                         {"metric": "train_mfu_v5e", "value": 0.40}),
            bench_record(tmp_path, 2, None, rc=1),
        ]
        ok, msgs = trajectory_check(load_records(paths))
        assert ok
        assert any("crash" in m for m in msgs)

    def test_empty_history_passes_vacuously(self):
        ok, msgs = trajectory_check([])
        assert ok
