"""Fake TPU kubelet device plugin + node labeler.

SURVEY.md §4.5 names "a fake TPU device plugin for KinD-level tests" as a
gap this framework must fill: nothing in a stock kind cluster provides
`google.com/tpu` allocatable, so the TPU scheduling contract (indexed STS
placement, gang scale, worker env) can only be certified with one.  The
reference's envtest suites sidestep the problem by faking Node objects
(`/root/reference/components/odh-notebook-controller/controllers/suite_test.go:112-125`);
on a real kubelet that is not enough — extended resources come from the
device-plugin gRPC API.

Two layers, matching the two substrates:

1. `FakeTpuDevicePlugin` — a REAL kubelet device plugin speaking the
   v1beta1 gRPC protocol over unix sockets: registers with kubelet
   (`Register` on kubelet.sock), serves `GetDevicePluginOptions` /
   `ListAndWatch` (streamed device list, health transitions re-streamed) /
   `Allocate` (per-container device specs + env).  The protobuf messages
   are built dynamically from a FileDescriptorProto, so the module needs
   only grpcio + protobuf at runtime — no protoc, no generated code to
   drift.  Wire-compatible with kubelet: package `v1beta1`, services
   `Registration`/`DevicePlugin`, the standard socket-dir handshake.
2. `label_tpu_node` — the apiserver-side fallback for clusters where the
   kubelet is out of reach (kind without a privileged DaemonSet): patches
   `google.com/tpu` into Node status capacity/allocatable and applies the
   GKE TPU topology labels, via this framework's own KubeClient (works
   against the wire server and a genuine apiserver alike).

`tests/test_device_plugin.py` certifies the gRPC layer with a harness
acting as the kubelet (Registration server + DevicePlugin client over real
unix sockets).
"""

from __future__ import annotations

import os
import threading
from concurrent import futures
from dataclasses import dataclass, field
from typing import Optional

API_VERSION = "v1beta1"
KUBELET_SOCKET = "kubelet.sock"
DEFAULT_RESOURCE = "google.com/tpu"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

# GKE TPU node labels (public contract; tpu/topology.py uses the same)
LABEL_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
LABEL_TOPOLOGY = "cloud.google.com/gke-tpu-topology"


# ---------------------------------------------------------------------------
# v1beta1 protobuf messages, built dynamically (no protoc, no gencode)

_TYPE = {"string": 9, "bool": 8, "int64": 3, "message": 11}
_LABEL = {"optional": 1, "repeated": 3}


def _build_messages():
    from google.protobuf import (
        descriptor_pb2,
        descriptor_pool,
        message_factory,
    )

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "kubeflow_tpu/deviceplugin_v1beta1.proto"
    fdp.package = API_VERSION
    fdp.syntax = "proto3"

    def msg(name):
        m = fdp.message_type.add()
        m.name = name
        return m

    def add_field(m, num, name, ftype, label="optional", type_name=""):
        f = m.field.add()
        f.name = name
        f.number = num
        f.type = _TYPE[ftype]
        f.label = _LABEL[label]
        if type_name:
            f.type_name = f".{API_VERSION}.{type_name}"

    def map_entry(parent, entry_name):
        e = parent.nested_type.add()
        e.name = entry_name
        e.options.map_entry = True
        for i, n in ((1, "key"), (2, "value")):
            f = e.field.add()
            f.name = n
            f.number = i
            f.type = _TYPE["string"]
            f.label = _LABEL["optional"]

    msg("Empty")

    m = msg("DevicePluginOptions")
    add_field(m, 1, "pre_start_required", "bool")
    add_field(m, 2, "get_preferred_allocation_available", "bool")

    m = msg("RegisterRequest")
    add_field(m, 1, "version", "string")
    add_field(m, 2, "endpoint", "string")
    add_field(m, 3, "resource_name", "string")
    add_field(m, 4, "options", "message", type_name="DevicePluginOptions")

    m = msg("Device")
    add_field(m, 1, "ID", "string")
    add_field(m, 2, "health", "string")

    m = msg("ListAndWatchResponse")
    add_field(m, 1, "devices", "message", "repeated", "Device")

    m = msg("ContainerAllocateRequest")
    add_field(m, 1, "devicesIDs", "string", "repeated")

    m = msg("AllocateRequest")
    add_field(m, 1, "container_requests", "message", "repeated",
              "ContainerAllocateRequest")

    m = msg("Mount")
    add_field(m, 1, "container_path", "string")
    add_field(m, 2, "host_path", "string")
    add_field(m, 3, "read_only", "bool")

    m = msg("DeviceSpec")
    add_field(m, 1, "container_path", "string")
    add_field(m, 2, "host_path", "string")
    add_field(m, 3, "permissions", "string")

    m = msg("ContainerAllocateResponse")
    map_entry(m, "EnvsEntry")
    add_field(m, 1, "envs", "message", "repeated",
              "ContainerAllocateResponse.EnvsEntry")
    add_field(m, 2, "mounts", "message", "repeated", "Mount")
    add_field(m, 3, "devices", "message", "repeated", "DeviceSpec")

    m = msg("AllocateResponse")
    add_field(m, 1, "container_responses", "message", "repeated",
              "ContainerAllocateResponse")

    m = msg("PreStartContainerRequest")
    add_field(m, 1, "devicesIDs", "string", "repeated")

    msg("PreStartContainerResponse")

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    classes = message_factory.GetMessageClassesForFiles([fdp.name], pool)
    return {
        name.rsplit(".", 1)[-1]: cls
        for name, cls in classes.items()
        if "." not in name.rsplit(f"{API_VERSION}.", 1)[-1]
    }


_MSGS = None
_MSGS_LOCK = threading.Lock()


def messages():
    """The v1beta1 message classes, keyed by short name (lazy singleton —
    grpc/protobuf import deferred until a plugin is actually used)."""
    global _MSGS
    with _MSGS_LOCK:
        if _MSGS is None:
            _MSGS = _build_messages()
    return _MSGS


# ---------------------------------------------------------------------------
# the plugin daemon


@dataclass
class FakeTpuDevicePlugin:
    """Advertises `chips` fake TPU devices to the kubelet in `socket_dir`.

    start() serves the DevicePlugin gRPC service on its own socket and, if
    `<socket_dir>/kubelet.sock` exists, performs the standard registration
    handshake.  set_health() flips a device and re-streams the list to
    every ListAndWatch watcher (how the real plugin reports a dead chip;
    chaos drills use it to trigger the controller's failure handling).
    """

    socket_dir: str
    chips: int = 4
    resource_name: str = DEFAULT_RESOURCE
    endpoint: str = "kubeflow-tpu.sock"
    device_prefix: str = "/dev/accel"

    _server: Optional[object] = field(default=None, repr=False)
    _health: dict = field(default_factory=dict, repr=False)
    _version: int = 0
    _cond: threading.Condition = field(default_factory=threading.Condition,
                                       repr=False)

    def __post_init__(self):
        self._health = {f"tpu-{i}": HEALTHY for i in range(self.chips)}

    # -- gRPC service handlers -------------------------------------------------

    def _options(self, request, context):
        return messages()["DevicePluginOptions"]()

    def _device_list(self):
        M = messages()
        resp = M["ListAndWatchResponse"]()
        for dev_id, health in sorted(self._health.items()):
            d = resp.devices.add()
            d.ID = dev_id
            d.health = health
        return resp

    def _list_and_watch(self, request, context):
        seen = -1
        while True:
            with self._cond:
                if seen == self._version:
                    # wake on health flips; periodic timeout keeps the
                    # stream responsive to cancellation
                    self._cond.wait(timeout=0.5)
                if seen == self._version:
                    if not context.is_active():
                        return
                    continue
                seen = self._version
                resp = self._device_list()
            yield resp

    def _allocate(self, request, context):
        M = messages()
        resp = M["AllocateResponse"]()
        for creq in request.container_requests:
            cresp = resp.container_responses.add()
            ids = list(creq.devicesIDs)
            for dev_id in ids:
                spec = cresp.devices.add()
                idx = dev_id.rsplit("-", 1)[-1]
                spec.container_path = f"{self.device_prefix}{idx}"
                spec.host_path = f"{self.device_prefix}{idx}"
                spec.permissions = "rw"
            cresp.envs["TPU_FAKE_DEVICE_IDS"] = ",".join(ids)
            cresp.envs["TPU_CHIPS_ALLOCATED"] = str(len(ids))
        return resp

    def _pre_start(self, request, context):
        return messages()["PreStartContainerResponse"]()

    # -- lifecycle -------------------------------------------------------------

    @property
    def socket_path(self) -> str:
        return os.path.join(self.socket_dir, self.endpoint)

    def start(self, register: bool = True) -> None:
        import grpc

        M = messages()
        ser = lambda m: m.SerializeToString()  # noqa: E731
        handlers = {
            "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
                self._options,
                request_deserializer=M["Empty"].FromString,
                response_serializer=ser),
            "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                self._list_and_watch,
                request_deserializer=M["Empty"].FromString,
                response_serializer=ser),
            "Allocate": grpc.unary_unary_rpc_method_handler(
                self._allocate,
                request_deserializer=M["AllocateRequest"].FromString,
                response_serializer=ser),
            "PreStartContainer": grpc.unary_unary_rpc_method_handler(
                self._pre_start,
                request_deserializer=M["PreStartContainerRequest"].FromString,
                response_serializer=ser),
        }
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                f"{API_VERSION}.DevicePlugin", handlers),
        ))
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        if register and os.path.exists(
                os.path.join(self.socket_dir, KUBELET_SOCKET)):
            self.register()

    def register(self) -> None:
        """The kubelet handshake: dial kubelet.sock, announce our endpoint
        and resource name."""
        import grpc

        M = messages()
        kubelet = os.path.join(self.socket_dir, KUBELET_SOCKET)
        with grpc.insecure_channel(f"unix://{kubelet}") as chan:
            register = chan.unary_unary(
                f"/{API_VERSION}.Registration/Register",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=M["Empty"].FromString)
            req = M["RegisterRequest"]()
            req.version = API_VERSION
            req.endpoint = self.endpoint
            req.resource_name = self.resource_name
            register(req, timeout=5)

    def set_health(self, dev_id: str, healthy: bool) -> None:
        with self._cond:
            if dev_id not in self._health:
                raise KeyError(dev_id)
            self._health[dev_id] = HEALTHY if healthy else UNHEALTHY
            self._version += 1
            self._cond.notify_all()

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.2)
            self._server = None
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)


# ---------------------------------------------------------------------------
# apiserver-side fallback


def label_tpu_node(client, node_name: str, chips: int = 4,
                   accelerator: str = "tpu-v5-lite-podslice",
                   topology: str = "2x2",
                   resource_name: str = DEFAULT_RESOURCE):
    """Patch a Node to advertise TPU capacity without a kubelet: GKE TPU
    labels on metadata, `google.com/tpu` in status capacity/allocatable.
    Works against the wire server and a genuine apiserver via the same
    KubeClient; kind lanes use it when the device-plugin DaemonSet is not
    deployed."""
    node = client.get("Node", "", node_name)
    node.metadata.labels[LABEL_ACCELERATOR] = accelerator
    node.metadata.labels[LABEL_TOPOLOGY] = topology
    node = client.update(node)

    status = node.status
    for key in ("capacity", "allocatable"):
        res = dict(status.get(key) or {})
        res[resource_name] = str(chips)
        status[key] = res
    return client.update_status(node)


__all__ = [
    "FakeTpuDevicePlugin",
    "label_tpu_node",
    "messages",
    "API_VERSION",
    "DEFAULT_RESOURCE",
    "HEALTHY",
    "UNHEALTHY",
]


def main(argv=None) -> None:
    """DaemonSet entrypoint: serve + register, re-registering whenever the
    kubelet restarts (its socket is recreated, which wipes plugin
    registrations — the standard device-plugin re-register loop)."""
    import argparse
    import time

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--socket-dir",
                        default="/var/lib/kubelet/device-plugins")
    parser.add_argument("--chips", type=int, default=4)
    parser.add_argument("--resource", default=DEFAULT_RESOURCE)
    args = parser.parse_args(argv)

    plugin = FakeTpuDevicePlugin(args.socket_dir, chips=args.chips,
                                 resource_name=args.resource)
    plugin.start(register=False)
    print(f"fake-tpu device plugin serving {args.chips} chips on "
          f"{plugin.socket_path}", flush=True)
    kubelet = os.path.join(args.socket_dir, KUBELET_SOCKET)
    registered_ino = None
    try:
        while True:
            # a restarting kubelet wipes the device-plugins dir (including
            # OUR socket) before recreating kubelet.sock — re-serve first,
            # so the registration we then send points at a live endpoint
            if not os.path.exists(plugin.socket_path):
                plugin.stop()
                plugin.start(register=False)
                registered_ino = None
                print("socket wiped (kubelet restart?); re-serving",
                      flush=True)
            try:
                ino = os.stat(kubelet).st_ino
            except FileNotFoundError:
                ino = None
            if ino is not None and ino != registered_ino:
                try:
                    plugin.register()
                    registered_ino = ino
                    print("registered with kubelet", flush=True)
                except Exception as exc:  # kubelet mid-restart; retry
                    print(f"register failed, retrying: {exc}", flush=True)
            time.sleep(5)
    except KeyboardInterrupt:
        pass
    finally:
        plugin.stop()


if __name__ == "__main__":
    main()
