"""Bounded in-process time-series store with tiered downsampling.

A scrape is a point in time; a loadtest (or an incident) is a curve.  The
flight recorder retains recent *attempts*, the SLO engine retains burn
*windows* — but nothing retains "p99 ready-time, queue depth, and stage
latency as functions of time", so "where does the curve bend as the fleet
grows" is unanswerable after the fact.  This module is that retained
history: a tiny TSDB fed once per ``NotebookMetrics.scrape()`` with a
handful of pre-selected series.

Storage per series is a three-tier downsampling ring:

  raw   — every sample, deque(maxlen=raw_capacity)
  10s   — fold into 10-second buckets (count/sum/min/max/last)
  60s   — fold into 60-second buckets

Folding happens at append time (no background compaction thread), every
tier is a bounded deque, and the whole store is O(series x capacity)
memory.  Tier capacities default to ~85 minutes of raw history at a 10s
scrape cadence, ~2.8 hours at 10s resolution and ~17 hours at 60s —
enough to carry a whole loadtest or an incident window in a diagnostics
bundle.

Timestamps are INJECTED (``sample(t, values)``): the store never reads a
clock, so it is FakeClock-deterministic in tests and satisfies the
ci/analyzers clock discipline by construction.  Queryable at
``/debug/timeline?series=...&tier=...`` and captured wholesale into the
``ops/diagnose`` bundle via ``dump()``, so a run's p99-vs-time curve is
reconstructable offline.
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque

TIERS = ("raw", "10s", "60s")
_TIER_WIDTH = {"10s": 10.0, "60s": 60.0}


class TimeSeriesStore:
    """See module docstring.  `max_series` bounds the name space (extra
    series are dropped, counted in `dropped_series_total`) so a label
    explosion upstream cannot grow this store without bound."""

    def __init__(self, raw_capacity: int = 512,
                 tier10_capacity: int = 1024,
                 tier60_capacity: int = 1024,
                 max_series: int = 256) -> None:
        self.raw_capacity = raw_capacity
        self.tier_capacity = {"10s": tier10_capacity,
                              "60s": tier60_capacity}
        self.max_series = max_series
        self._lock = threading.Lock()
        # name -> {"raw": deque[(t, v)], "10s": deque[bucket],
        #          "60s": deque[bucket]} with bucket =
        #          {"t": start, "count", "sum", "min", "max", "last"}
        self._series: "OrderedDict[str, dict]" = OrderedDict()
        self.samples_total = 0
        self.dropped_series_total = 0

    # -- write side (NotebookMetrics.scrape) ----------------------------------
    def sample(self, t: float, values: dict) -> None:
        """Record one observation per named series at injected time `t`.
        Non-finite / non-numeric values are skipped."""
        with self._lock:
            for name, value in values.items():
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    continue
                if v != v or v in (float("inf"), float("-inf")):
                    continue
                s = self._series.get(name)
                if s is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series_total += 1
                        continue
                    s = {"raw": deque(maxlen=self.raw_capacity),
                         "10s": deque(maxlen=self.tier_capacity["10s"]),
                         "60s": deque(maxlen=self.tier_capacity["60s"])}
                    self._series[name] = s
                s["raw"].append((t, v))
                for tier, width in _TIER_WIDTH.items():
                    bucket_t = (t // width) * width
                    ring = s[tier]
                    head = ring[-1] if ring else None
                    if head is not None and head["t"] == bucket_t:
                        head["count"] += 1
                        head["sum"] += v
                        head["min"] = min(head["min"], v)
                        head["max"] = max(head["max"], v)
                        head["last"] = v
                    else:
                        ring.append({"t": bucket_t, "count": 1, "sum": v,
                                     "min": v, "max": v, "last": v})
                self.samples_total += 1

    # -- read side (/debug/timeline, ops/diagnose, loadtest) ------------------
    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, name: str, tier: str = "raw") -> dict:
        """One series at one tier.  Raw points are [t, v] pairs; the
        downsampled tiers return the folded bucket dicts (count/sum/min/
        max/last, plus a derived mean).  Unknown series/tier yields an
        empty point list with an `error` field rather than raising —
        the debug surface must never 500."""
        if tier not in TIERS:
            return {"series": name, "tier": tier, "points": [],
                    "error": "unknown tier (expected %s)" % (TIERS,)}
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return {"series": name, "tier": tier, "points": [],
                        "error": "unknown series"}
            if tier == "raw":
                points = [[t, v] for (t, v) in s["raw"]]
            else:
                points = [{**b, "mean": b["sum"] / b["count"]}
                          for b in s[tier]]
            return {"series": name, "tier": tier, "points": points}

    def dump(self) -> dict:
        """Every series at every tier — the diagnostics-bundle capture
        that makes a run's curves reconstructable offline."""
        with self._lock:
            out = {}
            for name, s in self._series.items():
                out[name] = {
                    "raw": [[t, v] for (t, v) in s["raw"]],
                    "10s": [dict(b) for b in s["10s"]],
                    "60s": [dict(b) for b in s["60s"]],
                }
            return {
                "samples_total": self.samples_total,
                "dropped_series_total": self.dropped_series_total,
                "bounds": {"raw_capacity": self.raw_capacity,
                           "tier10_capacity": self.tier_capacity["10s"],
                           "tier60_capacity": self.tier_capacity["60s"],
                           "max_series": self.max_series},
                "series": out,
            }

    def snapshot(self) -> dict:
        """The /debug/timeline body when no ?series= is asked for: the
        inventory plus bounds, so an operator can discover what to query."""
        with self._lock:
            inventory = {
                name: {"raw_points": len(s["raw"]),
                       "10s_buckets": len(s["10s"]),
                       "60s_buckets": len(s["60s"])}
                for name, s in self._series.items()
            }
        return {
            "tiers": list(TIERS),
            "samples_total": self.samples_total,
            "dropped_series_total": self.dropped_series_total,
            "bounds": {"raw_capacity": self.raw_capacity,
                       "tier10_capacity": self.tier_capacity["10s"],
                       "tier60_capacity": self.tier_capacity["60s"],
                       "max_series": self.max_series},
            "series": inventory,
        }

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self.samples_total = 0
            self.dropped_series_total = 0


__all__ = ["TimeSeriesStore", "TIERS"]
