"""In-notebook performance metrics: MFU, throughput, HBM.

The north-star metrics from BASELINE.md are measured here (the control-plane
Prometheus metrics live in core/metrics.py; this is the data-plane side,
exported in Prometheus text format so the same scrape infra picks both up).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

import jax

from ..models.configs import TransformerConfig


def hbm_usage_bytes() -> dict[str, int]:
    """Per-device HBM in use (0s on backends without memory_stats)."""
    usage = {}
    for dev in jax.local_devices():
        stats = getattr(dev, "memory_stats", lambda: None)() or {}
        usage[str(dev)] = int(stats.get("bytes_in_use", 0))
    return usage


@dataclass
class StepTimer:
    """Rolling train-step telemetry; call `observe()` once per synced step."""

    config: TransformerConfig
    batch: int
    seq_len: int
    num_chips: int
    accelerator: str = "v5e"
    window: int = 20
    _times: list[float] = field(default_factory=list)
    _last: Optional[float] = None

    def observe(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._times.append(now - self._last)
            if len(self._times) > self.window:
                self._times.pop(0)
        self._last = now

    @property
    def step_time_s(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    @property
    def tokens_per_s(self) -> float:
        st = self.step_time_s
        return self.batch * self.seq_len / st if st else 0.0

    @property
    def mfu(self) -> float:
        from ..models.train import mfu as mfu_fn

        return mfu_fn(
            self.tokens_per_s,
            self.config,
            self.seq_len,
            self.num_chips,
            self.accelerator,
        )

    def report(self) -> dict:
        return {
            "step_time_s": self.step_time_s,
            "tokens_per_s": self.tokens_per_s,
            "mfu": self.mfu,
            "hbm_bytes_in_use": sum(hbm_usage_bytes().values()),
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition the workbench image can serve on /metrics."""
        r = self.report()
        lines = []
        for key, value in r.items():
            name = f"notebook_training_{key}"
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {value}")
        return "\n".join(lines) + "\n"
