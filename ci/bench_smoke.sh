#!/usr/bin/env bash
# Bench smoke on whatever backend is present (CPU in CI): asserts bench.py
# emits exactly one valid JSON line.
set -euo pipefail
cd "$(dirname "$0")/.."
out=$(python bench.py 2 2>/dev/null | grep '^{')
echo "$out" | python -c 'import json,sys; d=json.load(sys.stdin); assert {"metric","value","unit","vs_baseline"} <= set(d), d; print("bench smoke ok:", d["metric"])'
