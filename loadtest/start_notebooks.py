"""Load test: spawn N notebooks, measure notebook-to-ready latency.

The reference load test templates N Notebook CRs and kubectl-applies them,
measuring nothing (loadtest/start_notebooks.py:1-60).  Ours drives the
standalone stack and reports the north-star metric BASELINE.md defines:
notebook-to-ready latency (p50/p95/max), for CPU and TPU shapes.

    python loadtest/start_notebooks.py -l 50 --tpu v5e:4x4

`--wire` routes everything through the real-cluster backend instead of
the in-memory store: the ApiServer is served over the k8s wire protocol
and both the controllers (KubeClient + informers) and the load driver
talk to it over sockets — end-to-end latency including the REST/watch
round trips.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from kubeflow_tpu.api.types import Notebook, TPUSpec  # noqa: E402
from kubeflow_tpu.main import build_manager  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("-l", "--count", type=int, default=3,
                        help="number of notebooks (reference default 3)")
    parser.add_argument("--namespace", default="loadtest")
    parser.add_argument("--tpu", default="",
                        help="accelerator:topology, e.g. v5e:4x4 (default CPU)")
    parser.add_argument("--timeout", type=float, default=30.0)
    parser.add_argument("--wire", action="store_true",
                        help="drive through the wire-protocol backend "
                        "(real sockets + informers) instead of in-memory")
    args = parser.parse_args(argv)

    srv = client = None
    if args.wire:
        from kubeflow_tpu.kube import ApiServer, FakeCluster
        from kubeflow_tpu.kube.client import KubeClient, RestConfig
        from kubeflow_tpu.kube.wire import KubeApiWireServer

        store = ApiServer()
        cluster = FakeCluster(store)
        srv = KubeApiWireServer(store).start()
        client = KubeClient(RestConfig(server=srv.url))
        mgr, api, _, _ = build_manager(api=client)
        client.start_informers(mgr.watched_kinds())
    else:
        mgr, api, cluster, _ = build_manager()
    cluster.add_node("cpu-node", allocatable={"cpu": "512", "memory": "2048Gi"})
    tpu = None
    if args.tpu:
        accel, topology = args.tpu.split(":")
        tpu = TPUSpec(accel, topology)
        shape = tpu.validate()
        cluster.add_tpu_slice_nodes(
            shape.accelerator.gke_label, shape.topology,
            shape.num_hosts * args.count, shape.chips_per_host,
        )
    mgr.start()

    latencies: list[float] = []
    try:
        t_start = time.perf_counter()
        for i in range(args.count):
            name = f"loadtest-nb-{i}"
            t0 = time.perf_counter()
            api.create(Notebook.new(name, args.namespace, tpu=tpu).obj)
            deadline = t0 + args.timeout
            while time.perf_counter() < deadline:
                live = api.try_get("Notebook", args.namespace, name)
                status = (live.body.get("status") or {}) if live else {}
                expected = tpu.shape.num_hosts if tpu else 1
                if status.get("readyReplicas") == expected:
                    latencies.append(time.perf_counter() - t0)
                    break
                time.sleep(0.01)
            else:
                print(f"TIMEOUT waiting for {name}", file=sys.stderr)
                return 1
        total = time.perf_counter() - t_start
    finally:
        mgr.stop()
        if client is not None:
            client.stop_informers()
        if srv is not None:
            srv.stop()

    latencies.sort()
    print(json.dumps({
        "notebooks": args.count,
        "backend": "wire" if args.wire else "in-memory",
        "tpu": args.tpu or "cpu",
        "total_s": round(total, 3),
        "ready_latency_p50_s": round(statistics.median(latencies), 4),
        "ready_latency_p95_s": round(
            latencies[max(0, int(len(latencies) * 0.95) - 1)], 4),
        "ready_latency_max_s": round(latencies[-1], 4),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
