"""e2e phase harness: create -> validate -> update -> delete over a fixture
matrix, the analog of the reference's real-cluster suite
(odh e2e/notebook_controller_setup_test.go:55-120: notebookContext list,
phased TestE2ENotebookController, poll-until helpers) run against the full
in-memory stack with the threaded manager — the closest thing to a cluster
this environment has.
"""

import time
from dataclasses import dataclass, field
from typing import Optional

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core import constants as CC
from kubeflow_tpu.core.culling_controller import setup_culling
from kubeflow_tpu.core.jupyter import FakeJupyterState
from kubeflow_tpu.core.metrics import NotebookMetrics
from kubeflow_tpu.core.notebook_controller import setup_core_controllers
from kubeflow_tpu.kube import ApiServer, FakeCluster, Manager
from kubeflow_tpu.odh import constants as OC
from kubeflow_tpu.odh.controller import setup_odh_controllers
from kubeflow_tpu.utils.config import CoreConfig, OdhConfig

CENTRAL_NS = "opendatahub"
# generous, like the reference's 3-minute e2e resource timeout
# (notebook_controller_setup_test.go:94): a full-suite run shares the host
# with compile-heavy compute tests, and a starved reconcile thread must
# show up as slow, not as a phase flake
POLL_TIMEOUT_S = 60.0
POLL_INTERVAL_S = 0.02


@dataclass
class NotebookContext:
    """One e2e fixture (reference notebookContext, setup_test.go:55-61)."""

    name: str
    tpu: Optional[TPUSpec] = None
    annotations: dict = field(default_factory=dict)
    namespace: str = "e2e"

    @property
    def expected_hosts(self) -> int:
        return (self.tpu.shape.num_hosts * self.tpu.slices) if self.tpu else 1

    @property
    def auth(self) -> bool:
        return self.annotations.get(OC.ANNOTATION_INJECT_AUTH) == "true"


CONTEXTS = [
    NotebookContext("e2e-cpu"),
    NotebookContext("e2e-tpu-1chip", tpu=TPUSpec("v5e", "1x1")),
    NotebookContext("e2e-tpu-multihost", tpu=TPUSpec("v5e", "4x4")),
    NotebookContext(
        "e2e-tpu-multislice", tpu=TPUSpec("v5e", "4x4", slices=2)
    ),
    NotebookContext(
        "e2e-tpu-auth",
        tpu=TPUSpec("v5e", "2x4"),
        annotations={OC.ANNOTATION_INJECT_AUTH: "true"},
    ),
]


def wait_for(cond, what: str):
    """PollUntilContextTimeout analog (e2e helper_test.go:28-56)."""
    deadline = time.time() + POLL_TIMEOUT_S
    while time.time() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(POLL_INTERVAL_S)
    raise AssertionError(f"timed out waiting for {what}")


def mutate_notebook(api, namespace, name, fn):
    """Read-mutate-update under the production conflict-retry helper —
    controllers write status and annotations concurrently with the test,
    exactly why the reference's e2e wraps every write in RetryOnConflict."""
    from kubeflow_tpu.kube import retry_on_conflict

    def attempt():
        nb = api.get("Notebook", namespace, name)
        fn(nb)
        return api.update(nb)

    return retry_on_conflict(attempt, steps=20,
                             initial_backoff_s=POLL_INTERVAL_S, factor=1.0)


@pytest.fixture(scope="module")
def stack():
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "256", "memory": "1024Gi"})
    # enough TPU capacity for every fixture simultaneously
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "4x4", 16, 4, "v5e-4x4")
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "1x1", 2, 1, "v5e-1x1")
    cluster.add_tpu_slice_nodes("tpu-v5-lite-podslice", "2x4", 4, 8, "v5e-2x4")
    mgr = Manager(api)
    setup_core_controllers(mgr, CoreConfig())
    setup_odh_controllers(mgr, OdhConfig(controller_namespace=CENTRAL_NS))
    mgr.start()
    yield api, cluster, mgr
    mgr.stop()


@pytest.mark.parametrize("ctx", CONTEXTS, ids=lambda c: c.name)
class TestE2ENotebookLifecycle:
    def test_phase_create(self, stack, ctx):
        api, _, _ = stack
        api.create(
            Notebook.new(
                ctx.name, ctx.namespace, tpu=ctx.tpu, annotations=ctx.annotations
            ).obj
        )
        wait_for(
            lambda: (nb := api.try_get("Notebook", ctx.namespace, ctx.name))
            is not None
            and OC.STOP_ANNOTATION not in nb.metadata.annotations,
            f"{ctx.name}: reconciliation lock removed",
        )
        wait_for(
            lambda: (nb := api.try_get("Notebook", ctx.namespace, ctx.name))
            is not None
            and nb.body.get("status", {}).get("readyReplicas")
            == ctx.expected_hosts,
            f"{ctx.name}: {ctx.expected_hosts} ready workers",
        )

    def test_phase_validate(self, stack, ctx):
        api, _, _ = stack
        # workload objects
        num_slices = ctx.tpu.slices if ctx.tpu else 1
        for s in range(num_slices):
            sts_name = (
                ctx.name if num_slices == 1 else f"{ctx.name}-slice-{s}"
            )
            sts = api.get("StatefulSet", ctx.namespace, sts_name)
            per_slice = ctx.tpu.shape.num_hosts if ctx.tpu else 1
            assert sts.spec["replicas"] == per_slice
        assert api.try_get("Service", ctx.namespace, ctx.name) is not None
        if ctx.tpu:
            headless = api.get("Service", ctx.namespace, f"{ctx.name}-workers")
            assert headless.spec["clusterIP"] == "None"
            status = api.get("Notebook", ctx.namespace, ctx.name).body["status"]
            assert status["sliceHealth"] == "Healthy"
            assert len(status["workerStates"]) == ctx.expected_hosts
            # distributed env on a worker pod
            sts0 = ctx.name if num_slices == 1 else f"{ctx.name}-slice-0"
            pod = api.get("Pod", ctx.namespace, f"{sts0}-0")
            env = {e["name"] for e in pod.spec["containers"][0]["env"]}
            assert {"TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES",
                    "JAX_COORDINATOR_ADDRESS"} <= env
            if num_slices > 1:
                assert "MEGASCALE_NUM_SLICES" in env
        # routing
        routes = api.list(
            "HTTPRoute", namespace=CENTRAL_NS,
            label_selector={"notebook-name": ctx.name},
        )
        assert len(routes) == 1
        backend = routes[0].spec["rules"][0]["backendRefs"][0]
        assert backend["port"] == (8443 if ctx.auth else 8888)
        assert (
            api.try_get("ReferenceGrant", ctx.namespace, OC.REFERENCEGRANT_NAME)
            is not None
        )
        # network policies
        assert api.try_get(
            "NetworkPolicy", ctx.namespace, f"{ctx.name}-ctrl-np"
        ) is not None
        if ctx.auth:
            assert api.try_get("ServiceAccount", ctx.namespace, ctx.name) is not None
            pod_containers = api.get(
                "Pod", ctx.namespace,
                f"{ctx.name if (not ctx.tpu or ctx.tpu.slices == 1) else ctx.name + '-slice-0'}-0",
            ).spec["containers"]
            assert any(c["name"] == "kube-rbac-proxy" for c in pod_containers)

    def test_phase_update_stop_resume(self, stack, ctx):
        api, _, _ = stack
        mutate_notebook(
            api, ctx.namespace, ctx.name,
            lambda nb: nb.metadata.annotations.__setitem__(
                CC.STOP_ANNOTATION, "2026-07-29T00:00:00Z"))
        wait_for(
            lambda: all(
                s.spec["replicas"] == 0
                for s in api.list("StatefulSet", namespace=ctx.namespace)
                if s.metadata.labels.get("notebook-name", s.name) == ctx.name
                or s.name == ctx.name
            ),
            f"{ctx.name}: slice-atomic stop",
        )
        mutate_notebook(
            api, ctx.namespace, ctx.name,
            lambda nb: nb.metadata.annotations.pop(CC.STOP_ANNOTATION, None))
        wait_for(
            lambda: api.get("Notebook", ctx.namespace, ctx.name)
            .body.get("status", {})
            .get("readyReplicas")
            == ctx.expected_hosts,
            f"{ctx.name}: resume",
        )

    def test_phase_cull_uncull(self, stack, ctx):
        """Idle-culling against the LIVE threaded stack (the reference's
        e2e culls a real notebook, notebook_creation_test.go:31-83): mark
        the Jupyter server idle, watch the culler stop the workload
        slice-atomically, then un-cull and watch it resume."""
        api, _, mgr = stack
        jupyter = FakeJupyterState()
        # fast-cull config: a 3-second idle threshold (annotations
        # initialize to NOW and never move backwards, so the threshold is
        # real wall time); a busy kernel bumps last-activity every pass and
        # stays under it for the resume window; check period 0 re-evaluates
        # every reconcile
        # (check period must be >0: requeue_after=0 means "don't requeue",
        # so a 0 period would only ever re-check on watch events)
        cull_cfg = CoreConfig(enable_culling=True, cull_idle_time_min=0.05,
                              idleness_check_period_min=0.01)
        rec = setup_culling(mgr, cull_cfg, jupyter, NotebookMetrics(api))
        try:
            # every OTHER live context reports a busy kernel so only THIS
            # context's idle-detection is exercised — otherwise the first
            # cull phase would cull the whole module's notebooks and later
            # contexts would assert trivially against pre-culled state
            for other in CONTEXTS:
                if other.name != ctx.name:
                    jupyter.set_kernels(other.namespace, other.name, [{
                        "id": "k1", "name": "python3",
                        "last_activity": "2020-01-01T00:00:00Z",
                        "execution_state": "busy", "connections": 1}])
            # this context must arrive UN-culled (a prior context's phase
            # culling it would make the wait below assert stale state)
            assert CC.STOP_ANNOTATION not in api.get(
                "Notebook", ctx.namespace, ctx.name).metadata.annotations
            jupyter.set_kernels(ctx.namespace, ctx.name, [{
                "id": "k1", "name": "python3",
                "last_activity": "2020-01-01T00:00:00Z",
                "execution_state": "idle", "connections": 0}])
            mgr.enqueue_all("culling")
            wait_for(
                lambda: all(
                    s.spec["replicas"] == 0
                    for s in api.list("StatefulSet", namespace=ctx.namespace)
                    if s.name == ctx.name
                    or s.name.startswith(f"{ctx.name}-slice-")),
                f"{ctx.name}: culled slice-atomically")
            live = api.get("Notebook", ctx.namespace, ctx.name)
            assert CC.STOP_ANNOTATION in live.metadata.annotations
            # the user comes back: kernel goes busy (at a 0-minute idle
            # threshold anything else would be instantly re-culled)
            jupyter.set_kernels(ctx.namespace, ctx.name, [{
                "id": "k1", "name": "python3",
                "last_activity": "2020-01-01T00:00:00Z",
                "execution_state": "busy", "connections": 1}])
            # un-cull: the dashboard removes the stop annotation
            mutate_notebook(
                api, ctx.namespace, ctx.name,
                lambda nb: nb.metadata.annotations.pop(
                    CC.STOP_ANNOTATION, None))
            wait_for(
                lambda: api.get("Notebook", ctx.namespace, ctx.name)
                .body.get("status", {}).get("readyReplicas")
                == ctx.expected_hosts,
                f"{ctx.name}: resumed after un-cull")
        finally:
            # later phases (and other contexts) must not fight the culler
            mgr.unregister("culling")

    def test_phase_delete(self, stack, ctx):
        api, _, _ = stack
        api.delete("Notebook", ctx.namespace, ctx.name)
        wait_for(
            lambda: api.try_get("Notebook", ctx.namespace, ctx.name) is None,
            f"{ctx.name}: finalized",
        )
        wait_for(
            lambda: not api.list(
                "HTTPRoute", namespace=CENTRAL_NS,
                label_selector={"notebook-name": ctx.name},
            ),
            f"{ctx.name}: route cleanup",
        )
        # polled like every other phase check: a reconcile that raced the
        # cascade may briefly recreate a slice STS; the store's dangling-
        # owner GC (kube/store.py _collect_dangling_owners) must reap it
        wait_for(
            lambda: not [
                s for s in api.list("StatefulSet", namespace=ctx.namespace)
                if s.name.startswith(ctx.name)
            ],
            f"{ctx.name}: owned StatefulSets garbage-collected",
        )


@pytest.fixture(scope="module")
def istio_stack():
    """A second threaded stack with USE_ISTIO on — istio is a deploy-time
    profile (reference: USE_ISTIO env read at manager start), so it gets
    its own manager rather than a per-notebook context."""
    api = ApiServer()
    cluster = FakeCluster(api)
    cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
    mgr = Manager(api)
    setup_core_controllers(mgr, CoreConfig(use_istio=True))
    mgr.start()
    yield api, cluster, mgr
    mgr.stop()


class TestE2EIstio:
    """USE_ISTIO lifecycle against the live threaded manager — the e2e
    analog of the reference's istio test lane
    (install_istio.sh + notebook_controller.go:558-699)."""

    NS, NAME = "e2e-istio", "istio-nb"
    VS = "notebook-e2e-istio-istio-nb"

    def test_phase_create(self, istio_stack):
        api, _, _ = istio_stack
        api.create(Notebook.new(self.NAME, self.NS).obj)
        vs = wait_for(
            lambda: api.try_get("VirtualService", self.NS, self.VS),
            "VirtualService rendered")
        (route,) = vs.body["spec"]["http"]
        assert route["match"] == [
            {"uri": {"prefix": f"/notebook/{self.NS}/{self.NAME}/"}}]
        assert route["route"][0]["destination"]["host"] == \
            f"{self.NAME}.{self.NS}.svc.cluster.local"

    def test_phase_drift_repair(self, istio_stack):
        api, _, mgr = istio_stack
        vs = api.get("VirtualService", self.NS, self.VS)
        vs.body["spec"]["gateways"] = ["intruder/gw"]
        api.update(vs)
        mgr.enqueue_all("notebook")
        wait_for(
            lambda: api.get("VirtualService", self.NS, self.VS)
            .body["spec"]["gateways"] == ["kubeflow/kubeflow-gateway"],
            "VirtualService drift reverted")

    def test_phase_delete(self, istio_stack):
        api, _, _ = istio_stack
        api.delete("Notebook", self.NS, self.NAME)
        wait_for(
            lambda: api.try_get("VirtualService", self.NS, self.VS) is None,
            "VirtualService garbage-collected with the Notebook")
