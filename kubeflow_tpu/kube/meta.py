"""Object metadata and generic object model for the in-memory control plane.

The reference builds on k8s apimachinery (metav1.ObjectMeta and friends).  We
model the subset the notebook stack actually uses: names/namespaces, labels,
annotations, ownerReferences, finalizers, resourceVersion-based optimistic
concurrency, and deletionTimestamp-driven finalization.  Objects are typed
wrappers over plain dicts ("unstructured" style) because the Notebook CRD's
pod template is a raw PodSpec passthrough in the reference
(components/notebook-controller/api/v1/notebook_types.go:26-40) and dicts keep
that passthrough lossless.
"""

from __future__ import annotations

import copy
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _empty_view() -> dict:
    """Empty spec/status view of a frozen object: plain dict normally, a
    mutation-trapping FrozenDict under INVARIANTS_STRICT."""
    from ..utils import invariants

    if invariants.strict_enabled():
        return invariants.EMPTY_FROZEN_DICT
    return {}


def copy_tree(x):
    """Deep copy of a JSON-shaped tree (dicts/lists/scalars).

    API object bodies are unstructured JSON by construction (the CRD pod
    template is a raw passthrough), so the generic copy.deepcopy machinery
    — memo dict, reconstruct dispatch, keep-alive bookkeeping — is pure
    overhead on the store's hottest operation.  This specialized walk is
    ~10x faster and is what every KubeObject copy path uses.  Non-JSON
    leaves (never produced by the store itself) are shared, not copied."""
    if isinstance(x, dict):
        return {k: copy_tree(v) for k, v in x.items()}
    if isinstance(x, list):
        return [copy_tree(v) for v in x]
    return x


@dataclass
class OwnerReference:
    api_version: str
    kind: str
    name: str
    uid: str
    controller: bool = False
    block_owner_deletion: bool = False

    def to_dict(self) -> dict:
        return {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "name": self.name,
            "uid": self.uid,
            "controller": self.controller,
            "blockOwnerDeletion": self.block_owner_deletion,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "OwnerReference":
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=bool(d.get("controller", False)),
            block_owner_deletion=bool(d.get("blockOwnerDeletion", False)),
        )


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    generate_name: str = ""
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: str = ""
    deletion_timestamp: Optional[str] = None
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)
    finalizers: list[str] = field(default_factory=list)
    # server-side-apply bookkeeping: raw managedFields entries
    # ({manager, operation, apiVersion, fieldsType, fieldsV1}) — kept
    # unstructured like the body (kube/apply.py owns the semantics)
    managed_fields: list[dict] = field(default_factory=list)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "resourceVersion": str(self.resource_version),
            "generation": self.generation,
            "creationTimestamp": self.creation_timestamp,
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
        }
        if self.generate_name:
            d["generateName"] = self.generate_name
        if self.deletion_timestamp:
            d["deletionTimestamp"] = self.deletion_timestamp
        if self.owner_references:
            d["ownerReferences"] = [o.to_dict() for o in self.owner_references]
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        if self.managed_fields:
            d["managedFields"] = copy_tree(self.managed_fields)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
            generate_name=d.get("generateName", ""),
            uid=d.get("uid", ""),
            resource_version=int(d.get("resourceVersion", 0) or 0),
            generation=int(d.get("generation", 0) or 0),
            creation_timestamp=d.get("creationTimestamp", ""),
            deletion_timestamp=d.get("deletionTimestamp"),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            owner_references=[
                OwnerReference.from_dict(o) for o in d.get("ownerReferences") or []
            ],
            finalizers=list(d.get("finalizers") or []),
            managed_fields=copy_tree(d.get("managedFields") or []),
        )

    def controller_owner(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def copy(self) -> "ObjectMeta":
        return ObjectMeta(
            name=self.name,
            namespace=self.namespace,
            generate_name=self.generate_name,
            uid=self.uid,
            resource_version=self.resource_version,
            generation=self.generation,
            creation_timestamp=self.creation_timestamp,
            deletion_timestamp=self.deletion_timestamp,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_references=[copy.copy(r) for r in self.owner_references],
            finalizers=list(self.finalizers),
            managed_fields=copy_tree(self.managed_fields),
        )


@dataclass
class KubeObject:
    """Generic API object: typed metadata + unstructured body.

    `body` holds everything outside metadata (spec/status/data/subsets/...).

    `frozen` marks a committed store snapshot (set by the ApiServer at
    commit): frozen objects are SHARED — the store map, the watch history,
    every watcher and cache read the same instance — and must never be
    mutated.  `deepcopy()` always returns a mutable private copy.
    """

    api_version: str
    kind: str
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    body: dict[str, Any] = field(default_factory=dict)
    frozen: bool = field(default=False, compare=False, repr=False)

    # -- convenience accessors ------------------------------------------------
    @property
    def spec(self) -> dict:
        # a frozen (shared) object must not grow a skeleton key from a
        # mere read — return an empty view instead of mutating the body
        # (under INVARIANTS_STRICT a trapping view, so a write to the
        # empty view raises instead of silently vanishing)
        s = self.body.get("spec")
        if s is None:
            if self.frozen:
                return _empty_view()
            s = self.body.setdefault("spec", {})
        return s

    @spec.setter
    def spec(self, value: dict) -> None:
        self.body["spec"] = value

    @property
    def status(self) -> dict:
        s = self.body.get("status")
        if s is None:
            if self.frozen:
                return _empty_view()
            s = self.body.setdefault("status", {})
        return s

    @status.setter
    def status(self, value: dict) -> None:
        self.body["status"] = value

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    @property
    def labels(self) -> dict[str, str]:
        return self.metadata.labels

    @property
    def annotations(self) -> dict[str, str]:
        return self.metadata.annotations

    def gvk(self) -> tuple[str, str]:
        return (self.api_version, self.kind)

    def key(self) -> tuple[str, str, str]:
        return (self.kind, self.metadata.namespace, self.metadata.name)

    def deepcopy(self) -> "KubeObject":
        return KubeObject(
            api_version=self.api_version,
            kind=self.kind,
            metadata=self.metadata.copy(),
            body=copy_tree(self.body),
        )

    def same_as(self, other: "KubeObject") -> bool:
        """Semantic equality — what `to_dict() == to_dict()` used to
        decide on the write path, without materializing two dict copies.
        Dataclass equality on metadata plus structural dict equality on
        the body (the `frozen` marker never participates)."""
        return (
            self.api_version == other.api_version
            and self.kind == other.kind
            and self.metadata == other.metadata
            and self.body == other.body
        )

    def to_dict(self) -> dict:
        d = {
            "apiVersion": self.api_version,
            "kind": self.kind,
            "metadata": self.metadata.to_dict(),
        }
        d.update(copy_tree(self.body))
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "KubeObject":
        body = {k: v for k, v in d.items() if k not in ("apiVersion", "kind", "metadata")}
        return cls(
            api_version=d.get("apiVersion", ""),
            kind=d.get("kind", ""),
            metadata=ObjectMeta.from_dict(d.get("metadata") or {}),
            body=copy_tree(body),
        )

    def owner_reference(self, controller: bool = True) -> OwnerReference:
        return OwnerReference(
            api_version=self.api_version,
            kind=self.kind,
            name=self.metadata.name,
            uid=self.metadata.uid,
            controller=controller,
            block_owner_deletion=controller,
        )


def new_uid() -> str:
    return str(uuid.uuid4())


def set_controller_reference(owner: KubeObject, controlled: KubeObject) -> None:
    """Equivalent of controllerutil.SetControllerReference: exactly one
    controller ref, same namespace enforced (cross-namespace ownership is
    illegal in k8s — the reference works around it with finalizers for
    HTTPRoutes, odh notebook_controller.go:206-333)."""
    if owner.metadata.namespace != controlled.metadata.namespace:
        raise ValueError(
            "cross-namespace owner references are not allowed "
            f"({owner.metadata.namespace} -> {controlled.metadata.namespace})"
        )
    existing = controlled.metadata.controller_owner()
    if existing is not None and existing.uid != owner.metadata.uid:
        raise ValueError(f"object already controlled by {existing.name}")
    ref = owner.owner_reference(controller=True)
    controlled.metadata.owner_references = [
        r for r in controlled.metadata.owner_references if not r.controller
    ]
    controlled.metadata.owner_references.append(ref)
