"""Autoregressive generation with a static-shape KV cache.

TPU-first decode: the cache is a fixed [B, max_seq_len] ring per layer
(flax "cache" collection, stacked over the scanned layer axis), written
with `dynamic_update_slice` — no growing shapes, so the whole decode loop
is ONE compiled `lax.scan` program.  Prefill runs the prompt through the
same decode path in a single call (filling the cache), then the loop feeds
one token per step with its global position; rope is applied with global
positions before caching, so cached keys never need re-rotation.

Sampling: greedy (temperature=0) or temperature + top-k.  The reference
ships no inference path (it is a notebook controller); this is part of the
in-notebook compute plane the TPU build adds, and what a workbench uses to
serve/inspect a model it just trained.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .configs import TransformerConfig
from .transformer import Transformer


def decode_config(cfg: TransformerConfig,
                  unroll_layers: bool = True) -> TransformerConfig:
    """Training config -> decode config: remat off (nothing to rematerialize
    and the cache mutation must not be replayed), XLA attention (single-token
    queries never fit the flash kernel's tiling), and UNROLLED layers.

    scan_layers=False matters for bandwidth: under nn.scan the per-layer KV
    cache is a scanned variable, so every token step re-stacks the whole
    [layers, B, max_seq, kv_heads, head_dim] cache as fresh scan outputs —
    ~2x the step's HBM traffic in pure copies.  Unrolled, each layer's cache
    is a separate carry leaf of the token scan and the dynamic_update_slice
    aliases in place.  Measured on v5e (ci/decode_profile.py): 6.5k vs 3.6k
    tok/s at batch 16.  `unroll_layers=False` keeps the scanned stack (the
    profiler's A/B baseline).  Params from a scan_layers=True training run
    are converted by `generate` (see `unroll_params`).
    """
    # fused projections (one qkv + one gate_up matmul per layer) and
    # staged KV writes are the decode defaults — but only when CONVERTING
    # a training config: a cfg that is already decode-shaped keeps its
    # explicit settings, so callers can request the unfused layout or
    # unstaged writes (A/B profiling, old quantized trees, the
    # speculative rewind path) without this function overriding them.
    # "Already decode-shaped" is the explicit `decode` marker this
    # function stamps — NOT inferred from remat/attention_impl, so a
    # training config that happens to run remat=False + xla attention
    # still gets the decode defaults (ADVICE round 5)
    already_decode = cfg.decode
    fused = cfg.fused_projections if already_decode else True
    staged = cfg.staged_kv if already_decode else True
    if not unroll_layers:
        if already_decode and cfg.staged_kv:
            raise ValueError(
                "staged_kv is not supported under scanned layers "
                "(stage buffers would become scanned variables — the "
                "re-stacking cost staging exists to avoid)")
        staged = False
    return cfg.with_(decode=True, remat=False, attention_impl="xla",
                     scan_layers=not unroll_layers,
                     fused_projections=fused,
                     staged_kv=staged)


def unroll_params(params, num_layers: int):
    """Stacked training params ('layers' subtree with a leading layer axis,
    the scan_layers=True layout) -> the unrolled 'layer_i' layout the
    decode config's param tree uses.  Leaves boxes behind (nn.unbox): the
    stacked partition metadata names a 'layers' axis that does not exist on
    the per-layer slices."""
    import flax.linen as nn

    if "layers" not in params:
        return params
    stacked = nn.unbox(params["layers"])
    rest = {k: v for k, v in params.items() if k != "layers"}
    for i in range(num_layers):
        rest[f"layer_{i}"] = jax.tree.map(lambda a: a[i], stacked)
    return rest


def fuse_decode_params(params, cfg: TransformerConfig):
    """Training-layout layer params (separate q/k/v and gate/up kernels)
    -> the fused_projections layout (one qkv kernel [D, H+2kvH, Dh], one
    gate_up kernel [D, 2, M]).  Pure concatenation along the heads /
    fused axis, so it MUST run before quantization — int8/int4 scale
    tensors cannot be concatenated after the fact (per-last-dim scales
    are shared across exactly the axis the fusion concatenates).
    quantize_params / quantize_params_int4 walk the fused tree fine (the
    qkv/gate_up nodes carry ordinary `kernel` leaves).  No-op when the
    tree is already fused."""
    import flax.linen as nn

    def fuse_layer(layer):
        layer = dict(layer)
        attn = layer.get("attn")
        if attn is not None and "q" in attn:
            attn = dict(attn)
            qkv = jnp.concatenate(
                [nn.unbox(attn.pop(n)["kernel"]) for n in ("q", "k", "v")],
                axis=1)
            attn["qkv"] = {"kernel": qkv}
            layer["attn"] = attn
        mlp = layer.get("mlp")
        if mlp is not None and "gate" in mlp:
            mlp = dict(mlp)
            gate_up = jnp.stack(
                [nn.unbox(mlp.pop(n)["kernel"]) for n in ("gate", "up")],
                axis=1)
            mlp["gate_up"] = {"kernel": gate_up}
            layer["mlp"] = mlp
        return layer

    return {k: (fuse_layer(v) if k.startswith("layer_") else v)
            for k, v in nn.unbox(params).items()}


def prepare_decode(cfg: TransformerConfig, params,
                   unroll_layers: bool = True):
    """(training cfg, training-or-quantized params) -> (decode cfg,
    decode-layout params).  Unrolls a stacked tree, then fuses q/k/v and
    gate/up kernels into the fused_projections layout when the tree still
    carries raw `kernel` leaves.  An already-QUANTIZED unfused tree
    cannot be fused (scales don't concatenate) — the decode config falls
    back to fused_projections=False so old pipelines keep working;
    quantized flows that want the fusion win quantize AFTER this
    (bench.py, ci/llama*_decode.py)."""
    cfg = decode_config(cfg, unroll_layers=unroll_layers)
    if cfg.scan_layers:
        # scanned stack keeps the training layout
        return cfg.with_(fused_projections=False), params
    params = unroll_params(params, cfg.num_layers)
    attn0 = params.get("layer_0", {}).get("attn", {})
    if not cfg.fused_projections or "qkv" in attn0:
        return cfg, params
    if "kernel" in attn0.get("q", {}):
        return cfg, fuse_decode_params(params, cfg)
    return cfg.with_(fused_projections=False), params


def sample_token(
    logits: jax.Array,
    rng: Optional[jax.Array],
    temperature: float,
    top_k: int = 0,
) -> jax.Array:
    """[B, V] logits -> [B] token ids."""
    if temperature <= 0.0 or rng is None:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: int = 0,
    rng: Optional[jax.Array] = None,
    mesh=None,
    unroll_layers: bool = True,
) -> jax.Array:
    """prompt [B, P] int32 -> [B, P + max_new_tokens] completions.

    Prompts are assumed unpadded and equal-length (the notebook batch
    case); P + max_new_tokens must fit cfg.max_seq_len.  Accepts params in
    either layout: a scan_layers=True training run's stacked 'layers'
    subtree is converted to the decode layout on the fly (a trace-time
    reshuffle, free after jit).
    """
    cfg, params = prepare_decode(cfg, params, unroll_layers=unroll_layers)
    batch, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > cfg.max_seq_len:
        raise ValueError(
            f"prompt({prompt_len}) + new({max_new_tokens}) exceeds "
            f"max_seq_len {cfg.max_seq_len}")
    model = Transformer(cfg, mesh)
    if rng is None and temperature > 0.0:
        rng = jax.random.PRNGKey(0)

    # prefill: one full-prompt pass fills the cache and yields the first
    # sampled token from the last prompt position
    (logits, _aux), cache_vars = model.apply(
        {"params": params}, prompt, return_aux=True, decode=True,
        mutable=["cache"])
    step_rng = rng
    if step_rng is not None:
        step_rng, sub = jax.random.split(step_rng)
    else:
        sub = None
    next_tok = sample_token(logits[:, -1, :], sub, temperature, top_k)

    # thread the cache through the scan carry; every step is the same
    # static-shape program
    def scan_step(carry, _):
        cache, tok, pos, rng_ = carry
        positions = jnp.broadcast_to(pos, (batch, 1))
        (logits, _), new_cache = model.apply(
            {"params": params, **cache}, tok[:, None], return_aux=True,
            decode=True, positions=positions, mutable=["cache"])
        if rng_ is not None:
            rng_, sub = jax.random.split(rng_)
        else:
            sub = None
        nxt = sample_token(logits[:, -1, :], sub, temperature, top_k)
        return (new_cache, nxt, pos + 1, rng_), tok

    if max_new_tokens == 1:
        return jnp.concatenate([prompt, next_tok[:, None]], axis=1)

    carry = (cache_vars, next_tok, jnp.int32(prompt_len), step_rng)
    (_, last_tok, _, _), toks = jax.lax.scan(
        scan_step, carry, None, length=max_new_tokens - 1)
    # toks[i] is the token fed at step i (= sampled at step i-1); append the
    # final sample to complete the sequence
    generated = jnp.concatenate(
        [jnp.moveaxis(toks, 0, 1), last_tok[:, None]], axis=1)
    return jnp.concatenate([prompt, generated], axis=1)


__all__ = ["generate", "decode_config", "sample_token", "unroll_params",
           "fuse_decode_params", "prepare_decode"]
