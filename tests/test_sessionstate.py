"""Session-state store tests (core/sessionstate.py): generation
monotonicity, freshness metadata, the final-snapshot handler hook, the
dir-backed backend's torn-write safety, and the rendered checkpoint-sidecar
contract (core/workload.py)."""

import json

import pytest

from kubeflow_tpu.api.types import Notebook, TPUSpec
from kubeflow_tpu.core import constants as C
from kubeflow_tpu.core.sessionstate import (
    DeltaChainError,
    DirSessionStore,
    FollowerReplica,
    InMemorySessionStore,
    StaleWriterError,
    open_store,
    payload_digest,
)
from kubeflow_tpu.core.workload import generate_statefulsets
from kubeflow_tpu.utils.clock import FakeClock
from kubeflow_tpu.utils.config import CoreConfig


@pytest.fixture(params=["mem", "dir"])
def store(request, tmp_path):
    clock = FakeClock()
    if request.param == "mem":
        return InMemorySessionStore(clock=clock)
    return DirSessionStore(str(tmp_path / "sessions"), clock=clock)


class TestStoreSemantics:
    def test_generations_monotonic_and_latest(self, store):
        a = store.put("u1", "nb", 0, b"state-1")
        b = store.put("u1", "nb", 0, b"state-2")
        other = store.put("u1", "nb", 1, b"slice-1-state")
        assert (a.generation, b.generation) == (1, 2)
        assert other.generation == 1  # per-slice counters
        latest = store.latest("u1", "nb", 0)
        assert latest.generation == 2
        assert latest.digest == payload_digest(b"state-2")
        assert store.payload("u1", "nb", 0) == b"state-2"
        assert store.payload("u1", "nb", 0, generation=1) == b"state-1"
        assert store.info("u1", "nb", 0, 1).trigger == "periodic"
        assert store.latest("u1", "missing", 0) is None

    def test_freshness_metadata_uses_store_clock(self, store):
        first = store.put("u1", "nb", 0, b"x")
        store.clock.advance(120)
        second = store.put("u1", "nb", 0, b"y")
        assert second.saved_at - first.saved_at == pytest.approx(120)

    def test_pruned_to_max_to_keep(self, store):
        store.max_to_keep = 3
        for i in range(6):
            store.put("u1", "nb", 0, b"v%d" % i)
        gens = [s.generation for s in store.snapshots("u1", "nb", 0)]
        assert gens == [4, 5, 6]
        # pruning keeps generations monotonic (no reuse of dropped ids)
        assert store.put("u1", "nb", 0, b"v6").generation == 7

    def test_final_snapshot_handler_dispatch(self, store):
        calls = []

        def handler(ns, nb, slice_id):
            calls.append((ns, nb, slice_id))
            return store.put(ns, nb, slice_id, b"flushed", trigger="final")

        assert store.request_final_snapshot("u1", "nb", 0) is None  # unwired
        store.set_final_snapshot_handler(handler)
        info = store.request_final_snapshot("u1", "nb", 0)
        assert calls == [("u1", "nb", 0)]
        assert info.trigger == "final" and info.generation == 1

        # a handler that raises reads as "unreachable", never an error
        store.set_final_snapshot_handler(
            lambda *a: (_ for _ in ()).throw(RuntimeError("pod gone")))
        assert store.request_final_snapshot("u1", "nb", 0) is None


class TestDeltaChain:
    """Checkpoint-delta stream invariants (the replicated-kernel tier's
    substrate): strict chain ordering, digest-preserving compaction, and
    follower catch-up from any base — for both store backends."""

    def test_delta_requires_base_and_strict_order(self, store):
        with pytest.raises(DeltaChainError, match="no base snapshot"):
            store.append_delta("u1", "nb", 0, b"+orphan")
        store.put("u1", "nb", 0, b"base")
        store.append_delta("u1", "nb", 0, b"+d1", expected_seq=1)
        # a duplicate replay and a future slot are both out of order
        with pytest.raises(DeltaChainError, match="out-of-order"):
            store.append_delta("u1", "nb", 0, b"+dup", expected_seq=1)
        with pytest.raises(DeltaChainError, match="out-of-order"):
            store.append_delta("u1", "nb", 0, b"+skip", expected_seq=3)
        # rejected appends leave the chain untouched
        assert [d.seq for d in store.deltas("u1", "nb", 0)] == [1]
        assert store.materialize("u1", "nb", 0) == b"base+d1"

    def test_chain_head_tracks_base_then_deltas(self, store):
        assert store.chain_head("u1", "nb", 0) is None
        base = store.put("u1", "nb", 0, b"base")
        assert store.chain_head("u1", "nb", 0) == (1, 0, base.digest)
        d2 = [store.append_delta("u1", "nb", 0, b"+d%d" % i)
              for i in (1, 2)][-1]
        assert store.chain_head("u1", "nb", 0) == (1, 2, d2.digest)
        assert d2.digest == payload_digest(b"base+d1+d2")

    def test_compaction_preserves_digest_and_resets_chain(self, store):
        store.put("u1", "nb", 0, b"base")
        for i in range(3):
            store.append_delta("u1", "nb", 0, b"+d%d" % i)
        head_digest = store.chain_head("u1", "nb", 0)[2]
        folded = store.compact("u1", "nb", 0)
        # the folded base IS the old chain head, bit for bit
        assert folded.generation == 2
        assert folded.digest == head_digest
        assert store.payload("u1", "nb", 0) == b"base+d0+d1+d2"
        assert store.chain_head("u1", "nb", 0) == (2, 0, head_digest)
        assert store.deltas("u1", "nb", 0) == []
        # the chain restarts at seq 1 on the new base
        nxt = store.append_delta("u1", "nb", 0, b"+d3", expected_seq=1)
        assert (nxt.base_generation, nxt.seq) == (2, 1)

    def test_compact_without_chain_is_noop(self, store):
        assert store.compact("u1", "nb", 0) is None  # no base at all
        base = store.put("u1", "nb", 0, b"base")
        assert store.compact("u1", "nb", 0) == base  # empty chain

    def test_follower_catches_up_from_any_base(self, store):
        store.put("u1", "nb", 0, b"base")
        store.append_delta("u1", "nb", 0, b"+d1")
        follower = FollowerReplica(store, "u1", "nb", 0)
        assert follower.catch_up() == 2  # base reload + one delta
        assert follower.caught_up() and follower.lag() == 0
        # the primary moves on: another delta, then a compaction, then more
        store.append_delta("u1", "nb", 0, b"+d2")
        assert follower.lag() == 1 and not follower.caught_up()
        store.compact("u1", "nb", 0)
        store.append_delta("u1", "nb", 0, b"+d3")
        assert follower.lag() == 2  # stale base counts the full new chain
        assert follower.catch_up() == 2  # new-base reload + d3
        assert follower.state == b"base+d1+d2+d3"
        assert follower.digest == store.chain_head("u1", "nb", 0)[2]
        # a cold follower joining late needs only the compacted base
        late = FollowerReplica(store, "u1", "nb", 0)
        late.catch_up()
        assert late.state == follower.state
        assert late.caught_up()

    def test_follower_stops_at_chain_gap_and_verifies_digests(self, store):
        store.put("u1", "nb", 0, b"base")
        store.append_delta("u1", "nb", 0, b"+d1")
        store.append_delta("u1", "nb", 0, b"+d2")
        real = store.delta_payload
        # a delta pruned from under a lagging cursor stops the replay at
        # the last verified state instead of applying out of order
        lagging = FollowerReplica(store, "u1", "nb", 0)
        store.delta_payload = lambda *a, **k: None
        try:
            lagging.catch_up()
        finally:
            store.delta_payload = real
        assert (lagging.state, lagging.seq) == (b"base", 0)
        assert lagging.catch_up() == 2  # chain visible again: replay resumes
        assert lagging.state == b"base+d1+d2"
        # corrupted delta bytes never reach the follower's state
        corrupt = FollowerReplica(store, "u1", "nb", 0)
        store.delta_payload = lambda *a, **k: b"garbage"
        try:
            with pytest.raises(DeltaChainError, match="digest mismatch"):
                corrupt.catch_up()
        finally:
            store.delta_payload = real
        assert corrupt.state == b"base"  # stopped at the verified base

    def test_write_fence_rejects_demoted_epoch(self, store):
        store.put("u1", "nb", 0, b"base", writer_epoch=1)
        store.append_delta("u1", "nb", 0, b"+d1", writer_epoch=1)
        assert store.fence("u1", "nb", 2) == 2
        assert store.fence("u1", "nb", 1) == 2  # monotonic max
        for op in (
            lambda: store.put("u1", "nb", 0, b"x", writer_epoch=1),
            lambda: store.append_delta("u1", "nb", 0, b"+z", writer_epoch=1),
            lambda: store.compact("u1", "nb", 0, writer_epoch=1),
        ):
            with pytest.raises(StaleWriterError):
                op()
        # unfenced (non-replicated) writers and the new epoch still pass
        store.append_delta("u1", "nb", 0, b"+d2", writer_epoch=2)
        store.append_delta("u1", "nb", 0, b"+d3")
        assert store.materialize("u1", "nb", 0) == b"base+d1+d2+d3"
        assert store.fenced_rejections[("u1", "nb")] == 3


class TestDirStoreTornWrites:
    def test_payload_without_commit_marker_is_invisible_and_gced(
            self, tmp_path):
        store = DirSessionStore(str(tmp_path), clock=FakeClock())
        store.put("u1", "nb", 0, b"good")
        d = store._slice_dir("u1", "nb", 0)
        # simulate a sidecar killed after the payload write but before the
        # metadata commit marker landed
        (d / "gen-2.bin").write_bytes(b"torn")
        (d / ".tmp-gen-3.json-999").write_bytes(b"partial meta")
        snaps = store.snapshots("u1", "nb", 0)
        assert [s.generation for s in snaps] == [1]
        assert not (d / "gen-2.bin").exists()      # orphan GC'd
        assert not list(d.glob(".tmp-*"))          # stray tmp GC'd
        # the next save reuses the generation slot cleanly
        assert store.put("u1", "nb", 0, b"again").generation == 2

    def test_corrupt_commit_marker_drops_both_halves(self, tmp_path):
        store = DirSessionStore(str(tmp_path), clock=FakeClock())
        store.put("u1", "nb", 0, b"good")
        d = store._slice_dir("u1", "nb", 0)
        (d / "gen-5.json").write_text("{not json")
        (d / "gen-5.bin").write_bytes(b"whatever")
        assert [s.generation for s in store.snapshots("u1", "nb", 0)] == [1]
        assert not (d / "gen-5.json").exists()
        assert not (d / "gen-5.bin").exists()

    def test_survives_reopen(self, tmp_path):
        a = DirSessionStore(str(tmp_path), clock=FakeClock())
        info = a.put("u1", "nb", 2, b"persisted", trigger="pre-stop")
        b = DirSessionStore(str(tmp_path), clock=FakeClock())
        got = b.latest("u1", "nb", 2)
        assert got == info
        assert b.payload("u1", "nb", 2) == b"persisted"
        meta = json.loads(
            (b._slice_dir("u1", "nb", 2) / "gen-1.json").read_text())
        assert meta["trigger"] == "pre-stop"


class TestOpenStore:
    def test_uri_dispatch(self, tmp_path):
        assert isinstance(open_store("mem://x"), InMemorySessionStore)
        d = open_store(f"file://{tmp_path}/s")
        assert isinstance(d, DirSessionStore)
        bare = open_store(str(tmp_path / "bare"))
        assert isinstance(bare, DirSessionStore)
        assert bare.uri.startswith("file://")


class TestSidecarContractRender:
    """core/workload.py renders the checkpoint-sidecar contract into every
    TPU worker template when CHECKPOINT_STORE_URI is configured."""

    CFG = CoreConfig(checkpoint_store_uri="file:///ckpt/store",
                     checkpoint_interval_s=120.0)

    def _main(self, sts):
        return sts.spec["template"]["spec"]["containers"][0]

    def test_env_prestop_and_podinfo_rendered(self):
        nb = Notebook.new("nb", "u1", tpu=TPUSpec("v5e", "4x4"))
        (sts,) = generate_statefulsets(nb, self.CFG)
        main = self._main(sts)
        env = {e["name"]: e.get("value") for e in main["env"]}
        assert env[C.ENV_CHECKPOINT_STORE_URI] == "file:///ckpt/store"
        assert env[C.ENV_CHECKPOINT_INTERVAL_S] == "120"
        # no restore intent in status -> no restore stamping
        assert C.ENV_CHECKPOINT_RESTORE_GENERATION not in env
        assert main["lifecycle"]["preStop"]["exec"]["command"][-1] \
            == "--pre-stop"
        vols = {v["name"]: v for v in sts.spec["template"]["spec"]["volumes"]}
        items = vols["podinfo"]["downwardAPI"]["items"]
        assert items[0]["path"] == "checkpoint-requested"
        assert C.ANNOTATION_CHECKPOINT_REQUESTED in \
            items[0]["fieldRef"]["fieldPath"]
        mounts = {m["name"]: m for m in main["volumeMounts"]}
        assert mounts["podinfo"]["mountPath"] == "/etc/podinfo"

    def test_restore_intent_stamped_from_session_state(self):
        nb = Notebook.new("nb", "u1", tpu=TPUSpec("v5e", "4x4", slices=2))
        nb.obj.body["status"] = {"sessionState": {
            "1": {"restoreGeneration": 7, "phase": "migrating",
                  "restoreUri": "file:///ckpt/store/u1/nb/slice-1/gen-7"},
        }}
        slice0, slice1 = generate_statefulsets(nb, self.CFG)
        env0 = {e["name"]: e.get("value") for e in self._main(slice0)["env"]}
        env1 = {e["name"]: e.get("value") for e in self._main(slice1)["env"]}
        assert C.ENV_CHECKPOINT_RESTORE_GENERATION not in env0
        assert env1[C.ENV_CHECKPOINT_RESTORE_GENERATION] == "7"
        assert env1[C.ENV_CHECKPOINT_RESTORE_URI].endswith("slice-1/gen-7")

    def test_contract_absent_without_store_uri(self):
        nb = Notebook.new("nb", "u1", tpu=TPUSpec("v5e", "4x4"))
        (sts,) = generate_statefulsets(nb, CoreConfig())
        main = self._main(sts)
        env = {e["name"] for e in main["env"]}
        assert C.ENV_CHECKPOINT_STORE_URI not in env
        assert "lifecycle" not in main
        assert "volumes" not in sts.spec["template"]["spec"]
