"""Filtered watch dispatch + sharded store semantics (kube/store.py).

The fleet-scale apiserver rework changed three load-bearing contracts:

  1. `watch`/`subscribe` take kinds=/namespace= filters and dispatch
     through a per-kind subscriber index — a filtered subscriber must see
     EXACTLY the per-kind subsequence an unfiltered one sees, under any
     interleaving of kinds;
  2. the watch history is a bounded ring PER KIND with per-kind eviction
     floors — churn on one kind can never evict another kind's resume
     window, and a resume below a relevant floor (or after a
     reset_watch_history compaction) raises the "history starts at" 410
     rather than silently skipping evicted events;
  3. reads are copy-on-write: `get` returns a private mutable copy,
     `list` returns frozen shared snapshots, and the no-op/apply fast
     paths keep their semantics on top of that.

Plus the end-to-end check the whole rework exists for: a 2k-notebook
fleet converges to the identical normalized state with 1 and 8 workers
on the filtered path.
"""

from __future__ import annotations

import random

import pytest

from kubeflow_tpu.kube import ApiServer, KubeObject, ObjectMeta
from kubeflow_tpu.kube.errors import GoneError
from kubeflow_tpu.utils.config import CoreConfig


def mk(kind, name, ns="default", labels=None, **body):
    return KubeObject("v1", kind,
                      ObjectMeta(name=name, namespace=ns,
                                 labels=dict(labels or {})),
                      body=dict(body))


def sig(ev):
    return (ev.type.value, ev.obj.kind, ev.obj.name,
            ev.obj.metadata.resource_version)


class Recorder:
    """Plain callback watcher that records event signatures."""

    def __init__(self):
        self.events = []

    def __call__(self, ev):
        self.events.append(sig(ev))


class Resumable(Recorder):
    """Watcher with the drop/resume protocol (a client watch stream)."""

    def __init__(self):
        super().__init__()
        self.connected = True
        self.last_rv = 0

    def __call__(self, ev):
        rv = ev.obj.metadata.resource_version
        if rv > self.last_rv:
            self.last_rv = rv
        super().__call__(ev)

    def on_watch_dropped(self):
        self.connected = False


KINDS = ("Notebook", "Pod", "Service")


def churn(api, rng, steps, kinds=KINDS, ns_choices=("default",)):
    """Seeded random create/update/delete walk across kinds."""
    counters = {k: 0 for k in kinds}
    live: dict[str, list[str]] = {k: [] for k in kinds}
    for _ in range(steps):
        kind = rng.choice(kinds)
        ns = rng.choice(ns_choices)
        op = rng.random()
        if op < 0.5 or not live[kind]:
            counters[kind] += 1
            name = f"{kind.lower()}-{counters[kind]:03d}"
            api.create(mk(kind, name, ns=ns))
            live[kind].append(name)
        elif op < 0.8:
            name = rng.choice(live[kind])
            try:
                cur = api.get(kind, ns, name)
            except Exception:
                continue
            cur.metadata.labels["step"] = str(rng.randrange(1 << 30))
            api.update(cur)
        else:
            name = live[kind].pop(rng.randrange(len(live[kind])))
            try:
                api.delete(kind, ns, name)
            except Exception:
                pass


class TestFilteredDispatchEquivalence:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_filtered_sees_exact_per_kind_subsequence(self, seed):
        api = ApiServer()
        everything = Recorder()
        api.watch(everything)
        per_kind = {k: Recorder() for k in KINDS}
        for k, rec in per_kind.items():
            api.watch(rec, kinds=[k])
        pair = Recorder()
        api.watch(pair, kinds=["Notebook", "Service"])

        churn(api, random.Random(seed), 250)

        for k, rec in per_kind.items():
            expected = [s for s in everything.events if s[1] == k]
            assert rec.events == expected, f"filtered {k} diverged"
        expected_pair = [s for s in everything.events
                         if s[1] in ("Notebook", "Service")]
        assert pair.events == expected_pair
        # rv order must hold within every stream
        for rec in (everything, pair, *per_kind.values()):
            rvs = [s[3] for s in rec.events]
            assert rvs == sorted(rvs)

    def test_namespace_filter(self):
        api = ApiServer()
        ns1 = Recorder()
        api.watch(ns1, kinds=["Pod"], namespace="ns1")
        both = Recorder()
        api.watch(both, kinds=["Pod"])
        api.create(mk("Pod", "a", ns="ns1"))
        api.create(mk("Pod", "b", ns="ns2"))
        api.create(mk("Pod", "c", ns="ns1"))
        assert [s[2] for s in ns1.events] == ["a", "c"]
        assert [s[2] for s in both.events] == ["a", "b", "c"]

    def test_dispatch_audit_counts_skips(self):
        api = ApiServer()
        api.watch(Recorder(), kinds=["Notebook"])  # Notebook-only
        api.watch(Recorder(), kinds=["Notebook"])  # another one
        for i in range(50):
            api.create(mk("Pod", f"p{i}"))
        counts = api.watch_dispatch_counts()
        # Pod churn never touches the Notebook-only subscribers: every
        # would-be broadcast callback is a skip
        assert counts[("Pod", "skipped")] == 100
        assert counts.get(("Pod", "delivered"), 0) == 0
        api.create(mk("Notebook", "nb"))
        counts = api.watch_dispatch_counts()
        assert counts[("Notebook", "delivered")] == 2


class TestPerKindResume:
    def test_pod_churn_cannot_evict_notebook_resume_window(self):
        api = ApiServer(history_size=8)
        sub = Resumable()
        api.subscribe(sub, kinds=["Notebook"])
        api.create(mk("Notebook", "nb-0"))
        resume_rv = sub.last_rv
        assert api.drop_watch_connections() == 1
        # while away: 3 Notebook events (fit the ring) and WAY more Pod
        # events than any single shared ring would have retained
        for i in range(1, 4):
            api.create(mk("Notebook", f"nb-{i}"))
        for i in range(100):
            api.create(mk("Pod", f"p-{i}"))
        replayed = Recorder()
        api.subscribe(replayed, since_rv=resume_rv, kinds=["Notebook"])
        assert [s[2] for s in replayed.events] == ["nb-1", "nb-2", "nb-3"]
        # the same resume UNFILTERED is 410 Gone: the Pod ring evicted
        # events the subscriber would have been owed
        with pytest.raises(GoneError, match="history starts at"):
            api.subscribe(Recorder(), since_rv=resume_rv)

    def test_resume_below_kind_floor_raises(self):
        api = ApiServer(history_size=4)
        api.create(mk("Notebook", "nb-a"))
        early_rv = api.resource_version
        for i in range(10):  # overflow the Notebook ring itself
            api.create(mk("Notebook", f"nb-{i}"))
        with pytest.raises(GoneError, match="history starts at"):
            api.subscribe(Recorder(), since_rv=early_rv - 1,
                          kinds=["Notebook"])

    def test_multi_kind_replay_is_rv_ordered(self):
        api = ApiServer()
        api.create(mk("Notebook", "nb-seed"))
        cut = api.resource_version
        api.create(mk("Pod", "p-1"))
        api.create(mk("Notebook", "nb-1"))
        api.create(mk("Pod", "p-2"))
        api.create(mk("Service", "svc-1"))  # not in the filter
        rec = Recorder()
        api.subscribe(rec, since_rv=cut, kinds=["Notebook", "Pod"])
        assert [(s[1], s[2]) for s in rec.events] == [
            ("Pod", "p-1"), ("Notebook", "nb-1"), ("Pod", "p-2")]
        rvs = [s[3] for s in rec.events]
        assert rvs == sorted(rvs)

    def test_compaction_410s_every_kind(self):
        api = ApiServer()
        api.create(mk("Notebook", "nb"))
        api.create(mk("Pod", "p"))
        cut = api.resource_version
        api.reset_watch_history()
        for kinds in (["Notebook"], ["Pod"], None):
            with pytest.raises(GoneError, match="history starts at"):
                api.subscribe(Recorder(), since_rv=cut - 1, kinds=kinds)
        # resuming AT the compaction point is fine (nothing missed)
        ok = Recorder()
        api.subscribe(ok, since_rv=cut, kinds=["Notebook"])
        api.create(mk("Notebook", "nb-after"))
        assert [s[2] for s in ok.events] == ["nb-after"]

    def test_history_size_env_knob(self, monkeypatch):
        monkeypatch.setenv("WATCH_HISTORY_SIZE", "3")
        api = ApiServer()
        assert api.history_size == 3
        cfg = CoreConfig.from_env({"WATCH_HISTORY_SIZE": "7"})
        assert cfg.watch_history_size == 7
        # explicit constructor argument wins over env
        assert ApiServer(history_size=11).history_size == 11


class TestCopyOnWriteContract:
    def test_get_returns_private_mutable_copy(self):
        api = ApiServer()
        api.create(mk("Pod", "p", labels={"app": "a"}))
        got = api.get("Pod", "default", "p")
        assert not got.frozen
        got.metadata.labels["app"] = "changed"
        api.update(got)
        assert api.get("Pod", "default", "p").metadata.labels["app"] == \
            "changed"

    def test_list_returns_frozen_shared_snapshots(self):
        api = ApiServer()
        api.create(mk("Pod", "p", labels={"app": "a"}))
        listed = api.list("Pod")[0]
        assert listed.frozen
        # a frozen object's spec/status accessors never grow skeleton keys
        assert listed.status == {}
        assert "status" not in listed.body
        # the mutate-then-update flow goes through a private get() copy;
        # the frozen snapshot an earlier list handed out is unaffected
        fresh = api.get("Pod", "default", "p")
        fresh.metadata.labels["app"] = "b"
        api.update(fresh)
        assert listed.metadata.labels["app"] == "a"
        assert api.list("Pod")[0].metadata.labels["app"] == "b"

    def test_watch_events_share_one_frozen_object(self):
        api = ApiServer()
        seen = []
        api.watch(lambda ev: seen.append(ev.obj), kinds=["Pod"])
        api.watch(lambda ev: seen.append(ev.obj), kinds=["Pod"])
        api.create(mk("Pod", "p"))
        assert len(seen) == 2 and seen[0] is seen[1]
        assert seen[0].frozen

    def test_apply_digest_fast_path_keeps_semantics(self):
        api = ApiServer()
        manifest = {"apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": "cm", "namespace": "default"},
                    "data": {"k": "v"}}
        first = api.apply("ConfigMap", "default", "cm", manifest, "mgr")
        rv1 = first.metadata.resource_version
        # identical re-apply: served by the digest short-circuit, still a
        # no-op (no rv bump)
        again = api.apply("ConfigMap", "default", "cm", manifest, "mgr")
        assert again.metadata.resource_version == rv1
        # a third party touching the object invalidates the fast path: the
        # full apply flow must run and restore the applied field
        other = api.get("ConfigMap", "default", "cm")
        other.body["data"] = {"k": "drifted"}
        api.update(other)
        healed = api.apply("ConfigMap", "default", "cm", manifest, "mgr")
        assert healed.body["data"]["k"] == "v"
        assert healed.metadata.resource_version > rv1


class TestFleetEquivalenceOnFilteredPath:
    def test_2k_notebooks_identical_state_1_vs_8_workers(self):
        import importlib.util
        from pathlib import Path

        spec = importlib.util.spec_from_file_location(
            "loadtest_convergence",
            Path(__file__).parent.parent / "loadtest" / "convergence.py")
        conv = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(conv)

        one = conv.run_fleet(2000, 1)
        eight = conv.run_fleet(2000, 8)
        assert one["reconciles_per_notebook"] == \
            eight["reconciles_per_notebook"] == {"notebook": 2.0}
        assert one.pop("_state") == eight.pop("_state")
        # the fan-out audit proves events stayed filtered while 8 workers
        # hammered the store: nothing was broadcast to everyone
        assert one["watch_dispatch"].get("Notebook:skipped", 0) > 0
