#!/usr/bin/env bash
# Debug-surface smoke: boot the standalone manager (demo mode, so real
# reconciles run and the flight recorder has attempts), then exercise the
# operator introspection path end-to-end over real HTTP:
#   - /debug/reconciles returns recorded attempts with results/durations,
#   - /debug/workqueue returns the per-item queue view,
#   - /metrics negotiated as OpenMetrics carries exemplars context and the
#     spec-required `# EOF` terminator (and still serves classic
#     Prometheus text without the Accept header),
#   - an exemplar/recorded trace id resolves on /debug/traces/<id>,
#   - /debug/alerts serves the SLO engine's objectives with zero firing
#     alerts on a healthy demo fleet,
#   - /debug/fleet serves the per-namespace rollup with the demo notebook
#     counted ready,
#   - /debug/profile serves the continuous profiler's aggregation (the
#     manager runs with ENABLE_CONTINUOUS_PROFILER=true here) and its
#     overhead gauge stays under 5%,
#   - /debug/criticalpath serves the lifecycle ledger's stage ranking
#     with the demo notebook finalized and its conservation check clean,
#   - /debug/timeline serves the in-process TSDB inventory, a per-series
#     query, and the full ?dump=1 capture,
#   - /debug/tenants serves the tenant metering ledger's usage table with
#     the demo namespace attributed and its chip-second conservation
#     check clean, plus the tenancy (priority/quota/preemption)
#     admission-gate snapshot mirrored on /debug/fleet,
#   - `python -m kubeflow_tpu.ops.diagnose` captures a bundle over the
#     same surface from which the slowest attempt resolves offline.
# Wired into ci/run_tests.sh (controlplane lane).
set -euo pipefail
cd "$(dirname "$0")/.."

PORT="${DEBUG_SMOKE_PORT:-18479}"

ENABLE_CONTINUOUS_PROFILER=true \
python -m kubeflow_tpu.main --metrics-addr "$PORT" --webhook-port -1 \
  --demo --run-seconds 60 >/dev/null 2>&1 &
MGR_PID=$!
cleanup() {
  kill "$MGR_PID" 2>/dev/null || true
}
trap cleanup EXIT

python - "$PORT" <<'EOF'
import json
import sys
import time
import urllib.request

port = sys.argv[1]
base = f"http://127.0.0.1:{port}"


def get(path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), \
            resp.read().decode()


# wait until the manager has reconciled the demo notebook
deadline = time.time() + 30
while True:
    try:
        _, _, body = get("/debug/reconciles")
        snap = json.loads(body)
        if snap["recorded_total"] > 0:
            break
    except Exception:
        pass
    if time.time() > deadline:
        raise SystemExit("manager never recorded a reconcile attempt")
    time.sleep(0.25)

attempts = snap["attempts"]
assert attempts, snap
for a in attempts:
    assert a["result"] in ("success", "error", "requeue", "requeue_after"), a
    assert a["duration_s"] >= 0.0 and a["trace_id"], a
print(f"debug smoke: {snap['recorded_total']} attempts recorded, "
      f"{len(snap['objects'])} objects")

# per-object filter returns only that object's history
key = attempts[-1]["object"]
_, _, body = get(f"/debug/reconciles?object={key}")
per_obj = json.loads(body)
assert per_obj["attempts"], per_obj
assert all(a["object"] == key for a in per_obj["attempts"])

# a recorded trace resolves with its span tree
status, _, body = get(f"/debug/traces/{attempts[-1]['trace_id']}")
trace = json.loads(body)
assert status == 200 and trace["spans"], trace

status, _, body = get("/debug/workqueue")
wq = json.loads(body)
assert status == 200
assert "queued" in wq and "delayed" in wq and "retries" in wq, wq

# content negotiation: OpenMetrics on request, Prometheus text otherwise
status, ctype, body = get(
    "/metrics", headers={"Accept": "application/openmetrics-text"})
assert status == 200 and "application/openmetrics-text" in ctype, ctype
assert body.rstrip().endswith("# EOF"), body[-200:]
assert "# TYPE controller_runtime_reconcile_time_seconds histogram" in body

status, ctype, body = get("/metrics")
assert status == 200 and ctype.startswith("text/plain"), ctype
assert "# EOF" not in body

# SLO engine: objectives evaluated, nothing firing on a healthy demo
_, _, body = get("/debug/alerts")
alerts = json.loads(body)
assert alerts["firing"] == [], alerts["firing"]
assert "reconcile_errors" in alerts["objectives"], alerts
assert alerts["windows"] == ["5m", "1h"], alerts

# fleet rollup: the demo notebook is counted, and counts are consistent
_, _, body = get("/debug/fleet")
fleet = json.loads(body)
assert fleet["notebooks"] >= 1, fleet
assert sum(fleet["totals"].values()) == fleet["notebooks"], fleet
assert "default" in fleet["namespaces"], fleet

# data-plane rollup: the demo workers published telemetry annotations
# (main.py --demo plays the training loops), so /debug/fleet must carry
# the per-notebook worker rollup with roofline-consistent stats — poll
# briefly, the stamp lands just after the notebook turns Healthy
deadline = time.time() + 15
while True:
    _, _, body = get("/debug/fleet")
    dataplane = json.loads(body).get("dataplane") or {}
    if dataplane.get("notebooks"):
        break
    if time.time() > deadline:
        raise SystemExit("/debug/fleet never carried the data-plane rollup")
    time.sleep(0.25)
demo = dataplane["notebooks"]["default/demo"]
assert demo["workers"], demo
assert demo["tokens_per_s"] > 0 and 0 < demo["mfu"] < 1, demo
assert demo["straggler"] is None, demo  # healthy demo slice
assert dataplane["stragglers"] == [], dataplane
for w in demo["workers"].values():
    assert w["step_time_s"] > 0, demo

# the dataplane gauges surface on /metrics too
_, _, body = get("/metrics")
assert 'notebook_dataplane_mfu_ratio{namespace="default",name="demo"}' \
    in body, "dataplane gauge missing from scrape"

# lifecycle critical path: the demo notebook's event->ready window is
# attributed to stages, the fleet ranking is served, and the conservation
# check (attributed sum == measured wall time) holds with zero violations
deadline = time.time() + 15
while True:
    _, _, body = get("/debug/criticalpath")
    cp = json.loads(body)
    if cp.get("conservation", {}).get("finalized", 0) >= 1:
        break
    if time.time() > deadline:
        raise SystemExit("/debug/criticalpath never finalized a notebook")
    time.sleep(0.25)
assert cp["conservation"]["violations"] == 0, cp["conservation"]
assert isinstance(cp["ranking"], list), cp
for r in cp["ranking"]:
    assert r["stage"] and r["count"] >= 1 and r["total_s"] >= 0.0, r
assert "default" in cp["namespaces"], cp["namespaces"].keys()

# the stage histogram surfaces on /metrics with the ledger's buckets
_, _, body = get("/metrics")
assert "# TYPE notebook_stage_duration_seconds histogram" in body, \
    "stage histogram missing from scrape"

# /debug/fleet carries the per-namespace stage-latency rollup
_, _, body = get("/debug/fleet")
fleet = json.loads(body)
assert "default" in fleet["stage_latency"], fleet.get("stage_latency")
assert fleet["criticalpath"]["conservation"]["violations"] == 0, fleet

# in-process TSDB: the /metrics scrapes above each fed one sample, so
# the inventory is non-empty, a known series queries at every tier, and
# ?dump=1 returns the full multi-tier capture a bundle embeds
_, _, body = get("/debug/timeline")
tl = json.loads(body)
assert tl["tiers"] == ["raw", "10s", "60s"], tl
assert tl["samples_total"] > 0 and tl["series"], tl
name = sorted(tl["series"])[0]
for tier in ("raw", "10s", "60s"):
    _, _, body = get(f"/debug/timeline?series={name}&tier={tier}")
    q = json.loads(body)
    assert q["series"] == name and q["tier"] == tier, q
    assert "error" not in q and q["points"], q
_, _, body = get("/debug/timeline?dump=1")
dump = json.loads(body)
assert dump["series"][name]["raw"], dump.get("bounds")

# tenant metering: the demo namespace's control-plane work is attributed
# to it, the fairness detector has evaluated (nothing flagged on a
# healthy one-tenant demo), and chip-second conservation holds
_, _, body = get("/debug/tenants")
tn = json.loads(body)
assert tn["enabled"] is True, tn
assert "default" in tn["tenants"], sorted(tn["tenants"])
assert tn["tenants"]["default"]["dispatches"] > 0, tn["tenants"]["default"]
assert tn["conservation"]["violations"] == 0, tn["conservation"]
assert tn["fairness"]["evaluations"] > 0, tn["fairness"]
assert tn["fairness"]["flagged"] == [], tn["fairness"]
assert set(tn["buckets"]) == {"ready", "scheduling", "recovering",
                              "idle"}, tn["buckets"]

# tenancy (priority/quota/preemption) view: /debug/tenants embeds the
# admission-gate snapshot, /debug/fleet carries the same section, and
# the queue-wait family is registered even with nothing ever queued
assert "tenancy" in tn, sorted(tn)
tenancy = tn["tenancy"]
for k in ("queued", "usage_chips", "quota", "pending_preemptions",
          "recent_preemptions"):
    assert k in tenancy, (k, sorted(tenancy))
assert tenancy["queued"] == {}, tenancy["queued"]       # healthy demo
assert tenancy["pending_preemptions"] == 0, tenancy
_, _, body = get("/debug/fleet")
fleet = json.loads(body)
assert fleet["tenancy"]["pending_preemptions"] == 0, fleet.get("tenancy")
_, _, body = get("/metrics")
assert "# TYPE notebook_queue_wait_seconds histogram" in body, \
    "queue-wait family missing from scrape"
assert "# TYPE notebook_preemptions_total counter" in body, \
    "preemptions family missing from scrape"

# the tenant families surface on /metrics, and /debug/fleet embeds the
# same snapshot under its "tenants" key
_, _, body = get("/metrics")
assert "# TYPE notebook_tenant_queue_seconds_total counter" in body, \
    "tenant metering families missing from scrape"
assert "# TYPE metrics_labelsets_dropped_total counter" in body, \
    "cardinality-guard counter missing from scrape"
_, _, body = get("/debug/fleet")
fleet = json.loads(body)
assert fleet["tenants"]["conservation"]["violations"] == 0, \
    fleet.get("tenants")

# causal diagnosis: the explainer serves a verdict for the demo
# notebook (ranked candidates, every chain link citing evidence), an
# unknown object degrades to an error body (never a 500), and the
# change-point surface serves its findings/timeline shape
status, _, body = get("/debug/explain?object=default/demo")
ex = json.loads(body)
assert status == 200, status
assert ex["object"] == "default/demo", ex
assert ex["cause"] and ex["verdict"], ex
assert ex["chain"] and all("claim" in l and "evidence" in l
                           for l in ex["chain"]), ex["chain"]
assert ex["candidates"][0]["cause"] == ex["cause"], ex["candidates"][0]
scores = [c["score"] for c in ex["candidates"]]
assert scores == sorted(scores, reverse=True), scores

status, _, body = get("/debug/explain?object=default/no-such-notebook")
missing = json.loads(body)
assert status == 200 and missing["verdict"] == "", missing
assert "error" in missing, missing

status, _, body = get("/debug/changepoints")
cp = json.loads(body)
assert status == 200 and cp["enabled"] is True, cp
assert cp["evaluations"] > 0, cp
assert isinstance(cp["changepoints"], list), cp
assert isinstance(cp["timeline"], list), cp
for f in cp["changepoints"]:
    assert f["series"] and f["matched"], f
    assert f["t_end"] >= f["t_start"], f

# firing alerts carry a `diagnosis` line (vacuously checked on a healthy
# demo — the field contract is exercised by the chaos soak)
_, _, body = get("/debug/alerts")
alerts = json.loads(body)
for a in alerts["firing"]:
    assert "diagnosis" in a, a

# /debug/fleet embeds the diagnosis summary
_, _, body = get("/debug/fleet")
fleet = json.loads(body)
assert fleet["diagnosis"]["evaluations"] > 0, fleet.get("diagnosis")

# continuous profiler: enabled for this boot, samples flowing, overhead
# gauge under the 5% always-on budget
_, _, body = get("/debug/profile")
prof = json.loads(body)
assert prof["enabled"] is True, prof
assert prof["samples_total"] > 0, prof
assert prof["overhead_ratio"] < 0.05, prof
status, ctype, body = get("/debug/profile?format=collapsed")
assert status == 200 and ctype.startswith("text/plain")

print("debug smoke: OK (/debug/reconciles, /debug/traces, "
      "/debug/workqueue, /debug/alerts, /debug/fleet, /debug/profile, "
      "/debug/criticalpath, /debug/tenants, /debug/timeline, "
      "OpenMetrics negotiation)")
EOF

# one-shot diagnostics bundle over the same loopback surface: the CLI
# must exit 0 and the artifact must resolve its slowest attempt offline
BUNDLE="$(mktemp --suffix=.json)"
trap 'kill "$MGR_PID" 2>/dev/null || true; rm -f "$BUNDLE"' EXIT
python -m kubeflow_tpu.ops.diagnose --addr "127.0.0.1:$PORT" --out "$BUNDLE"
python - "$BUNDLE" <<'EOF'
import json
import sys

bundle = json.load(open(sys.argv[1]))
slowest = bundle["reconciles"]["slowest"][0]
trace = bundle["traces"][slowest["trace_id"]]
assert trace["spans"], slowest
assert bundle["fleet"]["notebooks"] >= 1
assert bundle["profile"]["samples_total"] > 0
assert "config" in bundle
# the bundle carries the worker telemetry rollup (offline straggler
# attribution), mirrored from the fleet rollup's dataplane section
telem = bundle["telemetry"]
assert telem and telem["notebooks"]["default/demo"]["workers"], telem
assert bundle["fleet"]["dataplane"]["notebooks"], bundle["fleet"].keys()
# critical-path attribution and the full TSDB capture ride the bundle:
# a run's p99-vs-time curve reconstructs offline from `timeline.series`
cp = bundle["criticalpath"]
assert cp["conservation"]["finalized"] >= 1, cp["conservation"]
assert cp["conservation"]["violations"] == 0, cp["conservation"]
tl = bundle["timeline"]
assert tl["samples_total"] > 0 and tl["series"], tl.get("bounds")
for name, tiers in tl["series"].items():
    assert set(tiers) == {"raw", "10s", "60s"}, (name, tiers.keys())
# tenant metering rides the bundle: per-tenant usage + the fairness
# verdict reconstruct offline
tn = bundle["tenants"]
assert tn["enabled"] is True and "default" in tn["tenants"], tn
assert tn["conservation"]["violations"] == 0, tn["conservation"]
# both diagnosis surfaces reconstruct offline: the per-object verdicts
# are captured, and re-running the detector over the bundle's raw
# curves is exactly what changepoints_from_bundle does
diag = bundle["diagnosis"]
assert diag["enabled"] is True, diag.get("enabled")
demo = diag["explanations"]["default/demo"]
assert demo["cause"] and demo["verdict"], demo
sys.path.insert(0, ".")
from kubeflow_tpu.utils.diagnosis import changepoints_from_bundle
offline = changepoints_from_bundle(bundle)
assert isinstance(offline, list)
print("diagnose smoke: OK (bundle resolves its slowest attempt offline, "
      "worker telemetry + critical path + tenants + timeline + "
      "diagnosis verdicts included)")
EOF
