"""Per-notebook NetworkPolicies.

Port of odh notebook_network.go: lock the Jupyter port down to the controller
namespace (the gateway data path enters through the central-ns HTTPRoute) and
open the kube-rbac-proxy port to everyone — it authenticates for itself
(notebook_network.go:44-211).  TPU extension: a third policy opening the JAX
coordinator / MEGASCALE ports *between the workers of the same notebook*, so
ICI/DCN bootstrap traffic flows while the slice stays isolated from other
tenants (SURVEY.md §7 step 7).
"""

from __future__ import annotations

from ..api.types import Notebook
from ..common import reconcilehelper as rh
from ..kube import ApiServer, KubeObject, ObjectMeta, set_controller_reference
from ..tpu import env as tpuenv
from . import constants as C


def _policy(nb: Notebook, name: str, spec: dict) -> KubeObject:
    return KubeObject(
        api_version="networking.k8s.io/v1",
        kind="NetworkPolicy",
        metadata=ObjectMeta(name=name, namespace=nb.namespace),
        body={"spec": spec},
    )


def new_notebook_network_policy(nb: Notebook, controller_namespace: str) -> KubeObject:
    """Allow :8888 only from the controller namespace
    (notebook_network.go:132-174)."""
    return _policy(
        nb,
        nb.name + "-ctrl-np",
        {
            "podSelector": {"matchLabels": {C.NOTEBOOK_NAME_LABEL: nb.name}},
            "ingress": [
                {
                    "ports": [{"protocol": "TCP", "port": C.NOTEBOOK_PORT}],
                    "from": [
                        {
                            "namespaceSelector": {
                                "matchLabels": {
                                    "kubernetes.io/metadata.name": controller_namespace
                                }
                            }
                        }
                    ],
                }
            ],
            "policyTypes": ["Ingress"],
        },
    )


def new_kube_rbac_proxy_network_policy(nb: Notebook) -> KubeObject:
    """Allow :8443 from anywhere — the proxy is the auth boundary
    (notebook_network.go:177-211)."""
    return _policy(
        nb,
        nb.name + C.KUBE_RBAC_PROXY_NETWORK_POLICY_SUFFIX,
        {
            "podSelector": {"matchLabels": {C.NOTEBOOK_NAME_LABEL: nb.name}},
            "ingress": [
                {"ports": [{"protocol": "TCP", "port": C.KUBE_RBAC_PROXY_PORT}]}
            ],
            "policyTypes": ["Ingress"],
        },
    )


def new_tpu_worker_network_policy(nb: Notebook) -> KubeObject:
    """TPU extension: workers of one notebook may reach each other on the
    distributed-runtime ports (JAX coordinator + MEGASCALE DCN transport).
    Selector on both sides is the notebook-name label, so the policy covers
    every slice of a multi-slice notebook."""
    peer = {
        "podSelector": {"matchLabels": {C.NOTEBOOK_NAME_LABEL: nb.name}},
    }
    return _policy(
        nb,
        nb.name + C.TPU_WORKER_NETWORK_POLICY_SUFFIX,
        {
            "podSelector": {"matchLabels": {C.NOTEBOOK_NAME_LABEL: nb.name}},
            "ingress": [
                {
                    "ports": [
                        {"protocol": "TCP", "port": tpuenv.JAX_COORDINATOR_PORT},
                        {"protocol": "TCP", "port": tpuenv.MEGASCALE_PORT},
                    ],
                    "from": [peer],
                }
            ],
            "policyTypes": ["Ingress"],
        },
    )


def reconcile_all_network_policies(
    api: ApiServer, nb: Notebook, controller_namespace: str
) -> None:
    """ReconcileAllNetworkPolicies (notebook_network.go:44-66) + the TPU
    worker policy when spec.tpu is set."""
    policies = [
        new_notebook_network_policy(nb, controller_namespace),
        new_kube_rbac_proxy_network_policy(nb),
    ]
    if nb.tpu is not None:
        policies.append(new_tpu_worker_network_policy(nb))
    for desired in policies:
        set_controller_reference(nb.obj, desired)
        rh.reconcile_object(api, desired, rh.copy_spec)
