"""Trusted-CA-bundle plumbing.

Port of CreateNotebookCertConfigMap / IsConfigMapDeleted /
UnsetNotebookCertConfig (odh notebook_controller.go:528-733): merge the
platform CA ConfigMaps into a per-namespace `workbench-trusted-ca-bundle`
with PEM validation; when that ConfigMap disappears, strip the cert
volume/mounts/env the webhook injected.
"""

from __future__ import annotations

import base64
import binascii
import re

from ..api.types import Notebook
from ..kube import ApiServer, KubeObject, NotFoundError, ObjectMeta, retry_on_conflict
from . import constants as C

_PEM_RE = re.compile(
    r"-----BEGIN ([A-Z ]+)-----\s*(.*?)\s*-----END \1-----", re.DOTALL
)

# ConfigMap name -> cert keys inspected (notebook_controller.go:541-546)
_SOURCE_KEYS = {
    C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP: (C.TRUSTED_CA_BUNDLE_FILE, "odh-ca-bundle.crt"),
    C.KUBE_ROOT_CA_CONFIGMAP: ("ca.crt",),
    C.OPENSHIFT_SERVICE_CA_CONFIGMAP: ("service-ca.crt",),
}


def valid_pem_certificate(cert_data: str) -> bool:
    """True when the blob contains at least one well-formed CERTIFICATE block
    (the reference pem.Decode + x509.ParseCertificate check,
    notebook_controller.go:578-593).  We validate PEM framing, base64 body,
    and the DER SEQUENCE tag without a full X.509 parse."""
    m = _PEM_RE.search(cert_data)
    if m is None or m.group(1) != "CERTIFICATE":
        return False
    try:
        der = base64.b64decode(re.sub(r"\s+", "", m.group(2)), validate=True)
    except (binascii.Error, ValueError):
        return False
    return len(der) > 2 and der[0] == 0x30  # X.509 certs are a DER SEQUENCE


def create_notebook_cert_configmap(api: ApiServer, nb: Notebook) -> None:
    """Merge odh-trusted-ca-bundle + kube-root-ca.crt +
    openshift-service-ca.crt (all read from the *notebook* namespace) into
    workbench-trusted-ca-bundle.  Absent odh bundle, or an empty
    ca-bundle.crt key, means cert injection is handled elsewhere — create
    nothing (notebook_controller.go:549-575)."""
    pool: list[str] = []
    for cm_name, keys in _SOURCE_KEYS.items():
        cm = api.try_get("ConfigMap", nb.namespace, cm_name)
        if cm is None:
            if cm_name == C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP:
                return
            continue
        data = cm.body.get("data") or {}
        for key in keys:
            cert = (data.get(key) or "").strip()
            if key == C.TRUSTED_CA_BUNDLE_FILE and cm_name == C.ODH_TRUSTED_CA_BUNDLE_CONFIGMAP:
                if not cert:
                    # inject-ca-bundle handles it; ours would be empty
                    return
            if not cert:
                continue
            if valid_pem_certificate(cert):
                pool.append(cert)

    if not pool:
        return
    desired = KubeObject(
        api_version="v1",
        kind="ConfigMap",
        metadata=ObjectMeta(
            name=C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP,
            namespace=nb.namespace,
            labels={"opendatahub.io/managed-by": "workbenches"},
        ),
        body={"data": {C.TRUSTED_CA_BUNDLE_FILE: "\n".join(pool)}},
    )
    found = api.try_get(
        "ConfigMap", nb.namespace, C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP
    )
    if found is None:
        api.create(desired)
    elif found.body.get("data") != desired.body.get("data"):
        found.body["data"] = desired.body["data"]
        api.update(found)


def notebook_mounts_ca_bundle(nb: Notebook) -> bool:
    """The notebook references workbench-trusted-ca-bundle as a volume
    (notebook_controller.go:653-663)."""
    for vol in nb.pod_spec.get("volumes") or []:
        cm = vol.get("configMap") or {}
        if cm.get("name") == C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP:
            return True
    return False


def is_configmap_deleted(api: ApiServer, nb: Notebook) -> bool:
    """workbench-trusted-ca-bundle is gone but the notebook still mounts it
    (notebook_controller.go:637-666)."""
    if not notebook_mounts_ca_bundle(nb):
        return False
    return (
        api.try_get("ConfigMap", nb.namespace, C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)
        is None
    )


def unset_notebook_cert_config(api: ApiServer, nb: Notebook) -> None:
    """Strip the injected cert volume, volumeMounts, and env vars from the
    live Notebook (notebook_controller.go:668-733)."""

    def strip() -> None:
        live = api.get("Notebook", nb.namespace, nb.name)
        live_nb = Notebook(live)
        spec = live_nb.pod_spec
        spec["volumes"] = [
            v
            for v in spec.get("volumes") or []
            if (v.get("configMap") or {}).get("name")
            != C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP
        ]
        if not spec["volumes"]:
            del spec["volumes"]
        for container in spec.get("containers") or []:
            mounts = [
                m
                for m in container.get("volumeMounts") or []
                if m.get("name") != C.TRUSTED_CA_BUNDLE_VOLUME
            ]
            if mounts:
                container["volumeMounts"] = mounts
            else:
                container.pop("volumeMounts", None)
            env = [
                e
                for e in container.get("env") or []
                if e.get("name") not in C.CA_BUNDLE_ENV_VARS
            ]
            if env:
                container["env"] = env
            else:
                container.pop("env", None)
        api.update(live)

    try:
        retry_on_conflict(strip)
    except NotFoundError:
        pass
