"""RFC 6902 JSON Patch: diff generation and application.

AdmissionReview mutating responses carry a JSONPatch from the webhook back
to the apiserver (controller-runtime's admission.PatchResponseFromRaw, used
at odh notebook_mutating_webhook.go:515, computes exactly this diff).  The
generator emits minimal add/remove/replace ops between two JSON documents;
the applier is used by the wire-protocol apiserver to apply a remote
webhook's patch before storing the object.
"""

from __future__ import annotations

import copy
from typing import Any


def _escape(token: str) -> str:
    return token.replace("~", "~0").replace("/", "~1")


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def diff(old: Any, new: Any, path: str = "") -> list[dict]:
    """Minimal JSON Patch transforming `old` into `new`."""
    if type(old) is not type(new):
        return [{"op": "replace" if path else "add", "path": path or "",
                 "value": copy.deepcopy(new)}] if old != new else []
    if isinstance(old, dict):
        ops: list[dict] = []
        for key in old:
            sub = f"{path}/{_escape(str(key))}"
            if key not in new:
                ops.append({"op": "remove", "path": sub})
            else:
                ops.extend(diff(old[key], new[key], sub))
        for key in new:
            if key not in old:
                ops.append({"op": "add", "path": f"{path}/{_escape(str(key))}",
                            "value": copy.deepcopy(new[key])})
        return ops
    if isinstance(old, list):
        if old == new:
            return []
        # element-wise for the common prefix, then add/remove the tail —
        # simple and correct (not minimal for reorders, which is fine)
        ops = []
        for i in range(min(len(old), len(new))):
            ops.extend(diff(old[i], new[i], f"{path}/{i}"))
        for i in range(len(old) - 1, len(new) - 1, -1):
            ops.append({"op": "remove", "path": f"{path}/{i}"})
        for i in range(len(old), len(new)):
            ops.append({"op": "add", "path": f"{path}/-",
                        "value": copy.deepcopy(new[i])})
        return ops
    if old != new:
        return [{"op": "replace", "path": path, "value": copy.deepcopy(new)}]
    return []


class PatchTestFailed(ValueError):
    """An RFC 6902 `test` op did not match — the apiserver surfaces this as
    an Invalid (422) response."""


def _resolve(doc: Any, tokens: list[str]) -> Any:
    cur = doc
    for t in tokens:
        cur = cur[int(t)] if isinstance(cur, list) else cur[t]
    return cur


def apply_patch(doc: Any, ops: list[dict]) -> Any:
    doc = copy.deepcopy(doc)
    for op in ops:
        tokens = [_unescape(t) for t in op["path"].split("/")[1:]]
        doc = _apply_one(doc, op, tokens)
    return doc


def _apply_one(doc: Any, op: dict, tokens: list[str]) -> Any:
    kind = op["op"]
    if kind == "test":
        try:
            actual = _resolve(doc, tokens)
        except (KeyError, IndexError, TypeError):
            raise PatchTestFailed(f"test path {op['path']!r} missing") from None
        if actual != op.get("value"):
            raise PatchTestFailed(
                f"test failed at {op['path']!r}: {actual!r} != "
                f"{op.get('value')!r}")
        return doc
    if kind in ("move", "copy"):
        src = [_unescape(t) for t in op["from"].split("/")[1:]]
        value = copy.deepcopy(_resolve(doc, src))
        if kind == "move":
            doc = _apply_one(doc, {"op": "remove", "path": op["from"]}, src)
        return _apply_one(doc, {"op": "add", "path": op["path"],
                                "value": value}, tokens)
    if not tokens:  # whole-document op
        if kind in ("add", "replace"):
            return copy.deepcopy(op["value"])
        raise ValueError(f"cannot {kind} whole document")
    parent = _resolve(doc, tokens[:-1])
    last = tokens[-1]
    if isinstance(parent, list):
        if kind == "add":
            idx = len(parent) if last == "-" else int(last)
            parent.insert(idx, copy.deepcopy(op["value"]))
        elif kind == "remove":
            del parent[int(last)]
        elif kind == "replace":
            parent[int(last)] = copy.deepcopy(op["value"])
        else:
            raise ValueError(f"unsupported op {kind}")
    else:
        if kind in ("add", "replace"):
            parent[last] = copy.deepcopy(op["value"])
        elif kind == "remove":
            parent.pop(last, None)
        else:
            raise ValueError(f"unsupported op {kind}")
    return doc


__all__ = ["diff", "apply_patch", "PatchTestFailed"]
