"""MLflow integration.

Port of notebook_mlflow.go: the `opendatahub.io/mlflow-instance` annotation
creates a RoleBinding `{name}-mlflow` for the notebook SA to the
`mlflow-operator-mlflow-integration` ClusterRole (requeueing until the
ClusterRole exists), and the webhook injects MLFLOW_* env vars with a
Gateway-derived tracking URI (notebook_mlflow.go:107-322).
"""

from __future__ import annotations

from typing import Optional

from ..api.types import Notebook
from ..kube import ApiServer, EventRecorder, KubeObject, NotFoundError, ObjectMeta, set_controller_reference
from ..tpu.env import merge_env
from ..utils.config import OdhConfig
from . import constants as C
from .gateway import get_hostname_for_public_endpoint

MLFLOW_IDENTIFIER = "mlflow"
MLFLOW_REQUEUE_SECONDS = 30.0


def mlflow_instance(nb: Notebook) -> str:
    return nb.metadata.annotations.get(C.ANNOTATION_MLFLOW_INSTANCE, "")


def get_mlflow_tracking_uri(api: ApiServer, cfg: OdhConfig, instance_name: str) -> str:
    """https://{gateway-host}/mlflow[-{instance}] (getMLflowTrackingURI,
    notebook_mlflow.go:107-142).  GATEWAY_URL overrides discovery."""
    hostname = cfg.gateway_url or get_hostname_for_public_endpoint(api, cfg)
    if not hostname:
        raise LookupError("unable to determine hostname for MLflow tracking URI")
    path = MLFLOW_IDENTIFIER
    if instance_name and instance_name != MLFLOW_IDENTIFIER:
        path = f"{MLFLOW_IDENTIFIER}-{instance_name}"
    if hostname.startswith(("http://", "https://")):
        return f"{hostname}/{path}"
    return f"https://{hostname}/{path}"


def new_mlflow_role_binding(nb: Notebook) -> KubeObject:
    return KubeObject(
        api_version="rbac.authorization.k8s.io/v1",
        kind="RoleBinding",
        metadata=ObjectMeta(
            name=nb.name + C.MLFLOW_ROLEBINDING_SUFFIX,
            namespace=nb.namespace,
            labels={C.NOTEBOOK_NAME_LABEL: nb.name},
        ),
        body={
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": C.MLFLOW_CLUSTER_ROLE,
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": nb.name,
                    "namespace": nb.namespace,
                }
            ],
        },
    )


def reconcile_mlflow_integration(
    api: ApiServer,
    nb: Notebook,
    recorder: Optional[EventRecorder] = None,
) -> Optional[float]:
    """Returns a requeue-after delay while the ClusterRole is absent, else
    None (ReconcileMLflowIntegration, notebook_mlflow.go:236-270)."""
    instance = mlflow_instance(nb)
    if not instance:
        # annotation removed -> drop the binding
        try:
            api.delete("RoleBinding", nb.namespace, nb.name + C.MLFLOW_ROLEBINDING_SUFFIX)
        except NotFoundError:
            pass
        return None
    if api.try_get("ClusterRole", "", C.MLFLOW_CLUSTER_ROLE) is None:
        if recorder is not None:
            recorder.event(
                nb.obj,
                "Warning",
                "MLflowClusterRoleMissing",
                f"ClusterRole {C.MLFLOW_CLUSTER_ROLE} not found; retrying",
            )
        return MLFLOW_REQUEUE_SECONDS
    desired = new_mlflow_role_binding(nb)
    set_controller_reference(nb.obj, desired)
    if api.try_get("RoleBinding", nb.namespace, desired.name) is None:
        api.create(desired)
    return None


def handle_mlflow_env_vars(api: ApiServer, nb: Notebook, cfg: OdhConfig) -> None:
    """Webhook-side: inject/update MLFLOW_* env vars in the first container;
    strip them when the annotation is absent (HandleMLflowEnvVars,
    notebook_mlflow.go:287-322)."""
    containers = nb.pod_spec.get("containers") or []
    if not containers:
        return
    main = containers[0]
    instance = mlflow_instance(nb)
    managed = (
        C.MLFLOW_TRACKING_URI_ENV,
        C.MLFLOW_K8S_INTEGRATION_ENV,
        C.MLFLOW_TRACKING_AUTH_ENV,
    )
    env = [e for e in main.get("env") or [] if e.get("name") not in managed]
    if instance:
        tracking_uri = get_mlflow_tracking_uri(api, cfg, instance)
        env = merge_env(
            env,
            [
                {"name": C.MLFLOW_TRACKING_URI_ENV, "value": tracking_uri},
                {"name": C.MLFLOW_K8S_INTEGRATION_ENV, "value": "true"},
                {
                    "name": C.MLFLOW_TRACKING_AUTH_ENV,
                    "value": C.MLFLOW_TRACKING_AUTH_VALUE,
                },
            ],
        )
    if env:
        main["env"] = env
    else:
        main.pop("env", None)
