#!/usr/bin/env bash
# Chaos knowledge-model drift check (reference
# .github/workflows/operator_chaos_validation.yaml analog).
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/test_chaos.py -q "$@"
