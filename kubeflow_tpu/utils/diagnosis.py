"""Causal diagnosis engine: per-notebook root-cause explanation and
fleet-wide change-point detection over the fused telemetry spine.

PRs 2–17 built six independent telemetry streams — spans + flight
recorder, SLO burn alerts, per-stage lifecycle ledger, in-process TSDB,
data-plane straggler rollup, tenant metering — but answering "why was
this notebook slow?" or "what changed at 14:03?" still meant an operator
hand-joining /debug endpoints.  This module is the join, with two halves
sharing one evidence model:

* **Per-notebook explainer** — ``explain(namespace, name)`` fuses the
  flight recorder's attempt history (including injected FaultRecords
  riding ``AttemptRecord.faults``), the lifecycle ledger's stage
  partition and excursion ring, Notebook status records (sliceRecovery,
  sessionState, replication/promotion), Events, the data-plane straggler
  rollup, tenant-metering noisy-neighbor flags, SLO alert exemplars, and
  overlapping change-point findings into a **ranked causal chain**::

      ready 92.0s vs fleet p50 8.0s <= schedule_cold 71.0s (77% of wall)
        <= fault plan 'api-degrade' injected 3 faults
        <= change point in stage_p99.retry_backoff at t=...

  Every link cites its evidence (trace_id, event, metric sample).
  Ranking is deterministic: causes backed by *direct* evidence (faults
  in the attempt record, an active straggler verdict, a promotion
  excursion, a noisy-neighbor flag) score ``10 + x`` and always outrank
  causes inferred from stage shares alone (share <= 1), so an injected
  degradation names itself rather than its symptom.

* **Fleet change-point detector** — a bounded, injected-clock
  **level-latch** detector over the TSDB's raw tier.  Per watched
  series it latches a baseline level (mean of the first ``window``
  points) and a spread (max deviation in that window); each evaluation
  it compares the tail-window mean against the latched level and fires
  when the shift clears ``max(min_abs, shift_factor*spread,
  rel_factor*|level|)``.  On fire it re-latches at the new level —
  one deduped finding per shift: a step fires exactly once, stationary
  noise never fires, a ramp fires at least once.  Each finding is
  correlated against the discrete event timeline (fault injections,
  promotions, shard membership epochs, warm-pool resizes, straggler
  onsets, noisy-neighbor flags, recovery excursions) within
  ``lookback_s`` and emitted with the matched event kind on the bounded
  ``notebook_changepoints_total{series,matched}`` counter.

Both halves run off injected clocks only (the detector consumes TSDB
sample timestamps, never a wall clock), hold no locks during reconcile
(the Manager feed is one deque append), and degrade to partial verdicts
when a stream is absent — a missing component never raises.

Served at ``/debug/explain?object=ns/name`` and ``/debug/changepoints``
(loopback only), summarized in ``/debug/fleet``, captured by
``ops/diagnose`` so both verdicts reconstruct offline from a bundle
(``changepoints_from_bundle`` re-runs the detector over the bundle's
raw curves), and wired into ``loadtest/convergence.py --sweep`` so each
sweep point names its binding stage.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from .metrics import Registry

# Closed cause taxonomy: every explainer verdict names one of these, so
# operators (and the chaos soak) can assert on the category rather than
# parse prose.
CAUSE_FAULT_INJECTION = "fault_injection"
CAUSE_SLOW_WORKER = "slow_worker"
CAUSE_PRIMARY_FAILOVER = "primary_failover"
CAUSE_NOISY_NEIGHBOR = "noisy_neighbor"
CAUSE_RECOVERY = "recovery"
CAUSE_COLD_SCHEDULE = "cold_schedule"
CAUSE_SHARD_HANDOFF = "shard_handoff"
CAUSE_QUEUE_BACKLOG = "queue_backlog"
CAUSE_NOMINAL = "nominal"

CAUSES = (
    CAUSE_FAULT_INJECTION, CAUSE_SLOW_WORKER, CAUSE_PRIMARY_FAILOVER,
    CAUSE_NOISY_NEIGHBOR, CAUSE_RECOVERY, CAUSE_COLD_SCHEDULE,
    CAUSE_SHARD_HANDOFF, CAUSE_QUEUE_BACKLOG, CAUSE_NOMINAL,
)

# Closed event-kind vocabulary for the discrete timeline — doubles as the
# bounded `matched` label set on notebook_changepoints_total.
EVENT_FAULT = "fault"
EVENT_PROMOTION = "promotion"
EVENT_RECOVERY = "recovery"
EVENT_SHARD_EPOCH = "shard_epoch"
EVENT_NOISY_NEIGHBOR = "noisy_neighbor"
EVENT_SLOW_WORKER = "slow_worker"
EVENT_WARMPOOL_RESIZE = "warmpool_resize"
MATCH_NONE = "none"

EVENT_KINDS = (
    EVENT_FAULT, EVENT_PROMOTION, EVENT_RECOVERY, EVENT_SHARD_EPOCH,
    EVENT_NOISY_NEIGHBOR, EVENT_SLOW_WORKER, EVENT_WARMPOOL_RESIZE,
)

# When a shift window correlates with events of several kinds, the most
# causally-specific kind wins the `matched` label (a fault plan explains
# a promotion better than the reverse).
_KIND_PRIORITY = {k: i for i, k in enumerate((
    EVENT_FAULT, EVENT_PROMOTION, EVENT_SLOW_WORKER, EVENT_NOISY_NEIGHBOR,
    EVENT_SHARD_EPOCH, EVENT_WARMPOOL_RESIZE, EVENT_RECOVERY))}

# TSDB series the detector watches (plus every `stage_p99.<stage>` series
# — the stage vocabulary is closed, so the label set stays bounded).
WATCHED_SERIES = (
    "ready_p99_s", "event_to_reconcile_p99_s", "workqueue_depth",
    "workqueue_backoff_pending", "criticalpath_violations",
    "metering_violations", "dataplane_stragglers",
    "reconcile_errors_delta", "promotions_delta",
)
_STAGE_PREFIX = "stage_p99."


def register_diagnosis_metrics(registry: Registry) -> dict:
    """The diagnosis family (registered by NotebookMetrics so the
    inventory is stable whether or not an engine is attached; the engine
    re-registers identically and gets the same object back)."""
    return {
        "changepoints": registry.counter(
            "notebook_changepoints_total",
            "Level shifts detected in watched TSDB series, labeled by "
            "series and the correlated discrete-event kind "
            "(see /debug/changepoints)",
            labels=("series", "matched")),
    }


def watched_series(name: str) -> bool:
    """Whether the detector tracks this TSDB series."""
    return name in WATCHED_SERIES or name.startswith(_STAGE_PREFIX)


class _LevelLatch:
    """Per-series level-shift state machine (see module docstring).

    ``push(t, v)`` returns a finding dict when the tail-window mean has
    shifted past the threshold, else None; the latch then re-anchors at
    the new level so one shift yields exactly one finding.
    """

    def __init__(self, window: int = 5, shift_factor: float = 4.0,
                 rel_factor: float = 0.25, min_abs: float = 0.5) -> None:
        if window < 2:
            raise ValueError("window must be >= 2")
        self.window = window
        self.shift_factor = shift_factor
        self.rel_factor = rel_factor
        self.min_abs = min_abs
        self.level: Optional[float] = None
        self.spread = 0.0
        self._tail: deque = deque(maxlen=window)

    def _threshold(self) -> float:
        return max(self.min_abs, self.shift_factor * self.spread,
                   self.rel_factor * abs(self.level))

    def push(self, t: float, v: float) -> Optional[dict]:
        self._tail.append((float(t), float(v)))
        if len(self._tail) < self.window:
            return None
        values = [p[1] for p in self._tail]
        mean = sum(values) / len(values)
        dev = max(abs(x - mean) for x in values)
        if self.level is None:
            # first full window latches the baseline
            self.level = mean
            self.spread = dev
            return None
        delta = mean - self.level
        if abs(delta) <= self._threshold():
            # quiet: let the spread estimate relax toward the current
            # noise amplitude so a settled post-shift series re-arms
            self.spread = min(self.spread, dev)
            return None
        finding = {
            "t_start": self._tail[0][0],
            "t_end": self._tail[-1][0],
            "baseline": self.level,
            "level": mean,
            "delta": delta,
            "direction": "up" if delta > 0 else "down",
        }
        # re-latch at the NEWEST point (where the series is heading, not
        # the transition-straddling tail mean) with the spread measured
        # around it, so the settling half of a step is suppressed and one
        # shift yields exactly one finding
        newest = self._tail[-1][1]
        self.level = newest
        self.spread = max(abs(x - newest) for x in values)
        return finding


def detect_level_shifts(points, *, window: int = 5,
                        shift_factor: float = 4.0, rel_factor: float = 0.25,
                        min_abs: float = 0.5) -> list[dict]:
    """Offline detector: run the level latch over a full raw series
    (``[[t, v], ...]``) and return every shift.  Same math as the online
    engine, so a diagnose bundle's curves reconstruct the live verdicts."""
    latch = _LevelLatch(window=window, shift_factor=shift_factor,
                        rel_factor=rel_factor, min_abs=min_abs)
    out = []
    for point in points:
        t, v = point[0], point[1]
        hit = latch.push(t, v)
        if hit is not None:
            out.append(hit)
    return out


def correlate_events(events, t_start: float, t_end: float,
                     lookback_s: float = 120.0) -> list[dict]:
    """Discrete-timeline events that could explain a shift window:
    anything from ``lookback_s`` before the window opened through its
    end (causes precede or accompany their symptoms)."""
    lo, hi = t_start - lookback_s, t_end
    return [e for e in events if lo <= e["t"] <= hi]


def matched_kind(matched: list[dict]) -> str:
    """The bounded `matched` label: the most causally-specific event
    kind in the correlation window, or "none"."""
    if not matched:
        return MATCH_NONE
    return min((e["kind"] for e in matched),
               key=lambda k: _KIND_PRIORITY.get(k, len(_KIND_PRIORITY)))


class DiagnosisEngine:
    """See module docstring.  One engine serves a whole sharded fleet
    (every replica's manager points at the same object, exactly like the
    lifecycle ledger)."""

    def __init__(self, clock, *, registry: Optional[Registry] = None,
                 recorder=None, lifecycle=None, slo_engine=None,
                 metering=None, tsdb=None, dataplane=None, fleet=None,
                 api=None,
                 window: int = 5, shift_factor: float = 4.0,
                 rel_factor: float = 0.25, min_abs: float = 0.5,
                 lookback_s: float = 120.0,
                 max_findings: int = 256, max_events: int = 512) -> None:
        self.clock = clock
        self.recorder = recorder
        self.lifecycle = lifecycle
        self.slo_engine = slo_engine
        self.metering = metering
        self.tsdb = tsdb
        self.dataplane = dataplane
        self.fleet = fleet
        self.api = api
        self.window = window
        self.shift_factor = shift_factor
        self.rel_factor = rel_factor
        self.min_abs = min_abs
        self.lookback_s = lookback_s
        self.max_findings = max_findings
        self.max_events = max_events
        self._registry = registry
        self._counter = (register_diagnosis_metrics(registry)["changepoints"]
                         if registry is not None else None)
        self._latches: dict[str, _LevelLatch] = {}
        self._consumed: dict[str, float] = {}
        self._events: deque = deque(maxlen=max_events)
        self._findings: deque = deque(maxlen=max_findings)
        self._seq = 0
        self.evaluations = 0
        # diff state for the discrete feeds
        self._last_epoch: Optional[int] = None
        self._last_flagged: set = set()
        self._last_stragglers: set = set()
        self._last_warmpool: Optional[float] = None

    # -- discrete event timeline (write side) ---------------------------------
    def _push_event(self, t: float, kind: str, detail: str,
                    object_key: str = "", trace_id: str = "") -> None:
        if self._events:
            last = self._events[-1]
            if (last["kind"] == kind and last["object"] == object_key
                    and last["detail"] == detail
                    and abs(t - last["t"]) <= 5.0):
                last["count"] += 1
                last["t"] = t
                return
        self._events.append({
            "t": t, "kind": kind, "detail": detail,
            "object": object_key, "trace_id": trace_id, "count": 1,
        })

    def observe_attempt(self, rec) -> None:
        """Manager feed (same call site as the SLO engine / ledger /
        metering): mine one finished attempt for discrete evidence.
        Must never raise into the reconcile loop."""
        if rec is None:
            return
        t = rec.end_time
        for fault in rec.faults or ():
            detail = str(fault.get("fault.rule")
                         or fault.get("fault.action") or "injected")
            self._push_event(t, EVENT_FAULT, detail, rec.object_key,
                             rec.trace_id)
        phases = rec.phases or {}
        # presence, not duration: a FakeClock promotion completes in zero
        # span time and is still a promotion
        if "promote" in phases:
            self._push_event(t, EVENT_PROMOTION,
                             f"promote {phases['promote']:.3f}s",
                             rec.object_key, rec.trace_id)
        if "recover" in phases or "migrate" in phases:
            dur = phases.get("recover", 0.0) + phases.get("migrate", 0.0)
            self._push_event(t, EVENT_RECOVERY, f"recover {dur:.3f}s",
                             rec.object_key, rec.trace_id)

    def _observe_discrete(self, now: float) -> None:
        """Diff the slow-moving control-plane surfaces into timeline
        events (called once per evaluation, off the injected clock)."""
        if self.fleet is not None:
            try:
                epoch = int(self.fleet.shard_snapshot().get("epoch", 0))
            except Exception:  # noqa: BLE001 — evidence is best-effort
                epoch = self._last_epoch
            if epoch is not None and epoch != self._last_epoch:
                if self._last_epoch is not None:
                    self._push_event(
                        now, EVENT_SHARD_EPOCH,
                        f"epoch {self._last_epoch}->{epoch}")
                self._last_epoch = epoch
        if self.metering is not None:
            try:
                flagged = set(self.metering.flagged())
            except Exception:  # noqa: BLE001
                flagged = self._last_flagged
            for ns in sorted(flagged - self._last_flagged):
                self._push_event(now, EVENT_NOISY_NEIGHBOR,
                                 f"tenant {ns} flagged noisy")
            self._last_flagged = flagged
        if self.dataplane is not None:
            # the scrape path already ran dataplane.evaluate() this cycle;
            # read its latched result rather than re-evaluating (which
            # would double the aggregator's check counters)
            last = getattr(self.dataplane, "_last", None) or {}
            stragglers = {
                (s["namespace"], s["name"], s["worker"])
                for s in last.get("stragglers", ())}
            for ns, nb, worker in sorted(stragglers
                                         - self._last_stragglers):
                self._push_event(now, EVENT_SLOW_WORKER,
                                 f"worker {worker} straggling",
                                 f"{ns}/{nb}")
            self._last_stragglers = stragglers
        if self._registry is not None:
            gauge = self._registry.get("notebook_warmpool_size")
            if gauge is not None:
                try:
                    size = sum(gauge.collect().values())
                except Exception:  # noqa: BLE001
                    size = self._last_warmpool
                if size is not None and size != self._last_warmpool:
                    if self._last_warmpool is not None:
                        self._push_event(
                            now, EVENT_WARMPOOL_RESIZE,
                            f"warm pool {self._last_warmpool:g}"
                            f"->{size:g}")
                    self._last_warmpool = size

    # -- change-point detection (evaluate side) -------------------------------
    def evaluate(self) -> list[dict]:
        """One detection round (called from the scrape path after the
        TSDB sample lands, and from /debug/changepoints): consume new
        raw points per watched series, emit one finding per shift."""
        self.evaluations += 1
        now = self.clock.now()
        self._observe_discrete(now)
        new: list[dict] = []
        if self.tsdb is None:
            return new
        for name in self.tsdb.series_names():
            if not watched_series(name):
                continue
            points = self.tsdb.query(name, tier="raw").get("points") or []
            latch = self._latches.get(name)
            if latch is None:
                latch = self._latches[name] = _LevelLatch(
                    window=self.window, shift_factor=self.shift_factor,
                    rel_factor=self.rel_factor, min_abs=self.min_abs)
            consumed = self._consumed.get(name)
            for t, v in points:
                if consumed is not None and t <= consumed:
                    continue
                hit = latch.push(t, v)
                if hit is not None:
                    new.append(self._emit(name, hit, now))
            if points:
                self._consumed[name] = points[-1][0]
        return new

    def _emit(self, series: str, hit: dict, now: float) -> dict:
        matched = correlate_events(list(self._events), hit["t_start"],
                                   hit["t_end"], self.lookback_s)
        kind = matched_kind(matched)
        alerts = []
        if self.slo_engine is not None:
            try:
                alerts = sorted(a.objective
                                for a in self.slo_engine.firing())
            except Exception:  # noqa: BLE001
                alerts = []
        self._seq += 1
        finding = dict(hit)
        finding.update({
            "seq": self._seq, "series": series, "detected_at": now,
            "matched": kind,
            "events": matched[-8:],
            "alerts": alerts,
        })
        self._findings.append(finding)
        if self._counter is not None:
            self._counter.labels(series, kind).inc()
        return finding

    def findings(self) -> list[dict]:
        return list(self._findings)

    # -- per-notebook explainer -----------------------------------------------
    def _object_events(self, namespace: str, name: str) -> list[dict]:
        """Warning/Normal Events recorded against the notebook (apiserver
        read; best-effort)."""
        if self.api is None:
            return []
        try:
            out = []
            for ev in self.api.list("Event", namespace=namespace):
                inv = ev.body.get("involvedObject") or {}
                if inv.get("name") == name:
                    out.append({
                        "reason": ev.body.get("reason", ""),
                        "type": ev.body.get("type", ""),
                        "message": ev.body.get("message", ""),
                        "count": ev.body.get("count", 1),
                    })
            return out[-16:]
        except Exception:  # noqa: BLE001
            return []

    def _fleet_p50_ready(self) -> float:
        """Fleet median ready time from the ledger's namespace rollup
        (the symptom link's baseline)."""
        if self.lifecycle is None:
            return 0.0
        try:
            walls = []
            for agg in self.lifecycle.namespace_rollup().values():
                if agg.get("ready_count"):
                    walls.append(agg.get("ready_mean_s", 0.0))
            if not walls:
                return 0.0
            walls.sort()
            return walls[len(walls) // 2]
        except Exception:  # noqa: BLE001
            return 0.0

    def explain(self, namespace: str, name: str) -> dict:
        """The ranked causal chain for one notebook (see module
        docstring).  Never raises: an unknown object returns a verdict-
        less body with an "error" field."""
        key = f"{namespace}/{name}"
        now = self.clock.now()
        attempts = []
        if self.recorder is not None:
            try:
                attempts = self.recorder.attempts(key)
            except Exception:  # noqa: BLE001
                attempts = []
        entry = None
        excursions = []
        if self.lifecycle is not None:
            try:
                entry = self.lifecycle.latest_entry(namespace, name)
            except Exception:  # noqa: BLE001
                entry = None
            try:
                excursions = self.lifecycle.excursions(namespace, name)
            except Exception:  # noqa: BLE001
                excursions = []
        base = {"object": key, "generated_at": now, "cause": "",
                "verdict": "", "chain": [], "candidates": []}
        if not attempts and entry is None:
            base["error"] = "no recorded evidence for object"
            return base

        status = self._object_status(namespace, name)
        events = self._object_events(namespace, name)
        trace_ids = {a.trace_id for a in attempts if a.trace_id}
        if entry and entry.get("trace_id"):
            trace_ids.add(entry["trace_id"])

        candidates = self._rank(key, attempts, entry, excursions, status,
                                events)
        chain = self._chain(key, attempts, entry, candidates)
        top = candidates[0]
        base.update({
            "cause": top["cause"],
            "verdict": " <= ".join(link["claim"] for link in chain),
            "chain": chain,
            "candidates": candidates,
            "evidence": {
                "attempts": len(attempts),
                "trace_ids": sorted(trace_ids)[:8],
                "entry": entry,
                "excursions": excursions[-8:],
                "status": status,
                "events": events,
                "alerts": self._object_alerts(trace_ids),
            },
        })
        return base

    def _object_status(self, namespace: str, name: str) -> dict:
        if self.api is None:
            return {}
        try:
            nb = self.api.try_get("Notebook", namespace, name)
            if nb is None:
                return {}
            st = nb.status
            out = {}
            for field_name in ("sessionState", "sliceRecovery"):
                if st.get(field_name):
                    out[field_name] = st.get(field_name)
            repl = st.get("replication") or {}
            if repl.get("promotion"):
                out["promotion"] = repl["promotion"]
            if "primary" in repl:
                out["primary"] = repl["primary"]
            return out
        except Exception:  # noqa: BLE001
            return {}

    def _object_alerts(self, trace_ids: set) -> list[str]:
        """Firing SLO objectives whose latched exemplar is one of this
        object's traces."""
        if self.slo_engine is None:
            return []
        try:
            return sorted(a.objective for a in self.slo_engine.firing()
                          if a.trace_id and a.trace_id in trace_ids)
        except Exception:  # noqa: BLE001
            return []

    def _rank(self, key: str, attempts, entry, excursions, status,
              events) -> list[dict]:
        """Deterministic candidate ranking.  Direct evidence scores
        ``10 + x``; stage-share inference scores ``share`` (<= 1);
        ``nominal`` floors the list so there is always a verdict."""
        stages = dict((entry or {}).get("stages") or {})
        wall = (entry or {}).get("wall_s") or 0.0
        grand = sum(stages.values()) or wall or 1.0
        candidates = []

        fault_attempts = [a for a in attempts if a.faults]
        if fault_attempts:
            n = sum(len(a.faults) for a in fault_attempts)
            rules = sorted({str(f.get("fault.rule", "injected"))
                            for a in fault_attempts for f in a.faults})
            candidates.append({
                "cause": CAUSE_FAULT_INJECTION,
                "score": 10.0 + min(n, 100) / 100.0,
                "detail": (f"fault plan {'/'.join(rules[:3])} injected "
                           f"{n} faults across "
                           f"{len(fault_attempts)} attempts"),
                "evidence": {"trace_id": fault_attempts[-1].trace_id,
                             "faults": n, "rules": rules[:8]},
            })

        straggler = self._straggler_for(key)
        if straggler is not None:
            candidates.append({
                "cause": CAUSE_SLOW_WORKER,
                "score": 10.0 + min(straggler.get("ratio", 0.0), 10.0) / 10.0,
                "detail": (f"worker {straggler.get('worker', '?')} step time "
                           f"{straggler.get('step_time_s', 0.0):.3f}s is "
                           f"{straggler.get('ratio', 0.0):.1f}x the slice "
                           "median"),
                "evidence": {"straggler": straggler, "metric":
                             "notebook_dataplane_step_time_seconds"},
            })

        promote_s = stages.get("promote", 0.0) + sum(
            x["duration_s"] for x in excursions if x["stage"] == "promote")
        if promote_s > 0.0 or status.get("promotion"):
            ex = next((x for x in reversed(excursions)
                       if x["stage"] == "promote"), None)
            candidates.append({
                "cause": CAUSE_PRIMARY_FAILOVER,
                "score": 10.0 + min(promote_s, 100.0) / 100.0,
                "detail": (f"primary failover: follower promoted in "
                           f"{promote_s:.3f}s"),
                "evidence": {"promotion": status.get("promotion"),
                             "trace_id": (ex or {}).get("trace_id", "")},
            })

        flagged = set()
        if self.metering is not None:
            try:
                flagged = set(self.metering.flagged())
            except Exception:  # noqa: BLE001
                flagged = set()
        ns = key.split("/", 1)[0]
        noisy_others = sorted(flagged - {ns})
        if noisy_others:
            candidates.append({
                "cause": CAUSE_NOISY_NEIGHBOR,
                "score": 9.0,
                "detail": (f"tenant {noisy_others[0]} flagged noisy while "
                           "this notebook queued"),
                "evidence": {"flagged": noisy_others,
                             "metric":
                             "notebook_tenant_fairness_checks_total"},
            })

        recover_s = (stages.get("recover", 0.0)
                     + stages.get("recovery_wait", 0.0)
                     + sum(x["duration_s"] for x in excursions
                           if x["stage"] in ("recover", "migrate")))
        if recover_s > 0.0 or status.get("sliceRecovery"):
            candidates.append({
                "cause": CAUSE_RECOVERY,
                "score": min(recover_s / grand, 1.0) + (
                    0.5 if status.get("sliceRecovery") else 0.0),
                "detail": f"slice recovery consumed {recover_s:.3f}s",
                "evidence": {"sliceRecovery": status.get("sliceRecovery"),
                             "seconds": recover_s},
            })

        cold_s = stages.get("schedule_cold", 0.0)
        if cold_s > 0.0:
            candidates.append({
                "cause": CAUSE_COLD_SCHEDULE,
                "score": cold_s / grand,
                "detail": (f"schedule_cold {cold_s:.3f}s "
                           f"({cold_s / grand:.0%} of wall): warm-pool "
                           "miss, gang provisioned cold"),
                "evidence": {"stage": "schedule_cold", "seconds": cold_s,
                             "metric": "notebook_warmpool_hits_total"},
            })

        handoff_s = stages.get("handoff_wait", 0.0)
        if handoff_s > 0.0:
            bump = 0.5 if any(e["kind"] == EVENT_SHARD_EPOCH
                              for e in self._events) else 0.0
            candidates.append({
                "cause": CAUSE_SHARD_HANDOFF,
                "score": handoff_s / grand + bump,
                "detail": (f"handoff_wait {handoff_s:.3f}s waiting for "
                           "shard ownership transfer"),
                "evidence": {"stage": "handoff_wait",
                             "seconds": handoff_s},
            })

        queue_s = (stages.get("queue_wait", 0.0)
                   + stages.get("retry_backoff", 0.0))
        if queue_s > 0.0:
            candidates.append({
                "cause": CAUSE_QUEUE_BACKLOG,
                "score": queue_s / grand,
                "detail": (f"queue_wait+retry_backoff {queue_s:.3f}s "
                           "behind the workqueue"),
                "evidence": {"seconds": queue_s,
                             "metric": "workqueue_depth"},
            })

        candidates.append({
            "cause": CAUSE_NOMINAL,
            "score": 0.01,
            "detail": (f"ready in {wall:.3f}s" if wall
                       else "no ready window recorded"),
            "evidence": {"wall_s": wall},
        })
        candidates.sort(key=lambda c: (-c["score"], c["cause"]))
        return candidates

    def _straggler_for(self, key: str) -> Optional[dict]:
        if self.dataplane is None:
            return None
        last = getattr(self.dataplane, "_last", None) or {}
        for s in last.get("stragglers", ()):
            if f"{s['namespace']}/{s['name']}" == key:
                return dict(s)
        return None

    def _chain(self, key: str, attempts, entry, candidates) -> list[dict]:
        """Symptom <= binding stage <= cause <= correlation, each link
        citing its evidence."""
        chain = []
        wall = (entry or {}).get("wall_s") or 0.0
        trace = ((entry or {}).get("trace_id")
                 or (attempts[-1].trace_id if attempts else ""))
        p50 = self._fleet_p50_ready()
        if wall:
            claim = f"ready {wall:.1f}s"
            if p50:
                claim += f" vs fleet p50 {p50:.1f}s"
            chain.append({"claim": claim, "evidence": {
                "trace_id": trace, "metric": "notebook_ready_seconds"}})
        else:
            dur = attempts[-1].duration_s if attempts else 0.0
            chain.append({
                "claim": f"last attempt {dur:.3f}s, not ready",
                "evidence": {"trace_id": trace}})
        stages = dict((entry or {}).get("stages") or {})
        if stages:
            binding = max(sorted(stages), key=lambda s: stages[s])
            share = stages[binding] / (sum(stages.values()) or 1.0)
            chain.append({
                "claim": (f"{binding} {stages[binding]:.1f}s "
                          f"({share:.0%} of wall)"),
                "evidence": {"trace_id": trace,
                             "metric": "notebook_stage_duration_seconds"},
            })
        top = candidates[0]
        if top["cause"] != CAUSE_NOMINAL:
            chain.append({"claim": top["detail"],
                          "evidence": top["evidence"]})
        correlated = self._correlated_finding(entry, attempts)
        if correlated is not None:
            chain.append({
                "claim": (f"change point in {correlated['series']} "
                          f"({correlated['direction']} "
                          f"{correlated['baseline']:.2f}"
                          f"->{correlated['level']:.2f}) at "
                          f"t={correlated['t_start']:.0f}"),
                "evidence": {"series": correlated["series"],
                             "seq": correlated["seq"],
                             "matched": correlated["matched"]},
            })
        return chain

    def _correlated_finding(self, entry, attempts) -> Optional[dict]:
        """A change-point finding overlapping this object's activity
        window, preferring the most recent."""
        if not self._findings:
            return None
        lo = hi = None
        if entry and entry.get("cause_ts"):
            lo = entry["cause_ts"]
            hi = entry.get("ready_ts") or self.clock.now()
        elif attempts:
            lo = attempts[0].start_time
            hi = attempts[-1].end_time
        if lo is None:
            return None
        for f in reversed(self._findings):
            if f["t_start"] <= hi + self.lookback_s \
                    and f["t_end"] >= lo - self.lookback_s:
                return f
        return None

    # -- alert annotation (/debug/alerts satellite) ---------------------------
    def one_line_cause(self, trace_id: str) -> str:
        """The explainer's one-line verdict for the object owning a
        trace, or "" — never an error (the /debug/alerts contract)."""
        try:
            if not trace_id or self.recorder is None:
                return ""
            for rec in reversed(self.recorder.attempts()):
                if rec.trace_id == trace_id:
                    ns, _, name = rec.object_key.partition("/")
                    return self.explain(ns, name).get("verdict", "")
            return ""
        except Exception:  # noqa: BLE001
            return ""

    def annotate_alerts(self, snapshot: dict) -> dict:
        """Return the SLO snapshot with a `diagnosis` line attached to
        each firing alert's exemplar trace."""
        try:
            out = dict(snapshot)
            firing = []
            for alert in out.get("firing", []):
                a = dict(alert)
                a["diagnosis"] = self.one_line_cause(a.get("trace_id", ""))
                firing.append(a)
            out["firing"] = firing
            return out
        except Exception:  # noqa: BLE001
            return snapshot

    # -- read side (/debug/changepoints, /debug/fleet, ops.diagnose) ----------
    def snapshot(self) -> dict:
        """The /debug/changepoints body."""
        return {
            "enabled": True,
            "evaluations": self.evaluations,
            "params": {
                "window": self.window, "shift_factor": self.shift_factor,
                "rel_factor": self.rel_factor, "min_abs": self.min_abs,
                "lookback_s": self.lookback_s,
            },
            "bounds": {"max_findings": self.max_findings,
                       "max_events": self.max_events},
            "watched": sorted(self._latches),
            "changepoints": list(self._findings),
            "timeline": list(self._events),
        }

    def fleet_summary(self) -> dict:
        """The /debug/fleet `diagnosis` section (kept light)."""
        return {
            "evaluations": self.evaluations,
            "changepoints": len(self._findings),
            "timeline_events": len(self._events),
            "recent": list(self._findings)[-5:],
        }

    def export(self, max_objects: int = 64) -> dict:
        """The ops/diagnose bundle section: the snapshot plus a verdict
        per recorded object, so explanations reconstruct offline."""
        out = self.snapshot()
        explanations = {}
        if self.recorder is not None:
            try:
                keys = sorted(self.recorder.objects())[:max_objects]
            except Exception:  # noqa: BLE001
                keys = []
            for key in keys:
                ns, _, name = key.partition("/")
                explanations[key] = self.explain(ns, name)
        out["explanations"] = explanations
        return out

    def clear(self) -> None:
        self._latches.clear()
        self._consumed.clear()
        self._events.clear()
        self._findings.clear()
        self._seq = 0
        self.evaluations = 0
        self._last_epoch = None
        self._last_flagged = set()
        self._last_stragglers = set()
        self._last_warmpool = None


def changepoints_from_bundle(bundle: dict, *, window: int = 5,
                             shift_factor: float = 4.0,
                             rel_factor: float = 0.25, min_abs: float = 0.5,
                             lookback_s: float = 120.0) -> list[dict]:
    """Offline reconstruction: re-run the detector over a diagnose
    bundle's raw TSDB curves and correlate against the bundle's captured
    discrete timeline — the same verdicts the live engine emitted."""
    series = (bundle.get("timeline") or {}).get("series") or {}
    events = (bundle.get("diagnosis") or {}).get("timeline") or []
    out = []
    for name in sorted(series):
        if not watched_series(name):
            continue
        raw = series[name].get("raw") or []
        for hit in detect_level_shifts(raw, window=window,
                                       shift_factor=shift_factor,
                                       rel_factor=rel_factor,
                                       min_abs=min_abs):
            matched = correlate_events(events, hit["t_start"], hit["t_end"],
                                       lookback_s)
            finding = dict(hit)
            finding.update({"series": name,
                            "matched": matched_kind(matched),
                            "events": matched[-8:]})
            out.append(finding)
    return out


def merge_timelines(bundles: list[dict]) -> dict:
    """`ops/diagnose --merge` satellite: fold each bundle's TSDB capture
    into one merged per-series curve, timestamp-sorted with a per-replica
    source tag, so sharded-fleet change-point analysis works offline
    across per-replica bundles."""
    merged: dict[str, list] = {}
    sources = []
    for i, bundle in enumerate(bundles):
        source = str(bundle.get("source") or f"bundle-{i}")
        sources.append(source)
        series = (bundle.get("timeline") or {}).get("series") or {}
        for name, tiers in series.items():
            for t, v in tiers.get("raw") or []:
                merged.setdefault(name, []).append(
                    {"t": t, "v": v, "source": source})
    for points in merged.values():
        points.sort(key=lambda p: (p["t"], p["source"]))
    return {
        "sources": sources,
        "series": {name: merged[name] for name in sorted(merged)},
        "points_total": sum(len(p) for p in merged.values()),
    }


__all__ = [
    "CAUSES", "CAUSE_COLD_SCHEDULE", "CAUSE_FAULT_INJECTION",
    "CAUSE_NOISY_NEIGHBOR", "CAUSE_NOMINAL", "CAUSE_PRIMARY_FAILOVER",
    "CAUSE_QUEUE_BACKLOG", "CAUSE_RECOVERY", "CAUSE_SHARD_HANDOFF",
    "CAUSE_SLOW_WORKER", "DiagnosisEngine", "EVENT_KINDS",
    "WATCHED_SERIES", "changepoints_from_bundle", "correlate_events",
    "detect_level_shifts", "matched_kind", "merge_timelines",
    "register_diagnosis_metrics", "watched_series",
]
