"""One-shot diagnostics bundle: `python -m kubeflow_tpu.ops.diagnose`.

An operator paged about a degraded fleet needs everything at once —
metrics, firing alerts, the flight recorder's retained attempts WITH
their span trees, the workqueue state, the live profile, and the config
the manager is actually running — in one artifact that can be attached
to an incident and analyzed offline, long after the pod restarted.

Two collection modes:

  - **HTTP** (the CLI default): walk the manager's loopback debug
    surface (`/metrics`, `/debug/{fleet,alerts,reconciles,workqueue,
    profile,criticalpath,tenants,timeline}`), then resolve the span trees
    of
    every retained slowest/
    errored attempt via `/debug/traces/<id>` — so the bundle can
    reconstruct, offline, exactly the attempts an operator gets paged
    about.  Run it where the manager runs (`kubectl exec`), like every
    other loopback debug consumer.
  - **in-process** (`collect_local`): the same bundle straight off live
    Manager/NotebookMetrics objects — what the fleet soak and the
    loadtest use, with no HTTP server in the loop.

Config capture is REDACTED: only recognized configuration variables are
included, and any name that smells like a credential has its value
masked — the bundle is made to be shared.

A third, offline mode — `--merge a.json b.json c.json` — takes one
bundle per manager replica of a sharded fleet and sweeps the COMBINED
attempt histories for same-key reconciles with overlapping real-time
windows: the cross-process double-reconcile audit that no single
replica's recorder can run alone.  It also folds each bundle's TSDB
timeline into one merged per-series curve (timestamp-sorted, tagged
with its source replica) and runs the offline change-point sweep over
the fused curves — fleet-wide level shifts that no single replica's
capture can see (pass --out to write the merged artifact).
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import urllib.error
import urllib.request
from typing import Mapping, Optional

from ..utils.clock import Clock

BUNDLE_FORMAT = 1

# environment prefixes that are configuration surface (utils/config.py +
# the observability/tracing knobs); everything else stays out of the
# bundle entirely
CONFIG_PREFIXES = (
    "SLO_", "WORKQUEUE_", "RECOVERY_", "CHECKPOINT_", "WARMPOOL_",
    "CULL", "ENABLE_", "TRACE_", "OTEL_", "PROFILER_", "WATCH_",
    "INVARIANTS_", "K8S_", "IDLENESS_", "CLUSTER_DOMAIN", "USE_ISTIO",
    "ISTIO_", "ADD_FSGROUP", "DEV", "SET_PIPELINE_", "GATEWAY_",
    "NOTEBOOK_GATEWAY_", "MLFLOW_", "INJECT_", "TPU_", "KUBE_",
    "DATAPLANE_", "TELEMETRY_", "LIFECYCLE_", "TSDB_", "METERING_",
    "TENANT_", "METRICS_",
)
_SECRET_RE = re.compile(r"TOKEN|SECRET|PASSWORD|PASSWD|CREDENTIAL|APIKEY"
                        r"|API_KEY|PRIVATE|CERT", re.IGNORECASE)
REDACTED = "**redacted**"


def redacted_config(env: Optional[Mapping[str, str]] = None) -> dict:
    """The recognized config surface of `env` (default: this process —
    under `kubectl exec` that IS the manager's environment), with
    credential-shaped names masked."""
    env = env if env is not None else os.environ
    out = {}
    for key in sorted(env):
        if not any(key.startswith(p) for p in CONFIG_PREFIXES):
            continue
        out[key] = REDACTED if _SECRET_RE.search(key) else env[key]
    return out


def _trace_ids(reconciles: dict) -> list[str]:
    """Trace ids of the retained slowest + errored attempts — the ones a
    bundle must make reconstructable offline."""
    ids: list[str] = []
    for section in ("slowest", "errored"):
        for a in reconciles.get(section, ()):
            tid = a.get("trace_id")
            if tid and tid not in ids:
                ids.append(tid)
    return ids


def collect_local(manager, metrics=None, env: Optional[Mapping[str, str]]
                  = None) -> dict:
    """Assemble the bundle from in-process objects (no HTTP).  `manager`
    is a kube.Manager; `metrics` a core.metrics.NotebookMetrics (scraped
    for the exposition + fleet rollup when given)."""
    engine = getattr(manager, "slo_engine", None)
    profiler = getattr(manager, "profiler", None)
    aggregator = getattr(manager, "telemetry_aggregator", None)
    ledger = getattr(manager, "lifecycle", None)
    metering = getattr(manager, "metering", None)
    tsdb = getattr(manager, "tsdb", None)
    diagnosis = getattr(manager, "diagnosis", None)
    reconciles = manager.flight_recorder.snapshot()
    traces = {}
    for tid in _trace_ids(reconciles):
        trace = manager.flight_recorder.trace(tid)
        if trace is not None:
            traces[tid] = trace
    return {
        "bundle_format": BUNDLE_FORMAT,
        "captured_at": manager.clock.now(),
        "source": "in-process",
        "metrics": (metrics.scrape() if metrics is not None
                    else manager.metrics_registry.render()),
        "fleet": (metrics.fleet_snapshot() if metrics is not None
                  else None),
        # firing alerts annotated with the diagnosis engine's one-line
        # verdict per exemplar (same body /debug/alerts serves)
        "alerts": ((diagnosis.annotate_alerts(engine.snapshot())
                    if diagnosis is not None else engine.snapshot())
                   if engine is not None else None),
        "slo_verdicts": engine.verdicts() if engine is not None else None,
        "reconciles": reconciles,
        "traces": traces,
        "workqueue": manager.workqueue_debug(),
        "profile": (profiler.snapshot() if profiler is not None
                    else {"enabled": False}),
        "telemetry": (aggregator.snapshot() if aggregator is not None
                      else None),
        "criticalpath": (ledger.snapshot() if ledger is not None
                         else None),
        # per-tenant usage + the noisy-neighbor verdict: who used the
        # chips/control plane and who was flagged, offline
        "tenants": (metering.snapshot() if metering is not None
                    else None),
        # full multi-tier dump, not just the inventory: the bundle is
        # what reconstructs a loadtest's p99-vs-time curve offline
        "timeline": tsdb.dump() if tsdb is not None else None,
        # change-point findings + per-object causal verdicts: both halves
        # of the diagnosis engine reconstruct offline (and
        # changepoints_from_bundle re-runs the detector over `timeline`)
        "diagnosis": diagnosis.export() if diagnosis is not None else None,
        "config": redacted_config(env),
    }


def _get(base: str, path: str, timeout: float) -> tuple[int, str]:
    req = urllib.request.Request(base + path)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def collect_http(addr: str, timeout: float = 10.0) -> dict:
    """Assemble the bundle over the manager's loopback debug surface."""
    base = addr.rstrip("/")
    if not base.startswith("http"):
        base = "http://" + base

    def get_json(path: str):
        code, body = _get(base, path, timeout)
        if code != 200:
            return {"error": f"GET {path} -> {code}"}
        return json.loads(body)

    code, metrics_text = _get(base, "/metrics", timeout)
    if code != 200:
        metrics_text = f"# GET /metrics -> {code}"
    fleet = get_json("/debug/fleet")
    reconciles = get_json("/debug/reconciles")
    traces = {}
    for tid in _trace_ids(reconciles):
        trace = get_json(f"/debug/traces/{tid}")
        if "error" not in trace:
            traces[tid] = trace
    alerts = get_json("/debug/alerts")
    # mirror collect_local's diagnosis export: the change-point snapshot
    # plus one causal verdict per recorded object (bounded)
    diagnosis = get_json("/debug/changepoints")
    if isinstance(diagnosis, dict) and "error" not in diagnosis:
        explanations = {}
        objects = (reconciles.get("objects") or {}
                   if isinstance(reconciles, dict) else {})
        for key in sorted(objects)[:64]:
            verdict = get_json(f"/debug/explain?object={key}")
            if isinstance(verdict, dict):
                explanations[key] = verdict
        diagnosis["explanations"] = explanations
    return {
        "bundle_format": BUNDLE_FORMAT,
        "captured_at": Clock().now(),
        "source": base,
        "metrics": metrics_text,
        "fleet": fleet,
        "alerts": alerts,
        "slo_verdicts": None,  # verdicts need an engine; alerts carry
        # the per-objective stats over HTTP
        "reconciles": reconciles,
        "traces": traces,
        "workqueue": get_json("/debug/workqueue"),
        "profile": get_json("/debug/profile"),
        # the fleet rollup's data-plane section, lifted to the same
        # top-level key collect_local uses so offline consumers need one
        # lookup path for worker telemetry
        "telemetry": (fleet.get("dataplane")
                      if isinstance(fleet, dict) else None),
        "criticalpath": get_json("/debug/criticalpath"),
        "tenants": get_json("/debug/tenants"),
        "timeline": get_json("/debug/timeline?dump=1"),
        "diagnosis": diagnosis,
        "config": redacted_config(),
    }


def merge_records(bundles) -> list:
    """Every recorded attempt across several managers' bundles, deduped
    by span id (an attempt retained in both the ring and a slowest/
    errored set must count once).  The input of the offline
    cross-process double-reconcile sweep."""
    from ..utils.flightrecorder import record_from_dict

    records, seen = [], set()
    for bundle in bundles:
        reconciles = bundle.get("reconciles") or {}
        for section in ("attempts", "slowest", "errored"):
            for d in reconciles.get(section) or ():
                key = d.get("span_id") or (
                    d.get("trace_id"), d.get("object"), d.get("attempt"),
                    d.get("mono_start"))
                if key in seen:
                    continue
                seen.add(key)
                records.append(record_from_dict(d))
    return records


def merge_overlaps(bundles) -> list:
    """Cross-process serialization audit: pairs of attempts for the same
    (controller, object) whose real-time windows overlap, swept over the
    MERGED attempt histories of several managers' bundles.  In a sharded
    fleet each replica records only its own attempts; an overlap that
    only exists across bundles is exactly a cross-process
    double-reconcile — the thing the shard map's fencing must prevent."""
    from ..utils.flightrecorder import sweep_overlaps

    return sweep_overlaps(merge_records(bundles))


def merge_timelines(bundles) -> dict:
    """Fold each bundle's TSDB capture into one merged per-series curve
    (timestamp-sorted, per-replica source tag) so sharded-fleet
    change-point analysis works offline across per-replica bundles."""
    from ..utils.diagnosis import merge_timelines as _merge

    return _merge(bundles)


def merge_changepoints(merged: dict, bundles) -> list:
    """Offline change-point sweep over the merged curves, correlated
    against the union of the bundles' discrete event timelines."""
    from ..utils.diagnosis import (correlate_events, detect_level_shifts,
                                   matched_kind, watched_series)

    events = []
    for bundle in bundles:
        events.extend((bundle.get("diagnosis") or {}).get("timeline") or ())
    events.sort(key=lambda e: e.get("t", 0.0))
    out = []
    for name, points in merged.get("series", {}).items():
        if not watched_series(name):
            continue
        for hit in detect_level_shifts([(p["t"], p["v"]) for p in points]):
            matched = correlate_events(events, hit["t_start"], hit["t_end"])
            hit.update({"series": name, "matched": matched_kind(matched),
                        "events": matched[-8:]})
            out.append(hit)
    return out


def summarize_merge(bundles, records, overlaps, merged=None,
                    changepoints=None) -> str:
    lines = [
        f"merged {len(bundles)} bundles: {len(records)} distinct attempts, "
        f"{len(overlaps)} overlapping pairs"
    ]
    for prev, cur in overlaps:
        lines.append(
            f"  OVERLAP {cur.controller} {cur.object_key}: "
            f"[{prev.mono_start:.6f}, {prev.mono_end:.6f}] vs "
            f"[{cur.mono_start:.6f}, {cur.mono_end:.6f}]")
    if merged is not None:
        lines.append(
            f"  timeline: {len(merged['series'])} merged series, "
            f"{merged['points_total']} points from "
            f"{len(merged['sources'])} sources")
    for cp in changepoints or ():
        lines.append(
            f"  CHANGEPOINT {cp['series']} {cp['direction']} "
            f"{cp['baseline']:.3g}->{cp['level']:.3g} at "
            f"t={cp['t_start']:.1f} (matched={cp['matched']})")
    return "\n".join(lines)


def summarize(bundle: dict) -> str:
    """One human line per bundle — printed by the CLI so the operator
    sees what they captured."""
    reconciles = bundle.get("reconciles") or {}
    alerts = bundle.get("alerts") or {}
    profile = bundle.get("profile") or {}
    fleet = bundle.get("fleet") or {}
    firing = alerts.get("firing")
    return (
        f"bundle: {reconciles.get('recorded_total', 0)} attempts recorded, "
        f"{len(reconciles.get('slowest') or ())} slowest + "
        f"{len(reconciles.get('errored') or ())} errored retained, "
        f"{len(bundle.get('traces') or {})} traces resolved, "
        f"{len(firing) if firing is not None else 0} alerts firing, "
        f"{profile.get('samples_total', 0)} profile samples, "
        f"{fleet.get('notebooks', 0)} notebooks in the fleet rollup"
    )


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubeflow_tpu.ops.diagnose",
        description="capture a one-shot diagnostics bundle from a running "
                    "manager's loopback debug surface")
    parser.add_argument("--addr", default="http://127.0.0.1:8080",
                        help="manager health/metrics address "
                             "(default %(default)s; loopback-only surface "
                             "— run this where the manager runs)")
    parser.add_argument("--out", default="bundle.json",
                        help="bundle output path (default %(default)s)")
    parser.add_argument("--timeout", type=float, default=10.0)
    parser.add_argument("--merge", nargs="+", metavar="BUNDLE",
                        help="offline mode: merge several managers' "
                             "bundles and sweep the combined attempt "
                             "histories for cross-process overlapping "
                             "reconciles (exit 1 when any pair overlaps)")
    args = parser.parse_args(argv)

    if args.merge:
        bundles = []
        for path in args.merge:
            try:
                with open(path) as f:
                    bundles.append(json.load(f))
            except (OSError, ValueError) as err:
                print(f"diagnose: cannot load {path}: {err}",
                      file=sys.stderr)
                return 1
        records = merge_records(bundles)
        overlaps = merge_overlaps(bundles)
        merged = merge_timelines(bundles)
        changepoints = merge_changepoints(merged, bundles)
        print(summarize_merge(bundles, records, overlaps, merged,
                              changepoints))
        if args.out != parser.get_default("out"):
            # an explicit --out in merge mode writes the merged artifact:
            # the fused per-series curves + the offline change-point sweep
            with open(args.out, "w") as f:
                json.dump({"merged_timeline": merged,
                           "changepoints": changepoints,
                           "bundles": len(bundles),
                           "overlaps": len(overlaps)},
                          f, indent=2, sort_keys=True, default=str)
                f.write("\n")
            print(f"wrote {args.out}")
        return 1 if overlaps else 0

    try:
        bundle = collect_http(args.addr, timeout=args.timeout)
    except (OSError, urllib.error.URLError) as err:
        print(f"diagnose: cannot reach {args.addr}: {err}", file=sys.stderr)
        return 1
    with open(args.out, "w") as f:
        json.dump(bundle, f, indent=2, sort_keys=True, default=str)
        f.write("\n")
    print(summarize(bundle))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
