"""Admission webhooks for the Notebook CR.

Port of notebook_mutating_webhook.go / notebook_validating_webhook.go with
the TPU-first image path:

Mutating (NotebookWebhook.Handle :360-516):
  CREATE      -> inject the reconciliation lock (stop-annotation = lock value)
  always      -> resolve the container image — ImageStream `last-image-selection`
                 resolution (:865-972) for CPU notebooks, and for `spec.tpu`
                 notebooks the NEW image-swap table mapping CUDA/default
                 images to JAX+libtpu workbench images (SURVEY.md §7.3)
              -> mount the trusted CA bundle + cert env (:700-859)
              -> sync + mount pipeline runtime images (:405-418)
              -> [SET_PIPELINE_SECRET] sync + mount the Elyra DSPA secret
              -> Feast mount/unmount by label (:439-452)
              -> [MLFLOW_ENABLED] MLflow env vars (:454-462)
              -> [inject-auth] kube-rbac-proxy sidecar (:183-334)
              -> [INJECT_CLUSTER_PROXY_ENV] proxy env (:473-490)
  UPDATE      -> restart-blocking: revert webhook-only pod-template changes on
                 a running notebook and stamp `update-pending` with the first
                 difference (:518-581) — with a TPU carve-out: a spec.tpu edit
                 is always a slice-atomic restart, never blocked.

Validating (notebook_validating_webhook.go:31-100):
  UPDATE      -> deny removing the mlflow-instance annotation while running.
"""

from __future__ import annotations

import copy
import logging
from typing import Optional

from ..api.types import Notebook
from ..kube import AdmissionDenied, AdmissionHook, ApiServer, KubeObject
from ..tpu.env import merge_env, upsert_by_name
from ..utils.config import OdhConfig
from ..utils.tracing import get_tracer
from . import constants as C
from .dspa import mount_elyra_runtime_config_secret, sync_elyra_runtime_config_secret
from .feast import apply_feast_config
from .mlflow import handle_mlflow_env_vars
from .runtime_images import mount_pipeline_runtime_images, sync_runtime_images_configmap

logger = logging.getLogger("kubeflow_tpu.odh.webhook")

IMAGE_STREAM_NOT_FOUND_EVENT = "ImageStreamNotFound"
IMAGE_STREAM_TAG_NOT_FOUND_EVENT = "ImageStreamTagNotFound"
INTERNAL_REGISTRY_HOST = "image-registry.openshift-image-registry.svc:5000"


def _main_container(nb: Notebook) -> Optional[dict]:
    for container in nb.pod_spec.get("containers") or []:
        if container.get("name") == nb.name:
            return container
    return None


# -- reconciliation lock -------------------------------------------------------


def inject_reconciliation_lock(nb: Notebook) -> None:
    """On CREATE the workload starts at 0 replicas until the ODH controller
    has its objects ready (notebook_mutating_webhook.go:106-122)."""
    nb.metadata.annotations.setdefault(
        C.STOP_ANNOTATION, C.RECONCILIATION_LOCK_VALUE
    )


# -- image resolution ----------------------------------------------------------


def set_container_image_from_registry(
    api: ApiServer, nb: Notebook, controller_namespace: str, span
) -> None:
    """ImageStream tag -> dockerImageReference
    (SetContainerImageFromRegistry, notebook_mutating_webhook.go:865-972)."""
    selection = nb.metadata.annotations.get(C.ANNOTATION_LAST_IMAGE_SELECTION)
    if not selection:
        return
    container = _main_container(nb)
    if container is None:
        raise ValueError(f"no container found matching the notebook name {nb.name}")
    if INTERNAL_REGISTRY_HOST in (container.get("image") or ""):
        return  # dashboard already resolved through the internal registry
    if selection.count(":") != 1:
        raise ValueError("invalid image selection format")
    stream_name, tag_name = selection.split(":")
    image_namespace = (
        nb.metadata.annotations.get(C.ANNOTATION_WORKBENCH_IMAGE_NAMESPACE, "").strip()
        or controller_namespace
    )
    stream = api.try_get("ImageStream", image_namespace, stream_name)
    if stream is None:
        span.add_event(IMAGE_STREAM_NOT_FOUND_EVENT)
        return
    tags = stream.status.get("tags") or []
    if not tags:
        span.add_event(IMAGE_STREAM_TAG_NOT_FOUND_EVENT)
        raise ValueError("ImageStream has no status or tags")
    for tag in tags:
        if tag.get("tag") != tag_name:
            continue
        items = tag.get("items") or []
        if not items:
            continue
        newest = max(items, key=lambda it: it.get("created", ""))
        container["image"] = newest.get("dockerImageReference", "")
        for env in container.get("env") or []:
            if env.get("name") == "JUPYTER_IMAGE":
                env["value"] = selection
                break
        return
    span.add_event(IMAGE_STREAM_TAG_NOT_FOUND_EVENT)


def swap_tpu_image(nb: Notebook, cfg: OdhConfig) -> None:
    """TPU path: replace CUDA/default workbench images with JAX+libtpu images
    keyed off spec.tpu — the replacement for the GPU ImageStream resolution
    (SURVEY.md §7.3).  Explicit map entries win; an image with no mapping and
    no TPU marker falls back to the default TPU workbench image."""
    if nb.tpu is None:
        return
    container = _main_container(nb) or (nb.pod_spec.get("containers") or [{}])[0]
    image = container.get("image") or ""
    if image in cfg.tpu_image_map:
        container["image"] = cfg.tpu_image_map[image]
        return
    # keep images the user already aimed at TPU
    if any(marker in image for marker in ("tpu", "jax", "libtpu")):
        return
    container["image"] = cfg.tpu_default_image


# -- CA bundle mount -----------------------------------------------------------


def inject_cert_config(nb: Notebook, configmap_name: str) -> None:
    """Mount the bundle at /etc/pki/tls/custom-certs and point the usual
    TLS-consuming env vars at it (InjectCertConfig,
    notebook_mutating_webhook.go:747-859)."""
    spec = nb.pod_spec
    cert_path = f"{C.TRUSTED_CA_MOUNT_PATH}/{C.TRUSTED_CA_BUNDLE_FILE}"
    volume = {
        "name": C.TRUSTED_CA_BUNDLE_VOLUME,
        "configMap": {
            "name": configmap_name,
            "optional": True,
            "items": [
                {"key": C.TRUSTED_CA_BUNDLE_FILE, "path": C.TRUSTED_CA_BUNDLE_FILE}
            ],
        },
    }
    upsert_by_name(spec.setdefault("volumes", []), volume)
    mount = {
        "name": C.TRUSTED_CA_BUNDLE_VOLUME,
        "mountPath": C.TRUSTED_CA_MOUNT_PATH,
        "readOnly": True,
    }
    for container in spec.get("containers") or []:
        upsert_by_name(container.setdefault("volumeMounts", []), mount)
        container["env"] = merge_env(
            container.get("env") or [],
            [{"name": name, "value": cert_path} for name in C.CA_BUNDLE_ENV_VARS],
        )


def check_and_mount_ca_cert_bundle(api: ApiServer, nb: Notebook) -> None:
    """Mount workbench-trusted-ca-bundle when it exists with a non-empty
    bundle (CheckAndMountCACertBundle,
    notebook_mutating_webhook.go:700-745)."""
    cm = api.try_get(
        "ConfigMap", nb.namespace, C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP
    )
    if cm is None:
        return
    bundle = (cm.body.get("data") or {}).get(C.TRUSTED_CA_BUNDLE_FILE, "").strip()
    if not bundle:
        return
    inject_cert_config(nb, C.WORKBENCH_TRUSTED_CA_BUNDLE_CONFIGMAP)


# -- kube-rbac-proxy sidecar ---------------------------------------------------


def parse_auth_sidecar_resources(nb: Notebook) -> dict:
    """Resources from annotations with validation; defaults 100m/64Mi,
    requests == limits (parseAndValidateAuthSidecarResources,
    notebook_mutating_webhook.go:134-181)."""

    def quantity(annotation: str, default: str) -> str:
        value = nb.metadata.annotations.get(annotation, "").strip()
        if not value:
            return default
        from ..kube import parse_quantity

        try:
            parsed = parse_quantity(value)
        except ValueError:
            raise AdmissionDenied(
                f"invalid resource quantity {value!r} in annotation {annotation}"
            ) from None
        if parsed <= 0:
            raise AdmissionDenied(
                f"non-positive resource quantity {value!r} in annotation {annotation}"
            )
        return value

    cpu_request = quantity(
        C.ANNOTATION_AUTH_SIDECAR_CPU_REQUEST, C.KUBE_RBAC_PROXY_DEFAULT_CPU
    )
    memory_request = quantity(
        C.ANNOTATION_AUTH_SIDECAR_MEMORY_REQUEST, C.KUBE_RBAC_PROXY_DEFAULT_MEMORY
    )
    cpu_limit = quantity(C.ANNOTATION_AUTH_SIDECAR_CPU_LIMIT, cpu_request)
    memory_limit = quantity(C.ANNOTATION_AUTH_SIDECAR_MEMORY_LIMIT, memory_request)
    return {
        "requests": {"cpu": cpu_request, "memory": memory_request},
        "limits": {"cpu": cpu_limit, "memory": memory_limit},
    }


def inject_kube_rbac_proxy(nb: Notebook, cfg: OdhConfig) -> None:
    """Sidecar + config/TLS volumes + dedicated SA (InjectKubeRbacProxy,
    notebook_mutating_webhook.go:183-334)."""
    resources = parse_auth_sidecar_resources(nb)
    sidecar = {
        "name": C.KUBE_RBAC_PROXY_CONTAINER_NAME,
        "image": cfg.kube_rbac_proxy_image,
        "args": [
            f"--secure-listen-address=0.0.0.0:{C.KUBE_RBAC_PROXY_PORT}",
            f"--upstream=http://127.0.0.1:{C.NOTEBOOK_PORT}/",
            "--auth-header-fields-enabled=true",
            f"--proxy-endpoints-port={C.KUBE_RBAC_PROXY_HEALTH_PORT}",
            f"--config-file={C.KUBE_RBAC_PROXY_CONFIG_MOUNT_PATH}/{C.KUBE_RBAC_PROXY_CONFIG_FILE}",
            f"--tls-cert-file={C.KUBE_RBAC_PROXY_TLS_MOUNT_PATH}/tls.crt",
            f"--tls-private-key-file={C.KUBE_RBAC_PROXY_TLS_MOUNT_PATH}/tls.key",
        ],
        "ports": [
            {
                "name": C.KUBE_RBAC_PROXY_PORT_NAME,
                "containerPort": C.KUBE_RBAC_PROXY_PORT,
                "protocol": "TCP",
            }
        ],
        "livenessProbe": {
            "httpGet": {
                "path": "/healthz",
                "port": C.KUBE_RBAC_PROXY_HEALTH_PORT,
                "scheme": "HTTPS",
            }
        },
        "readinessProbe": {
            "httpGet": {
                "path": "/healthz",
                "port": C.KUBE_RBAC_PROXY_HEALTH_PORT,
                "scheme": "HTTPS",
            }
        },
        "resources": resources,
        "volumeMounts": [
            {
                "name": C.KUBE_RBAC_PROXY_CONFIG_VOLUME,
                "mountPath": C.KUBE_RBAC_PROXY_CONFIG_MOUNT_PATH,
            },
            {
                "name": C.KUBE_RBAC_PROXY_TLS_VOLUME,
                "mountPath": C.KUBE_RBAC_PROXY_TLS_MOUNT_PATH,
            },
        ],
    }
    spec = nb.pod_spec
    upsert_by_name(spec.setdefault("containers", []), sidecar)
    volumes = spec.setdefault("volumes", [])
    for volume in (
        {
            "name": C.KUBE_RBAC_PROXY_CONFIG_VOLUME,
            "configMap": {"name": nb.name + C.KUBE_RBAC_PROXY_CONFIG_SUFFIX},
        },
        {
            "name": C.KUBE_RBAC_PROXY_TLS_VOLUME,
            "secret": {"secretName": nb.name + C.KUBE_RBAC_PROXY_TLS_SECRET_SUFFIX},
        },
    ):
        upsert_by_name(volumes, volume)
    # the proxy authenticates with its own (per-notebook) ServiceAccount
    spec["serviceAccountName"] = nb.name


def auth_injection_requested(nb: Notebook) -> bool:
    return nb.metadata.annotations.get(C.ANNOTATION_INJECT_AUTH) == "true"


# -- cluster proxy env ---------------------------------------------------------


def inject_proxy_config_env_vars(api: ApiServer, nb: Notebook) -> None:
    """HTTP(S)_PROXY/NO_PROXY from the cluster Proxy CR into the notebook's
    main container (InjectProxyConfigEnvVars,
    notebook_mutating_webhook.go:648-698)."""
    proxy = api.try_get("Proxy", "", "cluster")
    if proxy is None:
        return
    status = proxy.body.get("status") or {}
    values = {
        "HTTP_PROXY": status.get("httpProxy", ""),
        "HTTPS_PROXY": status.get("httpsProxy", ""),
        "NO_PROXY": status.get("noProxy", ""),
    }
    container = _main_container(nb)
    if container is None:
        return
    env = list(container.get("env") or [])
    for name in C.PROXY_ENV_VARS:
        value = values.get(name, "")
        if not value:
            continue
        for entry in env:
            if entry.get("name") == name:
                entry["value"] = value
                break
        else:
            env.append({"name": name, "value": value})
    container["env"] = env


# -- restart blocking ----------------------------------------------------------


def maybe_restart_running_notebook(
    op: str,
    old: Optional[KubeObject],
    submitted: KubeObject,
    mutated: Notebook,
    tracer,
) -> Optional[str]:
    """Returns a pending-update reason when webhook-caused pod-template
    changes on a running notebook were reverted, else None
    (maybeRestartRunningNotebook, notebook_mutating_webhook.go:518-581).

    TPU carve-out (SURVEY.md §7 hard parts): when spec.tpu itself changed,
    the workload restarts slice-atomically no matter what — blocking the
    webhook's consequent image/env updates would strand the new topology on
    the old image, so everything passes through.
    """
    with tracer.start_span("maybeRestartRunningNotebook"):
        if op == "CREATE" or old is None:
            return None
        annotations = mutated.metadata.annotations
        if C.STOP_ANNOTATION in annotations:
            return None
        if annotations.get("notebooks.opendatahub.io/notebook-restart"):
            return None
        old_spec = old.spec.get("template", {}).get("spec", {})
        submitted_spec = submitted.spec.get("template", {}).get("spec", {})
        if old.spec.get("tpu") != submitted.spec.get("tpu"):
            return None  # topology edit: always a restart
        if old_spec != submitted_spec:
            return None  # user's own edit restarts the pod anyway
        mutated_spec = mutated.pod_spec
        if mutated_spec == old_spec:
            return None  # webhook changed nothing
        from .diff import first_difference

        reason = first_difference(mutated_spec, submitted_spec)
        mutated.obj.spec.setdefault("template", {})["spec"] = copy.deepcopy(
            submitted_spec
        )
        return reason or "failed to compute the reason for why there is a pending restart"


# -- the webhooks --------------------------------------------------------------


class NotebookMutatingWebhook:
    """Callable registered as a mutating AdmissionHook on the ApiServer."""

    def __init__(self, api: ApiServer, cfg: OdhConfig):
        self.api = api
        self.cfg = cfg
        self.tracer = get_tracer("odh-notebook-controller/webhook")

    def handle(
        self, op: str, old: Optional[KubeObject], obj: KubeObject
    ) -> KubeObject:
        nb = Notebook(obj)
        submitted = obj.deepcopy()
        with self.tracer.start_span(
            "NotebookWebhook.Handle",
            {"notebook": nb.name, "namespace": nb.namespace, "operation": op},
        ) as span:
            if op == "CREATE":
                inject_reconciliation_lock(nb)
            set_container_image_from_registry(
                self.api, nb, self.cfg.controller_namespace, span
            )
            swap_tpu_image(nb, self.cfg)
            check_and_mount_ca_cert_bundle(self.api, nb)
            sync_runtime_images_configmap(
                self.api, nb.namespace, self.cfg.controller_namespace
            )
            mount_pipeline_runtime_images(nb)
            if self.cfg.set_pipeline_secret:
                try:
                    sync_elyra_runtime_config_secret(self.api, nb, self.cfg)
                except Exception as err:
                    # a broken DSPA must not block notebook admission
                    logger.warning("elyra secret sync failed: %s", err)
                mount_elyra_runtime_config_secret(nb)
            apply_feast_config(nb)
            if self.cfg.mlflow_enabled:
                handle_mlflow_env_vars(self.api, nb, self.cfg)
            if auth_injection_requested(nb):
                inject_kube_rbac_proxy(nb, self.cfg)
            if self.cfg.inject_cluster_proxy_env:
                inject_proxy_config_env_vars(self.api, nb)

            reason = maybe_restart_running_notebook(
                op, old, submitted, nb, self.tracer
            )
            if reason is not None:
                nb.metadata.annotations[C.ANNOTATION_UPDATE_PENDING] = reason
            else:
                nb.metadata.annotations.pop(C.ANNOTATION_UPDATE_PENDING, None)
        return nb.obj

    def hook(self) -> AdmissionHook:
        return AdmissionHook(
            kinds=("Notebook",),
            handler=self.handle,
            operations=("CREATE", "UPDATE"),
            mutating=True,
            name="mutate-notebook-v1",
        )


class NotebookValidatingWebhook:
    """UPDATE-only validation (notebook_validating_webhook.go:31-100)."""

    def __init__(self, api: ApiServer, cfg: OdhConfig):
        self.api = api
        self.cfg = cfg

    def handle(self, op: str, old: Optional[KubeObject], obj: KubeObject) -> None:
        if op != "UPDATE" or old is None:
            return
        self._validate_mlflow_annotation_removal(old, obj)

    def _validate_mlflow_annotation_removal(
        self, old: KubeObject, obj: KubeObject
    ) -> None:
        """Removing mlflow-instance while running would leave MLFLOW_* env
        vars outliving the RoleBinding
        (validateMLflowAnnotationRemoval :79-100)."""
        had = old.metadata.annotations.get(C.ANNOTATION_MLFLOW_INSTANCE, "")
        has = obj.metadata.annotations.get(C.ANNOTATION_MLFLOW_INSTANCE, "")
        if not had or has:
            return
        stopped = C.STOP_ANNOTATION in obj.metadata.annotations
        if not stopped:
            raise AdmissionDenied(
                "cannot remove the mlflow-instance annotation while the "
                "notebook is running; stop the notebook first"
            )

    def hook(self) -> AdmissionHook:
        return AdmissionHook(
            kinds=("Notebook",),
            handler=self.handle,
            operations=("UPDATE",),
            mutating=False,
            name="validate-notebook-v1",
        )
