"""Manager entrypoint: `python -m kubeflow_tpu.main`.

The analog of both reference binaries (notebook-controller/main.go:58-148 and
odh-notebook-controller/main.go:141-347) collapsed into ONE manager with all
controllers + webhooks — removing the cross-process webhook/controller race
the reference papers over with the lock annotation (SURVEY.md §7 hard
parts).  Flags mirror the reference; env vars are the config surface
(utils/config.py).

Backends:
- real cluster: `--kubeconfig PATH` or `--in-cluster` builds a KubeClient
  speaking the Kubernetes REST API (watches, optimistic concurrency, status
  subresource), starts informers for every watched kind, serves the
  admission webhooks over HTTPS (--webhook-port/--cert-dir, odh
  main.go:285-311), and optionally gates on Lease leader election
  (--enable-leader-election, main.go:91-93).
- standalone: the in-memory API server with the fake data plane — the
  `--demo` mode used by examples/ and the load test.
The healthz/readyz/metrics HTTP side is real either way.
"""

from __future__ import annotations

import argparse
import http.server
import json
import logging
import os
import socket
import threading
from typing import Optional

from .api.types import Notebook, TPUSpec
from .core.culling_controller import setup_culling
from .core.metrics import NotebookMetrics
from .core.notebook_controller import setup_core_controllers
from .kube import ApiServer, FakeCluster, LeaderElector, Manager
from .utils.clock import Clock
from .utils.config import CoreConfig, OdhConfig


PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"


def negotiate_metrics_format(accept: str) -> bool:
    """True when the Accept header asks for OpenMetrics.  Proper media-range
    parsing with q-values: Prometheus sends
    `application/openmetrics-text;version=1.0.0;q=0.5,text/plain;q=0.3`
    and expects the exemplar-capable format to win; a plain curl (Accept
    `*/*` or absent) gets the classic text format."""
    q_om, q_plain = 0.0, 0.0
    for part in (accept or "").split(","):
        bits = part.split(";")
        media = bits[0].strip().lower()
        q = 1.0
        for param in bits[1:]:
            param = param.strip()
            if param.startswith("q="):
                try:
                    q = float(param[2:])
                except ValueError:
                    q = 0.0
        if media == "application/openmetrics-text":
            q_om = max(q_om, q)
        elif media in ("text/plain", "text/*", "*/*"):
            q_plain = max(q_plain, q)
    return q_om > 0 and q_om >= q_plain


class HealthAndMetricsHandler(http.server.BaseHTTPRequestHandler):
    """Probe + scrape + debug surface (main.go:125-133, metrics on :8080):

    - /healthz  — liveness: process up and the manager not stopped;
    - /readyz   — readiness: additionally the manager STARTED, its
      watch/informer caches synced, and (when leader election is on) this
      replica actually leading — a follower pod is alive but must not
      receive traffic;
    - /metrics  — content-negotiated: OpenMetrics (exemplars + `# EOF`)
      when the scraper asks for it, Prometheus text 0.0.4 otherwise;
    - /debug/reconciles, /debug/traces/<id>, /debug/workqueue — the flight
      recorder and workqueue introspection, loopback-only (same rationale
      as /state: diagnosis happens via `kubectl exec`/port-forward, and
      trace payloads carry object names and error strings that must not be
      scrapeable from off-pod);
    - /debug/alerts — the SLO engine's burn-alert surface: objective
      stats, firing alerts (each annotated with the diagnosis engine's
      one-line verdict for its exemplar), and the bounded fire/resolve
      history (each alert carrying an exemplar trace_id resolvable at
      /debug/traces);
    - /debug/explain — ?object=<ns>/<name> returns the diagnosis
      engine's ranked causal chain for one notebook, every link citing
      its evidence (trace_id, event, metric sample);
    - /debug/changepoints — the fleet change-point detector's annotated
      findings over the TSDB's watched series, each correlated against
      the discrete event timeline (fault windows, promotions, shard
      epochs, warm-pool resizes, straggler onsets, noisy tenants);
    - /debug/profile — the continuous profiler's aggregated collapsed
      stacks (JSON, or flamegraph text with ?format=collapsed);
    - /debug/fleet — per-namespace / per-shape health rollup off the
      informer cache's incremental census (O(series) per request) plus
      the SLO verdicts;
    - /debug/criticalpath — the lifecycle ledger's fleet-wide stage
      ranking (mean/p99 contribution per stage to event->ready) and its
      conservation check (attributed sum vs measured wall time);
    - /debug/timeline — the in-process TSDB: ?series=<name>&tier=raw|10s|
      60s returns one downsampled series; ?dump=1 the full multi-tier
      capture (what ops/diagnose bundles); without either the inventory;
    - /debug/tenants — the tenant metering ledger: per-tenant chip-second
      buckets and control-plane attribution, top-K consumers, fairness
      verdicts (noisy-neighbor flags), and the chip-second conservation
      gate;
    - /state    — in-memory store dump (includes Secret data; additionally
      gated on --expose-state)."""

    manager: Optional[Manager] = None
    metrics: Optional[NotebookMetrics] = None
    elector = None  # LeaderElector when --enable-leader-election
    expose_state: bool = False  # /state dumps Secrets — loopback/debug only

    def _loopback_only(self) -> bool:
        """True when the request may see debug payloads: the TCP peer is a
        loopback address (pod-local exec / port-forward lands here)."""
        host = self.client_address[0]
        return host in ("127.0.0.1", "::1", "::ffff:127.0.0.1")

    def _not_ready(self) -> str:
        """Empty string when ready to serve traffic, else the reason."""
        mgr = self.manager
        if mgr is None:
            return "no manager"
        if mgr.stopped:
            return "manager stopped"
        if not mgr.started:
            return "manager not started"
        if not mgr.caches_synced():
            return "caches not synced"
        if self.elector is not None and not self.elector.is_leader:
            return "not the leader"
        return ""

    def do_GET(self):  # noqa: N802  (stdlib API)
        import urllib.parse

        url = urllib.parse.urlsplit(self.path)
        path = url.path
        if path == "/healthz":
            # liveness only: a stopped manager (TLS-profile restart, fatal
            # error) must fail so the Deployment actually restarts the pod,
            # but an unsynced follower is perfectly alive
            if self.manager is not None and self.manager.stopped:
                self._respond(503, "manager stopped", "text/plain")
            else:
                self._respond(200, "ok", "text/plain")
        elif path == "/readyz":
            reason = self._not_ready()
            if reason:
                self._respond(503, f"not ready: {reason}", "text/plain")
            else:
                self._respond(200, "ok", "text/plain")
        elif path == "/metrics":
            # scrape() recomputes list-derived gauges and folds in the
            # manager's reconcile/workqueue registry; a bare render() would
            # serve stale gauges and miss the controller_runtime_* families
            openmetrics = negotiate_metrics_format(
                self.headers.get("Accept", ""))
            if self.metrics is not None:
                body = self.metrics.scrape(openmetrics=openmetrics)
            else:
                body = "# EOF\n" if openmetrics else ""
            self._respond(200, body,
                          OPENMETRICS_CONTENT_TYPE if openmetrics
                          else PROMETHEUS_CONTENT_TYPE)
        elif path.startswith("/debug/"):
            if not self._loopback_only():
                self._respond(403, "debug endpoints are loopback-only",
                              "text/plain")
                return
            self._serve_debug(path, urllib.parse.parse_qs(url.query))
        elif path == "/state" and self.expose_state:
            if not self._loopback_only():
                self._respond(403, "/state is loopback-only", "text/plain")
                return
            api = self.manager.api if self.manager else None
            # the real-cluster KubeClient has no dump(); only the in-memory
            # store can be exported
            dump = getattr(api, "dump", None)
            body = json.dumps(dump() if callable(dump) else {}, default=str)
            self._respond(200, body, "application/json")
        else:
            self._respond(404, "not found", "text/plain")

    def _serve_debug(self, path: str, query: dict) -> None:
        mgr = self.manager
        if mgr is None:
            self._respond(503, "no manager", "text/plain")
            return
        recorder = mgr.flight_recorder
        if path == "/debug/reconciles":
            object_key = (query.get("object") or [None])[0]
            body = recorder.snapshot(object_key=object_key)
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path.startswith("/debug/traces/"):
            trace_id = path[len("/debug/traces/"):]
            trace = recorder.trace(trace_id)
            if trace is None:
                self._respond(404, json.dumps(
                    {"error": f"trace {trace_id!r} not recorded "
                     "(unknown, or evicted from the bounded trace store)"}),
                    "application/json")
            else:
                self._respond(200, json.dumps(trace, default=str),
                              "application/json")
        elif path == "/debug/workqueue":
            self._respond(200, json.dumps(mgr.workqueue_debug(), default=str),
                          "application/json")
        elif path == "/debug/alerts":
            engine = getattr(mgr, "slo_engine", None)
            body = engine.snapshot() if engine is not None else {
                "enabled": False,
                "error": "no SLO engine attached to this manager"}
            diagnosis = getattr(mgr, "diagnosis", None)
            if engine is not None and diagnosis is not None:
                # each firing alert gains a one-line `diagnosis` verdict
                # for its latched exemplar ("" when no verdict, never an
                # error)
                body = diagnosis.annotate_alerts(body)
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path == "/debug/explain":
            diagnosis = getattr(mgr, "diagnosis", None)
            object_key = (query.get("object") or [""])[0]
            if diagnosis is None:
                body = {"enabled": False,
                        "error": "no diagnosis engine attached to this "
                                 "manager"}
            elif "/" not in object_key:
                body = {"error": "pass ?object=<namespace>/<name>",
                        "object": object_key, "verdict": ""}
            else:
                ns, _, name = object_key.partition("/")
                body = diagnosis.explain(ns, name)
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path == "/debug/changepoints":
            diagnosis = getattr(mgr, "diagnosis", None)
            if diagnosis is None:
                body = {"enabled": False,
                        "error": "no diagnosis engine attached to this "
                                 "manager"}
            else:
                # evaluate on read so an operator polling between scrapes
                # sees shifts in the latest samples, not the last scrape's
                diagnosis.evaluate()
                body = diagnosis.snapshot()
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path == "/debug/profile":
            profiler = getattr(mgr, "profiler", None)
            fmt = (query.get("format") or ["json"])[0]
            if profiler is None:
                if fmt == "collapsed":
                    self._respond(200, "", "text/plain")
                else:
                    self._respond(200, json.dumps(
                        {"enabled": False, "samples_total": 0, "stacks": [],
                         "hint": "set ENABLE_CONTINUOUS_PROFILER=true"}),
                        "application/json")
            elif fmt == "collapsed":
                self._respond(200, profiler.collapsed(), "text/plain")
            else:
                self._respond(200, json.dumps(profiler.snapshot(),
                                              default=str),
                              "application/json")
        elif path == "/debug/fleet":
            if self.metrics is None:
                self._respond(503, "no metrics", "text/plain")
                return
            self._respond(200, json.dumps(self.metrics.fleet_snapshot(),
                                          default=str),
                          "application/json")
        elif path == "/debug/criticalpath":
            ledger = getattr(mgr, "lifecycle", None)
            body = ledger.snapshot() if ledger is not None else {
                "enabled": False,
                "error": "no lifecycle ledger attached to this manager"}
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path == "/debug/tenants":
            metering = getattr(mgr, "metering", None)
            body = metering.snapshot() if metering is not None else {
                "enabled": False,
                "error": "no tenant metering ledger attached to this "
                         "manager"}
            if self.metrics is not None:
                # per-tenant admission-gate view (queue depth, quota
                # usage, recent preemptions) — same source as the
                # tenancy section of /debug/fleet
                body["tenancy"] = self.metrics.tenancy_snapshot()
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        elif path == "/debug/timeline":
            store = getattr(mgr, "tsdb", None)
            if store is None:
                body = {"enabled": False,
                        "error": "no time-series store attached"}
            else:
                series = (query.get("series") or [None])[0]
                tier = (query.get("tier") or ["raw"])[0]
                dump = (query.get("dump") or [""])[0]
                if series:
                    body = store.query(series, tier=tier)
                elif dump in ("1", "true"):
                    body = store.dump()  # full capture, for bundles
                else:
                    body = store.snapshot()
            self._respond(200, json.dumps(body, default=str),
                          "application/json")
        else:
            self._respond(404, "not found", "text/plain")

    def _respond(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # quiet
        pass


def serve_http(port: int, manager: Manager, metrics: NotebookMetrics,
               expose_state: bool = False, elector=None):
    """Health + metrics on all interfaces (the kubelet probes the pod IP and
    Prometheus scrapes :8080 from outside the pod, as in the reference).
    The /debug/* introspection endpoints answer only loopback clients, and
    the /state debug dump — which includes Secret data — additionally needs
    `expose_state` (--expose-state, standalone/demo use)."""
    handler = type(
        "Handler",
        (HealthAndMetricsHandler,),
        {"manager": manager, "metrics": metrics, "elector": elector,
         "expose_state": expose_state},
    )
    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def build_manager(
    core_cfg: Optional[CoreConfig] = None,
    odh_cfg: Optional[OdhConfig] = None,
    with_fake_cluster: bool = True,
    api=None,
):
    """Wire the full stack; returns (manager, api, cluster, metrics).

    `api` may be a KubeClient (real cluster) or None (in-memory standalone);
    both expose the same read/write/watch surface."""
    real_cluster = api is not None
    core_cfg = core_cfg or CoreConfig.from_env()
    if api is None:
        api = ApiServer(history_size=core_cfg.watch_history_size)
    cluster = FakeCluster(api) if (with_fake_cluster and not real_cluster) else None
    mgr = Manager(api)
    odh_cfg = odh_cfg or OdhConfig.from_env()
    metrics = NotebookMetrics(api, manager=mgr)
    # fleet SLO engine: declared objectives (SLO_* knobs) over the metric
    # streams, evaluated at every scrape; alerts serve at /debug/alerts
    from .utils.slo import SLOEngine, default_objectives

    engine = SLOEngine(
        default_objectives(core_cfg),
        registries=[metrics.registry, mgr.metrics_registry],
        clock=mgr.clock,
        windows=(core_cfg.slo_short_window_s, core_cfg.slo_long_window_s),
        burn_threshold=core_cfg.slo_burn_alert_threshold,
        recorder=mgr.flight_recorder)
    metrics.attach_slo(engine)
    mgr.slo_engine = engine
    # data-plane rollup: per-worker telemetry annotations -> per-notebook
    # series + straggler detection, evaluated at every scrape (before the
    # SLO engine, which burns against its verdict counters) and surfaced
    # in /debug/fleet and the diagnose bundle
    from .core.telemetry import WorkerTelemetryAggregator
    from .kube import EventRecorder

    aggregator = WorkerTelemetryAggregator(
        api, metrics.registry, mgr.clock, cache=mgr.cache,
        recorder=EventRecorder(api, "dataplane-telemetry"),
        straggler_ratio=core_cfg.dataplane_straggler_ratio,
        min_workers=core_cfg.dataplane_straggler_min_workers,
        mfu_target=core_cfg.dataplane_mfu_target)
    metrics.attach_dataplane(aggregator)
    mgr.telemetry_aggregator = aggregator
    # lifecycle stage ledger + in-process TSDB: the manager feeds the
    # ledger with every finished attempt (critical-path attribution at
    # /debug/criticalpath), and each metrics scrape appends one TSDB
    # sample (p99-vs-time history at /debug/timeline, captured into the
    # ops/diagnose bundle)
    from .utils.lifecycle import LifecycleLedger
    from .utils.tsdb import TimeSeriesStore

    ledger = LifecycleLedger(
        registry=metrics.registry,
        max_notebooks=core_cfg.lifecycle_max_notebooks,
        samples_per_stage=core_cfg.lifecycle_samples_per_stage,
        tolerance=core_cfg.lifecycle_tolerance)
    mgr.lifecycle = ledger
    metrics.attach_lifecycle(ledger)
    tsdb = TimeSeriesStore(
        raw_capacity=core_cfg.tsdb_raw_capacity,
        tier10_capacity=core_cfg.tsdb_tier10_capacity,
        tier60_capacity=core_cfg.tsdb_tier60_capacity,
        max_series=core_cfg.tsdb_max_series)
    mgr.tsdb = tsdb
    metrics.attach_tsdb(tsdb, clock=mgr.clock)
    # tenant metering ledger: chip-second accrual + control-plane
    # attribution + noisy-neighbor detection, fed by the manager's
    # dispatch/attempt hooks and each metrics scrape; serves at
    # /debug/tenants and rides in /debug/fleet + the diagnose bundle
    from .utils.metering import TenantMeteringLedger

    metering = TenantMeteringLedger(
        mgr.clock, registry=metrics.registry,
        recorder=EventRecorder(api, "tenant-metering"),
        max_tenants=core_cfg.metering_max_tenants,
        max_notebooks=core_cfg.metering_max_notebooks,
        tolerance=core_cfg.metering_tolerance,
        fairshare_factor=core_cfg.tenant_fairshare_factor,
        top_k=core_cfg.tenant_top_k,
        slo_engine=engine)
    mgr.metering = metering
    metrics.attach_metering(metering)
    # causal diagnosis engine: fuses every stream above into per-notebook
    # verdicts (/debug/explain) and TSDB change-point findings
    # (/debug/changepoints); evaluated once per scrape after the TSDB
    # sample lands
    from .utils.diagnosis import DiagnosisEngine

    diagnosis = DiagnosisEngine(
        mgr.clock, registry=metrics.registry,
        recorder=mgr.flight_recorder, lifecycle=ledger, slo_engine=engine,
        metering=metering, tsdb=tsdb, dataplane=aggregator, api=api)
    mgr.diagnosis = diagnosis
    metrics.attach_diagnosis(diagnosis)
    if core_cfg.enable_continuous_profiler:
        # always-on (controller, phase) CPU attribution; self-overhead is
        # exported so "can it stay on" is a gauge (/debug/profile)
        from .utils.profiler import ContinuousProfiler

        mgr.profiler = ContinuousProfiler(
            registry=metrics.registry,
            interval_s=max(core_cfg.profiler_interval_ms, 1.0) / 1000.0)
        mgr.profiler.start()
    # the fake cluster doubles as the warm-pool provisioner (cloud-provider
    # hook): ENABLE_SLICE_SCHEDULER turns capacity up/down through it
    setup_core_controllers(mgr, core_cfg, metrics, provisioner=cluster)
    setup_culling(mgr, core_cfg, metrics=metrics)
    from .odh.controller import setup_odh_controllers
    from .odh.tls_profile import SecurityProfileWatcher, fetch_apiserver_tls_profile

    setup_odh_controllers(mgr, odh_cfg)

    # TLS posture: resolve at startup, restart-on-change (odh main.go:178-214,
    # 324-340); in standalone mode the "restart" is a manager stop — the
    # supervising process (Deployment) brings it back with the new profile
    profile = fetch_apiserver_tls_profile(api)
    logging.getLogger("kubeflow_tpu").info(
        "TLS profile: %s (min %s)", profile.source, profile.min_version
    )
    watcher = SecurityProfileWatcher(
        api,
        profile,
        on_change=lambda old, new: (
            logging.getLogger("kubeflow_tpu").warning(
                "TLS profile changed (%s -> %s); initiating graceful restart",
                old.min_version, new.min_version,
            ),
            mgr.stop(),
        ),
    )
    watcher.setup(mgr)
    return mgr, api, cluster, metrics


def build_sharded_fleet(
    core_cfg: Optional[CoreConfig] = None,
    count: Optional[int] = None,
    with_fake_cluster: bool = True,
    clock=None,
):
    """Active-active standalone control plane (SHARD_COUNT > 1): `count`
    ShardedReplicas over one in-memory ApiServer, each running the full
    core controller set against its fenced client (kube/shard.py), so a
    deposed shard's late writes are rejected with a stale epoch instead
    of racing the new owner.  Returns (fleet, api, cluster, metrics);
    per-shard health lands in /debug/fleet via metrics.attach_shard()."""
    core_cfg = core_cfg or CoreConfig.from_env()
    count = count or core_cfg.shard_count
    api = ApiServer(history_size=core_cfg.watch_history_size)
    cluster = FakeCluster(api) if with_fake_cluster else None
    metrics = NotebookMetrics(api)
    # ONE lifecycle ledger + TSDB across every replica: a notebook's
    # attempts land on one timeline no matter which shard ran them, so a
    # manager-id change between consecutive attempts reads as
    # handoff/adoption wait (utils/lifecycle.py)
    from .utils.lifecycle import LifecycleLedger
    from .utils.tsdb import TimeSeriesStore

    ledger = LifecycleLedger(
        registry=metrics.registry,
        max_notebooks=core_cfg.lifecycle_max_notebooks,
        samples_per_stage=core_cfg.lifecycle_samples_per_stage,
        tolerance=core_cfg.lifecycle_tolerance)
    metrics.attach_lifecycle(ledger)
    tsdb = TimeSeriesStore(
        raw_capacity=core_cfg.tsdb_raw_capacity,
        tier10_capacity=core_cfg.tsdb_tier10_capacity,
        tier60_capacity=core_cfg.tsdb_tier60_capacity,
        max_series=core_cfg.tsdb_max_series)
    # clock=None falls back to the first replica manager's clock at feed
    # time (setup_core_controllers attaches it to `metrics`)
    metrics.attach_tsdb(tsdb, clock=clock)
    # ONE metering ledger across every replica (same sharing rationale as
    # the lifecycle ledger): tenant attribution survives shard handoffs
    from .kube import EventRecorder
    from .utils.metering import TenantMeteringLedger

    metering = TenantMeteringLedger(
        clock, registry=metrics.registry,
        recorder=EventRecorder(api, "tenant-metering"),
        max_tenants=core_cfg.metering_max_tenants,
        max_notebooks=core_cfg.metering_max_notebooks,
        tolerance=core_cfg.metering_tolerance,
        fairshare_factor=core_cfg.tenant_fairshare_factor,
        top_k=core_cfg.tenant_top_k)
    metrics.attach_metering(metering)
    # ONE diagnosis engine across every replica (same sharing rationale):
    # change points and verdicts read the fleet-wide fused timeline
    from .utils.diagnosis import DiagnosisEngine

    diagnosis = DiagnosisEngine(
        clock, registry=metrics.registry, lifecycle=ledger,
        metering=metering, tsdb=tsdb, api=api)
    metrics.attach_diagnosis(diagnosis)

    def controllers(replica):
        # replica.manager.api is the FencedApi: every controller write is
        # epoch-checked against the committed shard map before it lands
        replica.manager.lifecycle = ledger
        replica.manager.manager_id = replica.shard_id
        replica.manager.tsdb = tsdb
        replica.manager.metering = metering
        replica.manager.diagnosis = diagnosis
        if metering.clock is None:
            # clock=None build: the first replica's manager clock drives
            # the accrual timestamps (same fallback as the TSDB feed)
            metering.clock = replica.manager.clock
        if diagnosis.clock is None:
            diagnosis.clock = replica.manager.clock
        if diagnosis.recorder is None:
            # the first replica's flight recorder anchors trace->object
            # resolution for alert annotation (each replica records its
            # own attempts; explain() still works per replica via the
            # shared ledger)
            diagnosis.recorder = replica.manager.flight_recorder
        setup_core_controllers(replica.manager, core_cfg, metrics,
                               provisioner=cluster)
        setup_culling(replica.manager, core_cfg, metrics=metrics)

    from .kube import ShardedFleet

    fleet = ShardedFleet(
        api, count=count, clock=clock, controller_factory=controllers,
        lease_duration_s=core_cfg.shard_lease_duration_s)
    metrics.attach_shard(fleet)
    # membership epochs feed the diagnosis engine's discrete timeline
    diagnosis.fleet = fleet
    return fleet, api, cluster, metrics


def build_real_backend(args):
    """KubeClient from --kubeconfig/--in-cluster with qps/burst knobs
    (notebook-controller/main.go:71-89)."""
    from .kube.client import KubeClient, RestConfig

    if args.kubeconfig:
        cfg = RestConfig.from_kubeconfig(args.kubeconfig)
    else:
        cfg = RestConfig.in_cluster()
    cfg.qps = args.qps
    cfg.burst = args.burst
    return KubeClient(cfg)


def start_webhook_server(api, args):
    """Serve collected AdmissionHooks over HTTPS (odh main.go:285-311).
    Certs come from --cert-dir (tls.crt/tls.key, the serving-cert layout);
    absent certs are minted dev-style like envtest."""
    hooks = getattr(api, "admission_hooks", None)
    if not hooks or args.webhook_port < 0:
        return None
    from .odh.webhook_server import AdmissionReviewServer

    cert = os.path.join(args.cert_dir, "tls.crt") if args.cert_dir else ""
    if cert and os.path.exists(cert):
        server = AdmissionReviewServer(
            hooks, cert_file=cert,
            key_file=os.path.join(args.cert_dir, "tls.key"),
            host="0.0.0.0", port=args.webhook_port)
    else:
        from .kube.certs import mint_serving_cert

        logging.warning("no serving certs in %r; minting a self-signed pair",
                        args.cert_dir)
        server = AdmissionReviewServer(
            hooks, bundle=mint_serving_cert(),
            host="0.0.0.0", port=args.webhook_port)
    server.start()
    logging.info("webhook server on %s", server.url)
    return server


def main(argv: Optional[list[str]] = None) -> int:
    parser = argparse.ArgumentParser(description="kubeflow-tpu notebook controller")
    parser.add_argument("--metrics-addr", type=int, default=8080,
                        help="port for /metrics + health endpoints")
    parser.add_argument("--kubeconfig", default="",
                        help="path to a kubeconfig; reconcile that cluster")
    parser.add_argument("--in-cluster", action="store_true",
                        help="use the ServiceAccount token mount")
    parser.add_argument("--qps", type=float, default=0.0,
                        help="client-side request rate limit (0 = unlimited)")
    parser.add_argument("--burst", type=int, default=0,
                        help="client-side burst size")
    parser.add_argument("--webhook-port", type=int, default=9443,
                        help="admission webhook HTTPS port (-1 = disabled)")
    parser.add_argument("--cert-dir", default="",
                        help="dir with tls.crt/tls.key for the webhook server")
    parser.add_argument("--enable-leader-election", action="store_true",
                        help="gate reconciling on a coordination.k8s.io Lease")
    parser.add_argument("--watch-namespace", default="",
                        help="scope informers to one namespace instead of "
                             "cluster-wide list/watch")
    parser.add_argument("--leader-election-namespace", default="",
                        help="namespace for the election Lease")
    parser.add_argument("--demo", action="store_true",
                        help="create a sample TPU notebook and print state")
    parser.add_argument("--demo-topology", default="4x4")
    parser.add_argument("--demo-accelerator", default="v5e")
    parser.add_argument("--run-seconds", type=float, default=0.0,
                        help="exit after N seconds (0 = run forever)")
    parser.add_argument("--expose-state", action="store_true",
                        help="serve the /state object-store dump (includes "
                             "Secret data; standalone/debug only)")
    parser.add_argument("--serve-api", type=int, default=-1, metavar="PORT",
                        help="standalone mode: serve the in-memory store "
                             "over the Kubernetes REST wire protocol on "
                             "PORT (0 = ephemeral; used by the conformance "
                             "profile's black-box runner)")
    parser.add_argument("--fake-tpu-nodes", type=int, default=0,
                        metavar="N",
                        help="standalone mode: seed N fake v5e TPU nodes "
                             "(GKE labels + google.com/tpu allocatable) so "
                             "TPU workloads actually schedule — the "
                             "in-memory analog of the kind lane's fake "
                             "device plugin (tpu/device_plugin.py)")
    parser.add_argument("--audit-log", default="", metavar="PATH",
                        help="with --serve-api: append a JSONL request "
                             "trail (ts/verb/path/code) — the analog of "
                             "envtest's apiserver audit-log debug knob")
    parser.add_argument("--debug-log", action="store_true")
    parser.add_argument("--log-format", choices=("text", "json"),
                        default="text",
                        help="json: structured one-object-per-line logs "
                             "with trace_id/span_id correlation "
                             "(utils/logging.py)")
    args = parser.parse_args(argv)

    level = logging.DEBUG if args.debug_log else logging.INFO
    if args.log_format == "json":
        from .utils.logging import setup_structured_logging

        setup_structured_logging(level)
    else:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    # webhook spans leave the process when OTEL_EXPORTER_OTLP_ENDPOINT is
    # set (odh main wires real OTel the same way; default stays noop)
    from .utils.tracing import setup_exporter_from_env

    otlp_exporter = setup_exporter_from_env()
    real = bool(args.kubeconfig or args.in_cluster)
    backend = build_real_backend(args) if real else None
    mgr, api, cluster, metrics = build_manager(api=backend)
    if cluster is not None:
        cluster.add_node("cpu-node", allocatable={"cpu": "64", "memory": "256Gi"})
        if args.fake_tpu_nodes > 0:
            # v5e full hosts: 8 chips each (a 2x4 slice is one host)
            cluster.add_tpu_slice_nodes(
                "tpu-v5-lite-podslice", "2x4",
                num_hosts=args.fake_tpu_nodes, chips_per_host=8)
    if args.expose_state and real:
        logging.warning("--expose-state ignored with a real cluster backend "
                        "(the KubeClient has no store to dump; /state stays 404)")
    # the elector is built before the HTTP server so /readyz can gate on
    # leadership (a follower is alive but not ready); it starts later
    elector: Optional[LeaderElector] = None
    if args.enable_leader_election:
        from .utils.config import OdhConfig as _Odh

        elector = LeaderElector(
            api,
            lease_name="kubeflow-tpu-notebook-controller",
            namespace=args.leader_election_namespace
            or _Odh.from_env().controller_namespace,
            identity=f"{socket.gethostname()}-{os.getpid()}",
        )
    server = serve_http(args.metrics_addr, mgr, metrics,
                        expose_state=args.expose_state and not real,
                        elector=elector)
    webhook_server = start_webhook_server(api, args) if real else None
    wire_server = None
    if args.serve_api >= 0 and real:
        logging.warning("--serve-api ignored with a real cluster backend "
                        "(there is no in-memory store to serve)")
    if args.serve_api >= 0 and not real:
        from .api.types import convert_notebook_dict
        from .kube.wire import KubeApiWireServer

        # seed the Notebook CRD object so /openapi serves its per-field
        # models (the wire server reads field schemas off stored CRDs,
        # exactly like a real apiserver)
        from .deploy.manifests import notebook_crd
        from .kube.meta import KubeObject

        if api.try_get("CustomResourceDefinition", "",
                       "notebooks.kubeflow.org") is None:
            api.create(KubeObject.from_dict(
                notebook_crd(conversion_webhook=False)))

        wire_server = KubeApiWireServer(
            api, host="127.0.0.1", port=args.serve_api,
            converter=convert_notebook_dict,
            audit_log=args.audit_log or None).start()
        logging.info("wire apiserver on %s", wire_server.url)
        print(f"WIRE_API={wire_server.url}", flush=True)

    def start_reconciling():
        if real:
            api.start_informers(mgr.watched_kinds(),
                                namespace=args.watch_namespace or None)
        mgr.start()
        logging.info("manager started; metrics on :%d", args.metrics_addr)

    if elector is not None:
        elector.start_background(
            on_started=start_reconciling,
            on_stopped=mgr.stop,  # lost lease -> exit 1 -> pod restart
        )
        logging.info("leader election enabled; waiting for lease")
    else:
        start_reconciling()

    if args.demo and cluster is not None:
        tpu = TPUSpec(args.demo_accelerator, args.demo_topology)
        shape = tpu.validate()
        cluster.add_tpu_slice_nodes(
            shape.accelerator.gke_label, shape.topology,
            shape.num_hosts, shape.chips_per_host,
        )
        nb = Notebook.new("demo", "default", tpu=tpu)
        api.create(nb.obj)
        wall = Clock()  # real polling wait on the threaded manager
        deadline = wall.now() + 10
        while wall.now() < deadline:
            live = api.try_get("Notebook", "default", "demo")
            if live and live.body.get("status", {}).get("sliceHealth") == "Healthy":
                break
            wall.sleep(0.05)
        live = api.get("Notebook", "default", "demo")
        # play the workers' training loops: publish one telemetry summary
        # per demo worker (real TelemetryAgent -> pod annotation), so the
        # /debug/fleet data-plane rollup and the diagnose bundle carry a
        # live slice in the CI smokes
        from .models.configs import LLAMA2_350M

        cluster.stamp_worker_telemetry(
            "default", "demo", step_time_s=0.5, config=LLAMA2_350M,
            batch=8, seq_len=2048, num_chips=shape.chips // shape.num_hosts,
            accelerator=args.demo_accelerator, now=wall.now())
        print(json.dumps(live.body.get("status", {}), indent=2))

    exit_code = 0
    try:
        # exits when run_seconds elapses OR the manager stops itself (e.g.
        # TLS-profile change) — a non-zero exit makes the Deployment restart
        # the pod with the new posture
        timeout = args.run_seconds if args.run_seconds > 0 else None
        stopped = mgr.wait_until_stopped(timeout)
        if stopped and timeout is None:
            logging.warning("manager stopped itself; exiting for restart")
            exit_code = 1
    except KeyboardInterrupt:
        pass
    finally:
        if elector is not None:
            elector.stop()
        if mgr.profiler is not None:
            mgr.profiler.stop()
        mgr.stop()
        if wire_server is not None:
            wire_server.stop()
        if webhook_server is not None:
            webhook_server.stop()
        if real:
            api.stop_informers()
        if otlp_exporter is not None:
            otlp_exporter.shutdown()
        server.shutdown()
    return exit_code


if __name__ == "__main__":
    raise SystemExit(main())
