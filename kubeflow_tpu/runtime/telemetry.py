"""Worker-side data-plane telemetry: the TelemetryAgent.

PRs 2/3/10 made the *control* plane deeply observable; the JAX runtime
stayed a black box — `runtime/metrics.py` had a bare StepTimer whose
numbers never left the worker.  The TelemetryAgent is the data-plane
analog of the controller's span/metric spine:

  - **step samples**: the train/generate loop calls `step_boundary()`
    once per synced step (or `record_step(dt)` with an explicit
    duration).  Timing reads the injected `time_fn` — monotonic seconds,
    `time.perf_counter` by default — so tests drive the agent off a
    FakeClock and assert exact samples; the agent itself never reads a
    wall clock (analyzer clock discipline holds with zero allowlist
    entries).
  - **per-phase attribution**: `with agent.scope("fwd"): ...` accumulates
    named sub-durations (fwd/bwd/opt by convention) that attach to the
    NEXT recorded step — the worker-side analog of the controller's
    render/apply/status phase spans.
  - **roofline attribution**: every sample carries MFU and roofline
    fraction computed through `runtime.roofline` — the SAME definition
    bench.py reports, so a worker's published MFU and the headline
    bench number can never disagree for the same (config, step time).
  - **bounded JSONL ring**: samples spool to an in-memory ring
    (`ring_size` newest kept) and optionally to a JSONL file with the
    same bound (`spool_to`) — the flight-recorder idea, worker-side.
  - **publication**: `summary()` is the rolling contract the control
    plane reads; `maybe_publish()` rate-limits pushes of that summary
    through an injected `publish_fn` (on a real worker: patch the pod's
    `notebooks.kubeflow.org/telemetry` annotation via the downward API
    sidecar; in tests: FakeCluster.stamp_worker_telemetry plays this).

The exported metric families are the existing notebook_training_* set
(register_step_metrics) — the StepTimer now routes through an agent, so
the histogram and the agent's samples are one stream by construction.
`jax` stays a lazy import (HBM gauge only): the control plane, the drift
check, and the fast test lane import this module jax-free.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.metrics import Histogram, Registry
from . import roofline
from .metrics import hbm_usage_bytes, register_step_metrics

# pod annotation the agent's summaries publish under and the control
# plane's WorkerTelemetryAggregator reads (core/telemetry.py keeps a
# matching literal — it must not import the runtime package)
TELEMETRY_ANNOTATION = "notebooks.kubeflow.org/telemetry"
SUMMARY_VERSION = 1


class JsonlRing:
    """Append-only JSONL spool bounded to the newest `max_records` lines.

    Appends are O(1); when the file grows past 2x the bound it is
    compacted in place (write temp, atomic rename) so the spool a crashed
    worker leaves behind is always parseable and never unbounded."""

    def __init__(self, path: str, max_records: int = 512) -> None:
        self.path = path
        self.max_records = max(1, int(max_records))
        self._since_compact = 0

    def append(self, record: dict) -> None:
        line = json.dumps(record, sort_keys=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
        self._since_compact += 1
        if self._since_compact >= self.max_records:
            self._compact()

    def _compact(self) -> None:
        lines = self.read_lines()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            f.writelines(lines)
        os.replace(tmp, self.path)
        self._since_compact = 0

    def read_lines(self) -> list[str]:
        try:
            with open(self.path) as f:
                lines = f.readlines()
        except OSError:
            return []
        return lines[-self.max_records:]

    def read(self) -> list[dict]:
        return [json.loads(ln) for ln in self.read_lines() if ln.strip()]


@dataclass
class TelemetryAgent:
    """Rolling step telemetry for one worker; see module docstring.

    `config` is a models.configs.TransformerConfig (duck-typed: only
    `flops_per_token`/`num_params`/dtype fields are read, so the control
    plane can pass any object with those).  Pass `flops_per_token`
    explicitly to skip the config entirely (FakeCluster's data-plane
    stamping does)."""

    config: Optional[object] = None
    batch: int = 1
    seq_len: int = 1
    num_chips: int = 1
    accelerator: str = "v5e"
    mode: str = "train"                  # train | decode
    worker: str = ""                     # pod name (summary attribution)
    window: int = 20                     # rolling-stat sample count
    ring_size: int = 512                 # TELEMETRY_RING_SIZE
    flops_per_token: float = 0.0         # override: config-free callers
    registry: Optional[Registry] = None
    time_fn: Callable[[], float] = time.perf_counter
    hbm_fn: Optional[Callable[[], dict]] = None  # None = jax (lazy)
    publish_fn: Optional[Callable[[dict], None]] = None
    publish_interval_s: float = 30.0     # TELEMETRY_PUBLISH_INTERVAL_S

    _durations: deque = field(default_factory=deque, repr=False)
    _ring: deque = field(default_factory=deque, repr=False)

    def __post_init__(self) -> None:
        if self.registry is None:
            self.registry = Registry()
        m = register_step_metrics(self.registry)
        self._step_hist: Histogram = m["step_duration"]
        # derived gauges recompute at collect()/render() time so a scrape
        # is always current without the loop pushing anything
        m["tokens_per_second"].set_function(lambda: self.tokens_per_s)
        m["mfu_ratio"].set_function(lambda: self.mfu)
        m["hbm_bytes_in_use"].set_function(
            lambda: float(self.hbm_bytes_in_use()))
        self._ring = deque(maxlen=max(1, int(self.ring_size)))
        self._last_boundary: Optional[float] = None
        self._pending_phases: dict[str, float] = {}
        self._last_publish: Optional[float] = None
        self._spool: Optional[JsonlRing] = None
        self.steps_recorded = 0

    # -- workload accounting --------------------------------------------------
    def _flops_per_token(self) -> float:
        if self.flops_per_token:
            return self.flops_per_token
        if self.config is not None:
            return float(self.config.flops_per_token(self.seq_len))
        return 0.0

    def estimate(self) -> Optional[roofline.RooflineEstimate]:
        """The analytic floor for this agent's workload (None without a
        config: roofline floors need the traffic model, not just FLOPs)."""
        if self.config is None:
            return None
        if self.mode == "decode":
            return roofline.decode_estimate(
                self.config, self.batch, num_chips=self.num_chips,
                accelerator=self.accelerator)
        return roofline.train_estimate(
            self.config, self.batch, self.seq_len,
            num_chips=self.num_chips, accelerator=self.accelerator)

    def hbm_bytes_in_use(self) -> int:
        fn = self.hbm_fn if self.hbm_fn is not None else hbm_usage_bytes
        try:
            return int(sum(fn().values()))
        except Exception:  # noqa: BLE001 — no accelerator = no HBM stat
            return 0

    # -- recording ------------------------------------------------------------
    @contextlib.contextmanager
    def scope(self, name: str):
        """Accumulate a named phase duration (fwd/bwd/opt) attached to
        the next recorded step."""
        t0 = self.time_fn()
        try:
            yield
        finally:
            dt = self.time_fn() - t0
            self._pending_phases[name] = \
                self._pending_phases.get(name, 0.0) + dt

    def step_boundary(self) -> Optional[dict]:
        """Mark one synced-step boundary; the first call arms the timer,
        each later call records the elapsed interval as a step."""
        now = self.time_fn()
        sample = None
        if self._last_boundary is not None:
            sample = self.record_step(now - self._last_boundary, at=now)
        self._last_boundary = now
        return sample

    def record_step(self, duration_s: float,
                    at: Optional[float] = None) -> dict:
        """Record one step of `duration_s`; returns the sample dict that
        entered the ring (and the JSONL spool, when attached)."""
        at = self.time_fn() if at is None else at
        self._durations.append(duration_s)
        while len(self._durations) > self.window:
            self._durations.popleft()
        self._step_hist.observe(duration_s)
        self.steps_recorded += 1
        fpt = self._flops_per_token()
        tok_s = self.tokens_per_step / duration_s if duration_s > 0 else 0.0
        est = self.estimate()
        sample = {
            "t": at,
            "step": self.steps_recorded,
            "step_time_s": duration_s,
            "tokens_per_s": tok_s,
            "mfu": roofline.mfu_from_flops(
                tok_s, fpt, self.num_chips, self.accelerator),
            "hbm_bytes": self.hbm_bytes_in_use(),
        }
        if est is not None:
            sample["roofline_fraction"] = est.roofline_fraction(duration_s)
            sample["bound"] = est.bound
        if self._pending_phases:
            sample["phases"] = dict(self._pending_phases)
            self._pending_phases = {}
        self._ring.append(sample)
        if self._spool is not None:
            self._spool.append(sample)
        self.maybe_publish(now=at)
        return sample

    # -- rolling stats (shared with the StepTimer shim) -----------------------
    @property
    def tokens_per_step(self) -> int:
        return self.batch * (self.seq_len if self.mode == "train" else 1)

    @property
    def step_time_s(self) -> float:
        d = self._durations
        return sum(d) / len(d) if d else 0.0

    @property
    def tokens_per_s(self) -> float:
        st = self.step_time_s
        return self.tokens_per_step / st if st else 0.0

    @property
    def mfu(self) -> float:
        return roofline.mfu_from_flops(
            self.tokens_per_s, self._flops_per_token(), self.num_chips,
            self.accelerator)

    # -- spool / publish ------------------------------------------------------
    def spool_to(self, path: str) -> JsonlRing:
        self._spool = JsonlRing(path, max_records=self.ring_size)
        return self._spool

    def samples(self) -> list[dict]:
        return list(self._ring)

    def summary(self) -> dict:
        """The rolling summary the control plane consumes — the pod
        annotation payload (`TELEMETRY_ANNOTATION`)."""
        est = self.estimate()
        out = {
            "v": SUMMARY_VERSION,
            "worker": self.worker,
            "mode": self.mode,
            "steps": self.steps_recorded,
            "step_time_s": self.step_time_s,
            "tokens_per_s": self.tokens_per_s,
            "mfu": self.mfu,
            "hbm_bytes": self.hbm_bytes_in_use(),
            "t": self.time_fn(),
        }
        if est is not None and self.step_time_s > 0:
            out["roofline_fraction"] = est.roofline_fraction(self.step_time_s)
            out["bound"] = est.bound
        phases: dict[str, float] = {}
        for s in self._ring:
            for k, v in (s.get("phases") or {}).items():
                phases[k] = phases.get(k, 0.0) + v
        if phases:
            out["phases"] = phases
        return out

    def maybe_publish(self, now: Optional[float] = None) -> bool:
        """Push the rolling summary through `publish_fn`, at most once
        per `publish_interval_s` (the first recorded step publishes
        immediately so a fresh worker shows up fast)."""
        if self.publish_fn is None:
            return False
        now = self.time_fn() if now is None else now
        if (self._last_publish is not None
                and now - self._last_publish < self.publish_interval_s):
            return False
        self._last_publish = now
        self.publish_fn(self.summary())
        return True

    def publish_now(self) -> bool:
        """Unconditional publish (loop teardown / final flush)."""
        if self.publish_fn is None:
            return False
        self._last_publish = self.time_fn()
        self.publish_fn(self.summary())
        return True


def annotation_payload(summary: dict) -> str:
    """Serialize a summary for the pod annotation (stable key order so
    repeated publishes with identical stats produce identical patches)."""
    return json.dumps(summary, sort_keys=True)


def parse_annotation(payload: str) -> Optional[dict]:
    """Parse a telemetry annotation; None for malformed/foreign payloads
    (the aggregator must never crash on a worker's bad write)."""
    try:
        out = json.loads(payload)
    except (ValueError, TypeError):
        return None
    if not isinstance(out, dict) or out.get("v") != SUMMARY_VERSION:
        return None
    return out


__all__ = [
    "JsonlRing", "SUMMARY_VERSION", "TELEMETRY_ANNOTATION",
    "TelemetryAgent", "annotation_payload", "parse_annotation",
]
