"""Session-state tier: the per-notebook slice checkpoint inventory.

Self-healing (core/selfheal.py) restores slice *membership* but not the
user's in-memory kernel/JAX session — the one thing notebook users care
about.  ElasticNotebook (arXiv:2309.11083) shows notebook state can be
snapshotted and live-migrated; NotebookOS (arXiv:2503.20591) replicates
kernel state for exactly this failure mode.  This module is the contract
between the two planes:

- the **data plane** (runtime/checkpoint.py sidecar hooks inside the
  worker pods) writes periodic / pre-stop / final snapshots of the
  session payload into a `SessionStateStore`;
- the **control plane** (RecoveryEngine's `migrate` verb) reads snapshot
  freshness + generation to decide whether a disrupted slice can be
  migrated (snapshot -> whole-slice restart -> restore) instead of
  bare-restarted, and mirrors the restore intent into
  `status.sessionState` (write-ahead, crash/failover-safe like
  `status.sliceRecovery`).

The store itself is an object-store *stub* in the same spirit as the
fake ApiServer: an in-memory backend for unit tests and a dir-backed
backend whose writes are torn-write-safe (payload first, fsync, then an
atomically renamed metadata commit marker) so a killed sidecar never
leaves a snapshot that restores garbage.  `request_final_snapshot` is
the control plane's "flush now if you still can" RPC; the registered
handler (the in-pod sidecar in production, FakeCluster in tests) returns
the fresh SnapshotInfo or None when the slice is unreachable.

**Replicated-kernel tier** (spec.replication): on top of base snapshots
the store keeps per-slice **delta chains** — an ordered append-only
stream of incremental state writes anchored to a base generation.  The
primary kernel appends deltas between full snapshots; follower kernels
replay them through a `FollowerReplica` cursor so catch-up costs one
delta, not one restore.  Every delta records the digest of the
*materialized* state after applying it, so `compact()` can fold a chain
into a fresh base generation only after verifying the replayed bytes
match the chain head (a digest mismatch leaves the chain untouched).
Out-of-order appends are rejected (`DeltaChainError`) — the chain is a
log, not a set.

Writes carry an optional **writer epoch** checked against a per-notebook
fence (`fence()`): once the promote verb (core/selfheal.py) raises the
fence, a demoted primary's writes raise `StaleWriterError` instead of
landing — the store-side half of the "zombie primary can never ack
writes" guarantee (the CR-side half is the write-ahead promotion record
in status.replication).  The fence is runtime state; the durable
authority is the CR epoch, and promotion re-fences on resume.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

from ..utils.clock import Clock

# snapshot triggers — a bounded set (they label
# notebook_checkpoint_snapshots_total{trigger})
TRIGGER_PERIODIC = "periodic"
TRIGGER_PRE_STOP = "pre-stop"
TRIGGER_FINAL = "final"
TRIGGER_CULL = "cull"
TRIGGER_COMPACT = "compact"

DEFAULT_MAX_TO_KEEP = 5

FinalSnapshotHandler = Callable[[str, str, int], Optional["SnapshotInfo"]]


class DeltaChainError(Exception):
    """A delta append/replay violated the chain contract: missing base,
    out-of-order sequence, or a replay digest that does not match the
    recorded chain head (the write/compaction is refused, never applied
    half-way)."""


class StaleWriterError(Exception):
    """A write carried an epoch below the notebook's fence — the writer
    was demoted and must not ack state (core/selfheal.py promote verb)."""


@dataclass(frozen=True)
class SnapshotInfo:
    """Metadata of one stored slice checkpoint.  `digest` fingerprints the
    payload — restored-state equivalence drills compare it across the
    snapshot/restore boundary."""

    namespace: str
    notebook: str
    slice_id: int
    generation: int
    saved_at: float
    digest: str
    trigger: str
    uri: str
    size: int


@dataclass(frozen=True)
class DeltaInfo:
    """Metadata of one incremental state delta.  `digest` fingerprints the
    MATERIALIZED state after applying this delta (base payload + every
    delta through `seq`) — the replay-correctness anchor compaction and
    follower catch-up verify against; `delta_digest` fingerprints the
    delta bytes themselves."""

    namespace: str
    notebook: str
    slice_id: int
    base_generation: int
    seq: int
    saved_at: float
    digest: str
    delta_digest: str
    uri: str
    size: int


def payload_digest(payload: bytes) -> str:
    return hashlib.sha256(payload).hexdigest()[:16]


class SessionStateStore:
    """Backend-agnostic snapshot inventory keyed by
    (namespace, notebook, slice_id), generations monotonic per key."""

    def __init__(self, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        self.clock = clock or Clock()
        self.max_to_keep = max_to_keep
        self._lock = threading.RLock()
        self._final_handler: Optional[FinalSnapshotHandler] = None
        # per-notebook write fence (replicated tier): writes carrying an
        # epoch below the fence are rejected.  Runtime state by design —
        # the durable epoch lives on the CR (status.replication) and the
        # promote verb re-fences on crash/failover resume.
        self._fences: dict[tuple[str, str], int] = {}
        self.fenced_rejections: dict[tuple[str, str], int] = {}
        # optional observer (ns, nb) -> None, wired by the controller to
        # count rejections into notebook_replication_fenced_writes_total
        self.on_fenced_write: Optional[Callable[[str, str], None]] = None

    # -- identity --------------------------------------------------------------
    @property
    def uri(self) -> str:
        raise NotImplementedError

    def snapshot_uri(self, namespace: str, notebook: str, slice_id: int,
                     generation: int) -> str:
        return (f"{self.uri}/{namespace}/{notebook}/slice-{slice_id}/"
                f"gen-{generation}")

    def delta_uri(self, namespace: str, notebook: str, slice_id: int,
                  base_generation: int, seq: int) -> str:
        return (f"{self.uri}/{namespace}/{notebook}/slice-{slice_id}/"
                f"delta-{base_generation}-{seq}")

    # -- the write fence (replicated tier) -------------------------------------
    def fence(self, namespace: str, notebook: str, epoch: int) -> int:
        """Raise the notebook's write fence to `epoch` (monotonic max —
        re-fencing with an old epoch is a no-op, so promotion resume is
        idempotent).  Returns the fence now in force."""
        with self._lock:
            key = (namespace, notebook)
            cur = self._fences.get(key, 0)
            if epoch > cur:
                self._fences[key] = epoch
                cur = epoch
            return cur

    def fence_epoch(self, namespace: str, notebook: str) -> int:
        with self._lock:
            return self._fences.get((namespace, notebook), 0)

    def _check_fence(self, namespace: str, notebook: str,
                     writer_epoch: Optional[int]) -> None:
        """Caller holds the lock.  `writer_epoch=None` (non-replicated
        writers) always passes; a fenced write is counted and raised."""
        if writer_epoch is None:
            return
        if writer_epoch < self._fences.get((namespace, notebook), 0):
            key = (namespace, notebook)
            self.fenced_rejections[key] = \
                self.fenced_rejections.get(key, 0) + 1
            cb = self.on_fenced_write
            if cb is not None:
                try:
                    cb(namespace, notebook)
                except Exception:  # noqa: BLE001 — observer must not
                    pass           # turn a correct rejection into a crash
            raise StaleWriterError(
                f"write to {namespace}/{notebook} with epoch "
                f"{writer_epoch} below fence "
                f"{self._fences.get(key, 0)}: writer was demoted")

    # -- writes ----------------------------------------------------------------
    def put(self, namespace: str, notebook: str, slice_id: int,
            payload: bytes, trigger: str = TRIGGER_PERIODIC,
            writer_epoch: Optional[int] = None) -> SnapshotInfo:
        with self._lock:
            self._check_fence(namespace, notebook, writer_epoch)
            latest = self.latest(namespace, notebook, slice_id)
            generation = (latest.generation + 1) if latest else 1
            info = SnapshotInfo(
                namespace=namespace,
                notebook=notebook,
                slice_id=slice_id,
                generation=generation,
                saved_at=self.clock.now(),
                digest=payload_digest(payload),
                trigger=trigger,
                uri=self.snapshot_uri(namespace, notebook, slice_id,
                                      generation),
                size=len(payload),
            )
            self._store(info, payload)
            self._prune(namespace, notebook, slice_id)
            kept = {s.generation
                    for s in self.snapshots(namespace, notebook, slice_id)}
            self._prune_deltas(namespace, notebook, slice_id, kept)
            return info

    def append_delta(self, namespace: str, notebook: str, slice_id: int,
                     delta: bytes, expected_seq: Optional[int] = None,
                     writer_epoch: Optional[int] = None) -> DeltaInfo:
        """Append one incremental state delta to the chain anchored at the
        latest base snapshot.  The chain is strictly ordered: `expected_seq`
        (when given) must name the next slot, or the append is rejected —
        a primary that raced a compaction or replayed a duplicate cannot
        corrupt the log."""
        with self._lock:
            self._check_fence(namespace, notebook, writer_epoch)
            base = self.latest(namespace, notebook, slice_id)
            if base is None:
                raise DeltaChainError(
                    f"no base snapshot for {namespace}/{notebook}/"
                    f"slice-{slice_id}: delta chains anchor to a base")
            chain = self.deltas(namespace, notebook, slice_id)
            next_seq = (chain[-1].seq + 1) if chain else 1
            if expected_seq is not None and expected_seq != next_seq:
                raise DeltaChainError(
                    f"out-of-order delta for {namespace}/{notebook}/"
                    f"slice-{slice_id}: expected_seq={expected_seq}, "
                    f"chain head wants {next_seq}")
            head = self.materialize(namespace, notebook, slice_id)
            state = (head or b"") + delta
            info = DeltaInfo(
                namespace=namespace,
                notebook=notebook,
                slice_id=slice_id,
                base_generation=base.generation,
                seq=next_seq,
                saved_at=self.clock.now(),
                digest=payload_digest(state),
                delta_digest=payload_digest(delta),
                uri=self.delta_uri(namespace, notebook, slice_id,
                                   base.generation, next_seq),
                size=len(delta),
            )
            self._store_delta(info, delta)
            return info

    def compact(self, namespace: str, notebook: str, slice_id: int,
                trigger: str = TRIGGER_COMPACT,
                writer_epoch: Optional[int] = None) -> Optional[SnapshotInfo]:
        """Fold the current delta chain into a fresh base generation —
        digest-verified: the replayed bytes must hash to the chain head's
        recorded digest or the compaction is refused and the chain stays
        untouched.  An empty chain is a no-op (returns the current base)."""
        with self._lock:
            self._check_fence(namespace, notebook, writer_epoch)
            base = self.latest(namespace, notebook, slice_id)
            if base is None:
                return None
            chain = self.deltas(namespace, notebook, slice_id)
            if not chain:
                return base
            state = self.materialize(namespace, notebook, slice_id)
            if state is None or payload_digest(state) != chain[-1].digest:
                raise DeltaChainError(
                    f"compaction digest mismatch for {namespace}/"
                    f"{notebook}/slice-{slice_id}: replayed "
                    f"{payload_digest(state or b'')} != recorded "
                    f"{chain[-1].digest}; chain left untouched")
            return self.put(namespace, notebook, slice_id, state,
                            trigger=trigger, writer_epoch=writer_epoch)

    # -- reads -----------------------------------------------------------------
    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        raise NotImplementedError

    def latest(self, namespace: str, notebook: str,
               slice_id: int) -> Optional[SnapshotInfo]:
        snaps = self.snapshots(namespace, notebook, slice_id)
        return snaps[-1] if snaps else None

    def info(self, namespace: str, notebook: str, slice_id: int,
             generation: int) -> Optional[SnapshotInfo]:
        return next((s for s in self.snapshots(namespace, notebook, slice_id)
                     if s.generation == generation), None)

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        raise NotImplementedError

    def deltas(self, namespace: str, notebook: str, slice_id: int,
               base_generation: Optional[int] = None) -> list[DeltaInfo]:
        """The ordered delta chain anchored at `base_generation` (default:
        the latest base snapshot's chain; empty when no base exists)."""
        with self._lock:
            if base_generation is None:
                base = self.latest(namespace, notebook, slice_id)
                if base is None:
                    return []
                base_generation = base.generation
            chain = [d for d, _ in
                     self._delta_entries(namespace, notebook, slice_id)
                     if d.base_generation == base_generation]
            return sorted(chain, key=lambda d: d.seq)

    def delta_payload(self, namespace: str, notebook: str, slice_id: int,
                      base_generation: int, seq: int) -> Optional[bytes]:
        with self._lock:
            return next(
                (p for d, p in
                 self._delta_entries(namespace, notebook, slice_id)
                 if d.base_generation == base_generation and d.seq == seq),
                None)

    def materialize(self, namespace: str, notebook: str, slice_id: int,
                    upto_seq: Optional[int] = None) -> Optional[bytes]:
        """Replay the latest base payload plus its delta chain (through
        `upto_seq` when given) into the current session state."""
        with self._lock:
            base = self.latest(namespace, notebook, slice_id)
            if base is None:
                return None
            state = self.payload(namespace, notebook, slice_id,
                                 generation=base.generation)
            if state is None:
                return None
            for d in self.deltas(namespace, notebook, slice_id):
                if upto_seq is not None and d.seq > upto_seq:
                    break
                chunk = self.delta_payload(namespace, notebook, slice_id,
                                           d.base_generation, d.seq)
                if chunk is None:
                    break
                state = state + chunk
            return state

    def chain_head(self, namespace: str, notebook: str,
                   slice_id: int) -> Optional[tuple[int, int, str]]:
        """(base_generation, head_seq, head_digest) of the current chain —
        the freshness mark follower catch-up and the promote verb compare
        against; None when no base snapshot exists."""
        with self._lock:
            base = self.latest(namespace, notebook, slice_id)
            if base is None:
                return None
            chain = self.deltas(namespace, notebook, slice_id)
            if not chain:
                return (base.generation, 0, base.digest)
            return (base.generation, chain[-1].seq, chain[-1].digest)

    # -- the control-plane "flush now" hook ------------------------------------
    def set_final_snapshot_handler(
            self, handler: Optional[FinalSnapshotHandler]) -> None:
        """Register the data-plane responder (the in-pod sidecar; in tests,
        FakeCluster).  One handler — the store is per-fleet, the handler
        fans out to the addressed slice itself."""
        self._final_handler = handler

    def request_final_snapshot(self, namespace: str, notebook: str,
                               slice_id: int) -> Optional[SnapshotInfo]:
        """Ask the slice to snapshot RIGHT NOW (pre-migration flush).
        Returns the fresh SnapshotInfo, or None when no handler is wired
        or the slice is unreachable/failed to snapshot."""
        handler = self._final_handler
        if handler is None:
            return None
        try:
            return handler(namespace, notebook, slice_id)
        except Exception:  # noqa: BLE001 — an unreachable slice is a
            return None    # normal outcome, not an engine error

    # -- backend hooks ---------------------------------------------------------
    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        raise NotImplementedError

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        raise NotImplementedError

    def _store_delta(self, info: DeltaInfo, delta: bytes) -> None:
        raise NotImplementedError

    def _delta_entries(self, namespace: str, notebook: str,
                       slice_id: int) -> list[tuple[DeltaInfo, bytes]]:
        raise NotImplementedError

    def _prune_deltas(self, namespace: str, notebook: str, slice_id: int,
                      keep_bases: set[int]) -> None:
        """Drop delta chains whose base generation was pruned (a chain
        without its base can never be replayed)."""
        raise NotImplementedError


class InMemorySessionStore(SessionStateStore):
    """Dict-backed store for unit tests and single-process drills."""

    def __init__(self, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        super().__init__(clock=clock, max_to_keep=max_to_keep)
        self._data: dict[tuple[str, str, int],
                         list[tuple[SnapshotInfo, bytes]]] = {}
        self._delta_data: dict[tuple[str, str, int],
                               list[tuple[DeltaInfo, bytes]]] = {}

    @property
    def uri(self) -> str:
        return "mem://session-state"

    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        with self._lock:
            return [info for info, _ in
                    self._data.get((namespace, notebook, slice_id), [])]

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            entries = self._data.get((namespace, notebook, slice_id), [])
            if not entries:
                return None
            if generation is None:
                return entries[-1][1]
            return next((p for info, p in entries
                         if info.generation == generation), None)

    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        key = (info.namespace, info.notebook, info.slice_id)
        self._data.setdefault(key, []).append((info, bytes(payload)))

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        key = (namespace, notebook, slice_id)
        entries = self._data.get(key, [])
        if len(entries) > self.max_to_keep:
            self._data[key] = entries[-self.max_to_keep:]

    def _store_delta(self, info: DeltaInfo, delta: bytes) -> None:
        key = (info.namespace, info.notebook, info.slice_id)
        self._delta_data.setdefault(key, []).append((info, bytes(delta)))

    def _delta_entries(self, namespace: str, notebook: str,
                       slice_id: int) -> list[tuple[DeltaInfo, bytes]]:
        with self._lock:
            return list(self._delta_data.get((namespace, notebook,
                                              slice_id), []))

    def _prune_deltas(self, namespace: str, notebook: str, slice_id: int,
                      keep_bases: set[int]) -> None:
        key = (namespace, notebook, slice_id)
        entries = self._delta_data.get(key)
        if entries:
            self._delta_data[key] = [
                (d, p) for d, p in entries
                if d.base_generation in keep_bases]


class DirSessionStore(SessionStateStore):
    """Directory-backed store with torn-write safety.

    Layout: `<root>/<ns>/<notebook>/slice-<id>/gen-<G>.bin` (payload) +
    `gen-<G>.json` (metadata).  A snapshot COMMITS when its metadata file
    lands, and the metadata is written tmp-file -> fsync -> atomic rename
    AFTER the fsync'd payload — a sidecar killed mid-save leaves a stray
    `.bin`/`.tmp-` orphan that reads as "no snapshot", never as a
    half-written generation.  Orphans are GC'd on the next scan."""

    def __init__(self, root: str, clock: Optional[Clock] = None,
                 max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> None:
        super().__init__(clock=clock, max_to_keep=max_to_keep)
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    @property
    def uri(self) -> str:
        return f"file://{self.root}"

    def _slice_dir(self, namespace: str, notebook: str,
                   slice_id: int) -> Path:
        return self.root / namespace / notebook / f"slice-{slice_id}"

    def snapshots(self, namespace: str, notebook: str,
                  slice_id: int) -> list[SnapshotInfo]:
        d = self._slice_dir(namespace, notebook, slice_id)
        if not d.is_dir():
            return []
        with self._lock:
            out = []
            for meta_path in sorted(d.glob("gen-*.json")):
                info = self._load_meta(meta_path)
                if info is not None:
                    out.append(info)
            self._gc_orphans(d, {s.generation for s in out})
            return sorted(out, key=lambda s: s.generation)

    def _load_meta(self, meta_path: Path) -> Optional[SnapshotInfo]:
        try:
            meta = json.loads(meta_path.read_text())
            info = SnapshotInfo(**meta)
        except (OSError, ValueError, TypeError):
            # torn/corrupt commit marker: GC both halves
            meta_path.unlink(missing_ok=True)
            meta_path.with_suffix(".bin").unlink(missing_ok=True)
            return None
        if not meta_path.with_suffix(".bin").exists():
            meta_path.unlink(missing_ok=True)
            return None
        return info

    def _gc_orphans(self, d: Path, committed: set[int]) -> None:
        """Drop payloads that never got their commit marker (a save killed
        between the payload write and the metadata rename) and any stray
        tmp files from interrupted writers."""
        for tmp in d.glob(".tmp-*"):
            tmp.unlink(missing_ok=True)
        for bin_path in d.glob("gen-*.bin"):
            try:
                gen = int(bin_path.stem.split("-", 1)[1])
            except ValueError:
                bin_path.unlink(missing_ok=True)
                continue
            if gen not in committed:
                bin_path.unlink(missing_ok=True)

    def payload(self, namespace: str, notebook: str, slice_id: int,
                generation: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            if generation is None:
                latest = self.latest(namespace, notebook, slice_id)
                if latest is None:
                    return None
                generation = latest.generation
            p = self._slice_dir(namespace, notebook,
                                slice_id) / f"gen-{generation}.bin"
            try:
                return p.read_bytes()
            except OSError:
                return None

    def _store(self, info: SnapshotInfo, payload: bytes) -> None:
        d = self._slice_dir(info.namespace, info.notebook, info.slice_id)
        d.mkdir(parents=True, exist_ok=True)
        bin_final = d / f"gen-{info.generation}.bin"
        _atomic_write(bin_final, payload)
        meta = {
            "namespace": info.namespace,
            "notebook": info.notebook,
            "slice_id": info.slice_id,
            "generation": info.generation,
            "saved_at": info.saved_at,
            "digest": info.digest,
            "trigger": info.trigger,
            "uri": info.uri,
            "size": info.size,
        }
        # the commit marker lands LAST: its atomic rename is the point of
        # no return, and everything before it is invisible to readers
        _atomic_write(d / f"gen-{info.generation}.json",
                      json.dumps(meta).encode())

    def _prune(self, namespace: str, notebook: str, slice_id: int) -> None:
        snaps = self.snapshots(namespace, notebook, slice_id)
        for stale in snaps[:-self.max_to_keep] if self.max_to_keep else []:
            d = self._slice_dir(namespace, notebook, slice_id)
            (d / f"gen-{stale.generation}.json").unlink(missing_ok=True)
            (d / f"gen-{stale.generation}.bin").unlink(missing_ok=True)

    # delta chains live beside the base snapshots as
    # `delta-<base>-<seq>.bin/.json` — a name shape the base-snapshot
    # globs (`gen-*`) never match, so snapshot orphan GC cannot eat a
    # committed delta.  Same commit discipline as _store: payload first,
    # metadata commit marker atomically renamed LAST.
    def _store_delta(self, info: DeltaInfo, delta: bytes) -> None:
        d = self._slice_dir(info.namespace, info.notebook, info.slice_id)
        d.mkdir(parents=True, exist_ok=True)
        stem = f"delta-{info.base_generation}-{info.seq}"
        _atomic_write(d / f"{stem}.bin", delta)
        meta = {
            "namespace": info.namespace,
            "notebook": info.notebook,
            "slice_id": info.slice_id,
            "base_generation": info.base_generation,
            "seq": info.seq,
            "saved_at": info.saved_at,
            "digest": info.digest,
            "delta_digest": info.delta_digest,
            "uri": info.uri,
            "size": info.size,
        }
        _atomic_write(d / f"{stem}.json", json.dumps(meta).encode())

    def _delta_entries(self, namespace: str, notebook: str,
                       slice_id: int) -> list[tuple[DeltaInfo, bytes]]:
        d = self._slice_dir(namespace, notebook, slice_id)
        if not d.is_dir():
            return []
        with self._lock:
            out = []
            committed: set[tuple[int, int]] = set()
            for meta_path in sorted(d.glob("delta-*.json")):
                try:
                    info = DeltaInfo(**json.loads(meta_path.read_text()))
                except (OSError, ValueError, TypeError):
                    # torn/corrupt commit marker: GC both halves
                    meta_path.unlink(missing_ok=True)
                    meta_path.with_suffix(".bin").unlink(missing_ok=True)
                    continue
                try:
                    payload = meta_path.with_suffix(".bin").read_bytes()
                except OSError:
                    meta_path.unlink(missing_ok=True)
                    continue
                committed.add((info.base_generation, info.seq))
                out.append((info, payload))
            for bin_path in d.glob("delta-*.bin"):
                parts = bin_path.stem.split("-")
                try:
                    key = (int(parts[1]), int(parts[2]))
                except (IndexError, ValueError):
                    bin_path.unlink(missing_ok=True)
                    continue
                if key not in committed:
                    bin_path.unlink(missing_ok=True)
            return sorted(out,
                          key=lambda e: (e[0].base_generation, e[0].seq))

    def _prune_deltas(self, namespace: str, notebook: str, slice_id: int,
                      keep_bases: set[int]) -> None:
        d = self._slice_dir(namespace, notebook, slice_id)
        if not d.is_dir():
            return
        for meta_path in d.glob("delta-*.json"):
            try:
                base = int(meta_path.stem.split("-")[1])
            except (IndexError, ValueError):
                continue
            if base not in keep_bases:
                meta_path.unlink(missing_ok=True)
                meta_path.with_suffix(".bin").unlink(missing_ok=True)


class FollowerReplica:
    """Follower catch-up cursor over one slice's base + delta stream.

    The cursor tracks (base_generation, seq) and replays forward on each
    `catch_up()` call: when the store's latest base generation moved (a
    fresh snapshot or a compaction folded the chain), the follower
    reloads that base in full — catch-up works from ANY base — then
    applies the missing deltas in order, verifying each recorded
    materialized-state digest as it goes.  A gap in the chain (a delta
    pruned from under the cursor) stops the replay at the last verified
    state rather than applying out of order.

    In production this loop runs in the follower pod's runtime sidecar;
    in tests FakeCluster drives one cursor per follower replica and
    stamps the freshness onto the follower pods
    (ANNOTATION_REPLICA_GENERATION/SEQ/DIGEST) for the promote verb."""

    def __init__(self, store: SessionStateStore, namespace: str,
                 notebook: str, slice_id: int = 0) -> None:
        self.store = store
        self.namespace = namespace
        self.notebook = notebook
        self.slice_id = slice_id
        self.base_generation = 0
        self.seq = 0
        self.state: Optional[bytes] = None
        self.applied_total = 0

    def catch_up(self) -> int:
        """Apply everything new; returns the number of replay steps taken
        (base reloads count as one step)."""
        applied = 0
        with self.store._lock:
            latest = self.store.latest(self.namespace, self.notebook,
                                       self.slice_id)
            if latest is None:
                return 0
            if latest.generation != self.base_generation:
                payload = self.store.payload(
                    self.namespace, self.notebook, self.slice_id,
                    generation=latest.generation)
                if payload is None:
                    return 0
                self.state = payload
                self.base_generation = latest.generation
                self.seq = 0
                applied += 1
            for d in self.store.deltas(self.namespace, self.notebook,
                                       self.slice_id,
                                       base_generation=self.base_generation):
                if d.seq <= self.seq:
                    continue
                if d.seq != self.seq + 1:
                    break  # chain gap: stop at the last verified state
                chunk = self.store.delta_payload(
                    self.namespace, self.notebook, self.slice_id,
                    d.base_generation, d.seq)
                if chunk is None:
                    break
                state = (self.state or b"") + chunk
                if payload_digest(state) != d.digest:
                    raise DeltaChainError(
                        f"follower replay digest mismatch at "
                        f"{self.namespace}/{self.notebook}/slice-"
                        f"{self.slice_id} delta {d.base_generation}-"
                        f"{d.seq}")
                self.state = state
                self.seq = d.seq
                applied += 1
        self.applied_total += applied
        return applied

    @property
    def digest(self) -> str:
        return payload_digest(self.state) if self.state is not None else ""

    def lag(self) -> int:
        """Replay steps between this cursor and the chain head (0 = fully
        caught up; a stale base counts the full chain behind the new
        base)."""
        head = self.store.chain_head(self.namespace, self.notebook,
                                     self.slice_id)
        if head is None:
            return 0
        head_gen, head_seq, _ = head
        if head_gen != self.base_generation:
            return 1 + head_seq
        return max(head_seq - self.seq, 0)

    def caught_up(self, max_lag: int = 0) -> bool:
        return self.lag() <= max_lag


def _atomic_write(final: Path, data: bytes) -> None:
    """tmp file in the target dir -> write -> fsync -> atomic rename ->
    fsync(dir): a crash at any point leaves either the old state or the
    new state, never a torn file under the final name."""
    tmp = final.parent / f".tmp-{final.name}-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    dirfd = os.open(final.parent, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def open_store(uri: str, clock: Optional[Clock] = None,
               max_to_keep: int = DEFAULT_MAX_TO_KEEP) -> SessionStateStore:
    """URI -> store: `mem://...` (fresh in-memory instance), `file://<path>`
    or a bare filesystem path (dir-backed)."""
    if uri.startswith("mem://"):
        return InMemorySessionStore(clock=clock, max_to_keep=max_to_keep)
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    return DirSessionStore(uri, clock=clock, max_to_keep=max_to_keep)


__all__ = [
    "DeltaChainError",
    "DeltaInfo",
    "DirSessionStore",
    "FollowerReplica",
    "InMemorySessionStore",
    "SessionStateStore",
    "SnapshotInfo",
    "StaleWriterError",
    "TRIGGER_COMPACT",
    "TRIGGER_CULL",
    "TRIGGER_FINAL",
    "TRIGGER_PERIODIC",
    "TRIGGER_PRE_STOP",
    "open_store",
    "payload_digest",
]
