"""Gateway hostname discovery shared by the DSPA/Elyra and MLflow integrations.

Port of getGatewayInstance / getHostnameForPublicEndpoint /
getHostnameFromRoute (notebook_dspa_secret.go:49-186): prefer the configured
Gateway's first listener hostname; fall back to an OpenShift Route labeled for
the gateway.
"""

from __future__ import annotations

from typing import Optional

from ..kube import ApiServer, KubeObject
from ..utils.config import OdhConfig


def get_gateway_instance(api: ApiServer, cfg: OdhConfig) -> Optional[KubeObject]:
    return api.try_get("Gateway", cfg.gateway_namespace, cfg.gateway_name)


def get_hostname_from_route(
    api: ApiServer, cfg: OdhConfig, gateway: KubeObject
) -> str:
    """Route fallback: only a Route owned by (or labeled for) the gateway
    counts — an arbitrary Route in openshift-ingress must not leak into
    public endpoints (notebook_dspa_secret.go:152-186)."""
    for route in api.list("Route", namespace=cfg.gateway_namespace):
        owned = any(
            ref.uid == gateway.metadata.uid
            for ref in route.metadata.owner_references
        )
        labeled = (
            route.metadata.labels.get("gateway.networking.k8s.io/gateway-name")
            == gateway.name
        )
        if not (owned or labeled):
            continue
        host = route.spec.get("host", "")
        if host:
            return host
    return ""


def get_hostname_for_public_endpoint(api: ApiServer, cfg: OdhConfig) -> str:
    """First Gateway listener hostname, else a gateway-owned Route host,
    else "" — and "" when the Gateway itself is absent
    (notebook_dspa_secret.go:106-148)."""
    gw = get_gateway_instance(api, cfg)
    if gw is None:
        return ""
    listeners = gw.spec.get("listeners") or []
    if listeners:
        hostname = listeners[0].get("hostname") or ""
        if hostname:
            return str(hostname)
    return get_hostname_from_route(api, cfg, gw)
