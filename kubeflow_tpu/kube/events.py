"""EventRecorder: records k8s Events against involved objects.

Mirrors record.EventRecorder usage in the reference (manager wiring
notebook-controller/main.go:105; re-emission onto the Notebook CR
controllers/notebook_controller.go:99-122).
"""

from __future__ import annotations

from .errors import AlreadyExistsError
from .meta import KubeObject, ObjectMeta
from .store import ApiServer


class EventRecorder:
    def __init__(self, api: ApiServer, component: str) -> None:
        self.api = api
        self.component = component
        self._seq = 0

    def event(
        self, involved: KubeObject, etype: str, reason: str, message: str
    ) -> KubeObject:
        """etype is "Normal" or "Warning" (corev1.EventTypeNormal/Warning)."""
        # aggregate identical events by bumping count, as client-go does
        for ev in self.api.list("Event", namespace=involved.namespace):
            io = ev.body.get("involvedObject", {})
            if (
                io.get("kind") == involved.kind
                and io.get("name") == involved.name
                and ev.body.get("reason") == reason
                and ev.body.get("message") == message
                and ev.body.get("type") == etype
            ):
                # listed objects are read-only shared snapshots: bump the
                # count on a private copy
                ev = ev.deepcopy()
                ev.body["count"] = int(ev.body.get("count", 1)) + 1
                return self.api.update(ev)
        body = {
            "involvedObject": {
                "apiVersion": involved.api_version,
                "kind": involved.kind,
                "namespace": involved.namespace,
                "name": involved.name,
                "uid": involved.metadata.uid,
            },
            "reason": reason,
            "message": message,
            "type": etype,
            "count": 1,
            "source": {"component": self.component},
        }
        # sequence names collide across recorder instances: a restarted
        # manager (or the new leader after failover) starts its counter at
        # zero while the previous holder's Events still exist.  Skip
        # forward over occupied slots — the loop is bounded by the number
        # of existing same-named Events.
        while True:
            self._seq += 1
            ev = KubeObject(
                api_version="v1",
                kind="Event",
                metadata=ObjectMeta(
                    name=f"{involved.name}.{self.component}.{self._seq:06d}",
                    namespace=involved.namespace or "default",
                ),
                body=dict(body),
            )
            try:
                return self.api.create(ev)
            except AlreadyExistsError:
                continue
