"""13B-class int4 decode on ONE v5e chip — the capacity demo, end to end.

BASELINE.md's int4 row used to claim "13B-class fits one 16-GiB chip" with
no number behind it; this script earns the row the way ci/llama7b_decode.py
did for int8: materialize the Llama-2-13B architecture host-side leaf by
leaf (random weights — decode throughput does not depend on values),
int4-quantize each leaf before device_put (models/quant.py
quantize_params_int4: nibble-packed int8 storage + per-64-group scales,
~6.8 GiB vs 26 GiB bf16), serve it through the Pallas dequant-matmul
kernel (ops/int4_matmul.py), and report measured tok/s against the honest
int4+KV HBM roofline.

Batch is 16: the Pallas kernel needs M >= 16 rows (int4_matmul.supported);
below that the XLA even/odd fallback path serves, measured ~2x slower on
the 470M bench (BASELINE.md int4 row).

Usage: python ci/llama13b_decode.py [batch] [new_tokens]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from kubeflow_tpu.models.configs import LLAMA2_13B  # noqa: E402
from kubeflow_tpu.models.generate import decode_config, generate  # noqa: E402
from kubeflow_tpu.models.quant import quantize_params_int4  # noqa: E402
from kubeflow_tpu.models.transformer import Transformer  # noqa: E402
from kubeflow_tpu.tpu.topology import ACCELERATORS  # noqa: E402

from llama7b_decode import host_random_params  # noqa: E402


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    new_tokens = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    prompt_len = 128
    cfg = decode_config(LLAMA2_13B).with_(
        max_seq_len=prompt_len + new_tokens, weight_dtype="int4")

    model_f = Transformer(decode_config(LLAMA2_13B).with_(
        max_seq_len=prompt_len + new_tokens))
    sample = jnp.ones((1, 8), jnp.int32)
    # host-side init + int4-quantize per leaf: the bf16 tree lives on
    # HOST, only the packed int4 tree touches HBM
    with jax.default_device(jax.devices("cpu")[0]):
        params = host_random_params(model_f, sample)
        qparams = quantize_params_int4(params)
        del params
    qparams = jax.device_put(qparams, jax.devices()[0])

    from kubeflow_tpu.models.quant import quantized_bytes

    w_bytes = quantized_bytes(qparams)  # streamed (embed lookup excluded)
    resident_bytes = quantized_bytes(qparams, exclude=())
    kv_bytes = (2 * batch * cfg.max_seq_len * cfg.num_kv_heads
                * cfg.head_dim * 2 * cfg.num_layers)
    print(f"int4 weights: {resident_bytes / 2**30:.2f} GiB resident "
          f"(bf16 would be {LLAMA2_13B.num_params * 2 / 2**30:.1f} GiB); "
          f"kv cache: {kv_bytes / 2**30:.2f} GiB", file=sys.stderr)

    prompt = jax.random.randint(jax.random.PRNGKey(0), (batch, prompt_len),
                                0, cfg.vocab_size)
    run = jax.jit(lambda p, t: generate(cfg, p, t, new_tokens))
    np.asarray(run(qparams, prompt))  # compile + warmup (value transfer)
    best = 0.0
    for i in range(3):
        p = jax.random.randint(jax.random.PRNGKey(100 + i),
                               (batch, prompt_len), 0, cfg.vocab_size)
        np.asarray(p)
        t0 = time.perf_counter()
        np.asarray(run(qparams, p))
        best = max(best, batch * new_tokens / (time.perf_counter() - t0))

    roofline = ACCELERATORS["v5e"].hbm_gbps * 1e9 / (w_bytes + kv_bytes) * batch
    print(json.dumps({
        "metric": "decode_tok_s_v5e_llama13b_int4",
        "value": round(best, 1),
        "unit": "tokens/s",
        "vs_baseline": round(best / roofline, 4),
        "detail": {
            "model": "llama2-13b-arch", "batch": batch,
            "prompt_len": prompt_len, "new_tokens": new_tokens,
            "weight_gb": round(resident_bytes / 2**30, 2),
            "streamed_weight_gb": round(w_bytes / 2**30, 2),
            "bf16_equiv_gb": round(LLAMA2_13B.num_params * 2 / 2**30, 1),
            "hbm_roofline_tok_s": round(roofline, 1),
        },
    }))


if __name__ == "__main__":
    main()
