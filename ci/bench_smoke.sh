#!/usr/bin/env bash
# Bench smoke on whatever backend is present (CPU in CI): asserts bench.py
# emits exactly one valid JSON line.  On TPU, first gate the bench hot path:
# the Pallas flash kernel must match the XLA reference (fwd + grads) across
# the block-size configs the bench uses — a tiling/numerics bug fails here
# before any MFU number is recorded (ci/flash_numerics.py).
set -euo pipefail
cd "$(dirname "$0")/.."
python ci/flash_numerics.py
out=$(python bench.py 2 2>/dev/null | grep '^{')
echo "$out" | python -c 'import json,sys; d=json.load(sys.stdin); assert {"metric","value","unit","vs_baseline"} <= set(d), d; print("bench smoke ok:", d["metric"])'
out=$(python bench.py --decode 2>/dev/null | grep '^{')
echo "$out" | python -c 'import json,sys; d=json.load(sys.stdin); assert {"metric","value","unit","vs_baseline"} <= set(d), d; print("bench smoke ok:", d["metric"])'
