"""Device-mesh construction for notebook training workloads.

The TPU-native replacement for the reference's absent distributed backend
(SURVEY.md §2.5): within a slice, parallelism axes ride ICI; across slices
(spec.tpu.slices > 1) the leading data-parallel axis rides DCN, exactly the
layout `jax.experimental.mesh_utils.create_hybrid_device_mesh` produces and
MEGASCALE_* coordination expects.

Axis convention (MaxText-style, outermost first):
  data     — batch data parallelism (DCN across slices, ICI within)
  fsdp     — parameter/optimizer sharding (ZeRO-3 style)
  sequence — sequence/context parallelism (ring attention)
  tensor   — tensor (Megatron) parallelism for MLP/attention heads
  pipeline — GPipe pipeline stages (parallel.pipeline; layer stack sharded
             stage-wise, activations ppermute stage->stage)
  expert   — MoE expert parallelism (models.moe; XLA inserts the
             dispatch/combine all-to-alls the einsum shardings imply)
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

MESH_AXES = ("data", "fsdp", "sequence", "tensor", "pipeline", "expert")


@dataclass(frozen=True)
class MeshConfig:
    """Parallelism degrees; -1 in `data` means "absorb remaining devices"."""

    data: int = -1
    fsdp: int = 1
    sequence: int = 1
    tensor: int = 1
    num_slices: int = 1  # >1 => hybrid mesh, data axis spans DCN
    pipeline: int = 1    # GPipe stages (innermost: stage neighbors on ICI)
    expert: int = 1      # MoE expert parallelism (models.moe; all-to-all
                         # dispatch/combine rides ICI)

    def resolved(self, num_devices: int) -> "MeshConfig":
        fixed = (self.fsdp * self.sequence * self.tensor
                 * self.pipeline * self.expert)
        data = self.data
        if data == -1:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by "
                    f"fsdp*sequence*tensor*pipeline*expert={fixed}"
                )
            data = num_devices // fixed
        if data * fixed != num_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.sequence}x{self.tensor}"
                f"x{self.pipeline}x{self.expert} != {num_devices} devices"
            )
        return MeshConfig(data, self.fsdp, self.sequence, self.tensor,
                          self.num_slices, self.pipeline, self.expert)

    @property
    def shape(self) -> tuple[int, int, int, int, int, int]:
        return (self.data, self.fsdp, self.sequence, self.tensor,
                self.pipeline, self.expert)


def make_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the training Mesh.

    Single-slice: `create_device_mesh` arranges devices so neighboring mesh
    coordinates are ICI neighbors (ring-friendly for psum/ppermute).
    Multi-slice: `create_hybrid_device_mesh` puts the data axis across
    slices (DCN) and everything else within a slice (ICI) — the layout the
    controller's MEGASCALE env injection (tpu/env.py) coordinates.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = (config or MeshConfig()).resolved(len(devices))
    if config.num_slices > 1:
        if config.data % config.num_slices != 0:
            raise ValueError(
                f"data={config.data} not divisible by num_slices={config.num_slices}"
            )
        per_slice = (
            config.data // config.num_slices,
            config.fsdp,
            config.sequence,
            config.tensor,
            config.pipeline,
            config.expert,
        )
        if devices and devices[0].platform == "cpu":
            # virtual CPU devices carry no slice_index attribute; emulate the
            # hybrid layout (slice-major outermost on the data axis) so the
            # multi-slice program still compiles in dry runs.  On real TPUs a
            # ValueError from create_hybrid_device_mesh is a genuine
            # misconfiguration and must propagate.
            device_array = np.asarray(devices).reshape(config.shape)
        else:
            device_array = mesh_utils.create_hybrid_device_mesh(
                per_slice,
                dcn_mesh_shape=(config.num_slices, 1, 1, 1, 1, 1),
                devices=devices,
            )
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                config.shape, devices=devices
            )
        except Exception:
            # virtual CPU devices have no topology info; plain reshape
            device_array = np.asarray(devices).reshape(config.shape)
    return Mesh(device_array, MESH_AXES)


def mesh_for_slice(
    num_devices: int,
    num_slices: int = 1,
    tensor: int = 1,
    sequence: int = 1,
    fsdp: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Convenience: fill fsdp with whatever data parallelism doesn't take.
    Default policy (fsdp=None): all non-tensor/sequence devices go to fsdp
    within a slice and data across slices — the standard recipe for
    memory-bound fine-tuning in a notebook."""
    per_slice = num_devices // num_slices
    if fsdp is None:
        fsdp = per_slice // (tensor * sequence)
    cfg = MeshConfig(
        data=-1, fsdp=fsdp, sequence=sequence, tensor=tensor, num_slices=num_slices
    )
    return make_mesh(cfg, devices=devices)


def num_devices_of(mesh: Mesh) -> int:
    return math.prod(mesh.devices.shape)
