"""Write-ahead discipline: destructive verbs dominated by persistence.

The recovery and scheduling protocols survive manager crashes only
because every destructive action (pod-deleting restarts, intent
annotation writes) happens strictly AFTER the bookkeeping that lets a
successor resume the work: the restore intent + attempt charge land on
status.sessionState/sliceRecovery before any pod dies, and the pool
claim commit lands on the TPUWarmPool status before the placement
annotation that points at it.  tests/test_interleave.py proves the
dynamic half (a seeded mutant fails a schedule); this analyzer pins the
static half: in each configured flow, every statement that may
(transitively) invoke a destructive verb must be DOMINATED on the
method's control-flow graph by a statement that performs the
status-persisting call — i.e. there is no entry->destroy path that skips
the persist.

Per-method statement-level CFG, stdlib `ast` only (same ethos as
lock_order.py).  Calls resolve one level deep through local nested
functions and same-class methods, including functions passed BY NAME as
call arguments (`retry_on_conflict(attempt)` executes `attempt`); a bare
destructive name passed as an argument (`self._execute_migrate(...,
restart_slice)`) marks the call site destructive.  The check is
intentionally strict: a statement that both destroys and persists does
NOT satisfy itself — ordering inside one call is invisible statically,
so the persist must happen in an earlier dominator.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from . import Module, Violation

CHECK = "writeahead"


@dataclass(frozen=True)
class Flow:
    path: str          # repo-relative module
    qualname: str      # Class.method the discipline applies to
    destructive: tuple  # dotted call patterns / bare callback names
    persist: tuple      # dotted call patterns that persist the intent


FLOWS: tuple[Flow, ...] = (
    # recovery: restore intent + attempt charge persist before pod deletes
    Flow("kubeflow_tpu/core/selfheal.py", "RecoveryEngine.maybe_recover",
         destructive=("restart_slice", "stamp_restore"),
         persist=("self._write_bookkeeping",)),
    # placement: the pool claim commit persists before the intent
    # annotation that points at it
    Flow("kubeflow_tpu/core/scheduler.py", "SliceScheduler._place",
         destructive=("self.api.update",),
         persist=("self.api.update_status",)),
    # reclamation: claims drain back to the pool before the intent
    # annotation (the crash-recovery pointer to them) is dropped
    Flow("kubeflow_tpu/core/scheduler.py", "SliceScheduler._release",
         destructive=("self.api.update",),
         persist=("self.api.update_status",)),
    # sharding: the membership commit (epoch bump + handoff record) lands
    # on the shard map before the replica drains or adopts any key —
    # adopting from local intent would reconcile keys nobody committed
    Flow("kubeflow_tpu/kube/shard.py", "ShardedReplica.join_fleet",
         destructive=("self._drain_and_adopt",),
         persist=("self.member.join",)),
    # preemption: the write-ahead eviction record lands on TenantQuota
    # status before any victim teardown — a crash between them would
    # leave half-evicted gangs no successor knows to finish (or worse,
    # re-evict)
    Flow("kubeflow_tpu/core/preemption.py", "PreemptionEngine.preempt",
         destructive=("self._teardown_victim",),
         persist=("self._commit_record",)),
)


def _dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    else:
        return ""
    return ".".join(reversed(parts))


# -- local call summaries ------------------------------------------------------
class _Summaries:
    """May-invoke summaries for every function/method in the module,
    keyed by simple name (closures and methods share one namespace —
    coarse, but collisions only ever widen the summary)."""

    def __init__(self, tree: ast.AST, flow: Flow) -> None:
        self.flow = flow
        self.fns: dict[str, ast.FunctionDef] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fns.setdefault(node.name, node)
        self.destroys: dict[str, bool] = {}
        self.persists: dict[str, bool] = {}
        self._solve()

    def _direct(self, fn) -> tuple[bool, bool, set]:
        """(destroys, persists, local callees) from fn's own statements,
        not descending into nested function definitions."""
        destroys = persists = False
        callees: set[str] = set()
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue   # executes only when called — summary per callee
            if isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in self.flow.destructive:
                    destroys = True
                if name in self.flow.persist:
                    persists = True
                simple = name.split(".")[-1] if name else ""
                if simple in self.fns:
                    callees.add(simple)
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        if arg.id in self.flow.destructive:
                            destroys = True
                        if arg.id in self.fns:
                            callees.add(arg.id)
            stack.extend(ast.iter_child_nodes(node))
        return destroys, persists, callees

    def _solve(self) -> None:
        direct = {name: self._direct(fn) for name, fn in self.fns.items()}
        self.destroys = {n: d for n, (d, _, _) in direct.items()}
        self.persists = {n: p for n, (_, p, _) in direct.items()}
        changed = True
        while changed:
            changed = False
            for name, (_, _, callees) in direct.items():
                for c in callees:
                    if self.destroys.get(c) and not self.destroys[name]:
                        self.destroys[name] = True
                        changed = True
                    if self.persists.get(c) and not self.persists[name]:
                        self.persists[name] = True
                        changed = True


# -- statement-level CFG -------------------------------------------------------
class _Node:
    __slots__ = ("idx", "stmt", "succ")

    def __init__(self, idx: int, stmt) -> None:
        self.idx = idx
        self.stmt = stmt
        self.succ: set[int] = set()


class _Cfg:
    """CFG over one function body.  Conservative: try-bodies may jump to
    their handlers after ANY statement, loops may skip their bodies,
    breaks exit the innermost loop."""

    def __init__(self, fn: ast.FunctionDef) -> None:
        self.nodes: list[_Node] = []
        entry = self._new(None)            # synthetic entry
        exits = self._build(fn.body, [entry.idx], loop_exits=None)
        self.entry = entry.idx
        self.exits = exits

    def _new(self, stmt) -> _Node:
        node = _Node(len(self.nodes), stmt)
        self.nodes.append(node)
        return node

    def _link(self, preds, node) -> None:
        for p in preds:
            self.nodes[p].succ.add(node.idx)

    def _build(self, stmts, preds, loop_exits) -> list:
        """Wire `stmts` after `preds`; returns the fall-through exits.
        `loop_exits` collects break targets for the innermost loop."""
        for stmt in stmts:
            node = self._new(stmt)
            self._link(preds, node)
            preds = [node.idx]
            if isinstance(stmt, ast.If):
                body = self._build(stmt.body, [node.idx], loop_exits)
                other = self._build(stmt.orelse, [node.idx], loop_exits) \
                    if stmt.orelse else [node.idx]
                preds = body + other
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                brk: list = []
                body = self._build(stmt.body, [node.idx], brk)
                for b in body:           # back edge
                    self.nodes[b].succ.add(node.idx)
                after = self._build(stmt.orelse, [node.idx], loop_exits) \
                    if stmt.orelse else [node.idx]
                preds = after + brk
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                preds = self._build(stmt.body, [node.idx], loop_exits)
            elif isinstance(stmt, ast.Try):
                start = len(self.nodes)
                body = self._build(stmt.body, [node.idx], loop_exits)
                # any body statement may raise straight into a handler
                raised = [node.idx] + list(range(start, len(self.nodes)))
                handler_exits: list = []
                for h in stmt.handlers:
                    handler_exits += self._build(h.body, raised, loop_exits)
                els = self._build(stmt.orelse, body, loop_exits) \
                    if stmt.orelse else body
                merged = els + handler_exits
                if stmt.finalbody:
                    preds = self._build(stmt.finalbody, merged, loop_exits)
                else:
                    preds = merged
            elif isinstance(stmt, (ast.Return, ast.Raise)):
                preds = []               # terminator
            elif isinstance(stmt, ast.Break):
                if loop_exits is not None:
                    loop_exits.append(node.idx)
                preds = []
            elif isinstance(stmt, ast.Continue):
                preds = []               # back edge folded into loop node
        return preds

    def dominators(self) -> list:
        """Iterative dominator sets (method-sized CFGs — quadratic is
        fine)."""
        n = len(self.nodes)
        preds: list[set[int]] = [set() for _ in range(n)]
        for node in self.nodes:
            for s in node.succ:
                preds[s].add(node.idx)
        full = set(range(n))
        dom = [full.copy() for _ in range(n)]
        dom[self.entry] = {self.entry}
        changed = True
        while changed:
            changed = False
            for i in range(n):
                if i == self.entry:
                    continue
                if not preds[i]:
                    new = {i}
                else:
                    new = set.intersection(
                        *(dom[p] for p in preds[i])) | {i}
                if new != dom[i]:
                    dom[i] = new
                    changed = True
        return dom


def _stmt_calls(stmt) -> list:
    """Calls directly attributable to this statement.  Compound
    statements contribute only their HEADER expressions (test, iterable,
    context managers) — their bodies are separate CFG nodes — and nested
    function definitions execute at their CALL sites, not here."""
    if isinstance(stmt, (ast.If, ast.While)):
        roots: list = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        roots = [stmt.iter]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        roots = [item.context_expr for item in stmt.items]
    elif isinstance(stmt, ast.Try):
        roots = []
    else:
        roots = [stmt]
    out = []
    stack = roots
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _labels(stmt, summaries: _Summaries, flow: Flow) -> tuple[bool, bool]:
    if stmt is None or isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return False, False
    destroys = persists = False
    for call in _stmt_calls(stmt):
        name = _dotted(call.func)
        simple = name.split(".")[-1] if name else ""
        if name in flow.destructive or summaries.destroys.get(simple):
            destroys = True
        if name in flow.persist or summaries.persists.get(simple):
            persists = True
        for arg in call.args:
            if isinstance(arg, ast.Name):
                if arg.id in flow.destructive or \
                        summaries.destroys.get(arg.id):
                    destroys = True
                if summaries.persists.get(arg.id):
                    persists = True
    return destroys, persists


def _find_method(tree: ast.AST, qualname: str):
    cls_name, meth = qualname.split(".", 1)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            for child in node.body:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) \
                        and child.name == meth:
                    return child
    return None


def analyze(mod: Module) -> list[Violation]:
    out: list[Violation] = []
    for flow in FLOWS:
        if mod.rel != flow.path:
            continue
        fn = _find_method(mod.tree, flow.qualname)
        if fn is None:
            out.append(Violation(
                CHECK, mod.rel, 1, flow.qualname,
                f"configured write-ahead flow {flow.qualname} not found — "
                "update ci/analyzers/write_ahead.py FLOWS"))
            continue
        summaries = _Summaries(mod.tree, flow)
        cfg = _Cfg(fn)
        labels = [_labels(n.stmt, summaries, flow) for n in cfg.nodes]
        dom = cfg.dominators()
        for node in cfg.nodes:
            destroys, _ = labels[node.idx]
            if not destroys:
                continue
            # strict dominators only: persist-then-destroy inside ONE
            # statement is not statically ordered
            if any(labels[d][1] for d in dom[node.idx]
                   if d != node.idx):
                continue
            line = getattr(node.stmt, "lineno", fn.lineno)
            out.append(Violation(
                CHECK, mod.rel, line, flow.qualname,
                "destructive call (%s) is not dominated by the "
                "status-persisting write (%s): a crash between them "
                "loses the write-ahead record this protocol resumes "
                "from" % (" | ".join(flow.destructive),
                          " | ".join(flow.persist))))
    return out
